"""The ``peasoup-sift run`` orchestration.

One run consumes the campaign candidate database end to end:

  load -> batch-fold (ops/survey_fold via sift/fold) -> known-pulsar
  cross-match -> multi-beam coincidence veto -> campaign-level
  harmonic/DM dedup (sky-position gated) -> calibrated candidate
  scoring (peasoup_tpu/rank, DM-curve refold + batched feature
  extraction) -> repeat single-pulse association -> one transaction
  writing the ``sift_*`` tables.

The run is wired into the full observability + resilience stacks: a
``sift`` status section (heartbeat/status.json + telemetry manifest),
stage transitions and per-pass events/timers, filterbank reads through
``IO_RETRY``, every DB transaction through ``DB_RETRY`` with the
``db.ingest`` fault seam, and the fold pass degrading (batch shrink)
under ``device.oom``. Re-running replaces the previous sifted product
wholesale (latest run wins), so the sift is an idempotent post-pass a
survey team can repeat as observations keep arriving.
"""

from __future__ import annotations

import dataclasses
import os
import time
import uuid

import numpy as np

from ..campaign.db import DB_FILENAME, CandidateDB
from ..obs import get_logger
from ..obs.telemetry import current as current_telemetry
from .crossmatch import load_catalogue, match_candidate
from .dedup import dedup_candidates, multibeam_veto
from .fold import FoldCandidate, FoldObservation, SurveyFolder
from .repeats import repeat_sources

log = get_logger("sift.service")


@dataclasses.dataclass
class SiftConfig:
    """Knobs for one sift run (persisted in the ``sift_runs`` row)."""

    workdir: str = "."  # campaign root (holds candidates.sqlite)
    db_path: str = ""  # explicit DB override
    # batched survey folding
    fold: bool = True
    fold_batch: int = 64  # candidates per fixed device batch
    fold_nbins: int = 64
    fold_nints: int = 16
    max_fold_per_obs: int = 256  # top-N by S/N folded per observation
    fold_snr_min: float = 6.0  # folded S/N confirming a candidate
    # adopt the optimiser's refined period only when the observation
    # spans at least this many pulses — the phase-shift period update
    # is meaningless when the fold holds a handful of rotations
    opt_period_min_pulses: float = 16.0
    # known-pulsar cross-match
    catalogue: str = ""  # "" = the checked-in convenience catalogue
    max_harm: int = 16
    period_tol: float = 2e-3
    dm_tol: float = 2.0
    dm_tol_frac: float = 0.05
    # campaign-level dedup
    dedup_max_harm: int = 8
    dedup_period_tol: float = 2e-3
    dedup_dm_tol: float = 2.0
    # multi-beam coincidence veto
    beam_thresh: int = 4
    coinc_snr: float = 6.0
    # repeat single-pulse association
    sp_dm_tol: float = 1.0
    sp_min_pulses: int = 3
    sp_min_obs: int = 2
    sp_min_period: float = 0.05
    sp_max_harm: int = 1000
    sp_phase_tol: float = 0.02
    # sky-position association gates (degrees; <= 0 disables): members
    # must lie within this angular separation to merge into one source
    # — a harmonic coincidence between opposite sky poles is not one
    # pulsar. Generous default: adjacent beams of one pointing pass,
    # antipodal detections never do.
    dedup_pos_tol_deg: float = 3.0
    sp_pos_tol_deg: float = 3.0
    # candidate ranking (peasoup-rank): score every catalogue row with
    # fold products through the calibrated model artifact
    score: bool = True
    score_model: str = ""  # "" = the checked-in default artifact
    score_batch: int = 64
    # per-tenant slice: sift only observations stamped with this tenant
    tenant: str = ""

    def resolved_db(self) -> str:
        return self.db_path or os.path.join(self.workdir, DB_FILENAME)


class SiftRun:
    """One sift pass over a campaign database."""

    def __init__(self, cfg: SiftConfig) -> None:
        self.cfg = cfg
        self._progress: dict = {"stage": "idle"}

    # --- the sift status section (status.json + manifest) -------------
    def status_section(self) -> dict:
        return dict(self._progress)

    def _mark(self, stage: str, **fields) -> None:
        self._progress.update({"stage": stage, **fields})

    # --- fold input assembly ------------------------------------------
    def build_fold_inputs(
        self, obs_rows: list[dict], cands: list[dict]
    ) -> list[FoldObservation]:
        """Re-dedisperse each observation at its candidates' DMs and
        package the survey folder's inputs. A missing/unreadable input
        file skips that observation with an event (the sift must
        survive an archive where raw data has been aged out)."""
        from ..io.sigproc import read_filterbank
        from ..ops.dedisperse import dedisperse_device, output_scale
        from ..plan.dm_plan import delay_table

        tel = current_telemetry()
        by_job: dict[str, list[dict]] = {}
        for c in cands:
            by_job.setdefault(c["job_id"], []).append(c)
        out: list[FoldObservation] = []
        for obs in obs_rows:
            rows = by_job.get(obs["job_id"])
            if not rows:
                continue
            rows = sorted(
                rows, key=lambda c: -float(c.get("snr") or 0.0)
            )[: self.cfg.max_fold_per_obs]
            try:
                fil = read_filterbank(obs["input"])
            except Exception as exc:
                tel.event(
                    "sift_obs_skipped", job_id=obs["job_id"],
                    input=obs.get("input"),
                    error=f"{type(exc).__name__}: {exc!s:.200}",
                )
                log.warning(
                    "skipping %s: cannot read %s (%s)",
                    obs["job_id"], obs.get("input"), exc,
                )
                continue
            hdr = fil.header
            # the dedisp-parity delay table at this observation's
            # geometry; one trial per distinct candidate DM
            per_unit = np.abs(
                delay_table(hdr.fch1, hdr.foff, hdr.nchans, hdr.tsamp)
            )
            dms = sorted({float(c["dm"]) for c in rows})
            dm_row = {dm: i for i, dm in enumerate(dms)}
            prod = (
                np.asarray(dms, dtype=np.float32)[:, None]
                * per_unit[None, :]
            ).astype(np.float32)
            delays = np.rint(prod).astype(np.int32)
            max_delay = int(delays.max()) if delays.size else 0
            out_nsamps = fil.nsamps - max_delay
            if out_nsamps < 64:
                tel.event(
                    "sift_obs_skipped", job_id=obs["job_id"],
                    error=f"too short after dedispersion "
                    f"({out_nsamps} samples)",
                )
                continue
            import jax

            trials = np.asarray(
                jax.device_get(
                    dedisperse_device(
                        fil.data, delays,
                        np.ones(hdr.nchans, dtype=np.float32),
                        out_nsamps,
                        scale=output_scale(hdr.nbits, hdr.nchans),
                    )
                )
            )
            out.append(
                FoldObservation(
                    job_id=obs["job_id"],
                    trials=trials,
                    trials_nsamps=out_nsamps,
                    tsamp=float(hdr.tsamp),
                    cands=[
                        FoldCandidate(
                            key=c["id"],
                            period=float(c["period"]),
                            acc=float(c.get("acc") or 0.0),
                            dm_row=dm_row[float(c["dm"])],
                        )
                        for c in rows
                    ],
                )
            )
        return out

    # --- candidate ranking --------------------------------------------
    def _dm_curve_refold(
        self, scorable: list[tuple[int, dict]], obs_rows: list[dict]
    ) -> dict[int, np.ndarray]:
        """Refold each scored lead at fractions of its own DM (same
        batched survey-fold path, synthetic candidate keys): the curve
        of optimised S/N over trial DM peaks at the candidate DM for a
        celestial signal and at zero for terrestrial interference — the
        scorer's strongest discriminant. Returns row-index -> curve."""
        from ..ops.candidate_features import DM_CURVE_FRACTIONS
        from ..parallel.multihost import run_survey_fold

        cfg = self.cfg
        ndm = len(DM_CURVE_FRACTIONS)
        per_obs_cap = max(1, cfg.max_fold_per_obs // ndm)
        synth: list[dict] = []
        per_job: dict[str, int] = {}
        for ridx, lead in scorable:
            jid = lead["job_id"]
            if per_job.get(jid, 0) >= per_obs_cap:
                continue
            per_job[jid] = per_job.get(jid, 0) + 1
            for fi, frac in enumerate(DM_CURVE_FRACTIONS):
                synth.append(
                    {
                        "id": ridx * ndm + fi,
                        "job_id": jid,
                        "dm": float(frac) * float(lead["dm"]),
                        "period": float(lead["eff_period"]),
                        "acc": float(lead.get("acc") or 0.0),
                        "snr": float(lead.get("snr") or 0.0),
                    }
                )
        if not synth:
            return {}
        fold_inputs = self.build_fold_inputs(obs_rows, synth)
        folder = SurveyFolder(
            nbins=cfg.fold_nbins, nints=cfg.fold_nints,
            batch=cfg.fold_batch,
        )
        curves: dict[int, np.ndarray] = {}
        for o in run_survey_fold(fold_inputs, folder):
            ridx, fi = divmod(int(o["key"]), ndm)
            curves.setdefault(
                ridx, np.zeros(ndm, dtype=np.float32)
            )[fi] = float(o["opt_sn"])
        return curves

    def _score_catalogue(
        self,
        catalogue_rows: list[dict],
        row_leads: list[tuple[int, dict]],
        outcomes_by_key: dict,
        obs_rows: list[dict],
    ) -> int:
        """Attach calibrated scores, triage tiers, and the model
        fingerprint to every catalogue row with fold products. The DM
        curve lands in the row's fold stamp so ``peasoup-rank score``
        can re-score the database later without raw data."""
        from ..ops.candidate_features import DM_CURVE_FRACTIONS
        from ..rank.model import RankModel, score_tier
        from ..rank.score import score_fold_products

        cfg = self.cfg
        scorable = [
            (ridx, lead)
            for ridx, lead in row_leads
            if outcomes_by_key.get(lead["id"]) is not None
        ]
        if not scorable:
            return 0
        model = RankModel.from_file(cfg.score_model or None)
        curves = self._dm_curve_refold(scorable, obs_rows)
        ndm = len(DM_CURVE_FRACTIONS)
        prof = np.stack(
            [
                np.asarray(
                    outcomes_by_key[lead["id"]]["opt_prof"],
                    dtype=np.float32,
                )
                for _, lead in scorable
            ]
        )
        subints = np.stack(
            [
                np.asarray(
                    outcomes_by_key[lead["id"]]["opt_fold"],
                    dtype=np.float32,
                )
                for _, lead in scorable
            ]
        )
        dm_curve = np.stack(
            [
                curves.get(ridx, np.zeros(ndm, dtype=np.float32))
                for ridx, _ in scorable
            ]
        )
        _feats, scores = score_fold_products(
            model, prof, subints, dm_curve, batch=cfg.score_batch
        )
        for (ridx, _), p, curve in zip(scorable, scores, dm_curve):
            row = catalogue_rows[ridx]
            row["score"] = round(float(p), 6)
            row["score_tier"] = score_tier(float(p))
            row["model_fp"] = model.fingerprint
            if row.get("fold") is not None:
                row["fold"]["dm_curve"] = [
                    round(float(v), 3) for v in curve
                ]
        log.info(
            "scored %d/%d catalogue rows (model %s)",
            len(scorable), len(catalogue_rows), model.fingerprint,
        )
        return len(scorable)

    # --- the run -------------------------------------------------------
    def run(self) -> dict:
        cfg = self.cfg
        tel = current_telemetry()
        tel.set_status_section("sift", self.status_section)
        t_run = time.perf_counter()
        db_path = cfg.resolved_db()
        if not os.path.exists(db_path):
            raise FileNotFoundError(
                f"no campaign database at {db_path} (run the campaign "
                "and `peasoup-campaign ingest` first)"
            )
        run_id = uuid.uuid4().hex[:12]

        with CandidateDB(db_path) as db:
            tel.set_stage("loading")
            self._mark("loading")
            obs_rows = db.observations()
            watermark_rowid = db.max_observation_rowid()
            periodicity = db.all_candidates("periodicity")
            single_pulse = db.all_candidates("single_pulse")
            if cfg.tenant:
                # per-tenant slice: only observations stamped with this
                # tenant (and their candidates) enter the sift
                keep = {
                    o["job_id"]
                    for o in obs_rows
                    if (o.get("tenant") or "") == cfg.tenant
                }
                obs_rows = [o for o in obs_rows if o["job_id"] in keep]
                periodicity = [
                    c for c in periodicity if c["job_id"] in keep
                ]
                single_pulse = [
                    c for c in single_pulse if c["job_id"] in keep
                ]
                tel.event(
                    "sift_tenant_filter", tenant=cfg.tenant,
                    observations=len(obs_rows),
                    periodicity=len(periodicity),
                    single_pulse=len(single_pulse),
                )
            self._mark(
                "loaded", observations=len(obs_rows),
                periodicity=len(periodicity),
                single_pulse=len(single_pulse),
            )

            # --- batched survey folding --------------------------------
            outcomes_by_key: dict = {}
            n_folded = 0
            if cfg.fold and periodicity:
                tel.set_stage("folding")
                self._mark("folding", folded=0)
                t0 = time.perf_counter()
                fold_inputs = self.build_fold_inputs(
                    obs_rows, periodicity
                )
                from ..parallel.multihost import run_survey_fold

                folder = SurveyFolder(
                    nbins=cfg.fold_nbins, nints=cfg.fold_nints,
                    batch=cfg.fold_batch,
                )
                outcomes = run_survey_fold(fold_inputs, folder)
                outcomes_by_key = {o["key"]: o for o in outcomes}
                n_folded = len(outcomes)
                tel.add_timer("sift_folding", time.perf_counter() - t0)
                tel.event(
                    "sift_folded", candidates=n_folded,
                    observations=len(fold_inputs),
                )
                self._mark("folded", folded=n_folded)

            # effective parameters post-fold: the optimiser's refined
            # period and S/N supersede the search's trial values
            for c in periodicity:
                o = outcomes_by_key.get(c["id"])
                c["eff_period"] = float(c["period"] or 0.0)
                if o is not None:
                    c["folded_snr"] = float(o["opt_sn"])
                    c["opt_period"] = float(o["opt_period"])
                    trial_p = float(c["period"] or 0.0)
                    if (
                        trial_p > 0
                        and o["tobs"]
                        >= cfg.opt_period_min_pulses * trial_p
                    ):
                        c["eff_period"] = float(o["opt_period"])

            # --- known-pulsar cross-match ------------------------------
            tel.set_stage("crossmatch")
            self._mark("crossmatch")
            t0 = time.perf_counter()
            catalogue = load_catalogue(cfg.catalogue or None)
            known_matches: list[dict] = []
            match_by_id: dict = {}
            for c in periodicity:
                m = match_candidate(
                    c["eff_period"], float(c["dm"]), catalogue,
                    max_harm=cfg.max_harm, period_tol=cfg.period_tol,
                    dm_tol=cfg.dm_tol, dm_tol_frac=cfg.dm_tol_frac,
                )
                if m is not None:
                    match_by_id[c["id"]] = m
                    known_matches.append(
                        dict(m, candidate_id=c["id"], job_id=c["job_id"])
                    )
            tel.add_timer("sift_crossmatch", time.perf_counter() - t0)
            tel.event(
                "sift_crossmatch", matches=len(known_matches),
                pulsars=len({m["psr"] for m in known_matches}),
            )
            self._mark("crossmatched", known=len(known_matches))

            # --- multi-beam coincidence veto ---------------------------
            tel.set_stage("coincidence")
            vetoed = multibeam_veto(
                [
                    {
                        "id": c["id"], "period": c["eff_period"],
                        "dm": c["dm"], "snr": c["snr"],
                        "beam": c.get("beam"),
                    }
                    for c in periodicity
                ],
                snr_thresh=cfg.coinc_snr,
                beam_thresh=cfg.beam_thresh,
                period_tol=cfg.dedup_period_tol,
                dm_cell=cfg.dedup_dm_tol,
            )
            tel.event("sift_coincidence", vetoed=len(vetoed))

            # --- campaign-level dedup ----------------------------------
            tel.set_stage("dedup")
            self._mark("dedup")
            t0 = time.perf_counter()
            groups = dedup_candidates(
                [
                    {
                        "id": c["id"], "job_id": c["job_id"],
                        "period": c["eff_period"], "dm": c["dm"],
                        "snr": c["snr"],
                        "src_raj": c.get("src_raj"),
                        "src_dej": c.get("src_dej"),
                    }
                    for c in periodicity
                ],
                max_harm=cfg.dedup_max_harm,
                period_tol=cfg.dedup_period_tol,
                dm_tol=cfg.dedup_dm_tol,
                pos_tol_deg=cfg.dedup_pos_tol_deg,
            )
            by_id = {c["id"]: c for c in periodicity}
            catalogue_rows: list[dict] = []
            row_leads: list[tuple[int, dict]] = []
            for g in groups:
                lead = by_id[g["leader"]["id"]]
                member_matches = [
                    match_by_id[m["id"]]
                    for m in g["members"]
                    if m["id"] in match_by_id
                ]
                known = (
                    min(
                        member_matches,
                        key=lambda m: m["period_frac_err"],
                    )
                    if member_matches else None
                )
                is_rfi = all(
                    m["id"] in vetoed for m in g["members"]
                ) and bool(vetoed)
                folded_snr = float(lead.get("folded_snr") or 0.0)
                confirmed = folded_snr >= cfg.fold_snr_min
                if known is not None:
                    label, tier = "known", 1
                elif is_rfi:
                    label, tier = "rfi", 3
                elif g["n_obs"] >= 2 and confirmed:
                    label, tier = "candidate", 1
                elif g["n_obs"] >= 2 or confirmed:
                    label, tier = "candidate", 2
                else:
                    label, tier = "candidate", 3
                fold_out = outcomes_by_key.get(lead["id"])
                catalogue_rows.append(
                    {
                        "kind": "periodicity",
                        "label": label,
                        "tier": tier,
                        "dm": float(lead["dm"]),
                        "snr": float(lead["snr"]),
                        "period": float(lead["eff_period"]),
                        "folded_snr": folded_snr or None,
                        "opt_period": lead.get("opt_period"),
                        "known_source": known["psr"] if known else None,
                        "harmonic": known["harmonic"] if known else None,
                        "n_obs": g["n_obs"],
                        "members": len(g["members"]),
                        "job_ids": g["job_ids"],
                        "fold": (
                            None
                            if fold_out is None
                            else {
                                "prof": [
                                    round(float(v), 3)
                                    for v in fold_out["opt_prof"]
                                ],
                                "subints": [
                                    [round(float(v), 3) for v in row]
                                    for row in fold_out["opt_fold"]
                                ],
                            }
                        ),
                    }
                )
                row_leads.append((len(catalogue_rows) - 1, lead))
            tel.add_timer("sift_dedup", time.perf_counter() - t0)
            tel.event(
                "sift_dedup", groups=len(groups),
                candidates=len(periodicity),
            )
            self._mark("deduped", catalogue=len(catalogue_rows))

            # --- candidate ranking -------------------------------------
            if cfg.score and catalogue_rows:
                tel.set_stage("scoring")
                self._mark("scoring")
                t0 = time.perf_counter()
                n_scored = self._score_catalogue(
                    catalogue_rows, row_leads, outcomes_by_key, obs_rows
                )
                tel.add_timer(
                    "sift_scoring", time.perf_counter() - t0
                )
                tel.event(
                    "sift_scored", scored=n_scored,
                    catalogue=len(catalogue_rows),
                )
                self._mark("scored", scored=n_scored)

            # --- repeat single-pulse association -----------------------
            tel.set_stage("repeats")
            t0 = time.perf_counter()
            sp_sources = repeat_sources(
                single_pulse,
                dm_tol=cfg.sp_dm_tol,
                min_pulses=cfg.sp_min_pulses,
                min_obs=cfg.sp_min_obs,
                min_period=cfg.sp_min_period,
                max_harm=cfg.sp_max_harm,
                phase_tol=cfg.sp_phase_tol,
                pos_tol_deg=cfg.sp_pos_tol_deg,
            )
            for s in sp_sources:
                s.pop("member_ids", None)
            tel.add_timer("sift_repeats", time.perf_counter() - t0)
            tel.event("sift_repeats", sources=len(sp_sources))

            # --- write the sifted product ------------------------------
            tel.set_stage("ingest")
            self._mark("ingest")
            config_doc = dataclasses.asdict(cfg)
            config_doc["n_folded"] = n_folded
            # Incremental-sift watermark: the highest observation rowid
            # this run saw.  `peasoup-sift run --incremental` no-ops
            # while the campaign DB is still at or below it.
            config_doc["watermark_rowid"] = watermark_rowid
            tally = db.ingest_sift_run(
                run_id, config_doc, catalogue_rows, known_matches,
                sp_sources,
            )
            tel.set_stage("done")
            summary = {
                "run_id": run_id,
                "db_path": db_path,
                "observations": len(obs_rows),
                "periodicity": len(periodicity),
                "single_pulse": len(single_pulse),
                "watermark_rowid": watermark_rowid,
                "duration_s": round(time.perf_counter() - t_run, 3),
                **tally,
            }
            self._mark("done", **{
                k: v for k, v in summary.items() if k != "db_path"
            })
            log.info(
                "sift run %s: %d folded, %d catalogue rows (%d known, "
                "%d rfi), %d repeat single-pulse sources in %.1fs",
                run_id, tally["n_folded"], tally["n_catalogue"],
                tally["n_known"], tally["n_rfi"],
                tally["n_sp_sources"], summary["duration_s"],
            )
            tel.event("sift_done", **summary)
            return summary
