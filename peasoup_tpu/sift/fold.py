"""Survey folding: every campaign candidate through fixed-size batches.

The per-observation :class:`~peasoup_tpu.pipeline.folder.MultiFolder`
folds the top handful of one observation's candidates. At campaign
scale the folding workload is the union over the whole database —
thousands of candidates spread over observations of several lengths —
and survey throughput hinges on folding them in bulk (PulsarX,
arXiv:2309.02544). This driver:

- derives each observation's fold geometry with the folder's own
  :func:`~peasoup_tpu.pipeline.folder.fold_geometry` (power-of-two
  truncation, f32 tsamp/tobs, whitening band edges), so every
  per-candidate result is **bitwise-equal** to the per-observation
  path (pinned in tests/test_sift.py);
- dereddens each needed (observation, DM trial) series exactly once;
- packs candidates into **fixed-size shape-bucketed batches** — bucket
  = the power-of-two series length — and streams them through the one
  jitted :func:`~peasoup_tpu.ops.survey_fold.survey_fold_batch`
  program per bucket, then optimises all folds in fixed-size
  :class:`~peasoup_tpu.ops.fold_optimise.FoldOptimiser` batches: zero
  steady-state recompiles across same-bucket batches;
- degrades under device OOM by halving the batch size (a
  :class:`~peasoup_tpu.resilience.DegradationLadder` rung, with the
  ``device.oom`` fault seam) — row independence keeps the shrunken
  batches bitwise-equal to the full-size ones.

Multi-host campaigns dispatch through
:func:`peasoup_tpu.parallel.multihost.run_survey_fold`, which deals
observations round-robin to processes and allgathers the outcomes.
"""

from __future__ import annotations

import dataclasses
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import get_logger
from ..obs.telemetry import current as current_telemetry
from ..ops.fold import fold_bins_np
from ..ops.fold_optimise import FoldOptimiser
from ..ops.survey_fold import survey_fold_batch
from ..pipeline.folder import _deredden_tim, fold_geometry
from ..resilience import DegradationLadder, faults, is_resource_exhausted

log = get_logger("sift.fold")


@dataclasses.dataclass
class FoldCandidate:
    """One candidate to fold: ``dm_row`` indexes the observation's
    ``trials`` array; ``key`` is the caller's opaque identity (the DB
    candidate id) carried through to the outcome."""

    key: object
    period: float
    acc: float
    dm_row: int


@dataclasses.dataclass
class FoldObservation:
    """One observation's fold input: dedispersed trials (u8, one row
    per needed DM) plus the candidates referencing them."""

    job_id: str
    trials: np.ndarray  # (nrows, >=trials_nsamps) u8 dedispersed series
    trials_nsamps: int
    tsamp: float
    cands: List[FoldCandidate] = dataclasses.field(default_factory=list)


class SurveyFolder:
    """Batched cross-observation folding with fixed-shape programs."""

    # same physicality gates as MultiFolder
    min_period = 1e-3
    max_period = 10.0

    def __init__(
        self, nbins: int = 64, nints: int = 16, batch: int = 64
    ) -> None:
        self.nbins = int(nbins)
        self.nints = int(nints)
        self.batch = int(batch)
        self.optimiser = FoldOptimiser(self.nbins, self.nints)

    # --- planning -----------------------------------------------------
    def _plan(self, observations: List[FoldObservation]):
        """Group foldable candidates by shape bucket (power-of-two
        series length). Returns {size: [(obs_idx, cand), ...]} plus the
        per-observation geometry list."""
        geoms = []
        buckets: dict[int, list] = {}
        for oi, obs in enumerate(observations):
            geom = fold_geometry(obs.trials_nsamps, obs.tsamp)
            geoms.append(geom)
            size = geom[0]
            for cand in obs.cands:
                if not self.min_period < cand.period < self.max_period:
                    continue
                if not 0 <= cand.dm_row < len(obs.trials):
                    continue
                buckets.setdefault(size, []).append((oi, cand))
        return buckets, geoms

    # --- the fold pass ------------------------------------------------
    def fold_outcomes(
        self, observations: List[FoldObservation]
    ) -> list[dict]:
        """Fold + optimise every foldable candidate. Returns one
        outcome dict per candidate: ``key``, ``job_id``, ``opt_sn``,
        ``opt_period``, ``opt_fold`` (nints, nbins), ``opt_prof``."""
        from ..ops.resample import accel_factor

        tel = current_telemetry()
        buckets, geoms = self._plan(observations)
        ladder = DegradationLadder("sift.fold", ("batch_shrink",))
        batch = self.batch

        all_folds: list[np.ndarray] = []
        all_meta: list[tuple] = []  # (obs_idx, cand, tobs)
        for size in sorted(buckets):
            entries = buckets[size]
            # deredden each needed (obs, dm_row) once per bucket; the
            # cache lives only for the bucket so peak host memory stays
            # one bucket's worth of f32 series
            xd_cache: dict[tuple[int, int], np.ndarray] = {}
            rows_xd = np.empty((len(entries), size), dtype=np.float32)
            afs = np.empty(len(entries), dtype=np.float32)
            used = self.nints * (size // self.nints)
            bins = np.empty((len(entries), used), dtype=np.int32)
            for i, (oi, cand) in enumerate(entries):
                obs = observations[oi]
                _, tsamp32, _, pos5, pos25 = geoms[oi]
                ck = (oi, cand.dm_row)
                if ck not in xd_cache:
                    xd_cache[ck] = np.asarray(
                        _deredden_tim(
                            jnp.asarray(obs.trials[cand.dm_row]),
                            size=size, pos5=pos5, pos25=pos25,
                        )
                    )
                rows_xd[i] = xd_cache[ck]
                # (a*tsamp) is an f32 product in the reference's
                # launcher; accel_factor replays it (folder.py idiom)
                afs[i] = accel_factor(
                    np.asarray([cand.acc]), tsamp32
                ).astype(np.float32)[0]
                bins[i] = fold_bins_np(
                    size, tsamp32, cand.period, self.nbins, self.nints
                )
            del xd_cache

            lo = 0
            while lo < len(entries):
                hi = min(lo + batch, len(entries))
                n = hi - lo
                # fixed batch width: pad by repeating the first row so
                # every dispatch of this bucket reuses ONE compiled
                # program (padding rows are dropped below)
                pad_idx = np.arange(batch) % n + lo
                try:
                    faults.fire(
                        "device.oom",
                        context=f"sift.fold:{size}:{lo}",
                    )
                    folds = np.asarray(
                        survey_fold_batch(
                            jnp.asarray(rows_xd[pad_idx]),
                            jnp.asarray(afs[pad_idx]),
                            jnp.asarray(bins[pad_idx]),
                            nbins=self.nbins,
                            nints=self.nints,
                        )
                    )[:n]
                except Exception as exc:
                    if not is_resource_exhausted(exc):
                        raise
                    if batch <= 1:
                        ladder.exhausted(
                            batch=batch, error=f"{exc!s:.200}"
                        )
                        raise
                    ladder.step(
                        "batch_shrink", batch_old=batch,
                        batch_new=batch // 2, error=f"{exc!s:.200}",
                    )
                    batch //= 2
                    continue  # retry the same rows at the smaller batch
                all_folds.append(folds)
                for oi, cand in entries[lo:hi]:
                    all_meta.append((oi, cand, geoms[oi][2]))
                lo = hi
            tel.event(
                "sift_fold_bucket", size=int(size),
                candidates=len(entries), batch=int(batch),
            )

        if not all_meta:
            return []
        folds = np.concatenate(all_folds, axis=0)
        periods = np.asarray(
            [c.period for _, c, _ in all_meta], dtype=np.float64
        )
        tobs = np.asarray([t for _, _, t in all_meta], dtype=np.float64)

        # optimise in the same fixed batch width (recycled-row padding,
        # the folder.py idiom) so the optimiser compiles once too
        outcomes: list[dict] = []
        lo = 0
        while lo < len(all_meta):
            hi = min(lo + batch, len(all_meta))
            n = hi - lo
            pad_idx = np.arange(batch) % n + lo
            results = self.optimiser.optimise(
                folds[pad_idx], periods[pad_idx], tobs[pad_idx]
            )[:n]
            for (oi, cand, t), res in zip(all_meta[lo:hi], results):
                outcomes.append(
                    {
                        "key": cand.key,
                        "job_id": observations[oi].job_id,
                        "opt_sn": res["opt_sn"],
                        "opt_period": res["opt_period"],
                        "opt_fold": res["opt_fold"],
                        "opt_prof": res["opt_prof"],
                        # fold context: consumers gate how much to
                        # trust the period refinement on how many
                        # pulses the observation actually spans
                        "period": float(cand.period),
                        "tobs": float(t),
                    }
                )
            lo = hi
        return outcomes
