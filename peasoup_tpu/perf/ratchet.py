"""The perf-regression ratchet: perf.json vs perf_baseline.json.

Mirrors the audit's ``audit_baseline.json`` discipline. The checked-in
baseline pins, per program, the tolerated execute/compile medians and
the structural facts (the program set itself). ``peasoup-perf check``
compares a fresh perf.json against it:

* **structural invariants** gate everywhere (CPU CI included): every
  baseline program must still exist and still compile/run (a deleted
  or broken registry program is a regression, not a shrinkage), no
  jitted entry point may be missing from the registry
  (ops.registry.unregistered_entry_points), and — checked by the CLI,
  not here — a warm registry pass must be 100% persistent-cache hits
  with zero real recompiles.
* **timing ratchets** apply on real backends (or with ``timing="on"``):
  a program whose execute median exceeds baseline x tolerance fails.
  CPU timings are recorded in the baseline for reference but gate
  nothing by default — shared-runner CI wall clocks are weather, not
  regressions; the device-anchored TPU numbers are the contract.

New programs never fail the check (growth is the point); they are
reported so the baseline can be re-pinned (``--write-baseline``),
which is also how a legitimate speedup or an accepted slowdown is
recorded — the file only changes deliberately, in review.
"""

from __future__ import annotations

from dataclasses import dataclass

BASELINE_SCHEMA = "peasoup_tpu.perf_baseline"
BASELINE_VERSION = 1

# default execute-median tolerance: generous enough to ride out
# device-clock jitter, tight enough that a real kernel regression
# (2x = a lost fusion, a serialised scan) trips it
DEFAULT_TOLERANCE = 1.6
# compile time is noisier (cache state, XLA version); ratchet it
# loosely — its job is catching a program whose compile EXPLODES
# (e.g. an unrolled loop), not 20% drift
DEFAULT_COMPILE_TOLERANCE = 4.0


@dataclass
class PerfProblem:
    """One ratchet violation."""

    kind: str  # missing_program | program_error | slower | compile_slower
    # | unregistered_entry_point | schema
    program: str
    message: str

    def render(self) -> str:
        return f"{self.program}: [{self.kind}] {self.message}"


def baseline_from_perf(
    doc: dict,
    tolerance: float = DEFAULT_TOLERANCE,
    compile_tolerance: float = DEFAULT_COMPILE_TOLERANCE,
) -> dict:
    """Pin a baseline document from a perf.json run (programs with
    errors are excluded — a broken program must be fixed, not
    baselined)."""
    programs = {
        name: {
            "execute_median_s": rec["execute_median_s"],
            "compile_s": rec["compile_s"],
            "args": rec.get("args", []),
        }
        for name, rec in sorted(doc["programs"].items())
        if not rec.get("error")
    }
    return {
        "schema": BASELINE_SCHEMA,
        "version": BASELINE_VERSION,
        "generated_by": "peasoup-perf check --write-baseline",
        "backend": doc["backend"],
        "device_kind": doc.get("device_kind", "unknown"),
        "tolerance": tolerance,
        "compile_tolerance": compile_tolerance,
        "programs": programs,
    }


def _load_baseline_strict(path: str) -> dict:
    import json

    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != BASELINE_SCHEMA:
        raise ValueError(
            f"{path}: not a {BASELINE_SCHEMA} document "
            f"(schema={doc.get('schema')!r})"
        )
    if not isinstance(doc.get("programs"), dict):
        raise ValueError(f"{path}: baseline lacks a programs map")
    return doc


def load_baseline(path: str) -> dict:
    """Baseline load under the unified corrupt-artifact policy: warn +
    structured event, but NO quarantine rename (the baseline is a
    checked-in file — renaming it would dirty the git tree) and NO
    silent empty default (an unreadable baseline must fail the perf
    gate loudly, or every regression would ratchet in as "new")."""
    from ..resilience import load_or_recover

    doc = load_or_recover(
        path, _load_baseline_strict, default=None, kind="perf baseline",
        action="failing the perf gate", quarantine=False,
    )
    if doc is None:
        raise ValueError(
            f"{path}: missing or unreadable perf baseline (re-pin with "
            "peasoup-perf check --write-baseline)"
        )
    return doc


def write_baseline(doc: dict, path: str) -> None:
    import json
    import os
    import tempfile

    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def timing_applies(perf_doc: dict, baseline: dict, timing: str) -> bool:
    """Whether the timing ratchet gates this comparison. ``auto``:
    only when backends match and the backend is a real accelerator —
    CPU CI machines measure scheduler weather."""
    if timing == "on":
        return True
    if timing == "off":
        return False
    same = perf_doc.get("backend") == baseline.get("backend")
    return same and perf_doc.get("backend") != "cpu"


def check_perf(
    perf_doc: dict,
    baseline: dict,
    timing: str = "auto",
) -> tuple[list[PerfProblem], list[str]]:
    """Compare a perf.json against the baseline. Returns (problems,
    notices): problems fail the gate, notices (new unbaselined
    programs, timing skipped) inform the report."""
    problems: list[PerfProblem] = []
    notices: list[str] = []
    recs = perf_doc.get("programs", {})
    base = baseline.get("programs", {})

    for name, b in sorted(base.items()):
        rec = recs.get(name)
        if rec is None:
            problems.append(
                PerfProblem(
                    "missing_program", name,
                    "in the baseline but absent from this run — a "
                    "registry program disappeared (deliberate removals "
                    "re-pin with --write-baseline)",
                )
            )
            continue
        if rec.get("error"):
            problems.append(
                PerfProblem(
                    "program_error", name,
                    f"failed to compile/execute: {rec['error']}",
                )
            )
            continue
        if not timing_applies(perf_doc, baseline, timing):
            continue
        tol = float(b.get("tolerance") or baseline.get(
            "tolerance", DEFAULT_TOLERANCE
        ))
        limit = float(b["execute_median_s"]) * tol
        if float(rec["execute_median_s"]) > limit:
            problems.append(
                PerfProblem(
                    "slower", name,
                    f"execute median {rec['execute_median_s']:.6f}s > "
                    f"{limit:.6f}s (baseline "
                    f"{b['execute_median_s']:.6f}s x {tol:g})",
                )
            )
        ctol = float(b.get("compile_tolerance") or baseline.get(
            "compile_tolerance", DEFAULT_COMPILE_TOLERANCE
        ))
        # only ratchet cold compiles: a cache-served compile measures
        # deserialisation, not XLA
        if not rec.get("compile_cache_hit") and float(
            rec.get("compile_s", 0.0)
        ) > float(b["compile_s"]) * ctol:
            problems.append(
                PerfProblem(
                    "compile_slower", name,
                    f"compile {rec['compile_s']:.3f}s > "
                    f"{float(b['compile_s']) * ctol:.3f}s (baseline "
                    f"{b['compile_s']:.3f}s x {ctol:g})",
                )
            )

    new = sorted(set(recs) - set(base))
    if new:
        notices.append(
            f"{len(new)} program(s) not in the baseline (re-pin with "
            f"--write-baseline): {', '.join(new[:8])}"
            + ("..." if len(new) > 8 else "")
        )
    if not timing_applies(perf_doc, baseline, timing):
        notices.append(
            "timing ratchet skipped "
            f"(backend {perf_doc.get('backend')!r} vs baseline "
            f"{baseline.get('backend')!r}, timing={timing}); structural "
            "invariants only"
        )
    return problems, notices
