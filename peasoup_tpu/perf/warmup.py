"""AOT warmup: compile every registered program before the data needs it.

A fresh process pays first-job XLA compiles (~70 s per subband-stage
shape, ~30 s for the fold phase at 2^21 samples — NOTES.md) before
touching data. The auto-tuning literature the pipeline follows
(arXiv:1601.01165, arXiv:2309.02544) treats per-shape compile cost as
something paid once offline, never per observation. This module is
that offline pass: walk :mod:`peasoup_tpu.ops.registry` and
``jax.jit(fn).lower(*specs).compile()`` every program — nothing
executes, but every compile lands in the persistent compilation cache
(utils/cache.py), so every subsequent process (and every campaign
worker on the same filesystem) cold-starts warm.

Two parameterisations:

* **representative** (``warm_registry()``) — each program's registered
  tiny shapes. Cheap; what ``peasoup-perf warmup`` and the CI
  structural gate use (a second pass must be 100% cache hits).
* **bucket** (``warm_registry(ctx=...)`` via each entry's ShapeCtx
  hook, or ``warm_bucket``) — the production shapes a campaign bucket
  implies, derived with the drivers' own plan machinery. The campaign
  runner warms each new bucket on a background thread, overlapping the
  first observation's filterbank read. ``mode="dryrun"`` additionally
  runs the real pipeline once over a synthetic bucket-shaped
  observation, which by construction traces every driver-side shape —
  the first real job then compiles exactly zero programs.

Attribution uses thread-local jax.monitoring sinks: compiles run on
the warmup thread, so concurrent workers' events never cross-pollute.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..obs import get_logger

log = get_logger("perf.warmup")

_TLS = threading.local()
_listeners_installed = False


def _install_listeners() -> None:
    """One pair of process-wide jax.monitoring listeners forwarding to
    whatever sink the CURRENT THREAD has active (the registry has no
    unregister, so per-call listeners would accumulate)."""
    global _listeners_installed
    if _listeners_installed:
        return
    _listeners_installed = True
    try:
        from jax import monitoring

        def _on_duration(event: str, duration: float, **kw) -> None:
            sink = getattr(_TLS, "sink", None)
            if sink is not None and "backend_compile" in event:
                sink["backend_compile"] += 1
                sink["backend_compile_s"] += max(0.0, float(duration))

        def _on_event(event: str, **kw) -> None:
            sink = getattr(_TLS, "sink", None)
            if sink is not None:
                if event.endswith("cache_hits"):
                    sink["cache_hits"] += 1
                elif event.endswith("cache_misses"):
                    sink["cache_misses"] += 1

        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
    except Exception:
        pass  # no monitoring API: reports lack hit/miss attribution


class _sink_scope:
    """Route this thread's compile/cache events into a fresh dict."""

    def __enter__(self) -> dict:
        _install_listeners()
        self._prev = getattr(_TLS, "sink", None)
        _TLS.sink = {
            "backend_compile": 0,
            "backend_compile_s": 0.0,
            "cache_hits": 0,
            "cache_misses": 0,
        }
        return _TLS.sink

    def __exit__(self, *exc) -> None:
        _TLS.sink = self._prev


@dataclass
class ProgramWarmup:
    """One program's warmup outcome."""

    name: str
    seconds: float  # wall time of lower + compile
    compiled: bool  # a real backend compile ran (persistent-cache miss)
    cache_hit: bool  # served from the persistent compilation cache
    error: str | None = None

    def to_doc(self) -> dict:
        return {
            "name": self.name,
            "seconds": round(self.seconds, 6),
            "compiled": self.compiled,
            "cache_hit": self.cache_hit,
            "error": self.error,
        }


@dataclass
class WarmupReport:
    """Aggregate of one warmup pass."""

    programs: list[ProgramWarmup] = field(default_factory=list)
    seconds: float = 0.0
    cache_dir: str | None = None
    parameterised: bool = False
    skipped: int = 0  # ctx mode: entries with no hook for this ctx

    @property
    def compiled(self) -> int:
        return sum(p.compiled for p in self.programs)

    @property
    def cache_hits(self) -> int:
        return sum(p.cache_hit for p in self.programs)

    @property
    def errors(self) -> list[ProgramWarmup]:
        return [p for p in self.programs if p.error]

    def to_doc(self) -> dict:
        return {
            "seconds": round(self.seconds, 3),
            "programs": len(self.programs),
            "compiled": self.compiled,
            "cache_hits": self.cache_hits,
            "skipped": self.skipped,
            "errors": [p.to_doc() for p in self.errors],
            "cache_dir": self.cache_dir,
            "parameterised": self.parameterised,
            "per_program": [p.to_doc() for p in self.programs],
        }


def warm_registry(
    specs=None,
    ctx=None,
    programs: list[str] | None = None,
) -> WarmupReport:
    """AOT-compile registered programs, populating the persistent
    compilation cache. With ``ctx`` (a ShapeCtx), entries are built
    through their shape-parameterisation hook at the ctx's production
    geometry — entries without a hook (or whose hook declines the ctx)
    are skipped and counted. Per-program failures are recorded, never
    raised: a program that stops tracing is the audit's PSC105 finding,
    not a warmup crash."""
    import jax

    from ..utils.cache import enable_compilation_cache

    if specs is None:
        from ..ops.registry import registered_programs

        specs = registered_programs()
    if programs:
        wanted = set(programs)
        specs = [s for s in specs if s.name in wanted]
    cache_dir = enable_compilation_cache()
    report = WarmupReport(
        cache_dir=cache_dir, parameterised=ctx is not None
    )
    t_all = time.perf_counter()
    for spec in specs:
        try:
            built = spec.build_for(ctx)
        except Exception as exc:
            report.programs.append(
                ProgramWarmup(
                    name=spec.name, seconds=0.0, compiled=False,
                    cache_hit=False,
                    error=f"build: {type(exc).__name__}: {exc!s:.300}",
                )
            )
            continue
        if built is None:
            report.skipped += 1
            continue
        fn, args, kwargs = built
        t0 = time.perf_counter()
        with _sink_scope() as sink:
            err = _compile_with_cache_recovery(
                jax, fn, args, kwargs, spec.name, cache_dir
            )
        report.programs.append(
            ProgramWarmup(
                name=spec.name,
                seconds=time.perf_counter() - t0,
                compiled=sink["cache_misses"] > 0
                or (sink["backend_compile"] > 0 and sink["cache_hits"] == 0),
                cache_hit=sink["cache_hits"] > 0,
                error=err,
            )
        )
    report.seconds = time.perf_counter() - t_all
    return report


def _compile_with_cache_recovery(
    jax, fn, args, kwargs, name: str, cache_dir: str | None
) -> str | None:
    """One program's lower+compile with the ``cache.corrupt`` recovery:
    a failure classified CORRUPT (an injected garbled entry, or a real
    torn cache deserialisation) quarantines the persistent cache's
    entries to ``*.corrupt`` and recompiles once from scratch — warmup
    degrades to a cold compile, never to a crash. Returns the error
    string (None on success, including success-after-recovery)."""
    from ..resilience import CORRUPT, classify, faults
    from ..utils.cache import quarantine_cache_entries

    for attempt in (1, 2):
        try:
            # the cache.corrupt seam: deterministic injection point for
            # "a garbled persistent-cache entry broke this compile"
            faults.fire("cache.corrupt", context=f"warmup:{name}")
            if not hasattr(fn, "lower"):
                fn = jax.jit(fn)
            fn.lower(*args, **kwargs).compile()
            return None
        except Exception as exc:
            suspect_cache = classify(exc) == CORRUPT or (
                "cache" in str(exc).lower() and "deserial" in str(exc).lower()
            )
            if attempt == 1 and suspect_cache:
                quarantined = quarantine_cache_entries(cache_dir)
                log.warning(
                    "warmup of %s hit a corrupt compilation-cache entry "
                    "(%.200s); quarantined %d entries to *.corrupt and "
                    "recompiling", name, exc, len(quarantined),
                )
                continue
            return f"{type(exc).__name__}: {exc!s:.300}"
    return None  # unreachable; the loop returns on both paths


# --------------------------------------------------------------------------
# campaign-bucket warmup
# --------------------------------------------------------------------------

def shape_ctx_for_bucket(bucket, pipeline: str, overrides: dict):
    """Derive the production ShapeCtx a campaign bucket implies, using
    the drivers' own plan machinery (DMPlan, the width bank, the auto
    dm_block formula — and for the periodicity pipeline the accel plan
    + fft plan, so the spectrum/resample/harmonics/peaks hooks compile
    at the wave loop's real (dm_block, accel_pad, fft_size) tile)
    so hook-built programs match what the pipeline will trace. Tuned
    dedispersion knobs (``subbands``/``subband_smear``/``dedisp_block``
    from the tuning cache, perf/tuning.py) flow in through
    ``overrides`` and land in the ctx, so warmup compiles the tuned
    shapes."""
    from ..ops.registry import ShapeCtx
    from ..ops.singlepulse import plan_pad
    from ..pipeline.single_pulse import SinglePulseConfig, SinglePulseSearch
    from ..plan.dm_plan import DMPlan

    nchans, nbits, nsamps, tsamp, fch1, foff = bucket
    base_cls = SinglePulseConfig
    if pipeline == "search":
        from ..pipeline.search import SearchConfig

        base_cls = SearchConfig
    elif pipeline == "ffa":
        # FFA shares only the dedispersion front end with the other
        # pipelines; its ctx carries the DM-plan geometry (the
        # dedisperse/unpack hooks build from it) and the staircase
        # programs trace on the dryrun
        from ..pipeline.ffa import FFAConfig

        base_cls = FFAConfig
    elif pipeline == "fdas":
        from ..pipeline.fdas import FdasConfig

        base_cls = FdasConfig
    cfg = _filtered_config(base_cls, overrides)
    plan = DMPlan.create(
        nsamps=int(nsamps), nchans=int(nchans), tsamp=float(tsamp),
        fch1=float(fch1), foff=float(foff), dm_start=cfg.dm_start,
        dm_end=cfg.dm_end, pulse_width=cfg.dm_pulse_width, tol=cfg.dm_tol,
    )
    widths: tuple[int, ...] = ()
    dm_block = 1
    pallas_span = 0
    sp_fused_span = 0
    fft_size = 0
    nharms = 4
    accel_pad = 0
    max_peaks = 128
    select_smax = 0
    pos5 = pos25 = 0
    fdas_templates = fdas_zmax = fdas_segment = 0
    if pipeline == "spsearch":
        search = SinglePulseSearch(cfg)
        widths = search.widths_for(plan.out_nsamps)
        tpad, span = plan_pad(plan.out_nsamps)
        if cfg.dm_block > 0:
            dm_block = cfg.dm_block
        else:
            per_trial = 16 * tpad
            dm_block = int(
                max(1, min(256, (search.TOTAL_HBM // 4) // max(1, per_trial)))
            )
        if cfg.use_pallas:
            # THE driver's kernel-selection ladder (fused chain at the
            # full span, retiled fused variants, boxcar kernel, jnp
            # twin) so warmup compiles exactly what the job dispatches
            try:
                from ..pipeline.single_pulse import select_sp_kernels

                pallas_span, sp_fused_span, _ = select_sp_kernels(
                    widths, span, tpad, cfg.decimate, cfg.use_pallas
                )
            except Exception:
                pallas_span = sp_fused_span = 0
    elif pipeline == "search":
        import numpy as np

        from ..ops.resample import accel_factor, select_span
        from ..pipeline.search import PeasoupSearch, _accel_pad
        from ..plan.accel_plan import AccelerationPlan
        from ..plan.fft_plan import choose_fft_size

        fft_size = choose_fft_size(int(nsamps), cfg.size)
        nharms = int(cfg.nharmonics)
        max_peaks = int(cfg.max_peaks)
        # the driver's whitening boundaries in bins (search.py:
        # bin_width = 1/tobs) — static args of the rednoise programs
        tobs = fft_size * float(tsamp)
        pos5 = int(cfg.boundary_5_freq * tobs)
        pos25 = int(cfg.boundary_25_freq * tobs)
        acc_plan = AccelerationPlan(
            acc_lo=cfg.acc_start, acc_hi=cfg.acc_end, tol=cfg.acc_tol,
            pulse_width=cfg.acc_pulse_width, nsamps=fft_size,
            tsamp=float(tsamp),
            cfreq=float(fch1) + (int(nchans) / 2.0 - 0.5) * float(foff),
            bw=float(foff),
        )
        # the widest accel list sits at DM 0 (alt_a grows with DM);
        # its padded column count is the wave loop's tile width
        accs = acc_plan.generate_accel_list(float(cfg.dm_start))
        accel_pad = _accel_pad(len(accs), cfg.accel_bucket)
        af_max = (
            float(np.abs(accel_factor(accs, float(tsamp))).max())
            if len(accs) else 0.0
        )
        select_smax = select_span(af_max, fft_size)
        # the driver's auto per-chip block formula (pipeline/search.py
        # build_chunks) without the one-shot escalation
        searcher = PeasoupSearch(cfg)
        size_spec_b = (fft_size // 2 + 1) * 4
        if cfg.dm_block > 0:
            dm_block = cfg.dm_block
        else:
            cells = max(8, int(searcher.MEM_BUDGET / (size_spec_b * 16)))
            dm_block = max(1, min(128, cells // max(1, accel_pad)))
    elif pipeline == "fdas":
        import numpy as np

        from ..fdas.templates import bank_geometry, effective_zmax
        from ..pipeline.fdas import FdasSearch
        from ..plan.fft_plan import choose_fft_size

        fft_size = choose_fft_size(int(nsamps), cfg.size)
        nharms = int(cfg.nharmonics)
        max_peaks = int(cfg.max_peaks)
        # mirror the driver's f32 bin-width rounding exactly — pos5/
        # pos25 are STATIC args of the whitening program
        tobs = float(np.float32(fft_size) * np.float32(tsamp))
        bin_width = float(np.float32(1.0 / tobs))
        pos5 = int(cfg.boundary_5_freq / bin_width)
        pos25 = int(cfg.boundary_25_freq / bin_width)
        nt, width, seg = bank_geometry(
            cfg.zmax, cfg.wmax, cfg.zstep, cfg.wstep
        )
        fdas_segment = cfg.segment or seg
        fdas_zmax = int(effective_zmax(cfg.zmax, cfg.wmax))
        searcher = FdasSearch(cfg)
        db, tb = searcher._auto_blocks(fft_size // 2 + 1, nt)
        tb = min(tb, nt)
        dm_block = min(db, int(plan.ndm))
        # the per-dispatch template BATCH (the bank is padded to a tb
        # multiple and dispatched tb rows at a time)
        fdas_templates = tb
    # survey-fold geometry: the sift layer (peasoup_tpu/sift/fold.py)
    # later batch-folds this bucket's candidates over the SAME
    # dedispersed trial length, so the fold bucket is derivable right
    # here — warm_bucket pre-compiles the survey-fold program too and
    # the first sift pass over a warmed campaign compiles nothing
    from ..pipeline.folder import fold_geometry

    fold_nints = int(overrides.get("fold_nints", 16))
    fold_size = int(fold_geometry(plan.out_nsamps, float(tsamp))[0])
    if fold_size < fold_nints:
        fold_size = 0  # too short to fold: the hook declines
    return ShapeCtx(
        nsamps=int(nsamps),
        nchans=int(nchans),
        nbits=int(nbits),
        ndm=int(plan.ndm),
        out_nsamps=int(plan.out_nsamps),
        dm_block=int(min(dm_block, max(1, plan.ndm))),
        dedisp_block=int(getattr(cfg, "dedisp_block", 16)),
        widths=tuple(int(w) for w in widths),
        min_snr=float(cfg.min_snr),
        max_events=int(getattr(cfg, "max_events", 256)),
        decimate=int(getattr(cfg, "decimate", 32)),
        pallas_span=int(pallas_span),
        sp_fused_span=int(sp_fused_span),
        subbands=int(getattr(cfg, "subbands", 0)),
        subband_smear=float(getattr(cfg, "subband_smear", 1.0)),
        dedisp_engine=str(getattr(cfg, "dedisp_engine", "")),
        subband_matmul=bool(getattr(cfg, "subband_matmul", False)),
        fft_size=int(fft_size),
        nharms=int(nharms),
        accel_pad=int(accel_pad),
        max_peaks=int(max_peaks),
        select_smax=int(select_smax),
        pos5=int(pos5),
        pos25=int(pos25),
        fdas_templates=int(fdas_templates),
        fdas_zmax=int(fdas_zmax),
        fdas_segment=int(fdas_segment),
        fold_batch=(
            int(overrides.get("fold_batch", 64)) if fold_size else 0
        ),
        fold_nsamps=fold_size,
        fold_nbins=int(overrides.get("fold_nbins", 64)),
        fold_nints=fold_nints,
    )


def _filtered_config(cls, overrides: dict, **fixed):
    """Best-effort config for warmup: unknown keys are dropped rather
    than rejected — a typo'd knob must fail the JOB loudly (the runner
    validates), not abort the warmup thread."""
    import dataclasses

    names = {f.name for f in dataclasses.fields(cls)}
    merged = {k: v for k, v in overrides.items() if k in names}
    merged.update(fixed)
    return cls(**merged)


def synthetic_bucket_observation(bucket, path: str, seed: int = 0):
    """Write a synthetic observation filling a bucket exactly: noise at
    the bucket's shape/dtype plus a strong periodic broadband pulse
    train (so the candidate paths — peak compaction, clustering,
    folding — trace over non-empty work, not a zero-candidate
    shortcut). Returns the re-read Filterbank, so sub-byte buckets get
    the packed ``raw`` payload exactly like a real observation."""
    import numpy as np

    from ..io.sigproc import (
        Filterbank,
        SigprocHeader,
        read_filterbank,
        write_filterbank,
    )

    nchans, nbits, nsamps, tsamp, fch1, foff = bucket
    nchans, nbits, nsamps = int(nchans), int(nbits), int(nsamps)
    rng = np.random.default_rng(seed)
    hi = (1 << min(nbits, 8)) - 1
    base = max(1, hi // 4)
    data = rng.integers(
        0, base + 1, size=(nsamps, nchans), dtype=np.uint8
    )
    # dispersion-free pulse train every ~50 ms: bright single pulses
    # AND a periodicity candidate, without needing per-channel delays
    period = max(64, int(round(0.05 / float(tsamp))))
    for s in range(period // 2, nsamps, period):
        data[s : min(s + 4, nsamps), :] = hi
    hdr = SigprocHeader(
        source_name="WARMUP", data_type=1, nchans=nchans, nbits=nbits,
        nifs=1, tsamp=float(tsamp), tstart=50000.0, fch1=float(fch1),
        foff=float(foff),
    )
    write_filterbank(path, Filterbank(header=hdr, data=data))
    return read_filterbank(path)


def warm_bucket(
    bucket,
    pipeline: str,
    overrides: dict,
    scratch_dir: str,
    mode: str = "dryrun",
) -> dict:
    """Warm one campaign bucket's compiled programs. ``mode="aot"``
    walks the registry through the ShapeCtx hooks (lower+compile only —
    no data execution; covers the registered programs at production
    shapes). ``mode="dryrun"`` instead runs the configured pipeline
    once over a synthetic bucket-shaped observation — costs one
    observation's device work but traces every driver-side shape, so
    the first real job compiles exactly zero programs. Never raises:
    failures come back in the stats dict."""
    import os
    import shutil

    t0 = time.perf_counter()
    stats: dict = {
        "bucket": list(bucket),
        "mode": mode,
        "seconds": 0.0,
        "programs_compiled": 0,
        "cache_hits": 0,
        "error": None,
    }
    try:
        if mode == "aot":
            ctx = shape_ctx_for_bucket(bucket, pipeline, overrides)
            rep = warm_registry(ctx=ctx)
            stats["programs_compiled"] = rep.compiled
            stats["cache_hits"] = rep.cache_hits
            stats["aot_skipped"] = rep.skipped
            if rep.errors:
                stats["error"] = rep.errors[0].to_doc()["error"]
        else:  # dryrun
            os.makedirs(scratch_dir, exist_ok=True)
            fil = synthetic_bucket_observation(
                bucket, os.path.join(scratch_dir, "warmup.fil")
            )
            with _sink_scope() as sink:
                _dryrun_pipeline(pipeline, overrides, scratch_dir, fil)
            stats["programs_compiled"] = max(
                0, sink["backend_compile"] - sink["cache_hits"]
            )
            stats["cache_hits"] = sink["cache_hits"]
            shutil.rmtree(scratch_dir, ignore_errors=True)
    except Exception as exc:
        stats["error"] = f"{type(exc).__name__}: {exc!s:.300}"
        log.warning("bucket warmup failed for %s: %s", bucket, exc)
    stats["seconds"] = round(time.perf_counter() - t0, 3)
    return stats


def _dryrun_pipeline(pipeline: str, overrides: dict, outdir, fil) -> None:
    """One end-to-end pipeline run over the synthetic observation (no
    outputs kept, no checkpoint, telemetry ambient — which on a warmup
    thread is the no-op sink)."""
    if pipeline == "spsearch":
        from ..pipeline.single_pulse import (
            SinglePulseConfig,
            SinglePulseSearch,
        )

        cfg = _filtered_config(
            SinglePulseConfig, overrides, outdir=str(outdir),
            checkpoint_file="",
        )
        SinglePulseSearch(cfg).run(fil)
    elif pipeline == "ffa":
        from ..pipeline.ffa import FFAConfig, FFASearch

        cfg = _filtered_config(
            FFAConfig, overrides, outdir=str(outdir),
            checkpoint_file="",
        )
        FFASearch(cfg).run(fil)
    elif pipeline == "fdas":
        from ..pipeline.fdas import FdasConfig, FdasSearch

        cfg = _filtered_config(
            FdasConfig, overrides, outdir=str(outdir),
            checkpoint_file="",
        )
        FdasSearch(cfg).run(fil)
    else:  # "search"
        from ..pipeline.search import PeasoupSearch, SearchConfig

        cfg = _filtered_config(
            SearchConfig, overrides, outdir=str(outdir),
            checkpoint_file="",
        )
        PeasoupSearch(cfg).run(fil)
