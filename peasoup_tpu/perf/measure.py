"""Shared measurement primitives for every performance number.

The BENCH round protocol (bench.py), the per-program microbenchmarks
(perf/microbench.py) and the streaming/latency accounting all used to
carry their own median/timing helpers; drift between them made numbers
silently incomparable. This module is the single measurement path:

* :func:`median` — true median (mean of the middle pair for even
  counts: a failed trace can shrink an odd sample set to an even one,
  and the upper-middle element would then be a max mislabeled as a
  median);
* :func:`timed_samples` — the median-of-k ``block_until_ready``
  discipline: k wall-clock samples of ``call()``, with an optional
  ``prepare()`` run OUTSIDE each timed window (re-staging donated
  operands, resetting caches);
* :func:`device_busy_seconds` — device-anchored seconds of one run via
  the shared profiler-trace parser (tools/scope_trace), 0.0 when
  tracing fails so callers can fall back to wall clock.
"""

from __future__ import annotations

import time

from ..obs import get_logger

log = get_logger("perf.measure")


def median(xs) -> float:
    """True median; 0.0 for an empty sample set."""
    xs = sorted(xs)
    if not xs:
        return 0.0
    n = len(xs)
    mid = n // 2
    return xs[mid] if n % 2 else 0.5 * (xs[mid - 1] + xs[mid])


def timed_samples(call, reps: int, prepare=None) -> list[float]:
    """``reps`` wall-clock samples of ``call()`` (seconds, sorted
    ascending). ``prepare()`` runs before each sample outside the
    timed window. ``call`` must block until its work is done (wrap
    device work in ``jax.block_until_ready``)."""
    samples = []
    for _ in range(max(1, int(reps))):
        if prepare is not None:
            prepare()
        t0 = time.perf_counter()
        call()
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return samples


def summarize(samples: list[float]) -> dict:
    """The record fields every timing table shares."""
    n = len(samples)
    return {
        "execute_median_s": round(median(samples), 9),
        "execute_min_s": round(samples[0], 9) if samples else 0.0,
        "execute_mean_s": round(sum(samples) / n, 9) if n else 0.0,
        "execute_all_s": [round(s, 9) for s in samples],
        "reps": n,
    }


def device_busy_seconds(run) -> float:
    """Total device-busy seconds of one ``run()`` call via the shared
    profiler-trace parser (tools/scope_trace). 0.0 when tracing fails
    — callers fall back to wall clock."""
    try:
        from ..tools.scope_trace import scope_trace

        with scope_trace() as res:
            run()
        return res.device_s
    except Exception as exc:  # profiling is best-effort
        log.warning("device-time trace failed: %r", exc)
        return 0.0
