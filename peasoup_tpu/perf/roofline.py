"""Per-stage roofline accounting shared by BENCH and the microbench.

One stage taxonomy — ``unpack / dedisperse / spectrum_chain / resample
/ harmonics / peaks / fold / other`` — classifies BOTH the profiler
trace's device events (tools/scope_trace stage_profile, driven by the
jit names and named scopes the drivers emit) and the registry's
programs (:func:`stage_for_program`), so a BENCH round and a
``peasoup-perf bench`` report attribute time to the SAME buckets and a
ratchet regression names the stage that moved.

Roofline fields: device-busy seconds and the trace's
``raw_bytes_accessed`` are MEASURED per stage; FLOPs are analytic
per-stage estimates supplied by the caller (bench.py derives them from
the run geometry). Against the device's peak FLOP/s and HBM bandwidth
(:func:`device_peaks` — datasheet numbers for the TPU generations the
fleet runs; conservative f32-MXU derates), each stage gets achieved
FLOP/s, achieved bytes/s, arithmetic intensity, the fraction of the
roofline it reaches, and whether the roofline says it is compute- or
memory-bound — the attribution that turns "the bench got slower" into
"the dedispersion stage fell off its bandwidth bound".
"""

from __future__ import annotations

STAGES = (
    "unpack",
    "dedisperse",
    "spectrum_chain",
    "resample",
    "harmonics",
    "peaks",
    "fdas",
    "fold",
    "other",
)

# program-name fragments -> stage, first match wins (checked against
# the full registered name, e.g. "ops.dedisperse.subband_stage1_matmul")
_PROGRAM_STAGE_RULES = (
    ("unpack", "unpack"),
    ("dedisperse", "dedisp"),
    # before "harmonic"/"correlate": the fused FDAS program contains
    # both fragments but books as its own MXU-correlation stage
    ("fdas", "fdas"),
    ("harmonics", "harmonic"),
    ("peaks", "peaks"),
    ("resample", "resample"),
    ("spectrum_chain", "spectrum."),
    ("spectrum_chain", "rednoise"),
    ("spectrum_chain", "zap"),
    ("spectrum_chain", "fft"),
    ("fold", "fold"),
    ("peaks", "singlepulse"),  # the sp chain ends in the peaks compaction
    ("peaks", "streaming"),
    ("peaks", "coincidence"),
    ("peaks", "correlate"),
    ("peaks", "ffa"),
)


def stage_for_program(name: str) -> str:
    """The roofline stage a registered program's time books under."""
    low = name.lower()
    for stage, frag in _PROGRAM_STAGE_RULES:
        if frag in low:
            return stage
    return "other"


# (device_kind substring, peak f32 FLOP/s, peak HBM bytes/s).
# Datasheet bf16 MXU peaks derated 4x for the f32 accumulate paths the
# pipeline runs (the MXU takes 4 passes for f32 operands); HBM numbers
# are the published per-chip bandwidths. Substring-matched against
# jax's device_kind so "TPU v5 lite" and "TPU v5e" both resolve.
_DEVICE_PEAKS = (
    ("v5p", 114e12, 2765e9),
    ("v5 lite", 49e12, 819e9),
    ("v5e", 49e12, 819e9),
    ("v6 lite", 230e12, 1640e9),
    ("v6e", 230e12, 1640e9),
    ("v4", 68e12, 1228e9),
    ("v3", 30e12, 900e9),
)


def device_peaks(device_kind: str) -> tuple[float, float] | None:
    """(peak f32 FLOP/s, peak HBM bytes/s) for a device kind, or None
    when unknown (CPU, new chips): roofline ratios then stay null
    rather than inventing a denominator."""
    low = (device_kind or "").lower()
    for frag, flops, bw in _DEVICE_PEAKS:
        if frag in low:
            return flops, bw
    return None


def roofline_fields(
    seconds: float,
    flops: float | None,
    nbytes: float | None,
    device_kind: str,
) -> dict:
    """The per-stage roofline record: achieved rates, arithmetic
    intensity, fraction-of-peak against the device roofline, and the
    bound the roofline model assigns. ``flops``/``bytes`` of None (or
    zero seconds) leave the derived fields null — absent attribution
    is visible, never faked."""
    out: dict = {
        "device_s": round(float(seconds), 6),
        "flops": None if flops is None else float(flops),
        "bytes": None if nbytes is None else float(nbytes),
        "achieved_flops_per_s": None,
        "achieved_bytes_per_s": None,
        "intensity_flops_per_byte": None,
        "peak_fraction": None,
        "bound": None,
    }
    if seconds <= 0:
        return out
    if flops:
        out["achieved_flops_per_s"] = round(flops / seconds, 3)
    if nbytes:
        out["achieved_bytes_per_s"] = round(nbytes / seconds, 3)
    if flops and nbytes:
        out["intensity_flops_per_byte"] = round(flops / nbytes, 6)
    peaks = device_peaks(device_kind)
    if peaks is None:
        return out
    peak_flops, peak_bw = peaks
    # the roofline: attainable FLOP/s at this intensity is
    # min(peak_flops, intensity * peak_bw); the binding resource is
    # whichever limit is lower
    if flops and nbytes:
        intensity = flops / nbytes
        ridge = peak_flops / peak_bw
        out["bound"] = "compute" if intensity >= ridge else "memory"
        attainable = min(peak_flops, intensity * peak_bw)
        out["peak_fraction"] = round((flops / seconds) / attainable, 4)
    elif nbytes:
        out["bound"] = "memory"
        out["peak_fraction"] = round((nbytes / seconds) / peak_bw, 4)
    elif flops:
        out["bound"] = "compute"
        out["peak_fraction"] = round((flops / seconds) / peak_flops, 4)
    return out


def stage_roofline(
    stage_profile: dict,
    stage_flops: dict | None,
    device_kind: str,
) -> dict:
    """Assemble the BENCH ``stages`` section: ``stage_profile`` maps
    stage -> (device seconds, measured bytes) from the trace
    (tools/scope_trace ScopeResult.stage_profile), ``stage_flops``
    maps stage -> analytic FLOPs (missing stages stay null)."""
    out = {}
    for stage, (secs, nbytes) in sorted(stage_profile.items()):
        flops = (stage_flops or {}).get(stage)
        out[stage] = roofline_fields(
            secs, flops, nbytes if nbytes else None, device_kind
        )
    return out
