"""Per-program microbenchmarks over the ops registry.

Each registered program's ShapeDtypeStructs are materialised into
device arrays and the AOT-compiled executable is timed over
median-of-k ``block_until_ready`` executions — the same
device-anchored discipline as bench.py's steady-state runs (the
compiled object is invoked directly, so no tracing, dispatch-cache or
compile time pollutes an execute sample; compile time is measured
separately, with its persistent-cache hit/miss attribution). The
result is a schema-validated ``perf.json`` keyed by program name (the
registered representative shapes are part of the record) with the
backend/device identity at top level — the document the ratchet
(perf/ratchet.py) compares against ``perf_baseline.json``.
"""

from __future__ import annotations

import time

from .warmup import _sink_scope

PERF_SCHEMA = "peasoup_tpu.perf"
# v2: per-program "stage" + top-level "stages" totals (the roofline
# taxonomy shared with BENCH, perf/roofline.py) and the resolved
# "dedisp" alternative record
PERF_VERSION = 2

DEFAULT_REPS = 5


def _materialise(arg, rng):
    """A device array for one build-thunk operand. ShapeDtypeStructs
    become deterministic pseudo-random floats in [0.5, 1.5) (safe for
    the div/sqrt/log in the stats programs) or zeros for integer/bool
    operands (always-valid indices/masks); concrete arrays (e.g. the
    fold templates) upload as-is."""
    import jax
    import numpy as np

    if isinstance(arg, jax.ShapeDtypeStruct):
        dt = np.dtype(arg.dtype)
        if np.issubdtype(dt, np.floating):
            x = rng.uniform(0.5, 1.5, size=arg.shape).astype(dt)
        elif np.issubdtype(dt, np.complexfloating):
            x = (
                rng.uniform(0.5, 1.5, size=arg.shape)
                + 1j * rng.uniform(-0.5, 0.5, size=arg.shape)
            ).astype(dt)
        else:
            x = np.zeros(arg.shape, dt)
        return jax.device_put(x)
    return jax.device_put(np.asarray(arg))


def _arg_sig(args) -> list[str]:
    """Compact shape/dtype signature, e.g. ``u8[256,8]``."""
    import jax
    import numpy as np

    out = []
    for a in args:
        if isinstance(a, jax.ShapeDtypeStruct):
            shape, dt = a.shape, np.dtype(a.dtype)
        else:
            arr = np.asarray(a)
            shape, dt = arr.shape, arr.dtype
        out.append(f"{dt.str.lstrip('<>|=')}[{','.join(map(str, shape))}]")
    return out


def bench_program(spec, reps: int = DEFAULT_REPS, ctx=None) -> dict:
    """Compile and time one registered program. Returns its perf.json
    record; failures come back as a record with ``error`` set."""
    import jax
    import numpy as np

    rec: dict = {"error": None}
    try:
        built = spec.build_for(ctx)
        if built is None:
            return {**rec, "error": "no parameterisation for ctx"}
        fn, args, kwargs = built
        rec["args"] = _arg_sig(args)
        if not hasattr(fn, "lower"):
            fn = jax.jit(fn)
        t0 = time.perf_counter()
        with _sink_scope() as sink:
            compiled = fn.lower(*args, **kwargs).compile()
        rec["compile_s"] = round(time.perf_counter() - t0, 6)
        rec["compile_cache_hit"] = sink["cache_hits"] > 0
        rec["backend_compile_s"] = round(sink["backend_compile_s"], 6)

        rng = np.random.default_rng(0)
        dev_args = [_materialise(a, rng) for a in args]
        # one untimed execution absorbs first-dispatch overhead
        jax.block_until_ready(compiled(*dev_args))

        def _restage():
            # donated operands are consumed per call: re-stage them
            # OUTSIDE the timed window
            nonlocal dev_args
            r = np.random.default_rng(0)
            dev_args = [_materialise(a, r) for a in args]

        # the shared measurement path (perf/measure.py): the same
        # median-of-k block_until_ready discipline bench.py uses
        from .measure import summarize, timed_samples

        samples = timed_samples(
            lambda: jax.block_until_ready(compiled(*dev_args)),
            reps,
            prepare=_restage if spec.donate else None,
        )
        rec.update(summarize(samples))
    except Exception as exc:
        rec["error"] = f"{type(exc).__name__}: {exc!s:.300}"
    return rec


def run_microbench(
    specs=None,
    reps: int = DEFAULT_REPS,
    programs: list[str] | None = None,
    ctx=None,
) -> dict:
    """Benchmark the registry into a perf.json document. Programs that
    fail keep a record (with ``error``) so the ratchet can tell a
    vanished program from a broken one."""
    import jax

    from ..utils.cache import enable_compilation_cache

    if specs is None:
        from ..ops.registry import registered_programs

        specs = registered_programs()
    if programs:
        wanted = set(programs)
        specs = [s for s in specs if s.name in wanted]
    from .roofline import stage_for_program

    cache_dir = enable_compilation_cache()
    devs = jax.local_devices()
    t0 = time.perf_counter()
    recs = {}
    for spec in specs:
        rec = bench_program(spec, reps=reps, ctx=ctx)
        rec["stage"] = stage_for_program(spec.name)
        recs[spec.name] = rec
    ok = [r for r in recs.values() if not r["error"]]
    # per-stage execute totals: the same taxonomy BENCH's device trace
    # uses (perf/roofline.py STAGES), so a ratchet regression and a
    # BENCH round name the same bucket
    stages: dict = {}
    for r in ok:
        st = stages.setdefault(
            r["stage"], {"programs": 0, "execute_s": 0.0}
        )
        st["programs"] += 1
        st["execute_s"] += r["execute_median_s"]
    for st in stages.values():
        st["execute_s"] = round(st["execute_s"], 6)
    doc = {
        "schema": PERF_SCHEMA,
        "version": PERF_VERSION,
        "created_unix": time.time(),
        "backend": jax.default_backend(),
        "device_kind": str(devs[0].device_kind) if devs else "unknown",
        "jax_version": jax.__version__,
        "cache_dir": cache_dir,
        "reps": reps,
        "programs": recs,
        "stages": stages,
        # the selected dedispersion alternative this bench's ctx (if
        # any) implies — BENCH records the same field from its tuned
        # plan, so the two reports stay comparable
        "dedisp": {
            "engine": (ctx.dedisp_engine or "exact") if ctx else "exact",
            "subbands": int(ctx.subbands) if ctx else 0,
            "subband_matmul": bool(ctx.subband_matmul) if ctx else False,
        },
        "totals": {
            "programs": len(recs),
            "errors": len(recs) - len(ok),
            "compile_s": round(sum(r["compile_s"] for r in ok), 6),
            "compile_cache_hits": sum(r["compile_cache_hit"] for r in ok),
            "execute_s": round(sum(r["execute_median_s"] for r in ok), 6),
            "wall_s": round(time.perf_counter() - t0, 3),
        },
    }
    return doc


def validate_perf(doc: dict) -> None:
    """Validate a perf.json document against the checked-in schema
    (obs/schema.py's dependency-free draft-07 subset); raises
    SchemaError on violation."""
    import json
    import os

    from ..obs.schema import validate

    path = os.path.join(os.path.dirname(__file__), "perf.schema.json")
    with open(path) as f:
        schema = json.load(f)
    validate(doc, schema)


def load_perf(path: str) -> dict:
    """Load + validate a perf.json document."""
    import json

    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != PERF_SCHEMA:
        raise ValueError(
            f"{path}: not a {PERF_SCHEMA} document "
            f"(schema={doc.get('schema')!r})"
        )
    validate_perf(doc)
    return doc


def write_perf(doc: dict, path: str) -> None:
    """Schema-validate and atomically write a perf.json document."""
    import json
    import os
    import tempfile

    validate_perf(doc)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
