"""Performance subsystem: AOT warmup over the program registry,
per-program microbenchmarks, and the perf-regression ratchet.

- :mod:`peasoup_tpu.perf.warmup` — ``jax.jit(...).lower().compile()``
  every registered program ahead of time, populating the persistent
  compilation cache (utils/cache.py) so later processes cold-start
  warm; parameterisable to a campaign bucket's production shapes.
- :mod:`peasoup_tpu.perf.microbench` — materialise each registry
  entry's representative shapes and time median-of-k
  ``block_until_ready`` executions into a schema-validated perf.json.
- :mod:`peasoup_tpu.perf.ratchet` — compare a perf.json against the
  checked-in ``perf_baseline.json`` (structural invariants everywhere,
  timing ratchets on real backends), the way ``audit_baseline.json``
  ratchets audit findings.

CLI: ``peasoup-perf warmup|bench|check`` (tools/perf.py).
"""

from .microbench import PERF_SCHEMA, PERF_VERSION, run_microbench
from .ratchet import (
    BASELINE_SCHEMA,
    check_perf,
    load_baseline,
    write_baseline,
)
from .warmup import WarmupReport, warm_bucket, warm_registry

__all__ = [
    "BASELINE_SCHEMA",
    "PERF_SCHEMA",
    "PERF_VERSION",
    "WarmupReport",
    "check_perf",
    "load_baseline",
    "run_microbench",
    "warm_bucket",
    "warm_registry",
    "write_baseline",
]
