"""Per-device empirical tuning of dedispersion shape knobs, and the
schema-validated tuning cache that makes it pay-once.

The planner (:mod:`peasoup_tpu.plan.dedisp_plan`) decides exact vs
subband analytically; which *shape knobs* run fastest — the
``dedisp_block`` DM-tile height, the subband count around the
analytic winner — is a device property ("Real-Time Dedispersion ...
using Auto Tuning", arXiv:1601.01165: empirical per-device tuning
beats any analytic model). This module times a small candidate grid
with the shared measurement path (:mod:`peasoup_tpu.perf.measure`,
median-of-k ``block_until_ready``) over a scaled probe of the
bucket's real geometry, and persists winners in a schema-validated
``tuning_cache.json`` keyed by (device fingerprint, pipeline + shape
bucket). Campaign workers and the pipeline drivers resolve plans
through :func:`resolve_plan_for_bucket`: a warm bucket loads its plan
with ZERO measurement calls (pinned by the :func:`measurement_count`
counter in tests), a corrupt cache re-tunes with a warning instead of
crashing, and concurrent writers last-win on an atomic replace (both
derive the same deterministic plan, so the race is benign).
"""

from __future__ import annotations

import json
import os
import tempfile
import time

import numpy as np

from ..obs import get_logger
from ..plan.dedisp_plan import DedispPlan

log = get_logger("perf.tuning")

TUNING_SCHEMA = "peasoup_tpu.tuning_cache"
TUNING_VERSION = 1

# every timed candidate bumps this counter; tests pin the warm-bucket
# contract ("second resolve of a tuned bucket performs ZERO
# measurements") against it
_TUNER_INVOCATIONS = 0

DEFAULT_REPS = 3
# probe budget: the tuner times a scaled slice of the bucket (the
# knobs' relative ranking is what matters, not absolute seconds), so
# a candidate grid stays seconds even at survey channel counts
PROBE_SAMPLE_BUDGET = 1 << 22
PROBE_MAX_TRIALS = 64
BLOCK_CANDIDATES = (8, 16, 32, 64)
# search-side knob grids (ISSUE 12 satellite): the wave-loop DM-block
# height, the accel-column padding bucket, and the Pallas resample
# tile — all ShapeCtx knobs the drivers consume
DM_BLOCK_CANDIDATES = (16, 32, 64)
ACCEL_BUCKET_CANDIDATES = (8, 16, 32)
PALLAS_BLOCK_CANDIDATES = (256, 512)


def measurement_count() -> int:
    """Total timed tuner measurements this process has performed."""
    return _TUNER_INVOCATIONS


def device_fingerprint() -> str:
    """The cache's device identity: backend + device kind + local
    chip count (a tuned block size is a per-chip property; the count
    guards against a pod slice masquerading as a single chip)."""
    import jax

    devs = jax.local_devices()
    kind = str(devs[0].device_kind) if devs else "none"
    return f"{jax.default_backend()}:{kind}:n{len(devs)}"


def bucket_key(bucket, pipeline: str) -> str:
    return pipeline + "|" + "|".join(str(x) for x in bucket)


def default_cache_path() -> str:
    env = os.environ.get("PEASOUP_TUNING_CACHE")
    if env:
        return env
    return os.path.join(
        os.path.expanduser("~"), ".cache", "peasoup_tpu",
        "tuning_cache.json",
    )


# --------------------------------------------------------------------------
# the cache document
# --------------------------------------------------------------------------

def _empty_cache() -> dict:
    return {
        "schema": TUNING_SCHEMA,
        "version": TUNING_VERSION,
        "devices": {},
    }


def validate_cache(doc: dict) -> None:
    """Validate a tuning-cache document against the checked-in schema
    (obs/schema.py's dependency-free validator); raises SchemaError."""
    from ..obs.schema import validate

    path = os.path.join(
        os.path.dirname(__file__), "tuning_cache.schema.json"
    )
    with open(path) as f:
        schema = json.load(f)
    validate(doc, schema)


def _load_cache_strict(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != TUNING_SCHEMA:
        raise ValueError(f"schema={doc.get('schema')!r}")
    validate_cache(doc)
    return doc


def load_cache(path: str) -> dict:
    """Load the tuning cache; a missing file yields an empty cache, a
    corrupt or schema-violating one yields an empty cache WITH A
    WARNING and the damaged file quarantined to ``*.corrupt`` (the
    contract: re-tune, never crash a worker on a torn shared file) —
    the unified resilience.load_or_recover semantics."""
    from ..resilience import faults, load_or_recover

    faults.maybe_corrupt_file(path, context=f"tuning_cache:{path}")
    return (
        load_or_recover(
            path, _load_cache_strict, default=None, kind="tuning cache",
            action="re-tuning from scratch", logger=log,
        )
        or _empty_cache()
    )


def save_cache(path: str, doc: dict) -> None:
    """Schema-validate and atomically replace the cache file."""
    validate_cache(doc)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def cache_lookup(doc: dict, fingerprint: str, key: str) -> dict | None:
    return (doc.get("devices", {}).get(fingerprint) or {}).get(key)


def cache_store(doc: dict, fingerprint: str, key: str, plan_doc: dict):
    # stamp the entry so `peasoup-perf tune --list/--prune` can report
    # ages and age-prune (entries written before the stamp existed
    # read as infinitely old)
    stored_unix = time.time()
    plan_doc = dict(plan_doc, stored_unix=round(stored_unix, 3))
    doc.setdefault("devices", {}).setdefault(fingerprint, {})[key] = plan_doc


# --------------------------------------------------------------------------
# cache hygiene: list entries with age, prune stale fingerprints
# --------------------------------------------------------------------------

def list_entries(cache_path: str | None = None) -> list[dict]:
    """One row per cached plan: fingerprint, bucket key, the shape
    knobs, age since it was stored, and whether the fingerprint is
    stale (not THIS device — a laptop cache full of pod-slice entries,
    or vice versa)."""
    path = cache_path or default_cache_path()
    doc = load_cache(path)
    now = time.time()
    fp_now = device_fingerprint()
    rows = []
    for fp, entries in sorted((doc.get("devices") or {}).items()):
        for key, plan_doc in sorted(entries.items()):
            stored = plan_doc.get("stored_unix")
            rows.append(
                {
                    "fingerprint": fp,
                    "key": key,
                    "engine": plan_doc.get("engine"),
                    "source": plan_doc.get("source"),
                    "dedisp_block": plan_doc.get("dedisp_block"),
                    "subbands": plan_doc.get("subbands"),
                    "stored_unix": stored,
                    "age_s": (
                        None if stored is None
                        else round(max(0.0, now - float(stored)), 3)
                    ),
                    "stale": fp != fp_now,
                }
            )
    return rows


def prune_cache(
    cache_path: str | None = None,
    *,
    older_than_s: float | None = None,
    keep_stale: bool = False,
    dry_run: bool = False,
) -> list[dict]:
    """Remove dead weight from the tuning cache; returns the removed
    rows (as :func:`list_entries` shapes them).

    Pruned: entries under a stale device fingerprint (unless
    ``keep_stale``), and — when ``older_than_s`` is given — entries on
    ANY fingerprint older than that (un-stamped legacy entries count
    as infinitely old). ``dry_run`` reports without rewriting."""
    path = cache_path or default_cache_path()
    doc = load_cache(path)
    now = time.time()
    fp_now = device_fingerprint()
    removed = []
    devices = doc.get("devices") or {}
    for fp in list(devices):
        for key in list(devices[fp]):
            plan_doc = devices[fp][key]
            stored = plan_doc.get("stored_unix")
            age = None if stored is None else now - float(stored)
            stale = fp != fp_now
            too_old = older_than_s is not None and (
                age is None or age > older_than_s
            )
            if (stale and not keep_stale) or too_old:
                removed.append(
                    {
                        "fingerprint": fp, "key": key,
                        "engine": plan_doc.get("engine"),
                        "source": plan_doc.get("source"),
                        "dedisp_block": plan_doc.get("dedisp_block"),
                        "subbands": plan_doc.get("subbands"),
                        "stored_unix": stored,
                        "age_s": (
                            None if age is None else round(age, 3)
                        ),
                        "stale": stale,
                    }
                )
                if not dry_run:
                    del devices[fp][key]
        if not dry_run and fp in devices and not devices[fp]:
            del devices[fp]
    if removed and not dry_run:
        save_cache(path, doc)
        log.info(
            "pruned %d tuning-cache entr%s from %s",
            len(removed), "y" if len(removed) == 1 else "ies", path,
        )
    return removed


# --------------------------------------------------------------------------
# the tuner
# --------------------------------------------------------------------------

def _probe_geometry(dm_plan, nchans: int):
    """Scaled probe slice of the bucket: enough samples/trials to rank
    candidates, small enough that a full candidate grid costs seconds.
    Uses the LOWEST-DM trials (smallest delays) so the probe input
    length stays close to the probe output length."""
    out = int(
        min(
            dm_plan.out_nsamps,
            max(2048, PROBE_SAMPLE_BUDGET // max(1, nchans)),
        )
    )
    # whole 128-blocks keep the blocked-row slicing representative
    out = max(256, (out // 128) * 128)
    ndm = int(min(dm_plan.ndm, PROBE_MAX_TRIALS))
    return out, ndm


def _measure(call, reps: int) -> float:
    """One tuner measurement: median-of-k block_until_ready via the
    shared measurement path. Counts toward measurement_count()."""
    global _TUNER_INVOCATIONS
    from .measure import median, timed_samples

    _TUNER_INVOCATIONS += 1
    return median(timed_samples(call, reps))


def tune_plan(
    plan: DedispPlan,
    dm_plan,
    *,
    nbits: int,
    reps: int = DEFAULT_REPS,
    block_candidates: tuple[int, ...] = BLOCK_CANDIDATES,
    pipeline: str = "search",
) -> DedispPlan:
    """Empirically refine ``plan``'s shape knobs on THIS device by
    timing a candidate grid over a scaled probe of the bucket's real
    delay table. Measures ``dedisp_block`` for the exact engine, the
    subband count around the analytic winner for the subband engine,
    and — for search-pipeline plans — RACES the parity-safe engine
    alternatives (exact / gate-approved subband, with and without
    matmul stages / banded matmul) over the same probe workload: the
    measured winner becomes ``plan.engine``, so the matmul engine is
    selected exactly when it is faster on THIS device (arXiv:1601.01165
    — the MXU advantage is a device property no model captures).
    Search-side knobs (``dm_block``, ``accel_bucket``, the Pallas
    resample tile) tune on the same pass. Never raises: a failed
    measurement keeps the analytic knobs (source stays "analytic") —
    tuning is an optimisation, not a correctness dependency."""
    import jax

    from ..ops.dedisperse import (
        dedisperse_block,
        dedisperse_device,
        dedisperse_matmul,
        dedisperse_subband,
        output_scale,
    )

    t0 = time.perf_counter()
    nchans = len(dm_plan.delays)
    probe_out, probe_ndm = _probe_geometry(dm_plan, nchans)
    if probe_ndm < 1:
        return plan
    delays = dm_plan.delay_samples()[:probe_ndm]
    t_in = probe_out + int(delays.max()) + 1
    rng = np.random.default_rng(0)
    hi = (1 << min(int(nbits), 8)) - 1
    fil_probe = rng.integers(
        0, hi + 1, size=(t_in, nchans), dtype=np.uint8
    )
    kill = np.ones(nchans, dtype=np.float32)
    scale = output_scale(int(nbits), nchans)
    trials: list[dict] = []
    try:
        fil_dev = jax.numpy.asarray(fil_probe)
        kill_dev = jax.numpy.asarray(kill)
        # medians per engine variant for the race below
        engine_meds: dict[str, float] = {}
        if plan.engine == "subband":
            cands = sorted(
                {
                    max(2, min(nchans // 2, s))
                    for s in (
                        plan.subbands // 2, plan.subbands, plan.subbands * 2
                    )
                }
            )
            best = None
            for nsub in cands:
                def run(nsub=nsub):
                    jax.block_until_ready(
                        dedisperse_subband(
                            fil_dev, delays, kill, probe_out,
                            nsub=nsub, max_smear=plan.subband_smear,
                            scale=scale,
                        )
                    )
                run()  # untimed compile/warm pass
                med = _measure(run, reps)
                trials.append(
                    {"params": {"subbands": int(nsub)},
                     "median_s": round(med, 6)}
                )
                if best is None or med < best[1]:
                    best = (nsub, med)
            if best is not None:
                plan.subbands = int(best[0])
                plan.source = "tuned"
                engine_meds["subband"] = best[1]
        # dedisp_block ranks by per-trial throughput of the direct
        # block program (the exact engine's unit of work; the subband
        # path also dispatches it for its registry/bench twin)
        best_b = None
        for b in sorted({min(b, probe_ndm) for b in block_candidates}):
            d_b = jax.numpy.asarray(delays[:b])

            def run(d_b=d_b, b=b):
                jax.block_until_ready(
                    dedisperse_block(
                        fil_dev, d_b, kill_dev,
                        out_nsamps=probe_out, scale=scale,
                    )
                )
            run()  # untimed compile/warm pass
            med = _measure(run, reps)
            per_trial = med / b
            trials.append(
                {"params": {"dedisp_block": int(b)},
                 "median_s": round(med, 6)}
            )
            if best_b is None or per_trial < best_b[1]:
                best_b = (b, per_trial)
        if best_b is not None:
            plan.dedisp_block = int(best_b[0])
            plan.source = "tuned"
        if pipeline == "search":
            _race_engines(
                plan, trials, engine_meds, fil_dev, delays, kill,
                probe_out, scale, reps,
                dedisperse_device, dedisperse_matmul, dedisperse_subband,
            )
            _tune_search_knobs(plan, trials, probe_out, reps)
        elif pipeline == "spsearch":
            _tune_dm_block_knob(plan, trials, probe_out, reps)
    except Exception as exc:
        log.warning(
            "dedispersion tuner failed (%s: %.200s); keeping analytic "
            "knobs", type(exc).__name__, exc,
        )
    plan.trials = trials
    plan.tuning_s = round(time.perf_counter() - t0, 3)
    return plan


def _race_engines(
    plan, trials, engine_meds, fil_dev, delays, kill,
    probe_out, scale, reps, dedisperse_device, dedisperse_matmul,
    dedisperse_subband,
) -> None:
    """The three-way engine race over the probe workload: exact always,
    the banded matmul when the analytic model flagged it a candidate,
    matmul-staged subband when the parity gate approved subband. Every
    variant is parity-safe (matmul is bitwise-equal; subband was
    gate-approved by select()), so the fastest MEASURED median wins —
    the acceptance contract that matmul is chosen only when measured
    faster. Medians land in ``trials`` with engine provenance."""
    import jax

    def _timed(name: str, fn) -> None:
        fn()  # untimed compile/warm pass
        med = _measure(fn, reps)
        engine_meds[name] = med
        trials.append(
            {"params": {"engine": name}, "median_s": round(med, 6)}
        )

    _timed(
        "exact",
        lambda: jax.block_until_ready(
            dedisperse_device(
                fil_dev, delays, kill, probe_out,
                scale=scale, block=plan.dedisp_block,
            )
        ),
    )
    if plan.matmul_candidate or plan.engine == "matmul":
        _timed(
            "matmul",
            lambda: jax.block_until_ready(
                dedisperse_matmul(
                    fil_dev, delays, kill, probe_out, scale=scale
                )
            ),
        )
    if plan.engine == "subband" and plan.subbands:
        _timed(
            "subband_matmul",
            lambda: jax.block_until_ready(
                dedisperse_subband(
                    fil_dev, delays, kill, probe_out,
                    nsub=plan.subbands, max_smear=plan.subband_smear,
                    scale=scale, use_matmul=True,
                )
            ),
        )
    if not engine_meds:
        return
    current = plan.engine if plan.engine in engine_meds else "exact"
    winner = min(engine_meds, key=lambda k: engine_meds[k])
    if winner != current and engine_meds[winner] < engine_meds.get(
        current, float("inf")
    ):
        if winner == "subband_matmul":
            plan.engine = "subband"
            plan.subband_matmul = True
        else:
            plan.engine = winner
            plan.subband_matmul = False
        plan.source = "tuned"
    log.info(
        "dedispersion engine race: %s (measured %s)",
        plan.engine
        + (" [matmul stages]" if plan.subband_matmul else ""),
        {k: round(v, 5) for k, v in engine_meds.items()},
    )


def _tune_dm_block_knob(plan, trials, probe_out, reps) -> None:
    """Rank wave-loop DM-block heights by per-trial throughput of the
    per-trial normaliser (the chain head every wave dispatches) over a
    probe row block."""
    import jax
    import jax.numpy as jnp

    from ..ops.singlepulse import normalise_trials

    rng = np.random.default_rng(1)
    n = int(min(probe_out, 1 << 16))
    best = None
    for b in DM_BLOCK_CANDIDATES:
        x = jnp.asarray(rng.normal(size=(b, n)).astype(np.float32))

        def run(x=x):
            jax.block_until_ready(normalise_trials(x))

        run()  # untimed compile/warm pass
        med = _measure(run, reps)
        trials.append(
            {"params": {"dm_block": int(b)}, "median_s": round(med, 6)}
        )
        per_trial = med / b
        if best is None or per_trial < best[1]:
            best = (b, per_trial)
    if best is not None:
        plan.dm_block = int(best[0])
        plan.source = "tuned"


def _tune_search_knobs(plan, trials, probe_out, reps) -> None:
    """The search-side knob grid: ``dm_block`` (per-trial normaliser
    throughput), ``accel_bucket`` (per-column resample throughput at
    the padded column counts the bucket implies), and — on Pallas
    backends only — the resample kernel's block size. Every timed
    candidate lands in ``trials`` with its knob provenance."""
    import jax
    import jax.numpy as jnp

    from ..ops.resample import resample_accel

    _tune_dm_block_knob(plan, trials, probe_out, reps)
    rng = np.random.default_rng(2)
    n = int(min(max(1024, probe_out), 1 << 15))
    x = jnp.asarray(rng.normal(size=n).astype(np.float32))
    best = None
    for b in ACCEL_BUCKET_CANDIDATES:
        af = 0.5 / (n * 64)
        afs = jnp.asarray(
            np.linspace(-af, af, b).astype(np.float32)
        )

        def run(afs=afs):
            jax.block_until_ready(resample_accel(x, afs))

        run()  # untimed compile/warm pass
        med = _measure(run, reps)
        trials.append(
            {"params": {"accel_bucket": int(b)}, "median_s": round(med, 6)}
        )
        per_col = med / b
        if best is None or per_col < best[1]:
            best = (b, per_col)
    if best is not None:
        plan.accel_bucket = int(best[0])
        plan.source = "tuned"
    _tune_pallas_block(plan, trials, x, reps)


def _tune_pallas_block(plan, trials, x, reps) -> None:
    """Pallas resample tile candidates — TPU backends only (the knob
    is meaningless elsewhere and the kernel will not compile); a
    failed candidate is skipped, never fatal."""
    import jax
    import jax.numpy as jnp

    from ..ops.pallas import backend_supports_pallas

    if not backend_supports_pallas():
        return
    from ..ops.pallas import probe_pallas_resample
    from ..ops.pallas.resample import resample_block_pallas

    n = x.shape[-1]
    best = None
    for blk in PALLAS_BLOCK_CANDIDATES:
        if not probe_pallas_resample(n, blk):
            continue
        af = 0.5 / (n * blk)
        afs = jnp.asarray(np.asarray([[af, -af]], dtype=np.float32))
        xr = x.reshape(1, -1)

        def run(afs=afs, xr=xr, blk=blk):
            jax.block_until_ready(
                resample_block_pallas(xr, afs, block=blk)
            )

        run()  # untimed compile/warm pass
        med = _measure(run, reps)
        trials.append(
            {"params": {"pallas_block": int(blk)},
             "median_s": round(med, 6)}
        )
        if best is None or med < best[1]:
            best = (blk, med)
    if best is not None:
        plan.pallas_block = int(best[0])
        plan.source = "tuned"


# --------------------------------------------------------------------------
# plan resolution: bucket -> cached-or-freshly-tuned DedispPlan
# --------------------------------------------------------------------------

def _dm_plan_for_bucket(bucket, overrides: dict):
    from ..plan.dm_plan import DMPlan

    nchans, _nbits, nsamps, tsamp, fch1, foff = bucket
    return DMPlan.create(
        nsamps=int(nsamps),
        nchans=int(nchans),
        tsamp=float(tsamp),
        fch1=float(fch1),
        foff=float(foff),
        dm_start=float(overrides.get("dm_start", 0.0)),
        dm_end=float(overrides.get("dm_end", 100.0)),
        pulse_width=float(overrides.get("dm_pulse_width", 64.0)),
        tol=float(overrides.get("dm_tol", 1.10)),
    )


def resolve_plan_for_bucket(
    bucket,
    pipeline: str,
    overrides: dict,
    cache_path: str | None = None,
    *,
    tune: bool = True,
    reps: int = DEFAULT_REPS,
    force: bool = False,
) -> DedispPlan:
    """The measure -> decide -> cache -> reuse loop for one shape
    bucket. Warm (fingerprint, bucket) entries return the cached plan
    with zero measurement calls; cold ones select analytically
    (plan/dedisp_plan.py), optionally tune on this device, and persist
    the winner. Telemetry gets a ``tuning`` event either way so the
    manifest records plan provenance."""
    from ..obs.telemetry import current as current_telemetry

    cache_path = cache_path or default_cache_path()
    fp = device_fingerprint()
    key = bucket_key(bucket, pipeline)
    doc = load_cache(cache_path)
    tel = current_telemetry()
    if not force:
        hit = cache_lookup(doc, fp, key)
        if hit is not None:
            plan = DedispPlan.from_doc(hit)
            plan.source = "cache"
            tel.event(
                "tuning_cache_hit", bucket=list(bucket),
                pipeline=pipeline, **plan.summary(),
            )
            return plan
    nchans, nbits = int(bucket[0]), int(bucket[1])
    dm_plan = _dm_plan_for_bucket(bucket, overrides)
    if pipeline == "search" and not overrides.get("subbands"):
        plan = DedispPlan.select(
            dm_plan,
            nbits=nbits,
            tsamp=float(bucket[3]),
            fch1=float(bucket[4]),
            foff=float(bucket[5]),
            max_smear=float(overrides.get("subband_smear", 1.0)),
            max_snr_loss=float(overrides.get("subband_snr_loss", 0.1)),
            pulse_width_us=float(overrides.get("dm_pulse_width", 64.0)),
        )
    else:
        # spsearch/stream have no subband path (and an explicit
        # --subbands is an operator decision the planner respects):
        # only the block knobs tune
        plan = DedispPlan(
            engine="exact",
            cost_exact=float(dm_plan.ndm)
            * nchans
            * max(1, dm_plan.out_nsamps),
        )
    if tune:
        plan = tune_plan(
            plan, dm_plan, nbits=nbits, reps=reps, pipeline=pipeline
        )
    cache_store(doc, fp, key, plan.to_doc())
    try:
        save_cache(cache_path, doc)
    except Exception as exc:
        log.warning(
            "could not persist tuning cache %s: %.200s", cache_path, exc
        )
    tel.event(
        "tuning", bucket=list(bucket), pipeline=pipeline,
        cache_path=cache_path, **plan.summary(),
    )
    return plan


def resolve_plan_for_filterbank(
    fil, pipeline: str, cfg, cache_path: str | None = None,
) -> DedispPlan:
    """Driver-side entry: derive the observation's shape bucket (the
    campaign bucketing convention, so a CLI run and a campaign worker
    share cache entries) and resolve its plan."""
    import dataclasses

    from ..campaign.runner import bucket_for_header

    bucket = bucket_for_header(fil.header)
    overrides = dataclasses.asdict(cfg)
    return resolve_plan_for_bucket(
        bucket, pipeline, overrides, cache_path or None
    )
