from .candidates import (
    Candidate,
    CandidateCollection,
    CANDIDATE_POD_DTYPE,
    SinglePulseCandidate,
    SinglePulseCandidateCollection,
)
