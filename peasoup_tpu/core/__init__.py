from .candidates import (
    Candidate,
    CandidateCollection,
    CANDIDATE_POD_DTYPE,
    FdasCandidate,
    SinglePulseCandidate,
    SinglePulseCandidateCollection,
)
