"""Candidate model (reference: include/data_types/candidates.hpp).

A Candidate carries the detection parameters plus a recursive ``assoc``
list of weaker detections absorbed by the distillers; folding adds
folded_snr / opt_period / fold. CandidatePOD is the 24-byte on-disk
record of candidates.peasoup (candidates.hpp:10-17).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

# struct CandidatePOD {float dm; int dm_idx; float acc; int nh; float snr; float freq;}
CANDIDATE_POD_DTYPE = np.dtype(
    [
        ("dm", "<f4"),
        ("dm_idx", "<i4"),
        ("acc", "<f4"),
        ("nh", "<i4"),
        ("snr", "<f4"),
        ("freq", "<f4"),
    ]
)


@dataclass
class Candidate:
    dm: float = 0.0
    dm_idx: int = 0
    acc: float = 0.0
    nh: int = 0
    snr: float = 0.0
    freq: float = 0.0
    folded_snr: float = 0.0
    opt_period: float = 0.0
    is_adjacent: bool = False
    is_physical: bool = False
    ddm_count_ratio: float = 0.0
    ddm_snr_ratio: float = 0.0
    assoc: List["Candidate"] = field(default_factory=list)
    fold: Optional[np.ndarray] = None  # (nints, nbins) when folded

    @property
    def period(self) -> float:
        return 1.0 / self.freq

    def append(self, other: "Candidate") -> None:
        self.assoc.append(other)

    def count_assoc(self) -> int:
        return sum(1 + c.count_assoc() for c in self.assoc)

    def collect_pods(self) -> np.ndarray:
        """Flatten self + assoc tree into CandidatePOD records
        (candidates.hpp:78-84, depth-first, self first)."""
        pods: list[tuple] = []

        def walk(c: "Candidate") -> None:
            pods.append((c.dm, c.dm_idx, c.acc, c.nh, c.snr, c.freq))
            for a in c.assoc:
                walk(a)

        walk(self)
        return np.array(pods, dtype=CANDIDATE_POD_DTYPE)


@dataclass
class FdasCandidate(Candidate):
    """A periodicity candidate found by the Fourier-domain
    acceleration search (pipeline/fdas.py), carrying its (f-dot,
    f-ddot) trial provenance alongside the base fields.

    ``acc`` holds the EQUIVALENT line-of-sight acceleration
    ``-fdot * c / f`` so every downstream consumer of periodicity
    candidates (distillers, folding, sift/rank ingest, the campaign
    DB's ``acc`` column) treats FDAS detections exactly like
    time-domain resampling ones; fdot/fddot preserve the native
    Fourier-domain parameters (overview.xml keeps them as extra
    candidate fields).
    """

    fdot: float = 0.0  # Hz/s at the detection frequency
    fddot: float = 0.0  # Hz/s^2 (0 unless the jerk plane is searched)
    z: float = 0.0  # matched template drift in bins over the obs
    w: float = 0.0  # matched template curvature in bins


@dataclass
class SinglePulseCandidate:
    """One clustered single-pulse detection in the DM-time plane.

    The periodicity Candidate has no single-pulse analogue in the
    reference (peasoup searches periodicity only); this model follows
    the candidate row of GPU single-pulse pipelines (Heimdall/GSP):
    the peak detection (dm, time, width, snr) plus the cluster's
    extent in every search dimension, so one broad pulse detected at
    many (DM trial, width, sample) cells reports as ONE candidate
    with its footprint."""

    dm: float = 0.0
    dm_idx: int = 0
    snr: float = 0.0
    time_s: float = 0.0  # peak boxcar START time (sample * tsamp)
    sample: int = 0  # peak boxcar start sample in the dedispersed series
    width: int = 1  # matched boxcar width (samples) at the peak
    width_idx: int = 0  # index into the run's width list
    members: int = 1  # events merged into this cluster
    # cluster extent (inclusive) over the friends-of-friends members
    dm_idx_lo: int = 0
    dm_idx_hi: int = 0
    sample_lo: int = 0
    sample_hi: int = 0
    width_lo: int = 1  # narrowest member width (samples)
    width_hi: int = 1  # widest member width (samples)


class CandidateCollection:
    def __init__(self, cands: Optional[List[Candidate]] = None):
        self.cands: List[Candidate] = list(cands) if cands else []

    def append(self, other) -> None:
        if isinstance(other, CandidateCollection):
            self.cands.extend(other.cands)
        else:
            self.cands.extend(other)

    def reset(self) -> None:
        self.cands.clear()

    def __len__(self) -> int:
        return len(self.cands)

    def __iter__(self):
        return iter(self.cands)

    def __getitem__(self, i):
        return self.cands[i]


class SinglePulseCandidateCollection(CandidateCollection):
    """List container for SinglePulseCandidate (the base collection is
    type-agnostic; the subclass names the intent in signatures)."""
