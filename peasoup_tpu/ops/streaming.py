"""Streaming single-pulse chunk program: one jitted step of the
real-time search.

The batch single-pulse program (ops/singlepulse.py) sees a whole
observation at once; the streaming driver (peasoup_tpu/stream/) sees an
endless dedispersed stream in fixed-length chunks. This module is the
device side of that loop: ONE jitted program per chunk that

* concatenates the carried-over tail (the last ``hold`` dedispersed
  samples of the previous chunk) with the new chunk into a fixed
  ``hold + chunk_len`` window, so a pulse spanning a chunk boundary is
  searched with full left/right context exactly as in batch mode;
* normalises the window with the same iterative sigma-clipped moment
  estimate as the batch path, restricted by a traced validity mask
  (the first chunk has no tail yet; the final chunk of a finite stream
  ends mid-window);
* runs the identical boxcar width sweep (prefix-sum differencing,
  narrowest-width ties) and dec-fold peak compaction, but windowed to
  a traced ``[emit_lo, emit_hi)`` block range so each absolute sample
  is emitted by exactly one chunk (events whose right context has not
  streamed in yet are deferred to the next chunk's window).

Every per-chunk quantity that varies (validity bounds, emit window) is
a traced i32 scalar, so the whole stream — first chunk, steady state,
and the final drain flush — reuses ONE compiled program: zero
steady-state recompiles, asserted by the driver via the telemetry
compile counters.

Geometry contract (checked at build time): ``hold`` and ``chunk_len``
are multiples of ``dec`` and ``hold >= max(widths)``. Chunk windows
then tile the absolute sample axis on ``dec``-block boundaries, so the
dec-fold maxima — and therefore the emitted events — line up exactly
with a batch run over the same samples.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from .peaks import find_peaks_device
from .singlepulse import (
    CLIP3_STD_RETENTION,
    boxcar_best_twin,
    plan_pad,
    prefix_sum_padded,
    width_extent,
    width_scales,
)


def stream_geometry(
    widths: tuple[int, ...], chunk_len: int, dec: int, hold: int = 0
) -> int:
    """Resolve (and validate) the carried-tail length ``hold`` for a
    width bank: at least the widest boxcar (full right context for
    every deferred event), rounded up to the decimation quantum. With
    an explicit ``hold`` the same constraints are enforced."""
    wmax = int(max(widths))
    if hold <= 0:
        hold = -(-max(wmax, dec) // dec) * dec
    if hold % dec or chunk_len % dec:
        raise ValueError(
            f"hold={hold} and chunk_len={chunk_len} must be multiples "
            f"of decimate={dec} (chunk windows must tile the absolute "
            f"dec-block grid)"
        )
    if hold < wmax:
        raise ValueError(
            f"hold={hold} is narrower than the widest boxcar ({wmax}): "
            "boundary-spanning pulses would lose right context"
        )
    if chunk_len < hold:
        raise ValueError(
            f"chunk_len={chunk_len} must be >= hold={hold} (the emit "
            "region of a steady chunk must cover its deferred zone)"
        )
    return hold


def normalise_window(
    x: jnp.ndarray,  # (D, W) f32 window
    valid: jnp.ndarray,  # (W,) bool validity mask
    *,
    clip_sigma: float = 3.0,
    n_rounds: int = 2,
) -> jnp.ndarray:
    """Masked twin of ops.singlepulse.normalise_trials: identical
    iterative sigma-clipped moments, but only ``valid`` samples enter
    the estimates and the output is zeroed outside them — so the
    prefix sums downstream see exactly the zero padding the batch path
    applies past the end of a trial row."""
    x = x.astype(jnp.float32)
    vm = valid.astype(jnp.float32)[None, :]
    corr = np.float32(CLIP3_STD_RETENTION if clip_sigma == 3.0 else 1.0)
    nv = jnp.maximum(jnp.sum(vm, axis=-1, keepdims=True), 1.0)
    mean = jnp.sum(x * vm, axis=-1, keepdims=True) / nv
    var = jnp.sum(vm * (x - mean) ** 2, axis=-1, keepdims=True) / nv
    std = jnp.sqrt(jnp.maximum(var, 1e-12))
    for _ in range(max(1, n_rounds)):
        keep = (jnp.abs(x - mean) <= clip_sigma * std) * vm
        nkeep = jnp.maximum(jnp.sum(keep, axis=-1, keepdims=True), 1.0)
        mean = jnp.sum(keep * x, axis=-1, keepdims=True) / nkeep
        var = jnp.sum(keep * (x - mean) ** 2, axis=-1, keepdims=True) / nkeep
        std = jnp.sqrt(jnp.maximum(var, 1e-12)) / corr
    return (x - mean) / std * vm


@lru_cache(maxsize=16)
def make_stream_chunk_fn(
    widths: tuple[int, ...],
    threshold: float,
    max_events: int,
    dec: int,
    hold: int,
    chunk_len: int,
):
    """One jitted streaming step. Returns
    ``fn(tail, new, valid_lo, nvalid, emit_lo, emit_hi)`` with

    * ``tail`` (D, hold) u8/f32 — the previous chunk's last ``hold``
      dedispersed samples (zeros before the first chunk),
    * ``new`` (D, chunk_len) u8/f32 — the freshly dedispersed chunk,
    * ``valid_lo``/``nvalid`` i32 — the window's real-data span
      [valid_lo, nvalid) (first chunk: [hold, W); steady: [0, W);
      final: [0, streamed tail length)),
    * ``emit_lo``/``emit_hi`` i32 — dec-block emit range (steady:
      [0, chunk_len/dec); final flush extends to W/dec),

    yielding ``(samples (D, K) i32 in WINDOW coordinates, width_idx
    (D, K) i32, snrs (D, K) f32, counts (D,) i32)`` with K =
    ``max_events`` — the same record layout as the batch program, so
    the driver shares its event-extraction path."""
    hold = stream_geometry(widths, chunk_len, dec, hold)
    w = hold + chunk_len
    tpad, _ = plan_pad(w)
    if tpad % dec:
        raise ValueError(
            f"decimate={dec} must divide the padded window length {tpad}"
        )
    wext = width_extent(widths)
    scales = width_scales(widths)

    def run(
        tail: jnp.ndarray,
        new: jnp.ndarray,
        valid_lo: jnp.ndarray,
        nvalid: jnp.ndarray,
        emit_lo: jnp.ndarray,
        emit_hi: jnp.ndarray,
    ):
        d = tail.shape[0]
        window = jnp.concatenate(
            [tail.astype(jnp.float32), new.astype(jnp.float32)], axis=-1
        )
        j = jnp.arange(w, dtype=jnp.int32)
        valid = (j >= valid_lo) & (j < nvalid)
        norm = normalise_window(window, valid)
        csum = prefix_sum_padded(norm, tpad, wext)
        best, bw = boxcar_best_twin(csum, widths, scales, nvalid, tpad)
        nbd = tpad // dec
        blocks = best.reshape(d, nbd, dec)
        bmax = jnp.max(blocks, axis=-1)
        barg = jnp.argmax(blocks, axis=-1).astype(jnp.int32)
        pidx, psnr, pcount = find_peaks_device(
            bmax, jnp.float32(threshold), emit_lo, emit_hi,
            max_peaks=max_events,
        )
        pvalid = pidx < nbd
        safe = jnp.minimum(pidx, nbd - 1)
        samples = safe * dec + jnp.take_along_axis(barg, safe, axis=-1)
        widx = jnp.take_along_axis(
            bw, jnp.clip(samples, 0, tpad - 1), axis=-1
        )
        samples = jnp.where(pvalid, samples, -1)
        widx = jnp.where(pvalid, widx, 0)
        return samples, widx, psnr, pcount

    return jax.jit(run)


# --- audit registry: the streaming chunk program, with a ShapeCtx hook
# so warmup/contracts/microbenchmarks cover the production stream
# geometry (ctx.stream_chunk/stream_hold are set by the streaming
# driver's ShapeCtx; campaign buckets leave them 0 and skip) ---
from .registry import register_program, sds  # noqa: E402


def _param_stream_chunk(ctx):
    if not (ctx.stream_chunk and ctx.widths):
        return None
    hold = int(ctx.stream_hold) or stream_geometry(
        tuple(int(x) for x in ctx.widths), int(ctx.stream_chunk),
        int(ctx.decimate),
    )
    scalar = sds((), "int32")
    return (
        make_stream_chunk_fn(
            tuple(int(x) for x in ctx.widths), float(ctx.min_snr),
            int(ctx.max_events), int(ctx.decimate), hold,
            int(ctx.stream_chunk),
        ),
        (
            sds((ctx.ndm, hold), "uint8"),
            sds((ctx.ndm, ctx.stream_chunk), "uint8"),
            scalar, scalar, scalar, scalar,
        ),
        {},
    )


register_program(
    "ops.streaming.stream_chunk_search",
    lambda: (
        make_stream_chunk_fn((1, 2, 4, 8), 7.0, 64, 8, 64, 960),
        (
            sds((2, 64), "uint8"),
            sds((2, 960), "uint8"),
            sds((), "int32"), sds((), "int32"),
            sds((), "int32"), sds((), "int32"),
        ),
        {},
    ),
    param=_param_stream_chunk,
)
