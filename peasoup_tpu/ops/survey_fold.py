"""Survey-scale batched folding: many candidates, one device program.

The per-observation folder (:mod:`peasoup_tpu.pipeline.folder`) batches
candidates *within* one DM trial: every group shares a single
dereddened series, so its resample vmaps over accelerations only. At
campaign scale the unit of work inverts — thousands of candidates from
*different* observations and DM trials fold together (PulsarX,
arXiv:2309.02544: survey throughput hinges on bulk folding) — so this
program carries one dereddened series **per row**: each row resamples
its own series at its own acceleration factor and folds through its own
phase-bin map. Row independence makes the result bitwise-identical to
the per-observation path on the same candidate (pinned by
tests/test_sift.py), while the fixed ``(batch, nsamps)`` shape lets the
sift service stream the whole campaign DB through ONE compiled program
per shape bucket with zero steady-state recompiles.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .fold import fold_time_series
from .resample import resample_accel_quadratic


@partial(jax.jit, static_argnames=("nbins", "nints"))
def survey_fold_batch(
    xd: jnp.ndarray,  # (B, N) f32 dereddened series, one per candidate
    afs: jnp.ndarray,  # (B,) f32 acceleration factors (a*tsamp/2c)
    flat_bins: jnp.ndarray,  # (B, used) i32 from fold_bins_np per row
    *,
    nbins: int,
    nints: int,
) -> jnp.ndarray:
    """Resample + fold a batch of candidates -> (B, nints, nbins).

    Exactly the folder's per-candidate chain (quadratic resample then
    the segment-sum fold with the reference's 1+hits count bias), just
    batched with per-row series instead of a shared one.
    """
    xr = jax.vmap(resample_accel_quadratic)(xd, afs)  # (B, N)
    used = flat_bins.shape[-1]
    return fold_time_series(
        xr[:, :used], flat_bins, nbins=nbins, nints=nints
    )


# --- audit registry: the representative shapes are tiny; the ShapeCtx
# hook rebuilds at the sift service's production fold bucket (batch x
# power-of-two series length) so campaign warmup covers it ---
from .registry import register_program, sds  # noqa: E402


def _param_survey_fold(ctx):
    if ctx.fold_batch <= 0 or ctx.fold_nsamps <= 0:
        return None
    used = ctx.fold_nints * (ctx.fold_nsamps // ctx.fold_nints)
    return (
        survey_fold_batch,
        (
            sds((ctx.fold_batch, ctx.fold_nsamps), "float32"),
            sds((ctx.fold_batch,), "float32"),
            sds((ctx.fold_batch, used), "int32"),
        ),
        {"nbins": ctx.fold_nbins, "nints": ctx.fold_nints},
    )


register_program(
    "ops.survey_fold.survey_fold_batch",
    lambda: (
        survey_fold_batch,
        (
            sds((4, 1024), "float32"),
            sds((4,), "float32"),
            sds((4, 1024), "int32"),
        ),
        {"nbins": 16, "nints": 4},
    ),
    param=_param_survey_fold,
)
