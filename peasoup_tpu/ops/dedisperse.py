"""Incoherent dedispersion as a batched XLA gather/reduce.

The reference delegates this to the external `dedisp` CUDA library
(reference: include/transforms/dedisperser.hpp:98-113). TPU-native
design: the (DM trial, channel) delay table becomes a per-channel
dynamic-slice of the (time, channel) filterbank, summed over channels —
one jitted program batched over a DM-trial block, which XLA lowers to
large fused gathers feeding the VPU. No scalar loops, static shapes.

Output matches the reference's u8 trials when ``quantize=True``
(dedisp is called with 8-bit output; for <=6-bit inputs with <=64
channels raw channel sums fit u8 exactly).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np


def _shift_slice(row_b: jax.Array, delay: jax.Array, nb: int) -> jax.Array:
    """row[delay : delay + nb*128] from a (T/128, 128) blocked channel
    row, decomposed as delay = 128q + s.

    An arbitrary-offset 1-D dynamic slice makes XLA rotate lanes the
    slow way (measured 10x over a static slice); slicing the BLOCKED
    row on its leading axis is pure addressing, and the s < 128
    residual becomes one whole-array lane-roll plus a row-boundary
    select — measured 2x faster end-to-end, bitwise identical.
    """
    q = delay // 128
    s = delay % 128
    # the 0 start index must carry q's dtype: a bare Python 0
    # canonicalises to i64 under enable_x64 and vmap then stacks
    # mismatched index dtypes (audit contract pass traces under x64)
    v = jax.lax.dynamic_slice(row_b, (q, jnp.int32(0)), (nb + 1, 128))
    a = jnp.roll(v, -s, axis=1)
    lane = jax.lax.broadcasted_iota(jnp.int32, (nb, 128), 1)
    return jnp.where(lane < 128 - s, a[:nb], a[1:]).reshape(-1)


def _pad_blocks(x_tc: jax.Array) -> jax.Array:
    """Zero-pad the time axis so every channel row reshapes to
    (T/128, 128) blocks with one spare block for _shift_slice's
    window (the pad is never read when delay + out_nsamps <= T)."""
    t = x_tc.shape[0]
    tpad = (-(-t // 128) + 2) * 128
    return jnp.pad(x_tc, ((0, tpad - t), (0, 0)))


def _dedisperse_core(
    x_cb: jax.Array,  # (C, T/128, 128) blocked, masked, f32-summable rows
    delays: jax.Array,  # (D, C) int32
    *,
    out_nsamps: int,
    quantize: bool,
    scale: float,
) -> jax.Array:
    """Channel-major shift-and-sum scan (the shared engine of the
    direct path and both subband stages; channel-major input means no
    transposes anywhere on the subband path)."""
    nb = -(-out_nsamps // 128)

    # accumulate channel by channel with a lax.scan: a (D, C, T_out)
    # shifted tensor would not fit HBM at survey scale (XLA materialises
    # vmapped dynamic slices before reducing), while the (D, T_out)
    # carry is one trial block. Channel sums of <=8-bit samples are
    # exact integers in f32, so the summation order cannot change the
    # result.
    def body(acc, cin):
        row_b, dcol = cin  # (T/128, 128) blocked samples, (D,) delays
        return (
            acc
            + jax.vmap(lambda d: _shift_slice(row_b, d, nb))(dcol)[
                :, :out_nsamps
            ],
            None,
        )

    acc0 = jnp.zeros((delays.shape[0], out_nsamps), jnp.float32)
    out, _ = jax.lax.scan(body, acc0, (x_cb, delays.T))  # (D, T_out)
    if scale != 1.0:
        out = out * jnp.float32(scale)
    if quantize:
        out = jnp.clip(jnp.rint(out), 0, 255).astype(jnp.uint8)
    return out


@partial(jax.jit, static_argnames=("out_nsamps", "quantize", "scale"))
def dedisperse_block(
    fil_tc: jax.Array,  # (T, C) uint8/float32 filterbank samples
    delays: jax.Array,  # (D, C) int32 per-trial per-channel delay in samples
    killmask: jax.Array,  # (C,) int32/float32, 1 = keep
    *,
    out_nsamps: int,
    quantize: bool = True,
    scale: float = 1.0,
) -> jax.Array:
    """Dedisperse one block of DM trials: out[d, t] = sum_c x[t + delay[d,c], c].

    ``scale`` rescales channel sums into the u8 output range like dedisp's
    8-bit output mode; use :func:`output_scale` for a data-independent
    factor (1.0 for the 2-bit golden data, keeping raw-sum parity).
    Returns (D, out_nsamps) u8 (quantize=True) or f32.
    """
    x_ct = _pad_blocks(fil_tc).astype(jnp.float32).T
    x_ct = x_ct * killmask.astype(jnp.float32)[:, None]
    x_cb = x_ct.reshape(x_ct.shape[0], -1, 128)  # (C, T/128, 128)
    return _dedisperse_core(
        x_cb, delays, out_nsamps=out_nsamps, quantize=quantize, scale=scale
    )


@partial(jax.jit, static_argnames=("nbits", "nsamps", "nchans"))
def unpack_fil_device(
    raw: jax.Array, *, nbits: int, nsamps: int, nchans: int
) -> jax.Array:
    """Unpack sub-byte filterbank samples ON DEVICE (LSB-first within
    each byte, matching io.sigproc.unpack_bits and libdedisp's sub-word
    extraction). The host uploads the PACKED bytes — 4x less
    host->device traffic for 2-bit data — exactly as the reference
    hands dedisp the packed filterbank and unpacks on the GPU."""
    per = 8 // nbits
    shifts = (jnp.arange(per, dtype=jnp.uint8) * nbits)[None, :]
    w = (raw[:, None] >> shifts) & jnp.uint8((1 << nbits) - 1)
    return w.reshape(nsamps, nchans)


def fil_to_device(fil) -> jax.Array:
    """Stage a Filterbank's samples on device, uploading packed bytes
    when the file had sub-byte samples."""
    raw = getattr(fil, "raw", None)
    if raw is not None and fil.nbits in (1, 2, 4):
        return unpack_fil_device(
            jnp.asarray(raw), nbits=fil.nbits, nsamps=fil.nsamps,
            nchans=fil.nchans,
        )
    return jnp.asarray(fil.data)


def output_scale(nbits: int, nchans_kept: int) -> float:
    """Data-independent factor keeping worst-case channel sums inside u8.

    1.0 whenever raw sums already fit (e.g. 2-bit x 64 channels = 192),
    else shrink so the maximum possible sum maps to 255.
    """
    max_sum = (2**nbits - 1) * max(1, nchans_kept)
    return 1.0 if max_sum <= 255 else 255.0 / max_sum


def dedisperse_device(
    fil_tc: np.ndarray,
    delays: np.ndarray,
    killmask: np.ndarray,
    out_nsamps: int,
    *,
    quantize: bool = True,
    scale: float = 1.0,
    block: int = 16,
    chunk_bytes: int = 3_000_000_000,
) -> jax.Array:
    """Channel-chunking front end: both engines below materialise an
    f32 copy of their input (C * T * 4 bytes), which at survey scale
    (2^21 samples x 1024+ channels ~ 8.6 GB) crowds HBM and has been
    seen to crash the XLA compile helper outright. Channels split into
    chunks whose f32 copy stays under ``chunk_bytes``; f32 partial
    sums accumulate in channel-ascending order (bitwise-identical for
    the <=8-bit integer inputs the pipeline produces — channel sums
    are exact in f32; pure-f32 filterbanks may differ by summation
    association, i.e. 1 quantized LSB), and quantize/scale apply once
    at the end. The DM axis also splits when the live f32 partials
    (acc + part) would exceed the chunk budget."""
    c = delays.shape[1]
    t_in = fil_tc.shape[0]
    cc = max(1, int(chunk_bytes // max(1, 4 * t_in)))
    if cc >= c:
        return _dedisperse_device_once(
            fil_tc, delays, killmask, out_nsamps,
            quantize=quantize, scale=scale, block=block,
        )
    delays = np.asarray(delays)
    seg = -(-max(block, chunk_bytes // (out_nsamps * 8)) // block) * block
    if seg < delays.shape[0]:
        # bound the two live (D, out) f32 partials: recurse per DM
        # segment (segments concatenate as quantized u8); when even one
        # block-sized segment overshoots the budget, proceed anyway —
        # a single block is the minimum unit of work
        parts = [
            dedisperse_device(
                fil_tc, delays[s0 : s0 + seg], killmask, out_nsamps,
                quantize=quantize, scale=scale, block=block,
                chunk_bytes=chunk_bytes,
            )
            for s0 in range(0, delays.shape[0], seg)
        ]
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)
    killmask = np.asarray(killmask)
    # pad the tail chunk (repeated delay column, zero killmask — inert)
    # so every chunk reuses ONE compiled shape
    cpad = -(-c // cc) * cc
    if cpad > c:
        delays = np.concatenate(
            [delays, np.tile(delays[:, -1:], (1, cpad - c))], axis=1
        )
        killmask = np.concatenate(
            [killmask, np.zeros(cpad - c, killmask.dtype)]
        )
        pad_cols = np.zeros(
            (t_in, cpad - c), dtype=np.asarray(fil_tc[:1, :1]).dtype
        )
    acc = None
    for lo in range(0, cpad, cc):
        if lo + cc <= c:
            fil_chunk = fil_tc[:, lo : lo + cc]
        else:
            fil_chunk = jnp.concatenate(
                [jnp.asarray(fil_tc[:, lo:c]), jnp.asarray(pad_cols)], axis=1
            )
        part = _dedisperse_device_once(
            fil_chunk,
            delays[:, lo : lo + cc],
            killmask[lo : lo + cc],
            out_nsamps,
            quantize=False,
            scale=1.0,
            block=block,
        )
        acc = part if acc is None else acc + part
    if scale != 1.0:
        acc = acc * jnp.float32(scale)
    if quantize:
        acc = jnp.clip(jnp.rint(acc), 0, 255).astype(jnp.uint8)
    return acc


def _dedisperse_device_once(
    fil_tc: np.ndarray,
    delays: np.ndarray,
    killmask: np.ndarray,
    out_nsamps: int,
    *,
    quantize: bool = True,
    scale: float = 1.0,
    block: int = 16,
) -> jax.Array:
    """Dedisperse all DM trials in device-sized blocks, keeping the
    (ndm, out_nsamps) result RESIDENT on device.

    The filterbank is transferred once and the trials never round-trip
    through the host — the search slices trial rows on device (the
    reference instead keeps trials in host RAM and re-uploads each one,
    timeseries.hpp:335-344). Blocks bound peak HBM ((block+1) * T * 4
    bytes of working set).

    On TPU backends where the probe passes, the whole trial set runs as
    ONE Pallas dispatch (ops/pallas/dedisperse.py: VMEM-resident
    accumulators, per-channel windows DMA'd at dynamic offsets) —
    bitwise equal to the jnp scan below, ~1.5x faster at survey scale
    and free of per-block dispatch overhead.
    """
    from .pallas import probe_pallas_dedisperse

    # probe first (cached, instant False off-TPU) so non-TPU backends
    # skip the O(D*C) monotonicity scan entirely; the kernel also needs
    # its full f32 output + padded f32 filterbank copy to fit HBM —
    # bigger sets stay on the blocked scan, whose working set is one
    # trial block
    if probe_pallas_dedisperse() and np.all(
        np.diff(np.asarray(delays), axis=0) >= 0
    ):
        from .pallas.dedisperse import (
            dedisperse_pallas,
            pallas_hbm_bytes,
            plan_spread,
        )

        spread = plan_spread(delays)
        need = pallas_hbm_bytes(
            fil_tc.shape[0], delays.shape[1], delays.shape[0], out_nsamps,
            spread=spread,
        )
        try:
            limit = (
                jax.local_devices()[0].memory_stats() or {}
            ).get("bytes_limit", 0) or 12_000_000_000
        except Exception:
            limit = 12_000_000_000
        if need < 0.6 * limit:
            try:
                res = dedisperse_pallas(
                    fil_tc, delays, killmask, out_nsamps,
                    quantize=quantize, scale=scale, spread=spread,
                )
                # force execution INSIDE the try: TPU runtime failures
                # that surface asynchronously (e.g. allocation at a
                # later sync) must also degrade to the jnp path, not
                # crash the search (ADVICE r1)
                jax.block_until_ready(res)
                return res
            except Exception as exc:
                # the probe runs at one small shape; degrade instead of
                # crashing if the production shape breaks Mosaic limits
                import warnings

                warnings.warn(
                    "Pallas dedispersion failed at the production "
                    f"shape; using the jnp scan: {exc!s:.200}"
                )
    ndm = delays.shape[0]
    fil_dev = jnp.asarray(fil_tc)
    kill_dev = jnp.asarray(killmask)
    outs = []
    for start in range(0, ndm, block):
        d = np.asarray(delays[start : start + block], dtype=np.int32)
        pad = 0
        if len(d) < block:  # pad to a fixed block shape to avoid recompiles
            pad = block - len(d)
            d = np.pad(d, ((0, pad), (0, 0)))
        res = dedisperse_block(
            fil_dev,
            jnp.asarray(d),
            kill_dev,
            out_nsamps=out_nsamps,
            quantize=quantize,
            scale=scale,
        )
        outs.append(res[: block - pad] if pad else res)
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)


# ---------------------------------------------------------------------------
# MXU banded-matmul engine (ISSUE 12): the shift-and-sum recast as a
# one-hot banded contraction so the inner loop runs on the MXU.
#
# For a block of adjacent DM trials the per-channel delays decompose as
# delay[d, c] = base[c] + resid[d, c] with base[c] the block minimum and
# resid small (adjacent trials' delays differ slowly). With the one-hot
# operand W[d, c, v] = (resid[d, c] == v) the shift-and-sum becomes
#
#     out[d, t] = sum_{c, v} W[d, c, v] * x[t + base[c] + v, c]
#
# — a VALID cross-correlation of the base-aligned channel windows with a
# (D, C, band) selection kernel, i.e. exactly the (trials x band) @
# (band x samples) banded matmul of arXiv:1201.5380's factorisation
# once XLA im2col-unfolds it, which on TPU lowers to MXU convolutions.
# MACs grow from D*C*T to D*C*band*T, but each MAC runs at matrix-unit
# rather than gather/add throughput; the planner's cost model
# (plan/dedisp_plan.py) and the per-device tuner (perf/tuning.py)
# arbitrate. Products are x*1 or x*0 and channel sums of <=8-bit
# samples are exact integers in f32, so the result is BITWISE equal to
# the gather engines for integer inputs regardless of summation order;
# pure-f32 filterbanks may differ by association (pinned ULP tolerance
# in tests/test_matmul_dedisp.py).
# ---------------------------------------------------------------------------

MATMUL_BAND_QUANT = 8  # resid band rounds up to this (bounds compile count)
MATMUL_BLOCK = 64  # DM trials per banded-matmul dispatch


def matmul_band(delays_block: np.ndarray, quant: int = MATMUL_BAND_QUANT) -> int:
    """The padded one-hot band of one DM-trial block: the largest
    per-channel delay spread across the block plus one, rounded up to
    ``quant`` so nearby blocks share a compiled shape."""
    d = np.asarray(delays_block)
    spread = int((d.max(axis=0) - d.min(axis=0)).max()) + 1
    return -(-spread // quant) * quant


def banded_onehot(
    delays_block: np.ndarray, band: int
) -> tuple[np.ndarray, np.ndarray]:
    """(base (C,) i32, onehot (D, C, band) f32) for one trial block:
    the sparse shift-selection operand of the banded matmul."""
    d = np.asarray(delays_block, dtype=np.int64)
    base = d.min(axis=0)
    resid = d - base[None, :]
    onehot = (
        resid[:, :, None] == np.arange(band, dtype=np.int64)[None, None, :]
    ).astype(np.float32)
    return base.astype(np.int32), onehot


def _banded_conv(xb: jax.Array, onehot: jax.Array) -> jax.Array:
    """out[d, t] = sum_{c, v} onehot[d, c, v] * xb[c, t + v] as a VALID
    1-D correlation (XLA lowers this to the MXU on TPU backends)."""
    return jax.lax.conv_general_dilated(
        xb[None],
        onehot,
        window_strides=(1,),
        padding="VALID",
        dimension_numbers=("NCH", "OIH", "NCH"),
        preferred_element_type=jnp.float32,
    )[0]


@partial(jax.jit, static_argnames=("out_nsamps", "quantize", "scale"))
def dedisperse_matmul_block(
    fil_tc: jax.Array,  # (T, C) u8/f32 filterbank (zero-padded so that
    # base[c] + out_nsamps + band - 1 <= T for every channel)
    base: jax.Array,  # (C,) i32 per-channel block-minimum delay
    onehot: jax.Array,  # (D, C, band) f32 one-hot shift selection
    killmask: jax.Array,  # (C,) 1 = keep
    *,
    out_nsamps: int,
    quantize: bool = True,
    scale: float = 1.0,
) -> jax.Array:
    """One DM-trial block on the MXU: slice each channel's base-aligned
    window, then contract against the one-hot band. Returns
    (D, out_nsamps) u8 (quantize) or f32, bitwise equal to
    :func:`dedisperse_block` for integer inputs."""
    band = onehot.shape[-1]
    win = out_nsamps + band - 1
    x_ct = fil_tc.T  # stays in the upload dtype until after the slice
    xb = jax.vmap(
        lambda row, b: jax.lax.dynamic_slice(row, (b,), (win,))
    )(x_ct, base)
    xb = xb.astype(jnp.float32) * killmask.astype(jnp.float32)[:, None]
    out = _banded_conv(xb, onehot)
    if scale != 1.0:
        out = out * jnp.float32(scale)
    if quantize:
        out = jnp.clip(jnp.rint(out), 0, 255).astype(jnp.uint8)
    return out


def dedisperse_matmul(
    fil_tc,  # (T, C) u8/f32 filterbank (numpy or device array)
    delays: np.ndarray,  # (D, C) int32
    killmask: np.ndarray,
    out_nsamps: int,
    *,
    quantize: bool = True,
    scale: float = 1.0,
    block: int = MATMUL_BLOCK,
    band_quant: int = MATMUL_BAND_QUANT,
    chunk_bytes: int = 3_000_000_000,
) -> jax.Array:
    """All DM trials through the banded-matmul engine, ``block`` trials
    per dispatch. Per block, the one-hot band adapts to the real delay
    spread (rounded to ``band_quant`` so a survey's blocks share a few
    compiled shapes). Channels chunk when a block's f32 window copy
    (C * (out + band) * 4 bytes) would exceed ``chunk_bytes``, with f32
    partials accumulated channel-ascending exactly like
    :func:`dedisperse_device` (bitwise-identical for integer inputs)."""
    delays = np.asarray(delays, dtype=np.int32)
    d, c = delays.shape
    # per-block bands first: the input pad must cover the largest window
    blocks = []
    for lo in range(0, d, block):
        blk = delays[lo : lo + block]
        blocks.append((lo, lo + len(blk), matmul_band(blk, band_quant)))
    band_max = max(b for _, _, b in blocks)
    win_max = out_nsamps + band_max - 1
    cc = max(1, int(chunk_bytes // max(1, 4 * win_max)))
    if cc < c:
        # channel-chunk recursion: unquantized partials, one final tail
        acc = None
        for c0 in range(0, c, cc):
            part = dedisperse_matmul(
                fil_tc[:, c0 : c0 + cc], delays[:, c0 : c0 + cc],
                np.asarray(killmask)[c0 : c0 + cc], out_nsamps,
                quantize=False, scale=1.0, block=block,
                band_quant=band_quant, chunk_bytes=chunk_bytes,
            )
            acc = part if acc is None else acc + part
        if scale != 1.0:
            acc = acc * jnp.float32(scale)
        if quantize:
            acc = jnp.clip(jnp.rint(acc), 0, 255).astype(jnp.uint8)
        return acc
    t_in = fil_tc.shape[0]
    t_need = int(delays.max()) + out_nsamps + band_max
    x_dev = jnp.asarray(fil_tc)
    if t_need > t_in:  # zero tail: only ever multiplied by onehot zeros
        x_dev = jnp.pad(x_dev, ((0, t_need - t_in), (0, 0)))
    kill_dev = jnp.asarray(np.asarray(killmask))
    outs = []
    for lo, hi, band in blocks:
        blk = delays[lo:hi]
        pad = 0
        if hi - lo < block:  # repeat the last trial: one shape per band
            pad = block - (hi - lo)
            blk = np.concatenate([blk, np.repeat(blk[-1:], pad, axis=0)])
        base, onehot = banded_onehot(blk, band)
        res = dedisperse_matmul_block(
            x_dev, jnp.asarray(base), jnp.asarray(onehot), kill_dev,
            out_nsamps=out_nsamps, quantize=quantize, scale=scale,
        )
        outs.append(res[: block - pad] if pad else res)
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)


def subband_groups(
    delay_table: np.ndarray,  # (D, C) int32 per-trial per-channel delays
    nsub: int,
    max_smear: float,
    budgets: np.ndarray | None = None,
) -> list[tuple[int, int]]:
    """Greedy grouping of adjacent DM trials sharing one nominal DM for
    two-stage subband dedispersion (the scheme of the dedisp library
    the reference links, dedisperser.hpp:25-31 — there hidden inside
    `dedisp_execute`). Trials join the group opened by trial ``lo``
    while the worst-case intra-subband smear of substituting trial lo's
    channel shape stays <= the joining trial's budget — ``max_smear``
    samples for every trial, or ``budgets[hi]`` when the caller passes
    the DM-scaled per-trial budgets (plan/dedisp_plan.py:
    dm_smear_budgets, so high-DM trials whose intrinsic smearing
    already dwarfs a sample stop forcing conservative plans).
    ``max_smear=0`` gives singleton groups (exact direct equality).
    Returns [lo, hi) spans.
    """
    D, C = delay_table.shape
    w = -(-C // nsub)
    groups = []
    lo = 0
    while lo < D:
        hi = lo + 1
        while hi < D:
            cap = max_smear if budgets is None else float(budgets[hi])
            # smear of trial hi under trial lo's intra-band shape:
            # max_c |(d[hi,c]-d[hi,ref]) - (d[lo,c]-d[lo,ref])|
            err = 0
            for b in range(0, C, w):
                dl = delay_table[lo, b : b + w]
                dh = delay_table[hi, b : b + w]
                # same min-reference convention as dedisperse_subband,
                # so this bound is exactly the stage-2 index error
                err = max(
                    err, int(np.abs((dh - dh.min()) - (dl - dl.min())).max())
                )
                if err > cap:
                    break
            if err > cap:
                break
            hi += 1
        groups.append((lo, hi))
        lo = hi
    return groups


@partial(jax.jit, static_argnames=("nb1",))
def _subband_stage1(
    x_swt: jax.Array,  # (S, w, T) u8/f32 filterbank grouped into subbands
    kill_sw: jax.Array,  # (S, w) f32 killmask in the same grouping
    d1: jax.Array,  # (S, w) int32 intra-band delays at the nominal DM
    *,
    nb1: int,  # output length in 128-blocks (ceil(t1/128) + 2 spare)
) -> jax.Array:
    """Per-subband shift-and-sum at one nominal DM:
    out[b, t] = sum_c kill[b, c] * x[b, c, t + d1[b, c]] — the same
    scan-over-channels pattern as the direct core, vmapped over
    subbands. The f32 cast + killmask happen per scan step so the
    resident grouped filterbank stays u8. Output is the CHANNEL-MAJOR
    BLOCKED (S, nb1, 128) form that stage 2's core consumes directly,
    so the subband path has no transposes at all."""
    s_count, _, t_tot = x_swt.shape
    x_blk = x_swt.reshape(s_count, -1, t_tot // 128, 128)

    def body(acc, cin):
        rows, kcol, dcol = cin  # (S, T/128, 128), (S,), (S,)
        sl = jax.vmap(lambda r, d: _shift_slice(r, d, nb1))(rows, dcol)
        if sl.dtype != jnp.float32:  # spill path keeps the rows u8
            sl = sl.astype(jnp.float32)
        return acc + sl * kcol[:, None], None

    acc0 = jnp.zeros((s_count, nb1 * 128), jnp.float32)
    out, _ = jax.lax.scan(
        body, acc0, (jnp.swapaxes(x_blk, 0, 1), kill_sw.T, d1.T)
    )
    return out.reshape(s_count, nb1, 128)


@lru_cache(maxsize=None)
def _stage1_batched(nb1: int):
    """Jitted group-batched stage 1, cached so repeat calls (multi-file
    surveys, resumed runs) reuse the compiled program."""
    return jax.jit(
        jax.vmap(partial(_subband_stage1, nb1=nb1), in_axes=(None, None, 0))
    )


@lru_cache(maxsize=None)
def _stage2_batched(out_nsamps: int, quantize: bool, scale: float):
    """Jitted group-batched stage 2 (the channel-major core over
    subbands), cached like _stage1_batched."""
    return jax.jit(
        jax.vmap(
            partial(
                _dedisperse_core,
                out_nsamps=out_nsamps,
                quantize=quantize,
                scale=scale,
            ),
        )
    )


@lru_cache(maxsize=None)
def _stage1_matmul_batched(out_len: int, band: int):
    """Jitted group-batched stage 1 as a banded matmul: the grouped
    filterbank's per-band rows correlate against a per-(group, band)
    one-hot shift selection, vmapped over subbands — the stage-1 twin
    of :func:`dedisperse_matmul_block` (groups play the trial role:
    adjacent nominal DMs have slowly-varying intra-band shapes, so the
    one-hot band stays narrow). fn(x_swt (S, w, T) u8/f32,
    kill_sw (S, w), base_sw (S, w) i32, onehot (G, S, w, band)) ->
    (G, S, out_len/128, 128) f32, bitwise the scan stage's output for
    integer inputs."""

    def per_band(x_wt, kill_w, base_w, onehot_gwb):
        rows = x_wt.astype(jnp.float32) * kill_w[:, None]
        # static tail pad keeps every base-aligned window in range; the
        # pad region is only ever multiplied by one-hot zeros
        rows = jnp.pad(rows, ((0, 0), (0, band)))
        win = out_len + band - 1
        xb = jax.vmap(
            lambda r, b: jax.lax.dynamic_slice(r, (b,), (win,))
        )(rows, base_w)
        return _banded_conv(xb, onehot_gwb)  # (G, out_len)

    def run(x_swt, kill_sw, base_sw, onehot_gswb):
        out = jax.vmap(per_band, in_axes=(0, 0, 0, 1))(
            x_swt, kill_sw, base_sw, onehot_gswb
        )  # (S, G, out_len)
        g = out.shape[1]
        return jnp.swapaxes(out, 0, 1).reshape(g, out.shape[0], -1, 128)

    return jax.jit(run)


@lru_cache(maxsize=None)
def _stage2_matmul_batched(
    out_nsamps: int, quantize: bool, scale: float, band: int
):
    """Jitted group-batched stage 2 as a banded matmul over subband
    partial series (subbands play the channel role). fn(s1
    (G, S, nb1, 128) f32, base (G, S) i32, onehot (G, g_pad, S, band))
    -> (G, g_pad, out_nsamps), bitwise the scan stage's output for
    integer-valued stage-1 sums."""

    def per_group(x_blk, base_s, onehot_dsb):
        rows = x_blk.reshape(x_blk.shape[0], -1)
        rows = jnp.pad(rows, ((0, 0), (0, band)))
        win = out_nsamps + band - 1
        xb = jax.vmap(
            lambda r, b: jax.lax.dynamic_slice(r, (b,), (win,))
        )(rows, base_s)
        out = _banded_conv(xb, onehot_dsb)
        if scale != 1.0:
            out = out * jnp.float32(scale)
        if quantize:
            out = jnp.clip(jnp.rint(out), 0, 255).astype(jnp.uint8)
        return out

    return jax.jit(jax.vmap(per_group))


def dedisperse_subband(
    fil_tc,  # (T, C) u8/f32 filterbank (numpy or device)
    delay_table: np.ndarray,  # (D, C) int32 from DMPlan.delay_samples()
    killmask: np.ndarray,
    out_nsamps: int,
    *,
    nsub: int,
    max_smear: float = 1.0,
    quantize: bool = True,
    scale: float = 1.0,
    to_host: bool = False,
    use_matmul: bool = False,
    budgets: np.ndarray | None = None,
):
    """Two-stage subband dedispersion of ALL trials.

    Stage 1 (once per nominal DM, the first trial of each group):
    align channels WITHIN each of ``nsub`` subbands, giving (S, T1)
    partial time series. Stage 2 (per trial): combine the nominal's
    subbands with the trial's own reference-channel delays — which is
    exactly :func:`dedisperse_block` treating subbands as channels.
    Arithmetic per group of g trials: C*T + g*S*T instead of the direct
    g*C*T — ~sqrt(C)-fold less at survey channel counts when
    g ~ C/S ~ S. The approximation replaces each trial's intra-band
    delay shape by its nominal's; grouping bounds that error to
    ``max_smear`` samples (0 => bitwise equal to the direct path), or
    to the per-trial ``budgets`` when given (the DM-scaled smear
    budget, plan/dedisp_plan.py). With ``use_matmul`` both stages run
    as banded matmuls on the MXU (bitwise-identical for integer
    inputs; see the banded-matmul engine block above).

    Returns (D, out_nsamps), device-resident (or numpy with
    ``to_host``, for surveys whose trial block spills to host RAM).
    """
    delay_table = np.asarray(delay_table, dtype=np.int32)
    D, C = delay_table.shape
    # effective band count: ceil(C / w) bands of width w cover C for ANY
    # requested nsub (e.g. nsub=5 over 16 chans -> w=4, 4 bands)
    w = -(-C // max(1, min(nsub, C)))
    nsub = -(-C // w)
    cpad = w * nsub - C
    groups = subband_groups(delay_table, nsub, max_smear, budgets)

    # per-band reference = the band's MINIMUM delay (robust to either
    # frequency ordering and to rint non-monotonicity): d1 >= 0 always
    band_of = np.minimum(np.arange(C) // w, nsub - 1)
    refdel = np.stack(
        [delay_table[:, b : b + w].min(axis=1) for b in range(0, C, w)],
        axis=1,
    )  # (D, S)
    d1_all = delay_table - refdel[:, band_of]
    t1 = fil_tc.shape[0] - int(d1_all[[lo for lo, _ in groups]].max())
    # rint rounding can leave t1 one or two samples short of what
    # stage 2 addresses (interior-band rounded spans may exceed the
    # last band's); pad the time axis with zeros to cover the deficit.
    # For max_smear=0 the stage-2 index telescopes to t + d[d, c]
    # < fil_tc.shape[0], so the pad is NEVER read (exactness holds);
    # with smear it only touches the last <= smear samples per channel.
    deficit = max(0, int(refdel.max()) + out_nsamps - t1)
    t1 += deficit

    # the grouped filterbank stays in its upload dtype (u8 for packed
    # files), and stage 1 upcasts after slicing: HBM holds one u8 copy
    # instead of an f32 one (per-window upcasting before the roll was
    # tried and regressed — extra f32 write per slice, see NOTES.md)
    x = jnp.asarray(fil_tc)
    # pad time to whole 128-blocks (+3 spare: stage 1 windows reach
    # q1 + nb1 + 1 blocks with nb1 = ceil(t1/128) + 2) and pad channels
    # to equal-width bands; all pad zeros are inert
    nb1 = -(-t1 // 128) + 2
    t_need = fil_tc.shape[0] + deficit
    tpad = (-(-t_need // 128) + 3) * 128 - t_need
    if cpad or deficit or tpad:
        x = jnp.pad(x, ((0, deficit + tpad), (0, cpad)))
    x_swt = x.T.reshape(nsub, w, -1)  # (S, w, T)
    kill_sw = jnp.asarray(
        np.pad(np.asarray(killmask, np.float32), (0, cpad)).reshape(nsub, w)
    )

    # process groups in vmapped batches: per-group dispatches (2 per
    # group) would dominate at survey scale where groups hold only a
    # few trials each. Group heights shrink with DM, so first bucket
    # the (DM-ordered) groups into contiguous runs sharing a
    # power-of-two padded height, then size each bucket's batches from
    # ITS height so the live working set — the (gb, S, nb1*128) stage-1
    # partials PLUS the (gb, g_pad, out_nsamps) stage-2 f32 output
    # (ADVICE r1: the output term dominates for tall groups) — stays
    # ~1 GB without one tall low-DM bucket collapsing the batching of
    # the small-group tail. Compiled shapes: one per (gb, g_pad) bucket.
    stage1_b = None if use_matmul else _stage1_batched(nb1)
    stage2_b = (
        None if use_matmul else _stage2_batched(out_nsamps, quantize, scale)
    )

    def g_pad_of(lo, hi):
        return 1 << (hi - lo - 1).bit_length() if hi - lo > 1 else 1

    def band_of(resid) -> int:
        return -(
            -(int(resid.max()) + 1) // MATMUL_BAND_QUANT
        ) * MATMUL_BAND_QUANT

    def onehot_of(resid, band):
        return (
            resid[..., None] == np.arange(band, dtype=resid.dtype)
        ).astype(np.float32)

    outs = []
    i = 0
    while i < len(groups):
        g_pad = g_pad_of(*groups[i])
        j = i
        while j < len(groups) and g_pad_of(*groups[j]) == g_pad:
            j += 1
        per_group = 4 * nsub * nb1 * 128 + 4 * g_pad * out_nsamps
        gb = max(1, min(j - i, 1_000_000_000 // max(1, per_group)))
        for b0 in range(i, j, gb):
            batch = groups[b0 : min(b0 + gb, j)]
            if len(batch) < gb and b0 > i:  # pad: keep one shape per bucket
                batch = batch + [batch[-1]] * (gb - len(batch))
            d1 = np.stack(
                [
                    np.pad(d1_all[lo], (0, cpad)).reshape(nsub, w)
                    for lo, _ in batch
                ]
            )
            if use_matmul:
                # both stages as banded matmuls: groups play the trial
                # role in stage 1 (adjacent nominals' intra-band shapes
                # vary slowly), trials within a group in stage 2; pad
                # trials repeat the last row so the band stays narrow
                # (zero-delay pad rows would blow it open)
                base1 = d1.min(axis=0)
                r1 = d1 - base1[None]
                band1 = band_of(r1)
                rd = np.stack(
                    [
                        np.pad(
                            refdel[lo:hi],
                            ((0, g_pad - (hi - lo)), (0, 0)),
                            mode="edge",
                        )
                        for lo, hi in batch
                    ]
                )
                base2 = rd.min(axis=1)
                r2 = rd - base2[:, None, :]
                band2 = band_of(r2)
                s1 = _stage1_matmul_batched(nb1 * 128, band1)(
                    x_swt, kill_sw,
                    jnp.asarray(base1.astype(np.int32)),
                    jnp.asarray(onehot_of(r1, band1)),
                )
                res = _stage2_matmul_batched(
                    out_nsamps, quantize, scale, band2
                )(
                    s1,
                    jnp.asarray(base2.astype(np.int32)),
                    jnp.asarray(onehot_of(r2, band2)),
                )
            else:
                rd = np.stack(
                    [
                        np.pad(
                            refdel[lo:hi], ((0, g_pad - (hi - lo)), (0, 0))
                        )
                        for lo, hi in batch
                    ]
                )
                s1 = stage1_b(x_swt, kill_sw, jnp.asarray(d1))
                res = stage2_b(s1, jnp.asarray(rd, dtype=np.int32))
            if to_host:
                res = np.asarray(res)  # ONE transfer per batch
            for bi, (lo, hi) in enumerate(batch[: min(b0 + gb, j) - b0]):
                outs.append(res[bi, : hi - lo])
        i = j
    if to_host:
        return np.concatenate(outs, axis=0)
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)


def dedisperse(
    fil_tc: np.ndarray,
    delays: np.ndarray,
    killmask: np.ndarray,
    out_nsamps: int,
    *,
    quantize: bool = True,
    scale: float = 1.0,
    block: int = 16,
) -> np.ndarray:
    """Host-resident variant: trials land in host RAM segment by
    segment, so HBM never holds more than one DM segment's outputs
    (for surveys whose full trial set would crowd the chip; cf.
    reference host-RAM trials, dedisperser.hpp:101-103). The u8
    filterbank stages on device ONCE and every segment routes through
    dedisperse_device, inheriting its Pallas dispatch and
    channel-chunking (the f32-input-copy bound applies here too)."""
    ndm = delays.shape[0]
    delays = np.asarray(delays)
    fil_dev = jnp.asarray(fil_tc)
    seg = -(-max(block, 1_000_000_000 // max(1, out_nsamps)) // block) * block
    outs = []
    for start in range(0, ndm, seg):
        res = dedisperse_device(
            fil_dev, delays[start : start + seg], killmask, out_nsamps,
            quantize=quantize, scale=scale, block=block,
        )
        outs.append(np.asarray(res))
    return np.concatenate(outs, axis=0)


# --- audit registry: representative shapes for the contract engine
# (peasoup_tpu/analysis/contracts.py) plus ShapeCtx hooks so the AOT
# warmup (peasoup_tpu/perf/warmup.py) can compile at a campaign
# bucket's production geometry; build thunks are lazy, nothing traces
# at import time ---
from .registry import register_program, sds  # noqa: E402


def _param_dedisperse_block(ctx):
    # the single-channel-chunk driver path: full filterbank against
    # one dedisp_block of delay rows, quantized at the bucket's
    # data-independent output scale (scale is a static argname, so it
    # is part of the compiled program's identity)
    d = max(1, min(ctx.dedisp_block, ctx.ndm))
    return (
        dedisperse_block,
        (
            sds((ctx.nsamps, ctx.nchans), "uint8"),
            sds((d, ctx.nchans), "int32"),
            sds((ctx.nchans,), "float32"),
        ),
        {
            "out_nsamps": ctx.out_nsamps,
            "scale": output_scale(ctx.nbits, ctx.nchans),
        },
    )


def _param_unpack(ctx):
    if ctx.nbits not in (1, 2, 4):  # byte data uploads unpacked
        return None
    return (
        unpack_fil_device,
        (sds((ctx.nsamps * ctx.nchans * ctx.nbits // 8,), "uint8"),),
        {"nbits": ctx.nbits, "nsamps": ctx.nsamps, "nchans": ctx.nchans},
    )


register_program(
    "ops.dedisperse.dedisperse_block",
    lambda: (
        dedisperse_block,
        (sds((256, 8), "uint8"), sds((4, 8), "int32"), sds((8,), "float32")),
        {"out_nsamps": 192},
    ),
    param=_param_dedisperse_block,
)
register_program(
    "ops.dedisperse.unpack_fil_device",
    lambda: (
        unpack_fil_device,
        (sds((128,), "uint8"),),
        {"nbits": 2, "nsamps": 64, "nchans": 8},
    ),
    param=_param_unpack,
)
def _param_subband_stage1(ctx):
    # the tuned-plan subband path (plan/dedisp_plan.py selects, the
    # tuning cache persists): compile stage 1 at the bucket's grouped
    # filterbank geometry. Declines non-subband ctxs.
    if ctx.subbands <= 0:
        return None
    c = ctx.nchans
    w = -(-c // max(1, min(ctx.subbands, c)))
    nsub = -(-c // w)
    nb1 = -(-ctx.out_nsamps // 128) + 2
    tpad = (-(-ctx.nsamps // 128) + 3) * 128
    return (
        _subband_stage1,
        (
            sds((nsub, w, tpad), "uint8"),
            sds((nsub, w), "float32"),
            sds((nsub, w), "int32"),
        ),
        {"nb1": nb1},
    )


register_program(
    "ops.dedisperse.subband_stage1",
    lambda: (
        _subband_stage1,
        (
            sds((2, 4, 512), "uint8"),
            sds((2, 4), "float32"),
            sds((2, 4), "int32"),
        ),
        {"nb1": 2},
    ),
    param=_param_subband_stage1,
)
def _param_stage1_batched(ctx):
    # the gather-staged subband engine's group-batched stage 1; the
    # matmul-staged variant has its own hooks below
    if ctx.subbands <= 0 or ctx.subband_matmul:
        return None
    c = ctx.nchans
    w = -(-c // max(1, min(ctx.subbands, c)))
    nsub = -(-c // w)
    nb1 = -(-ctx.out_nsamps // 128) + 2
    tpad = (-(-ctx.nsamps // 128) + 3) * 128
    return (
        _stage1_batched(nb1),
        (
            sds((nsub, w, tpad), "uint8"),
            sds((nsub, w), "float32"),
            sds((4, nsub, w), "int32"),  # vmapped over DM groups
        ),
        {},
    )


def _param_stage2_batched(ctx):
    if ctx.subbands <= 0 or ctx.subband_matmul:
        return None
    c = ctx.nchans
    w = -(-c // max(1, min(ctx.subbands, c)))
    nsub = -(-c // w)
    nb1 = -(-ctx.out_nsamps // 128) + 2
    d = max(1, min(ctx.dedisp_block, ctx.ndm))
    return (
        _stage2_batched(
            ctx.out_nsamps, True, output_scale(ctx.nbits, ctx.nchans)
        ),
        (
            sds((4, nsub, nb1, 128), "float32"),
            sds((4, d, nsub), "int32"),
        ),
        {},
    )


register_program(
    "ops.dedisperse.subband_stage1_batched",
    lambda: (
        _stage1_batched(2),
        (
            sds((2, 4, 512), "uint8"),
            sds((2, 4), "float32"),
            sds((3, 2, 4), "int32"),  # vmapped over DM groups
        ),
        {},
    ),
    param=_param_stage1_batched,
)
register_program(
    "ops.dedisperse.subband_stage2",
    lambda: (
        _stage2_batched(192, True, 1.0),
        (
            sds((2, 4, 4, 128), "float32"),  # (G, S, T/128, 128) blocked
            sds((2, 3, 4), "int32"),  # (G, D, S) stage-2 delays
        ),
        {},
    ),
    param=_param_stage2_batched,
)


def _param_dedisperse_matmul(ctx):
    # the banded-matmul engine's unit of work (the planner's third
    # alternative): one MATMUL_BLOCK trial chunk at the bucket's padded
    # window geometry. Declines ctxs whose resolved plan names another
    # engine — warmup compiles what the driver will dispatch.
    if ctx.dedisp_engine not in ("", "matmul"):
        return None
    d = max(1, min(MATMUL_BLOCK, ctx.ndm))
    band = MATMUL_BAND_QUANT
    return (
        dedisperse_matmul_block,
        (
            sds((ctx.nsamps + band, ctx.nchans), "uint8"),
            sds((ctx.nchans,), "int32"),
            sds((d, ctx.nchans, band), "float32"),
            sds((ctx.nchans,), "float32"),
        ),
        {
            "out_nsamps": ctx.out_nsamps,
            "scale": output_scale(ctx.nbits, ctx.nchans),
        },
    )


register_program(
    "ops.dedisperse.dedisperse_matmul_block",
    lambda: (
        dedisperse_matmul_block,
        (
            sds((256, 8), "uint8"),
            sds((8,), "int32"),
            sds((4, 8, 8), "float32"),
            sds((8,), "float32"),
        ),
        {"out_nsamps": 192},
    ),
    param=_param_dedisperse_matmul,
)


def _param_subband_matmul(ctx):
    """Shared geometry for the subband matmul-stage hooks: the tuned
    plan must have selected the matmul-staged subband engine."""
    if ctx.subbands <= 0 or not ctx.subband_matmul:
        return None
    c = ctx.nchans
    w = -(-c // max(1, min(ctx.subbands, c)))
    nsub = -(-c // w)
    nb1 = -(-ctx.out_nsamps // 128) + 2
    tpad = (-(-ctx.nsamps // 128) + 3) * 128
    return nsub, w, nb1, tpad


def _param_stage1_matmul(ctx):
    geo = _param_subband_matmul(ctx)
    if geo is None:
        return None
    nsub, w, nb1, tpad = geo
    return (
        _stage1_matmul_batched(nb1 * 128, MATMUL_BAND_QUANT),
        (
            sds((nsub, w, tpad), "uint8"),
            sds((nsub, w), "float32"),
            sds((nsub, w), "int32"),
            sds((4, nsub, w, MATMUL_BAND_QUANT), "float32"),
        ),
        {},
    )


def _param_stage2_matmul(ctx):
    geo = _param_subband_matmul(ctx)
    if geo is None:
        return None
    nsub, w, nb1, tpad = geo
    return (
        _stage2_matmul_batched(
            ctx.out_nsamps, True, output_scale(ctx.nbits, ctx.nchans),
            MATMUL_BAND_QUANT,
        ),
        (
            sds((4, nsub, nb1, 128), "float32"),
            sds((4, nsub), "int32"),
            sds((4, 8, nsub, MATMUL_BAND_QUANT), "float32"),
        ),
        {},
    )


register_program(
    "ops.dedisperse.subband_stage1_matmul",
    lambda: (
        _stage1_matmul_batched(256, 8),
        (
            sds((2, 4, 512), "uint8"),  # (S, w, T) grouped filterbank
            sds((2, 4), "float32"),  # (S, w) killmask
            sds((2, 4), "int32"),  # (S, w) batch-min intra-band delays
            sds((3, 2, 4, 8), "float32"),  # (G, S, w, band) one-hot
        ),
        {},
    ),
    param=_param_stage1_matmul,
)
register_program(
    "ops.dedisperse.subband_stage2_matmul",
    lambda: (
        _stage2_matmul_batched(192, True, 1.0, 8),
        (
            sds((2, 4, 4, 128), "float32"),  # (G, S, nb1, 128) stage-1 sums
            sds((2, 4), "int32"),  # (G, S) group-min stage-2 delays
            sds((2, 3, 4, 8), "float32"),  # (G, D, S, band) one-hot
        ),
        {},
    ),
    param=_param_stage2_matmul,
)
