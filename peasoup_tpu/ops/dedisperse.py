"""Incoherent dedispersion as a batched XLA gather/reduce.

The reference delegates this to the external `dedisp` CUDA library
(reference: include/transforms/dedisperser.hpp:98-113). TPU-native
design: the (DM trial, channel) delay table becomes a per-channel
dynamic-slice of the (time, channel) filterbank, summed over channels —
one jitted program batched over a DM-trial block, which XLA lowers to
large fused gathers feeding the VPU. No scalar loops, static shapes.

Output matches the reference's u8 trials when ``quantize=True``
(dedisp is called with 8-bit output; for <=6-bit inputs with <=64
channels raw channel sums fit u8 exactly).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("out_nsamps", "quantize", "scale"))
def dedisperse_block(
    fil_tc: jax.Array,  # (T, C) uint8/float32 filterbank samples
    delays: jax.Array,  # (D, C) int32 per-trial per-channel delay in samples
    killmask: jax.Array,  # (C,) int32/float32, 1 = keep
    *,
    out_nsamps: int,
    quantize: bool = True,
    scale: float = 1.0,
) -> jax.Array:
    """Dedisperse one block of DM trials: out[d, t] = sum_c x[t + delay[d,c], c].

    ``scale`` rescales channel sums into the u8 output range like dedisp's
    8-bit output mode; use :func:`output_scale` for a data-independent
    factor (1.0 for the 2-bit golden data, keeping raw-sum parity).
    Returns (D, out_nsamps) u8 (quantize=True) or f32.
    """
    x_ct = fil_tc.astype(jnp.float32).T * killmask.astype(jnp.float32)[:, None]

    # accumulate channel by channel with a lax.scan: a (D, C, T_out)
    # shifted tensor would not fit HBM at survey scale (XLA materialises
    # vmapped dynamic slices before reducing), while the (D, T_out)
    # carry is one trial block. Channel sums of <=8-bit samples are
    # exact integers in f32, so the summation order cannot change the
    # result.
    def one_channel(row: jax.Array, delay: jax.Array) -> jax.Array:
        return jax.lax.dynamic_slice_in_dim(row, delay, out_nsamps)

    def body(acc, cin):
        row, dcol = cin  # (T,) samples, (D,) per-trial delays
        return acc + jax.vmap(lambda d: one_channel(row, d))(dcol), None

    acc0 = jnp.zeros((delays.shape[0], out_nsamps), jnp.float32)
    out, _ = jax.lax.scan(body, acc0, (x_ct, delays.T))  # (D, T_out)
    if scale != 1.0:
        out = out * jnp.float32(scale)
    if quantize:
        out = jnp.clip(jnp.rint(out), 0, 255).astype(jnp.uint8)
    return out


@partial(jax.jit, static_argnames=("nbits", "nsamps", "nchans"))
def unpack_fil_device(
    raw: jax.Array, *, nbits: int, nsamps: int, nchans: int
) -> jax.Array:
    """Unpack sub-byte filterbank samples ON DEVICE (LSB-first within
    each byte, matching io.sigproc.unpack_bits and libdedisp's sub-word
    extraction). The host uploads the PACKED bytes — 4x less
    host->device traffic for 2-bit data — exactly as the reference
    hands dedisp the packed filterbank and unpacks on the GPU."""
    per = 8 // nbits
    shifts = (jnp.arange(per, dtype=jnp.uint8) * nbits)[None, :]
    w = (raw[:, None] >> shifts) & jnp.uint8((1 << nbits) - 1)
    return w.reshape(nsamps, nchans)


def fil_to_device(fil) -> jax.Array:
    """Stage a Filterbank's samples on device, uploading packed bytes
    when the file had sub-byte samples."""
    raw = getattr(fil, "raw", None)
    if raw is not None and fil.nbits in (1, 2, 4):
        return unpack_fil_device(
            jnp.asarray(raw), nbits=fil.nbits, nsamps=fil.nsamps,
            nchans=fil.nchans,
        )
    return jnp.asarray(fil.data)


def output_scale(nbits: int, nchans_kept: int) -> float:
    """Data-independent factor keeping worst-case channel sums inside u8.

    1.0 whenever raw sums already fit (e.g. 2-bit x 64 channels = 192),
    else shrink so the maximum possible sum maps to 255.
    """
    max_sum = (2**nbits - 1) * max(1, nchans_kept)
    return 1.0 if max_sum <= 255 else 255.0 / max_sum


def dedisperse_device(
    fil_tc: np.ndarray,
    delays: np.ndarray,
    killmask: np.ndarray,
    out_nsamps: int,
    *,
    quantize: bool = True,
    scale: float = 1.0,
    block: int = 16,
) -> jax.Array:
    """Dedisperse all DM trials in device-sized blocks, keeping the
    (ndm, out_nsamps) result RESIDENT on device.

    The filterbank is transferred once and the trials never round-trip
    through the host — the search slices trial rows on device (the
    reference instead keeps trials in host RAM and re-uploads each one,
    timeseries.hpp:335-344). Blocks bound peak HBM ((block+1) * T * 4
    bytes of working set).
    """
    ndm = delays.shape[0]
    fil_dev = jnp.asarray(fil_tc)
    kill_dev = jnp.asarray(killmask)
    outs = []
    for start in range(0, ndm, block):
        d = np.asarray(delays[start : start + block], dtype=np.int32)
        pad = 0
        if len(d) < block:  # pad to a fixed block shape to avoid recompiles
            pad = block - len(d)
            d = np.pad(d, ((0, pad), (0, 0)))
        res = dedisperse_block(
            fil_dev,
            jnp.asarray(d),
            kill_dev,
            out_nsamps=out_nsamps,
            quantize=quantize,
            scale=scale,
        )
        outs.append(res[: block - pad] if pad else res)
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)


def dedisperse(
    fil_tc: np.ndarray,
    delays: np.ndarray,
    killmask: np.ndarray,
    out_nsamps: int,
    *,
    quantize: bool = True,
    scale: float = 1.0,
    block: int = 16,
) -> np.ndarray:
    """Host-resident variant: trials are fetched per device block, so
    HBM never holds more than one block (for surveys whose full trial
    set would crowd the chip; cf. reference host-RAM trials,
    dedisperser.hpp:101-103)."""
    ndm = delays.shape[0]
    fil_dev = jnp.asarray(fil_tc)
    kill_dev = jnp.asarray(killmask)
    outs = []
    for start in range(0, ndm, block):
        d = np.asarray(delays[start : start + block], dtype=np.int32)
        pad = 0
        if len(d) < block:
            pad = block - len(d)
            d = np.pad(d, ((0, pad), (0, 0)))
        res = np.asarray(
            dedisperse_block(
                fil_dev, jnp.asarray(d), kill_dev,
                out_nsamps=out_nsamps, quantize=quantize, scale=scale,
            )
        )
        outs.append(res[: block - pad] if pad else res)
    return np.concatenate(outs, axis=0)
