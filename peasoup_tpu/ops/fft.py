"""Real FFT for the per-accel hot path: a packed-real four-step matmul
rfft that beats XLA's TPU FFT on both axes.

XLA lowers TPU FFTs to matmul passes too, but its radix-128
decomposition for a 2^17-point real transform moves ~18.5 MB/trial in
transpose/copy passes (measured by trace `raw_bytes_accessed`,
NOTES.md) and its accuracy is the known TPU-FFT ~1e-5..1e-3 envelope.
This formulation packs the real series into a half-length complex
sequence (z[m] = x[2m] + i*x[2m+1]), runs ONE four-step complex DFT
(two dense (sqrt(M), sqrt(M)) MXU einsums at Precision.HIGHEST with a
twiddle multiply between), and untwists to the true rfft bins.
Measured on v5e at (1416, 131072): 27.8 ms device vs 48.5 ms for
jnp.fft.rfft — 1.75x — with max rel error 1.4e-6 vs the f64 oracle
(~35x tighter than stock, which also tightens candidate S/N parity).

Gating: the matmul path needs a power-of-two length >= _MIN_N and only
wins on TPU-class backends (on CPU its O(N^1.5) arithmetic would bury
pocketfft); everything else falls back to jnp.fft.rfft.
"""

from __future__ import annotations

import os as _os
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

_MIN_N = 1 << 14

# Matmul precision for the packed four-step DFT einsums. Measured trade
# (NOTES.md round-4 continuation): the chain is layout-bound, so HIGH
# buys only ~3 ms while perturbing the S/N chain the acc-tie parity
# analysis is anchored to — HIGHEST stays the default; the knob records
# the option. Read and validated ONCE at import, like the module's
# other knobs (PEASOUP_MATMUL_FFT, PEASOUP_PEAKS_SUB): it feeds traced
# code, so a post-compile change could never take effect anyway — set
# it before the first import.
_PREC_CHOICES = {
    "highest": jax.lax.Precision.HIGHEST,
    "high": jax.lax.Precision.HIGH,
    "default": jax.lax.Precision.DEFAULT,
}
_PREC_NAME = _os.environ.get("PEASOUP_FFT_PRECISION", "highest").lower()
if _PREC_NAME not in _PREC_CHOICES:
    raise ValueError(
        f"PEASOUP_FFT_PRECISION must be one of {sorted(_PREC_CHOICES)}, "
        f"got {_PREC_NAME!r}"
    )
_PRECISION = _PREC_CHOICES[_PREC_NAME]


@lru_cache(maxsize=None)
def _plan(n: int):
    """DFT/twiddle/untwist constants for the packed four-step rfft of a
    pow2 length ``n``: M = n/2 = N1*N2 with N1 = 2^floor(log2(sqrt(M)))."""
    m = n // 2
    n1 = 1 << ((m.bit_length() - 1) // 2)
    n2 = m // n1
    w1 = np.exp(-2j * np.pi * np.outer(np.arange(n1), np.arange(n1)) / n1)
    w2 = np.exp(-2j * np.pi * np.outer(np.arange(n2), np.arange(n2)) / n2)
    tw = np.exp(-2j * np.pi * np.outer(np.arange(n1), np.arange(n2)) / m)
    k = np.arange(m + 1)
    # untwist phasor e^{-i theta_k} = unc - i*uns (uns = +sin theta_k)
    un = np.exp(-2j * np.pi * k / n)
    return {
        "n1": n1,
        "n2": n2,
        "d1r": np.ascontiguousarray(w1.real, np.float32),
        "d1i": np.ascontiguousarray(w1.imag, np.float32),
        "d2r": np.ascontiguousarray(w2.real, np.float32),
        "d2i": np.ascontiguousarray(w2.imag, np.float32),
        "twr": np.ascontiguousarray(tw.real, np.float32),
        "twi": np.ascontiguousarray(tw.imag, np.float32),
        "unc": np.ascontiguousarray(un.real, np.float32),
        "uns": np.ascontiguousarray(-un.imag, np.float32),
    }


def packed_dft_z(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The matmul four-step half-length packed complex DFT: returns
    (zr, zi), each (R, n//2) f32 with the batch flattened, Z in natural
    bin order. The untwist to rfft bins is left to the caller — either
    the jnp formulas below or the fused Pallas interbin kernel
    (ops/pallas/interbin.py)."""
    m = x.shape[-1] // 2
    # materialise the input ONCE: without the barrier XLA fuses the
    # producer chain (e.g. the resample select) separately into the
    # even- and odd-sample operands, computing it twice (measured:
    # resample_select 1.9 -> 94 ms when this fed the deinterleave)
    x = jax.lax.optimization_barrier(x.astype(jnp.float32))
    z = x.reshape(-1, m, 2)
    return packed_dft_z_parts(z[..., 0], z[..., 1])


def packed_dft_z_parts(
    xe: jnp.ndarray, xo: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """:func:`packed_dft_z` on pre-deinterleaved even/odd sample planes
    (..., n//2) — producers that can emit the planes directly (e.g.
    resample_select_packed) skip the stride-2 relayout entirely."""
    # one joint barrier: each plane feeds two einsum operands, and
    # without it XLA would fuse (= recompute) the producer chain into
    # every operand (see packed_dft_z)
    xe, xo = jax.lax.optimization_barrier((xe, xo))
    m = xe.shape[-1]
    n = 2 * m
    p = _plan(n)
    n1, n2 = p["n1"], p["n2"]
    P = _PRECISION
    d1r, d1i = jnp.asarray(p["d1r"]), jnp.asarray(p["d1i"])
    d2r, d2i = jnp.asarray(p["d2r"]), jnp.asarray(p["d2i"])
    twr, twi = jnp.asarray(p["twr"]), jnp.asarray(p["twi"])

    ar = xe.reshape(-1, n1, n2)  # A[j1, j2] = z[j1*n2 + j2]
    ai = xo.reshape(-1, n1, n2)
    # step 1: DFT over j1 (columns)  C[k1, j2] = sum_j1 W1[k1,j1] A[j1,j2]
    f1 = lambda D, A: jnp.einsum("lj,rjm->rlm", D, A, precision=P)
    cr = f1(d1r, ar) - f1(d1i, ai)
    ci = f1(d1r, ai) + f1(d1i, ar)
    # step 2: twiddle W_M^{k1*j2}
    tr = cr * twr - ci * twi
    ti = cr * twi + ci * twr
    # step 3: DFT over j2, emitted K2-MAJOR so the flat k = k1 + N1*k2
    # order falls out of a plain reshape (no transpose pass)
    f2 = lambda A, D: jnp.einsum("rlj,jk->rkl", A, D, precision=P)
    er = f2(tr, d2r) - f2(ti, d2i)
    ei = f2(tr, d2i) + f2(ti, d2r)
    zr = er.reshape(-1, m)  # (r, k2, k1) -> k = k1 + N1*k2
    zi = ei.reshape(-1, m)
    return zr, zi


def rfft_pow2_matmul_parts(
    x: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """rfft via the packed four-step matmul DFT, returned as lazy
    (re, im) f32 parts so elementwise consumers (interbin) fuse with
    the untwist instead of reading a materialised complex array."""
    n = x.shape[-1]
    m = n // 2
    p = _plan(n)
    batch = x.shape[:-1]
    zr, zi = packed_dft_z(x)

    # untwist the packed transform to the real-input spectrum:
    # X[k] = (Z[k] + conj(Z[M-k]))/2 - i/2 e^{-2pi i k/n}(Z[k] - conj(Z[M-k]))
    zkr = jnp.concatenate([zr, zr[..., :1]], axis=-1)  # Z[k], k = 0..M
    zki = jnp.concatenate([zi, zi[..., :1]], axis=-1)
    zmr = jnp.concatenate([zr[..., :1], zr[..., ::-1]], axis=-1)  # Z[M-k]
    zmi = jnp.concatenate([zi[..., :1], zi[..., ::-1]], axis=-1)
    arr = 0.5 * (zkr + zmr)
    aii = 0.5 * (zki - zmi)
    br = zkr - zmr
    bi = zki + zmi
    c = jnp.asarray(p["unc"])
    s = jnp.asarray(p["uns"])
    xr = arr + 0.5 * (c * bi - s * br)
    xi = aii - 0.5 * (c * br + s * bi)
    return xr.reshape(*batch, m + 1), xi.reshape(*batch, m + 1)


def rfft_pow2_matmul(x: jnp.ndarray) -> jnp.ndarray:
    """rfft of a pow2-length f32 series via the packed four-step matmul
    DFT; returns complex64 (..., n//2+1) like jnp.fft.rfft."""
    xr, xi = rfft_pow2_matmul_parts(x)
    return jax.lax.complex(xr, xi)


def _use_matmul(n: int) -> bool:
    # Opt-in (PEASOUP_MATMUL_FFT=1): standalone the matmul rfft beats
    # XLA's TPU FFT 1.75x at 35x better accuracy, but in the search
    # pipeline the pack/untwist passes offset the matmul win (measured
    # 280 vs 270 ms total device) and candidate parity is insensitive
    # to the per-accel FFT's accuracy (the residual lives in the
    # per-DM stats/whiten chain and CUDA's own f32 error) — so the
    # stock FFT stays the default.  See NOTES.md.
    import os

    if os.environ.get("PEASOUP_MATMUL_FFT", "0") != "1":
        return False
    if n < _MIN_N or n & (n - 1):
        return False
    try:
        platform = jax.default_backend()
    except Exception:
        return False
    # whitelist TPU-class backends: only v5e was measured to win; on a
    # GPU this would silently swap cuFFT for an O(N^1.5) dense DFT
    return platform in ("tpu", "axon")


def rfft(x: jnp.ndarray) -> jnp.ndarray:
    """Drop-in jnp.fft.rfft over the last axis, routed to the matmul
    four-step on accelerator backends for pow2 lengths >= 2^14."""
    if _use_matmul(x.shape[-1]):
        return rfft_pow2_matmul(x)
    return jnp.fft.rfft(x)
