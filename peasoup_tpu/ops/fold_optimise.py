"""Fold optimisation (mini-PDMP): phase-shift x boxcar-template matched
filtering of folded subintegrations.

Reference: FoldOptimiser (include/transforms/folder.hpp:65-335) and its
kernels (src/kernels.cu:653-865). Pipeline per fold:
  FFT subints along phase -> multiply by nshifts linear phase ramps
  (subint-proportional shift) -> collapse subints -> multiply by
  ntemplates FFT'd boxcars (/ sqrt(width), bin0 zeroed) -> inverse FFT
  -> |.| -> 3-D argmax (template, shift, bin) -> S/N from on/off-pulse
  statistics of the recovered profile.

TPU design: everything becomes a handful of batched einsum/FFT ops on
(K, nshifts, nints, nbins) tensors — K candidates are optimised in ONE
jitted call instead of the reference's one-candidate-at-a-time loop.
Quirks preserved for parity: the (32 - opt_shift) period-update constant
(folder.hpp:330, assumes nbins=64), calculate_sn's width coming from the
0-based template index, and S/N values > 99999 squashed to 0.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _shift_array(nbins: int, nints: int) -> np.ndarray:
    """(nshifts, nints, nbins) complex64 phase ramps (kernels.cu:665-684)."""
    nshifts = nbins
    shift_mags = np.arange(nshifts, dtype=np.float64) - nshifts // 2
    subint = np.arange(nints, dtype=np.float64)
    b = np.arange(nbins, dtype=np.float64)
    ramp = b * 2.0 * np.pi / nbins
    ramp = np.where(b > nbins / 2, ramp - 2.0 * np.pi, ramp)
    shift = (subint / nints)[None, :, None] * shift_mags[:, None, None]
    return np.exp(-1j * ramp[None, None, :] * shift).astype(np.complex64)


def _templates_fft(nbins: int) -> tuple[np.ndarray, int]:
    """FFT'd boxcar templates (ntemplates, nbins) (kernels.cu:686-696)."""
    ntemplates = nbins - 1
    w = np.arange(ntemplates)[:, None]
    b = np.arange(nbins)[None, :]
    boxcars = (b <= w).astype(np.complex64)
    return np.fft.fft(boxcars, axis=-1).astype(np.complex64), ntemplates


@partial(jax.jit, static_argnames=("nbins", "nints"))
def _optimise_device(
    folds: jnp.ndarray,  # (K, nints, nbins) float32
    shiftar_re: jnp.ndarray,  # (nshifts, nints, nbins) float32
    shiftar_im: jnp.ndarray,
    templates_re: jnp.ndarray,  # (ntemplates, nbins) float32
    templates_im: jnp.ndarray,
    *,
    nbins: int,
    nints: int,
):
    # complex tables are shipped as re/im pairs: the axon TPU transfer
    # path does not support complex dtypes across host<->device
    shiftar = jax.lax.complex(shiftar_re, shiftar_im)
    templates = jax.lax.complex(templates_re, templates_im)
    nshifts = nbins
    f = jnp.fft.fft(folds.astype(jnp.complex64), axis=-1)  # (K, I, B)
    shifted = f[:, None, :, :] * shiftar[None, :, :, :]  # (K, S, I, B)
    profiles = shifted.sum(axis=2)  # (K, S, B) collapse subints
    width = jnp.sqrt(jnp.arange(1, templates.shape[0] + 1, dtype=jnp.float32))
    final = (
        profiles[:, None, :, :]
        * templates[None, :, None, :]
        / width[None, :, None, None]
    )  # (K, W, S, B)
    final = final.at[..., 0].set(0.0)  # bin0 zeroed (kernels.cu:741-742)
    # cuFFT INVERSE is unnormalised; only |.| feeds argmax, so the
    # constant nbins factor is irrelevant here.
    tdom = jnp.abs(jnp.fft.ifft(final, axis=-1))
    flat = tdom.reshape(tdom.shape[0], -1)
    argmax = jnp.argmax(flat, axis=-1).astype(jnp.int32)
    opt_template = argmax // (nbins * nshifts)
    opt_bin = argmax % nbins - opt_template // 2
    opt_shift = (argmax // nbins) % nbins
    # Recover optimal subints and profile (unnormalised inverse -> *nbins
    # to match the reference's stored fold amplitudes).
    k = jnp.arange(folds.shape[0])
    opt_subs = (
        jnp.fft.ifft(shifted[k, opt_shift], axis=-1).real * nbins
    )  # (K, I, B)
    opt_prof = jnp.fft.ifft(profiles[k, opt_shift], axis=-1).real * nbins  # (K, B)
    return opt_template, opt_bin, opt_shift, opt_subs, opt_prof


def calculate_sn(
    prof: np.ndarray, bin: int, width: int, nbins: int
) -> tuple[float, float]:
    """On/off-pulse S/N of a profile (folder.hpp:140-183).

    ``width`` is the 0-based template index, as passed by the reference's
    optimise() (folder.hpp:311). Negative centred indices wrap positively
    here (the reference's C % would go out of bounds — UB we do not copy).
    """
    edge = int(width * 0.3 + 0.5)
    width_by_2 = int(width / 2.0 + 0.5)
    rprof = np.array(
        [prof[(bin - nbins // 2 + ii) % nbins] for ii in range(nbins)],
        dtype=prof.dtype,
    )
    centre = nbins // 2 - 1
    upper = centre + (width_by_2 + edge)
    lower = centre - (width_by_2 + edge)
    sel = (np.arange(nbins) <= upper) & (np.arange(nbins) >= lower)
    on, off = rprof[sel], rprof[~sel]
    on_mean = on.mean()
    off_mean = off.mean()
    off_std = np.sqrt(np.mean((off - off_mean) ** 2))
    with np.errstate(divide="ignore", invalid="ignore"):
        sn1 = (on_mean - off_mean) * np.sqrt(width) / off_std
        sn2 = ((rprof - off_mean) / off_std).sum() / np.sqrt(width)
    sn1 = 0.0 if not np.isfinite(sn1) or sn1 > 99999 else float(sn1)
    sn2 = 0.0 if not np.isfinite(sn2) or sn2 > 99999 else float(sn2)
    return sn1, sn2


class FoldOptimiser:
    """Batched fold optimiser; one device call for K candidates."""

    def __init__(self, nbins: int = 64, nints: int = 16):
        self.nbins = nbins
        self.nints = nints
        shiftar = _shift_array(nbins, nints)
        self.shiftar_re = jnp.asarray(np.real(shiftar).astype(np.float32))
        self.shiftar_im = jnp.asarray(np.imag(shiftar).astype(np.float32))
        templates, self.ntemplates = _templates_fft(nbins)
        self.templates_re = jnp.asarray(np.real(templates).astype(np.float32))
        self.templates_im = jnp.asarray(np.imag(templates).astype(np.float32))

    def optimise(
        self, folds: np.ndarray, periods: np.ndarray, tobs
    ) -> list[dict]:
        """Optimise K folded candidates.

        Args:
          folds: (K, nints, nbins) fold profiles.
          periods: (K,) trial periods in seconds.
          tobs: observation length (seconds) — a scalar, or a (K,)
            array when the batch mixes observations of different
            lengths (the survey folder's cross-observation batches).

        Returns one dict per candidate: opt_sn, opt_period, opt_width,
        opt_bin, opt_fold (nints, nbins), opt_prof (nbins,).
        """
        folds = jnp.asarray(np.asarray(folds, dtype=np.float32))
        opt_template, opt_bin, opt_shift, opt_subs, opt_prof = _optimise_device(
            folds,
            self.shiftar_re,
            self.shiftar_im,
            self.templates_re,
            self.templates_im,
            nbins=self.nbins,
            nints=self.nints,
        )
        opt_template = np.asarray(opt_template)
        opt_bin = np.asarray(opt_bin)
        opt_shift = np.asarray(opt_shift)
        opt_subs = np.asarray(opt_subs)
        opt_prof = np.asarray(opt_prof)
        tobs_k = np.broadcast_to(
            np.asarray(tobs, dtype=np.float64), (folds.shape[0],)
        )
        results = []
        for k in range(folds.shape[0]):
            sn1, sn2 = calculate_sn(
                opt_prof[k], int(opt_bin[k]), int(opt_template[k]), self.nbins
            )
            p = float(periods[k])
            opt_period = p * (((32.0 - float(opt_shift[k])) * p) / (self.nbins * float(tobs_k[k])) + 1.0)
            results.append(
                dict(
                    opt_sn=max(sn1, sn2),
                    opt_period=opt_period,
                    opt_width=int(opt_template[k]) + 1,
                    opt_bin=int(opt_bin[k]),
                    opt_shift=int(opt_shift[k]),
                    opt_fold=opt_subs[k],
                    opt_prof=opt_prof[k],
                )
            )
        return results


# --- audit registry: the shift/template operands come from the module's
# own host precompute (tiny at nbins=32) so the registered shapes stay
# consistent with the builders ---
from .registry import register_program  # noqa: E402


def _example_optimise(batch: int = 2, nbins: int = 32, nints: int = 8):
    import jax

    shiftar = _shift_array(nbins, nints)
    templates, _ = _templates_fft(nbins)
    return (
        _optimise_device,
        (
            jax.ShapeDtypeStruct((batch, nints, nbins), np.float32),
            shiftar.real.astype(np.float32),
            shiftar.imag.astype(np.float32),
            templates.real.astype(np.float32),
            templates.imag.astype(np.float32),
        ),
        {"nbins": nbins, "nints": nints},
    )


def _param_optimise(ctx):
    # candidate-level program: the fold bucket sets its geometry; the
    # candidate batch is rung-independent but bounded by fold_batch
    if ctx.fold_batch <= 0 or ctx.fold_nsamps <= 0:
        return None
    return _example_optimise(
        batch=max(2, min(ctx.fold_batch, 64)),
        nbins=ctx.fold_nbins,
        nints=ctx.fold_nints,
    )


register_program(
    "ops.fold_optimise.optimise_device", _example_optimise,
    param=_param_optimise,
)
