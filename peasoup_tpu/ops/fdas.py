"""The Fourier-domain acceleration-search (FDAS) device program.

Where the time-domain path (pipeline/accel_search.py) re-resamples
and re-FFTs the time series once per acceleration trial, FDAS forms
ONE dereddened/zapped spectrum per DM trial and recovers every
(f-dot, f-ddot) trial by correlating that spectrum against a bank of
finite-duration response templates (peasoup_tpu/fdas/templates.py) —
batched complex multiplies in the frequency domain, an MXU-friendly
shape. The whole (DM block x template batch) tile is one jitted
program: overlap-save correlation, interbin power, normalisation,
harmonic summing and per-level peak compaction stay fused; Python
only ever sees static-size peak sets.

Template rows are independent, so any row-split of the bank produces
bitwise-identical outputs — the OOM ladder in pipeline/fdas.py halves
the template batch under device pressure without perturbing results
(the halving-bitwise test in tests/test_fdas.py pins this).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .harmonics import harmonic_sums
from .peaks import cluster_peaks_device, find_peaks_device
from .rednoise import whiten_fseries
from .spectrum import form_interpolated, normalise, spectrum_stats
from .zap import zap_birdies


class FdasPeaks(NamedTuple):
    """Static-size peak sets for a block of DM trials.

    idxs/snrs: (D, nharms+1, T, max_peaks) — level 0 is the template
    correlation power itself, level h the 2^h-harmonic sum; T indexes
    the template (f-dot/f-ddot trial) batch. counts: (D, nharms+1, T)
    raw threshold crossings (overflow signal); ccounts the min-gap
    cluster counts actually compacted into idxs/snrs.
    """

    idxs: jax.Array
    snrs: jax.Array
    counts: jax.Array
    ccounts: jax.Array


def _pad_trial(tim, *, size, nsamps_valid):
    """Pad/truncate one trial to ``size`` with the mean-padded tail
    (same formula as pipeline/accel_search.py — ops/ must not import
    pipeline/, so the three lines are duplicated, pinned equal by the
    z=0 parity test)."""
    x = tim[:size].astype(jnp.float32)
    if nsamps_valid < size:
        x = jnp.pad(x, (0, size - x.shape[0]))
        mean_head = jnp.mean(x[:nsamps_valid])
        idx = jnp.arange(size)
        x = jnp.where(idx < nsamps_valid, x, mean_head)
    return x


# FFT-batch row alignment: every batched FFT inside correlate_bank
# runs over a template axis padded to this multiple, so the flattened
# transform count is lane-aligned for ANY template-batch size. Without
# it the backend's remainder path (the `batch mod unroll` tail rows)
# computes the same transforms through a differently-vectorised code
# path, and a template-batch split stops being bitwise-neutral — the
# property the OOM ladder's halving rung relies on.
_ROW_ALIGN = 8


def correlate_bank(fser, tmpl, *, segment):
    """Overlap-save correlation of one complex spectrum against every
    template row: out[t, r] = sum_j fser[r - half + j] * conj(tmpl[t, j])
    with ``half = (width-1)//2`` — the matched-filter output centred on
    bin r, for all nbins r and all T templates.

    The spectrum is cut into ``segment``-length windows advancing by
    ``step = segment - (width - 1)`` bins; each window's circular FFT
    correlation is valid (wraparound-free) on its first ``step``
    outputs, which tile the full output exactly. ``segment`` is a
    static power of two (fdas/templates.py:auto_segment), so the FFTs
    stay in the sizes the fft machinery is fastest at and the compiled
    shape is independent of nbins' factorisation.

    Each template row's output depends only on that row (rows are
    padded to a lane-aligned count, see _ROW_ALIGN), so any row-batch
    split of the bank is bitwise-identical to the unsplit call —
    pinned by tests/test_fdas.py.
    """
    nbins = fser.shape[-1]
    ntmpl, width = tmpl.shape
    half = (width - 1) // 2
    step = segment - (width - 1)
    if step <= 0:
        raise ValueError(
            f"segment {segment} too short for template width {width}"
        )
    tpad = -(-ntmpl // _ROW_ALIGN) * _ROW_ALIGN
    if tpad != ntmpl:
        tmpl = jnp.pad(tmpl, ((0, tpad - ntmpl), (0, 0)))
    nseg = -(-nbins // step)
    total = nseg * step + width - 1
    fpad = jnp.pad(fser, (half, total - nbins - half))
    starts = jnp.arange(nseg) * step
    segs = fpad[starts[:, None] + jnp.arange(segment)[None, :]]
    tf = jnp.conj(jnp.fft.fft(tmpl, n=segment, axis=-1))  # (tpad, segment)
    sf = jnp.fft.fft(segs, axis=-1)  # (nseg, segment)
    y = jnp.fft.ifft(sf[None, :, :] * tf[:, None, :], axis=-1)
    y = y[..., :step].reshape(tpad, nseg * step)[:ntmpl, :nbins]
    return y.astype(jnp.complex64)


def fdas_trial_core(
    tim: jax.Array,  # (>=size,) u8/f32 dedispersed time series
    tmpl: jax.Array,  # (T, width) c64 template batch (unit energy)
    zapmask: jax.Array,  # (size//2+1,) bool birdie mask
    windows: jax.Array,  # (nharms+1, 2) i32 [start, limit) per level
    *,
    threshold: float,
    size: int,
    nsamps_valid: int,
    segment: int,
    nharms: int,
    max_peaks: int,
    pos5: int,
    pos25: int,
):
    """Pure FDAS body for one DM trial; vmap-compatible. Returns
    per-level (nharms+1, T, max_peaks) peak sets."""
    x = _pad_trial(tim, size=size, nsamps_valid=nsamps_valid)
    fser = whiten_fseries(x, pos5=pos5, pos25=pos25)
    fser = zap_birdies(fser, zapmask)
    # normalisation stats come from the ZERO-drift spectrum (identical
    # to the plain chain's), so every template row is scored against
    # the same noise floor and the z=0 row reproduces the plain search
    s0 = form_interpolated(fser)
    mean, _, std = spectrum_stats(s0)
    with jax.named_scope("FDAS-Correlate"):
        corr = correlate_bank(fser, tmpl, segment=segment)  # (T, nbins)
    s = form_interpolated(corr)
    s = normalise(s, mean, std)
    with jax.named_scope("Harmonic summing"):
        sums = harmonic_sums(s, nharms=nharms, scaled=True)
    levels = [s] + sums
    idxs, snrs, counts, ccounts = [], [], [], []
    nbins = size // 2 + 1
    with jax.named_scope("Peaks"):
        for lvl, spec in enumerate(levels):
            i_, s_, c_ = find_peaks_device(
                spec,
                jnp.float32(threshold),
                windows[lvl, 0],
                windows[lvl, 1],
                max_peaks=max_peaks,
            )
            i_, s_, cc_ = cluster_peaks_device(i_, s_, jnp.int32(nbins))
            idxs.append(i_)
            snrs.append(s_)
            counts.append(c_)
            ccounts.append(cc_)
    return (
        jnp.stack(idxs, axis=0),
        jnp.stack(snrs, axis=0),
        jnp.stack(counts, axis=0),
        jnp.stack(ccounts, axis=0),
    )


def fdas_block_core(
    tims: jax.Array,  # (D, >=size) dedispersed time-series block
    tmpl: jax.Array,  # (T, width) c64 template batch
    zapmask: jax.Array,
    windows: jax.Array,
    *,
    threshold: float,
    size: int,
    nsamps_valid: int,
    segment: int,
    nharms: int,
    max_peaks: int,
    pos5: int,
    pos25: int,
) -> FdasPeaks:
    """Block-batched FDAS: the (D, T) DM-x-template tile as one array
    program. The template batch is shared across the block (templates
    depend only on the bank geometry, not the DM trial)."""
    i_, s_, c_, cc_ = jax.vmap(
        lambda tim: fdas_trial_core(
            tim, tmpl, zapmask, windows,
            threshold=threshold, size=size, nsamps_valid=nsamps_valid,
            segment=segment, nharms=nharms, max_peaks=max_peaks,
            pos5=pos5, pos25=pos25,
        )
    )(tims)
    return FdasPeaks(idxs=i_, snrs=s_, counts=c_, ccounts=cc_)


@lru_cache(maxsize=None)
def make_fdas_search_fn(threshold: float):
    """Build the jitted FDAS block program with the S/N threshold
    bound statically. Cached so repeat runs with the same threshold
    reuse the compiled executable; the driver dispatches a fixed
    (dm_block, template_batch) tile so ONE compile covers the run."""

    @partial(
        jax.jit,
        static_argnames=(
            "size", "nsamps_valid", "segment", "nharms", "max_peaks",
            "pos5", "pos25",
        ),
    )
    def fdas_dm_block(tims, tmpl, zapmask, windows, *, size, nsamps_valid,
                      segment, nharms, max_peaks, pos5, pos25) -> FdasPeaks:
        return fdas_block_core(
            tims, tmpl, zapmask, windows,
            threshold=threshold, size=size, nsamps_valid=nsamps_valid,
            segment=segment, nharms=nharms, max_peaks=max_peaks,
            pos5=pos5, pos25=pos25,
        )

    return fdas_dm_block


# --- audit registry: representative build at toy shapes; the ShapeCtx
# hook rebuilds at a campaign fdas bucket's production geometry (the
# (dm_block, fdas_templates, fft_size, fdas_segment) tile derived by
# perf.warmup.shape_ctx_for_bucket from the SAME fdas/templates.py
# geometry formulas the driver uses), so AOT warmup compiles exactly
# the program pipeline/fdas.py will dispatch ---
from .registry import register_program, sds  # noqa: E402


def _fdas_width(ctx):
    """Template width implied by the ctx's zmax via the shared
    geometry formula — the bank builder, driver and this hook all call
    fdas/templates.py so the compiled shapes agree."""
    from ..fdas.templates import template_half_width

    return 2 * template_half_width(ctx.fdas_zmax) + 1


def _param_fdas(ctx):
    if ctx.fdas_templates <= 0 or ctx.fft_size <= 0:
        return None  # not an FDAS ctx
    width = _fdas_width(ctx)
    # the driver uploads trials[:, :min(size, out_nsamps)] — the traced
    # time axis is the VALID length, not the padded fft size
    tlen = min(ctx.out_nsamps or ctx.fft_size, ctx.fft_size)
    return (
        make_fdas_search_fn(float(ctx.min_snr)),
        (
            sds((ctx.dm_block, tlen), "uint8"),
            sds((ctx.fdas_templates, width), "complex64"),
            sds((ctx.fft_size // 2 + 1,), "bool"),
            sds((ctx.nharms + 1, 2), "int32"),
        ),
        {
            "size": ctx.fft_size,
            "nsamps_valid": tlen,
            "segment": ctx.fdas_segment,
            "nharms": ctx.nharms,
            "max_peaks": ctx.max_peaks,
            "pos5": ctx.pos5,
            "pos25": ctx.pos25,
        },
    )


register_program(
    "ops.fdas.fdas_correlate_search",
    lambda: (
        make_fdas_search_fn(6.0),
        (
            sds((2, 4096), "uint8"),
            sds((5, 65), "complex64"),
            sds((2049,), "bool"),
            sds((3, 2), "int32"),
        ),
        {
            "size": 4096, "nsamps_valid": 4096, "segment": 1024,
            "nharms": 2, "max_peaks": 32, "pos5": 2, "pos25": 10,
        },
    ),
    param=_param_fdas,
)
# segment is a STATIC knob (it sizes the overlap-save FFTs), so the
# registered form binds it via static_argnames — the contract engine
# traces exactly the executable the fused program inlines
_correlate_bank_jit = jax.jit(correlate_bank, static_argnames=("segment",))

register_program(
    "ops.fdas.correlate_bank",
    lambda: (
        _correlate_bank_jit,
        (sds((2049,), "complex64"), sds((5, 65), "complex64")),
        {"segment": 1024},
    ),
    param=lambda ctx: None if ctx.fdas_templates <= 0 else (
        _correlate_bank_jit,
        (
            sds((ctx.fft_size // 2 + 1,), "complex64"),
            sds((ctx.fdas_templates, _fdas_width(ctx)), "complex64"),
        ),
        {"segment": ctx.fdas_segment},
    ),
)
