"""Registry of the jitted device programs in :mod:`peasoup_tpu.ops`.

Every jitted entry point registers itself here with a **build thunk**
that returns ``(fn, args, kwargs)`` over a tiny representative shape
set (``ShapeDtypeStruct``\\ s — nothing is executed, only traced). The
audit's contract engine (:mod:`peasoup_tpu.analysis.contracts`)
abstract-evals each program and lints its jaxpr/StableHLO: no f64 ops,
no unexpected host callbacks or custom calls, no oversized baked-in
constants, donation matching the ``donate`` declaration.

Registration is a one-liner at the bottom of each ops module, next to
the program it describes, so adding a jitted entry point and
registering it is the same diff. The thunks are lazy: nothing touches
jax until the contract engine runs them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

# modules whose import populates the registry (ops/__init__ pulls in
# all of these; listed explicitly so collect() works even if the
# package re-exports change)
_PROGRAM_MODULES = (
    "peasoup_tpu.ops.dedisperse",
    "peasoup_tpu.ops.spectrum",
    "peasoup_tpu.ops.rednoise",
    "peasoup_tpu.ops.zap",
    "peasoup_tpu.ops.resample",
    "peasoup_tpu.ops.harmonics",
    "peasoup_tpu.ops.peaks",
    "peasoup_tpu.ops.fold",
    "peasoup_tpu.ops.fold_optimise",
    "peasoup_tpu.ops.singlepulse",
    "peasoup_tpu.ops.ffa",
    "peasoup_tpu.ops.coincidence",
    "peasoup_tpu.ops.correlate",
)


def sds(shape: tuple[int, ...], dtype: str):
    """Shorthand for a ShapeDtypeStruct in registry build thunks."""
    import jax
    import numpy as np

    return jax.ShapeDtypeStruct(shape, np.dtype(dtype))


@dataclass(frozen=True)
class ProgramSpec:
    """One registered jitted program.

    ``build`` returns ``(fn, args, kwargs)``; ``fn`` is either a
    jit-wrapped callable (has ``.trace``) or a plain traceable
    function the contract engine will wrap. ``donate`` lists argument
    indices the DRIVER relies on being donated — the contract engine
    fails the audit when declaration and lowering disagree in either
    direction. ``allow_custom_calls`` extends the global custom-call
    allowlist for this program only.
    """

    name: str
    build: Callable[[], tuple[Callable, tuple, dict[str, Any]]]
    donate: tuple[int, ...] = ()
    allow_custom_calls: tuple[str, ...] = ()


_REGISTRY: dict[str, ProgramSpec] = {}


def register_program(
    name: str,
    build: Callable[[], tuple[Callable, tuple, dict[str, Any]]],
    *,
    donate: tuple[int, ...] = (),
    allow_custom_calls: tuple[str, ...] = (),
) -> None:
    if name in _REGISTRY:
        raise ValueError(f"duplicate program registration: {name}")
    _REGISTRY[name] = ProgramSpec(
        name=name,
        build=build,
        donate=tuple(donate),
        allow_custom_calls=tuple(allow_custom_calls),
    )


def registered_programs() -> tuple[ProgramSpec, ...]:
    """All registered programs, importing the ops modules first so
    their registration side effects have happened."""
    import importlib

    for mod in _PROGRAM_MODULES:
        importlib.import_module(mod)
    return tuple(_REGISTRY[k] for k in sorted(_REGISTRY))
