"""Registry of the jitted device programs in :mod:`peasoup_tpu.ops`.

Every jitted entry point registers itself here with a **build thunk**
that returns ``(fn, args, kwargs)`` over a tiny representative shape
set (``ShapeDtypeStruct``\\ s — nothing is executed, only traced). The
registry feeds three consumers:

* the audit's contract engine (:mod:`peasoup_tpu.analysis.contracts`)
  abstract-evals each program and lints its jaxpr/StableHLO: no f64
  ops, no unexpected host callbacks or custom calls, no oversized
  baked-in constants, donation matching the ``donate`` declaration;
* the AOT warmup pass (:mod:`peasoup_tpu.perf.warmup`)
  ``lower().compile()``\\ s every program ahead of time, populating the
  persistent compilation cache so later processes cold-start warm —
  optionally at the **production shapes** of a campaign bucket via the
  per-program :class:`ShapeCtx` parameterisation hook;
* the per-program microbenchmarks (:mod:`peasoup_tpu.perf.microbench`)
  execute each program over materialised representative arrays and
  ratchet the timings in CI (``peasoup-perf``).

Registration is a one-liner at the bottom of each ops module, next to
the program it describes, so adding a jitted entry point and
registering it is the same diff — and :func:`unregistered_entry_points`
(gated in CI by ``peasoup-perf check`` and tests/test_perf.py) catches
any top-level jitted program that skips it. The thunks are lazy:
nothing touches jax until a consumer runs them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

# modules whose import populates the registry (ops/__init__ pulls in
# all of these; listed explicitly so collect() works even if the
# package re-exports change)
_PROGRAM_MODULES = (
    "peasoup_tpu.ops.dedisperse",
    "peasoup_tpu.ops.spectrum",
    "peasoup_tpu.ops.rednoise",
    "peasoup_tpu.ops.zap",
    "peasoup_tpu.ops.resample",
    "peasoup_tpu.ops.harmonics",
    "peasoup_tpu.ops.peaks",
    "peasoup_tpu.ops.fold",
    "peasoup_tpu.ops.fold_optimise",
    "peasoup_tpu.ops.survey_fold",
    "peasoup_tpu.ops.singlepulse",
    "peasoup_tpu.ops.streaming",
    "peasoup_tpu.ops.ffa",
    "peasoup_tpu.ops.coincidence",
    "peasoup_tpu.ops.correlate",
    "peasoup_tpu.ops.candidate_features",
    "peasoup_tpu.ops.fdas",
)


def sds(shape: tuple[int, ...], dtype: str):
    """Shorthand for a ShapeDtypeStruct in registry build thunks."""
    import jax
    import numpy as np

    return jax.ShapeDtypeStruct(shape, np.dtype(dtype))


@dataclass(frozen=True)
class ShapeCtx:
    """Concrete production geometry for parameterised AOT warmup.

    One ShapeCtx describes the shapes a campaign bucket implies
    (:func:`peasoup_tpu.perf.warmup.shape_ctx_for_bucket` derives it
    from a bucket key + pipeline config using the drivers' own plan
    machinery). A program's ``param`` hook maps the ctx to the build
    spec the driver would trace at those shapes, so warmup compiles
    the production programs, not the tiny representative ones.
    """

    nsamps: int  # padded observation length (the bucket rung)
    nchans: int
    nbits: int
    ndm: int  # DM trials in the plan
    out_nsamps: int  # dedispersed trial length
    dm_block: int  # DM trials per device wave (driver formula)
    dedisp_block: int  # dedispersion DM-block size (tuned plans flow
    # in here via perf/tuning.py so warmup compiles the tuned tile)
    widths: tuple[int, ...] = ()  # single-pulse boxcar bank
    min_snr: float = 6.0
    max_events: int = 256
    decimate: int = 32
    pallas_span: int = 0
    sp_fused_span: int = 0  # fused sweep+dec-fold kernel tile (0 = off)
    # streaming geometry (peasoup_tpu/stream/): dedispersed samples per
    # chunk and carried-tail length; 0 = not a streaming ctx (batch
    # campaign buckets), so streaming-only hooks skip it
    stream_chunk: int = 0
    stream_hold: int = 0
    # subband dedispersion (the auto-selected/tuned plan,
    # plan/dedisp_plan.py): 0 = the direct engine
    subbands: int = 0
    subband_smear: float = 1.0
    # resolved dedispersion engine ("" = unknown/any; "exact" |
    # "subband" | "matmul") and whether the subband stages run as
    # banded matmuls — the matmul-program hooks decline ctxs whose
    # tuned plan names another engine, so warmup compiles only what
    # the driver will dispatch
    dedisp_engine: str = ""
    subband_matmul: bool = False
    # periodicity-chain geometry (pipeline "search" buckets, derived
    # via plan/accel_plan.py + plan/fft_plan.py in
    # perf.warmup.shape_ctx_for_bucket): 0 fft_size = not a
    # periodicity ctx, so the spectrum/resample/harmonics/peaks hooks
    # decline it
    fft_size: int = 0
    nharms: int = 4
    accel_pad: int = 0  # padded accel-trial columns per DM row
    max_peaks: int = 128
    select_smax: int = 0  # gather-free resample span (0 = gather path)
    # rednoise whitening boundaries in spectrum bins (the driver's
    # boundary_5_freq/boundary_25_freq over the bucket's bin width) —
    # static args of running_median/whiten_fseries, so part of the
    # compiled program's identity; 0 = not a periodicity ctx
    pos5: int = 0
    pos25: int = 0
    # survey-fold geometry (peasoup_tpu/sift/fold.py): candidates per
    # fixed batch and the bucket's power-of-two series length; 0 = not
    # a fold ctx, so the survey_fold hook declines it
    fold_batch: int = 0
    fold_nsamps: int = 0
    fold_nbins: int = 64
    fold_nints: int = 16
    # FDAS correlation-search geometry (pipeline "fdas" buckets,
    # derived in perf.warmup.shape_ctx_for_bucket from the bucket's
    # fft_size + the zmax knob): template rows per device dispatch,
    # the f-dot grid half-extent in bins, and the overlap-save segment
    # length. 0 templates = not an FDAS ctx, so the fdas hook declines
    fdas_templates: int = 0
    fdas_zmax: int = 0
    fdas_segment: int = 0


@dataclass(frozen=True)
class ProgramSpec:
    """One registered jitted program.

    ``build`` returns ``(fn, args, kwargs)``; ``fn`` is either a
    jit-wrapped callable (has ``.trace``) or a plain traceable
    function the contract engine will wrap. ``donate`` lists argument
    indices the DRIVER relies on being donated — the contract engine
    fails the audit when declaration and lowering disagree in either
    direction. ``allow_custom_calls`` extends the global custom-call
    allowlist for this program only. ``param`` is the optional
    shape-parameterisation hook: given a :class:`ShapeCtx` it returns
    the build spec at that production geometry (or None when the
    program does not apply to the ctx, e.g. the sub-byte unpacker on
    an 8-bit bucket).
    """

    name: str
    build: Callable[[], tuple[Callable, tuple, dict[str, Any]]]
    donate: tuple[int, ...] = ()
    allow_custom_calls: tuple[str, ...] = ()
    param: (
        Callable[[ShapeCtx], tuple[Callable, tuple, dict[str, Any]] | None]
        | None
    ) = None

    def build_for(
        self, ctx: ShapeCtx | None = None
    ) -> tuple[Callable, tuple, dict[str, Any]] | None:
        """The build spec at ``ctx`` shapes via the ``param`` hook, or
        the representative spec when no ctx is given. None when the
        program has no parameterisation for this ctx (ctx-mode callers
        skip it rather than warm an irrelevant shape)."""
        if ctx is None:
            return self.build()
        if self.param is None:
            return None
        return self.param(ctx)


_REGISTRY: dict[str, ProgramSpec] = {}

# Top-level jitted entry points whose compiled program registers under
# a different public name (builder-pattern factories). Keyed by
# "ops.<module>.<function>" as detected by unregistered_entry_points().
REGISTRY_ALIASES = {
    "ops.ffa._octave_fn": "ops.ffa.octave",
    "ops.singlepulse.make_single_pulse_search_fn": (
        "ops.singlepulse.single_pulse_search"
    ),
    "ops.streaming.make_stream_chunk_fn": (
        "ops.streaming.stream_chunk_search"
    ),
    "ops.dedisperse._stage1_batched": (
        "ops.dedisperse.subband_stage1_batched"
    ),
    "ops.dedisperse._stage2_batched": "ops.dedisperse.subband_stage2",
    "ops.dedisperse._stage1_matmul_batched": (
        "ops.dedisperse.subband_stage1_matmul"
    ),
    "ops.dedisperse._stage2_matmul_batched": (
        "ops.dedisperse.subband_stage2_matmul"
    ),
    "ops.candidate_features.make_score_apply_fn": (
        "ops.candidate_features.score_apply"
    ),
    "ops.fdas.make_fdas_search_fn": "ops.fdas.fdas_correlate_search",
    "ops.fdas._correlate_bank_jit": "ops.fdas.correlate_bank",
}


def register_program(
    name: str,
    build: Callable[[], tuple[Callable, tuple, dict[str, Any]]],
    *,
    donate: tuple[int, ...] = (),
    allow_custom_calls: tuple[str, ...] = (),
    param: (
        Callable[[ShapeCtx], tuple[Callable, tuple, dict[str, Any]] | None]
        | None
    ) = None,
) -> None:
    if name in _REGISTRY:
        raise ValueError(f"duplicate program registration: {name}")
    _REGISTRY[name] = ProgramSpec(
        name=name,
        build=build,
        donate=tuple(donate),
        allow_custom_calls=tuple(allow_custom_calls),
        param=param,
    )


def registered_programs() -> tuple[ProgramSpec, ...]:
    """All registered programs, importing the ops modules first so
    their registration side effects have happened."""
    import importlib

    for mod in _PROGRAM_MODULES:
        importlib.import_module(mod)
    return tuple(_REGISTRY[k] for k in sorted(_REGISTRY))


# --------------------------------------------------------------------------
# registry completeness: no jitted entry point escapes the registry
# --------------------------------------------------------------------------

def _jit_entry_points_in(path: str, modname: str) -> list[str]:
    """AST scan of one ops module for top-level jitted entry points:
    module-level functions decorated with ``jax.jit`` /
    ``partial(jax.jit, ...)``, module-level ``name = jax.jit(...)``
    assignments, and builder functions that ``return jax.jit(...)``
    (the lru_cache'd factory pattern)."""
    import ast

    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)

    def is_jax_jit(node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Attribute)
            and node.attr == "jit"
            and isinstance(node.value, ast.Name)
            and node.value.id == "jax"
        )

    def decorated_jit(dec: ast.AST) -> bool:
        if is_jax_jit(dec):
            return True
        # partial(jax.jit, ...) / functools.partial(jax.jit, ...)
        if isinstance(dec, ast.Call) and any(
            is_jax_jit(a) for a in dec.args
        ):
            return True
        return False

    found = []
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            if any(decorated_jit(d) for d in node.decorator_list):
                found.append(f"{modname}.{node.name}")
                continue
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.Return)
                    and isinstance(sub.value, ast.Call)
                    and is_jax_jit(sub.value.func)
                ):
                    found.append(f"{modname}.{node.name}")
                    break
        elif isinstance(node, ast.Assign):
            if (
                isinstance(node.value, ast.Call)
                and is_jax_jit(node.value.func)
                and node.targets
                and isinstance(node.targets[0], ast.Name)
            ):
                found.append(f"{modname}.{node.targets[0].id}")
    return found


def unregistered_entry_points() -> list[str]:
    """Top-level jitted entry points in ops/ (Pallas kernels excluded
    here — they have their own registry, ops/pallas/registry.py, whose
    completeness is gated by the audit's PSK201) with no registry
    coverage: neither a same-name registration (modulo a leading
    underscore) nor a REGISTRY_ALIASES mapping. Empty means every
    program is warmed, contract-checked and benchmarked."""
    import os

    registered = {s.name for s in registered_programs()}
    missing = []
    ops_dir = os.path.dirname(os.path.abspath(__file__))
    # every ops module on disk, not just _PROGRAM_MODULES — a new
    # module that forgot BOTH the registration and the module list is
    # exactly what this gate exists to catch
    for fname in sorted(os.listdir(ops_dir)):
        if not fname.endswith(".py") or fname in (
            "__init__.py", "registry.py"
        ):
            continue
        short = fname[:-3]
        path = os.path.join(ops_dir, fname)
        for ep in _jit_entry_points_in(path, f"ops.{short}"):
            mod_prefix, fn_name = ep.rsplit(".", 1)
            candidates = {
                ep,
                f"{mod_prefix}.{fn_name.lstrip('_')}",
                REGISTRY_ALIASES.get(ep, ""),
            }
            if not (candidates & registered):
                missing.append(ep)
    return sorted(missing)
