"""Batched candidate feature extraction + scorer forward pass.

The ranking stage's device programs (the PICS/PulsarX direction,
arXiv:2309.02544): one jitted program turns a fixed-width batch of
fold products — folded profile, subintegration stamp, DM curve — into
a feature matrix, and a second (a builder, so weight geometry stays an
argument) runs the small MLP scorer forward pass over it. Both are
registered so the audit's contract engine, AOT warmup (at
campaign-bucket shapes via the ``fold_batch``/``fold_nbins``/
``fold_nints`` ShapeCtx fields), the microbench and the perf ratchet
cover them like every other program.

Feature rows are **independent** — no cross-row reduction anywhere —
so the scoring driver (:mod:`peasoup_tpu.rank.score`) can halve the
batch under ``device.oom`` and get bitwise-identical features, the
same contract the survey folder honours.

The DM curve is the fold significance at :data:`DM_CURVE_FRACTIONS`
of the candidate DM (index 0 = the zero-DM hypothesis, last = the
candidate DM). Broadband terrestrial RFI peaks at zero DM; a real
dispersed pulsar peaks at its own DM — the contrast features carry
exactly that discriminant.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

#: DM-curve sample points, as fractions of the candidate DM. Fixed at
#: module level so every jit shape derives from (batch, nbins, nints)
#: alone and same-bucket scoring batches never recompile.
DM_CURVE_FRACTIONS = (0.0, 0.25, 0.5, 0.75, 1.0)
DM_CURVE_POINTS = len(DM_CURVE_FRACTIONS)

#: Feature-matrix columns, in order. FEATURE_NAMES[j] documents
#: features[:, j]; the model artifact pins this list so a stale model
#: can never silently read a reordered matrix.
FEATURE_NAMES = (
    "prof_snr",            # (peak - off-pulse mean) / off-pulse std
    "prof_sharpness",      # fraction of bins above half the peak
    "offpulse_cv",         # off-pulse std / profile dynamic range
    "offpulse_mad_ratio",  # off-pulse MAD/std (baseline gaussianity)
    "subint_chi2",         # mean sq. dev of normalised subints vs prof
    "subint_corr_mean",    # mean subint-profile correlation
    "subint_persistence",  # fraction of subints correlated with prof
    "subint_intermittency",  # std/mean of per-subint peak amplitude
    "dm_contrast",         # (S(dm_c) - S(0)) / (|S(0)| + |S(dm_c)|)
    "dm_peakedness",       # (max - mean) / std over the DM curve
    "dm_argmax_frac",      # argmax position on the curve (1 = cand DM)
)
NFEATURES = len(FEATURE_NAMES)

_EPS = 1e-6


def _median_last(x: jnp.ndarray) -> jnp.ndarray:
    """Median over the last axis via an explicit f32 sort.
    ``jnp.median``'s quantile path does its index arithmetic (floor/
    ceil/clamp on the scaled q) in float64, which trips the audit's
    PSC101 f64-drift contract; the sizes here are static, so the
    middle elements are compile-time indices."""
    n = x.shape[-1]
    s = jnp.sort(x, axis=-1)
    mid = n // 2
    if n % 2:
        return s[..., mid]
    return 0.5 * (s[..., mid - 1] + s[..., mid])


def _row_features(
    prof: jnp.ndarray, subints: jnp.ndarray, dm_curve: jnp.ndarray
) -> jnp.ndarray:
    """Features of ONE candidate: (nbins,), (nints, nbins), (D,)."""
    # --- profile shape ------------------------------------------------
    med = _median_last(prof)
    centred = prof - med
    peak = jnp.max(centred)
    on = centred > 0.5 * peak  # the half-max pulse window
    off = ~on
    n_off = jnp.maximum(jnp.sum(off), 1)
    off_mean = jnp.sum(jnp.where(off, prof, 0.0)) / n_off
    off_var = (
        jnp.sum(jnp.where(off, (prof - off_mean) ** 2, 0.0)) / n_off
    )
    off_std = jnp.sqrt(jnp.maximum(off_var, 0.0))
    off_mad = (
        jnp.sum(jnp.where(off, jnp.abs(prof - off_mean), 0.0)) / n_off
    )
    dyn = jnp.max(prof) - jnp.min(prof)
    prof_snr = (jnp.max(prof) - off_mean) / (off_std + _EPS)
    prof_sharpness = jnp.mean(on.astype(jnp.float32))
    offpulse_cv = off_std / (dyn + _EPS)
    offpulse_mad_ratio = off_mad / (off_std + _EPS)

    # --- subintegration persistence ----------------------------------
    nprof = (prof - jnp.mean(prof)) / (jnp.std(prof) + _EPS)
    smean = jnp.mean(subints, axis=1, keepdims=True)
    sstd = jnp.std(subints, axis=1, keepdims=True)
    nsub = (subints - smean) / (sstd + _EPS)
    corr = jnp.mean(nsub * nprof[None, :], axis=1)  # (nints,)
    subint_chi2 = jnp.mean((nsub - nprof[None, :]) ** 2)
    subint_corr_mean = jnp.mean(corr)
    subint_persistence = jnp.mean((corr > 0.15).astype(jnp.float32))
    peaks = jnp.max(subints, axis=1) - _median_last(subints)
    subint_intermittency = jnp.std(peaks) / (
        jnp.abs(jnp.mean(peaks)) + _EPS
    )

    # --- DM curve vs the zero-DM hypothesis --------------------------
    s0, sc = dm_curve[0], dm_curve[-1]
    dm_contrast = (sc - s0) / (jnp.abs(s0) + jnp.abs(sc) + _EPS)
    dm_peakedness = (jnp.max(dm_curve) - jnp.mean(dm_curve)) / (
        jnp.std(dm_curve) + _EPS
    )
    dm_argmax_frac = jnp.argmax(dm_curve).astype(jnp.float32) / float(
        max(dm_curve.shape[0] - 1, 1)
    )

    return jnp.stack(
        [
            prof_snr,
            prof_sharpness,
            offpulse_cv,
            offpulse_mad_ratio,
            subint_chi2,
            subint_corr_mean,
            subint_persistence,
            subint_intermittency,
            dm_contrast,
            dm_peakedness,
            dm_argmax_frac,
        ]
    ).astype(jnp.float32)


@partial(jax.jit, static_argnames=("nbins", "nints"))
def candidate_features_batch(
    prof: jnp.ndarray,  # (B, nbins) f32 folded profiles
    subints: jnp.ndarray,  # (B, nints, nbins) f32 subint stamps
    dm_curve: jnp.ndarray,  # (B, DM_CURVE_POINTS) f32 significances
    *,
    nbins: int,
    nints: int,
) -> jnp.ndarray:
    """Feature matrix of a fixed batch of fold products ->
    (B, NFEATURES) f32. ``nbins``/``nints`` are static for the same
    reason they are on ``survey_fold_batch``: they name the compiled
    geometry, which the scoring driver pins per campaign bucket."""
    del nbins, nints  # carried in the array shapes
    return jax.vmap(_row_features)(prof, subints, dm_curve)


def make_score_apply_fn():
    """The scorer forward pass: standardise features, one tanh hidden
    layer, logistic output. Weights are *arguments* (not baked-in
    constants), so one compiled program serves every model artifact of
    a given geometry — swapping models never recompiles."""

    def _apply(feats, mean, scale, w1, b1, w2, b2):
        z = (feats - mean[None, :]) / scale[None, :]
        h = jnp.tanh(z @ w1 + b1[None, :])
        logit = h @ w2 + b2
        return jax.nn.sigmoid(logit)

    return jax.jit(_apply)


# --- audit registry: tiny representative shapes; the ShapeCtx hooks
# rebuild at the sift service's production fold bucket so campaign
# warmup + the >=2-rung ladder contract trace cover both programs ---
from .registry import register_program, sds  # noqa: E402

_HIDDEN = 16  # the shipped artifact's hidden width


def _score_apply_args(batch: int):
    return (
        sds((batch, NFEATURES), "float32"),
        sds((NFEATURES,), "float32"),
        sds((NFEATURES,), "float32"),
        sds((NFEATURES, _HIDDEN), "float32"),
        sds((_HIDDEN,), "float32"),
        sds((_HIDDEN,), "float32"),
        sds((), "float32"),
    )


def _param_candidate_features(ctx):
    if ctx.fold_batch <= 0 or ctx.fold_nsamps <= 0:
        return None
    b, nbins, nints = ctx.fold_batch, ctx.fold_nbins, ctx.fold_nints
    return (
        candidate_features_batch,
        (
            sds((b, nbins), "float32"),
            sds((b, nints, nbins), "float32"),
            sds((b, DM_CURVE_POINTS), "float32"),
        ),
        {"nbins": nbins, "nints": nints},
    )


def _param_score_apply(ctx):
    if ctx.fold_batch <= 0 or ctx.fold_nsamps <= 0:
        return None
    return (make_score_apply_fn(), _score_apply_args(ctx.fold_batch), {})


register_program(
    "ops.candidate_features.candidate_features_batch",
    lambda: (
        candidate_features_batch,
        (
            sds((3, 16), "float32"),
            sds((3, 4, 16), "float32"),
            sds((3, DM_CURVE_POINTS), "float32"),
        ),
        {"nbins": 16, "nints": 4},
    ),
    param=_param_candidate_features,
)

register_program(
    "ops.candidate_features.score_apply",
    lambda: (make_score_apply_fn(), _score_apply_args(3), {}),
    param=_param_score_apply,
)
