"""Candidate peak extraction: device thresholding + host clustering.

Reference splits the same way: device_find_peaks compacts (index, snr)
pairs above threshold (Thrust copy_if, src/kernels.cu:384-416); the
host then clusters neighbours within ``min_gap`` bins
(PeakFinder::identify_unique_peaks, include/transforms/peakfinder.hpp:27-56).

TPU design: copy_if's dynamic output shape is hostile to XLA, so the
compaction uses jnp.nonzero with a static ``max_peaks`` size (the
reference hard-codes max_cands=100000 for the same reason,
peakfinder.hpp:61). Indices come out ascending, which the host
clustering pass requires. The search-range window [start_idx, limit)
is applied as part of the mask, mirroring the (min_freq, max_freq)
windowing in find_candidates (peakfinder.hpp:82-84).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("max_peaks",))
def find_peaks_device(
    spec: jnp.ndarray,  # (..., nbins) normalised spectrum or harmonic sum
    threshold: jnp.ndarray,
    start_idx: jnp.ndarray,  # scalar or (...,) first bin to consider
    limit: jnp.ndarray,  # scalar or (...,) one-past-last bin
    *,
    max_peaks: int = 4096,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Compact threshold crossings to fixed-size (idx, snr) arrays.

    Returns (indices (..., max_peaks) i32 ascending and padded with
    nbins, snrs (..., max_peaks) f32, count (...,) i32). ``count`` may
    exceed ``max_peaks``; callers should treat that as overflow.
    """
    nbins = spec.shape[-1]
    i = jnp.arange(nbins, dtype=jnp.int32)

    def one(s, thr, lo, hi):
        mask = (i >= lo) & (i < hi) & (s > thr)
        idxs = jnp.nonzero(mask, size=max_peaks, fill_value=nbins)[0].astype(
            jnp.int32
        )
        snrs = jnp.where(idxs < nbins, s[jnp.clip(idxs, 0, nbins - 1)], 0.0)
        return idxs, snrs, mask.sum().astype(jnp.int32)

    batch = spec.shape[:-1]
    if batch:
        flat = spec.reshape(-1, nbins)
        thr = jnp.broadcast_to(jnp.asarray(threshold), flat.shape[:1])
        lo = jnp.broadcast_to(jnp.asarray(start_idx), flat.shape[:1])
        hi = jnp.broadcast_to(jnp.asarray(limit), flat.shape[:1])
        idxs, snrs, count = jax.vmap(one)(flat, thr, lo, hi)
        return (
            idxs.reshape(*batch, max_peaks),
            snrs.reshape(*batch, max_peaks),
            count.reshape(batch),
        )
    return one(spec, threshold, start_idx, limit)


def cluster_peaks(
    idxs: np.ndarray, snrs: np.ndarray, count: int, min_gap: int = 30
) -> tuple[np.ndarray, np.ndarray]:
    """Exact port of identify_unique_peaks (peakfinder.hpp:27-56).

    Walks ascending indices; within a run where consecutive gaps stay
    below ``min_gap`` keeps the highest snr. Quirk preserved: ``lastidx``
    only advances when a higher snr is found, so a slow ramp of weak
    peaks can terminate a cluster early. Runs in the native C++ host
    runtime when available.
    """
    from .. import native

    res = native.cluster_peaks(np.asarray(idxs), np.asarray(snrs), count, min_gap)
    if res is not None:
        return res
    peak_idx = []
    peak_snr = []
    ii = 0
    count = int(min(count, len(idxs)))
    while ii < count:
        cpeak = snrs[ii]
        cpeakidx = idxs[ii]
        lastidx = idxs[ii]
        ii += 1
        while ii < count and (idxs[ii] - lastidx) < min_gap:
            if snrs[ii] > cpeak:
                cpeak = snrs[ii]
                cpeakidx = idxs[ii]
                lastidx = idxs[ii]
            ii += 1
        peak_idx.append(cpeakidx)
        peak_snr.append(cpeak)
    return np.asarray(peak_idx, dtype=np.int64), np.asarray(peak_snr, dtype=np.float64)
