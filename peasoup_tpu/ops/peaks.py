"""Candidate peak extraction: device thresholding + host clustering.

Reference splits the same way: device_find_peaks compacts (index, snr)
pairs above threshold (Thrust copy_if, src/kernels.cu:384-416); the
host then clusters neighbours within ``min_gap`` bins
(PeakFinder::identify_unique_peaks, include/transforms/peakfinder.hpp:27-56).

TPU design: copy_if's dynamic output shape is hostile to XLA, so the
compaction is static-size with ``max_peaks`` slots (the reference
hard-codes max_cands=100000 for the same reason, peakfinder.hpp:61).
The compaction itself runs as lax.top_k over the key ``-index`` masked
to crossings: top_k of the negated indices returns the FIRST max_peaks
crossings in ascending index order, which is exactly nonzero(size=k)
semantics but lowers ~10x faster on TPU than the cumsum/scatter
compaction XLA emits for sized nonzero. Indices come out ascending,
which the host clustering pass requires. The search-range window
[start_idx, limit) is applied as part of the mask, mirroring the
(min_freq, max_freq) windowing in find_candidates (peakfinder.hpp:82-84).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("max_peaks", "block"))
def find_peaks_device(
    spec: jnp.ndarray,  # (..., nbins) normalised spectrum or harmonic sum
    threshold: jnp.ndarray,
    start_idx: jnp.ndarray,  # scalar or (...,) first bin to consider
    limit: jnp.ndarray,  # scalar or (...,) one-past-last bin
    *,
    max_peaks: int = 4096,
    block: int = 64,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Compact threshold crossings to fixed-size (idx, snr) arrays.

    Returns (indices (..., max_peaks) i32 ascending and padded with
    nbins, snrs (..., max_peaks) f32, count (...,) i32). ``count`` may
    exceed ``max_peaks``; callers should treat that as overflow.

    TPU cost note: lax.top_k lowers to a full per-lane sort whose cost
    is independent of k, so a single top_k over the whole spectrum pays
    an O(nbins log nbins) sort per lane. Crossings are sparse, so the
    compaction runs in two stages: (1) find the first ``max_peaks``
    length-``block`` blocks that contain a crossing (top_k over
    nbins/block block keys), (2) gather those blocks and top_k over the
    ``max_peaks * block`` surviving bins. Identical output to the
    single-stage form in all cases: if count <= max_peaks the crossing
    blocks number <= max_peaks and are all selected; if count >
    max_peaks the first max_peaks crossings live in the first
    max_peaks crossing-blocks, and ``count`` flags the overflow either
    way (the driver re-dispatches with a larger size).
    """
    nbins = spec.shape[-1]
    i = jnp.arange(nbins, dtype=jnp.int32)

    k = min(max_peaks, nbins)
    nblk = -(-nbins // block)
    kb = min(max_peaks, nblk)
    two_stage = kb * block < nbins  # else the gather buys nothing

    def one(s, thr, lo, hi):
        mask = (i >= lo) & (i < hi) & (s > thr)
        count = mask.sum().astype(jnp.int32)
        if two_stage:
            pad = nblk * block - nbins
            maskp = jnp.pad(mask, (0, pad)).reshape(nblk, block)
            sp = jnp.pad(s, (0, pad)).reshape(nblk, block)
            bi = jnp.arange(nblk, dtype=jnp.int32)
            bkey = jnp.where(maskp.any(-1), -bi, jnp.int32(-nblk - 1))
            bkv, bki = jax.lax.top_k(bkey, kb)  # ascending block index
            bvalid = bkv > -nblk - 1
            selmask = maskp[bki] & bvalid[:, None]  # (kb, block)
            gidx = bki[:, None] * block + jnp.arange(block, dtype=jnp.int32)
            key = jnp.where(selmask, -gidx, jnp.int32(-nbins - 1)).reshape(-1)
            kv, ki = jax.lax.top_k(key, k)
            valid = kv > -nbins - 1
            idxs = jnp.where(valid, -kv, nbins).astype(jnp.int32)
            snrs = jnp.where(valid, sp[bki].reshape(-1)[ki], 0.0)
        else:
            # top_k over -index: picks the first k crossings, in
            # ascending index order (descending key order)
            key = jnp.where(mask, -i, jnp.int32(-nbins - 1))
            kv, ki = jax.lax.top_k(key, k)
            valid = kv > -nbins - 1
            idxs = jnp.where(valid, ki, nbins).astype(jnp.int32)
            snrs = jnp.where(valid, s[jnp.clip(ki, 0, nbins - 1)], 0.0)
        if k < max_peaks:
            idxs = jnp.pad(idxs, (0, max_peaks - k), constant_values=nbins)
            snrs = jnp.pad(snrs, (0, max_peaks - k))
        return idxs, snrs, count

    batch = spec.shape[:-1]
    if batch:
        flat = spec.reshape(-1, nbins)
        thr = jnp.broadcast_to(jnp.asarray(threshold), flat.shape[:1])
        lo = jnp.broadcast_to(jnp.asarray(start_idx), flat.shape[:1])
        hi = jnp.broadcast_to(jnp.asarray(limit), flat.shape[:1])
        idxs, snrs, count = jax.vmap(one)(flat, thr, lo, hi)
        return (
            idxs.reshape(*batch, max_peaks),
            snrs.reshape(*batch, max_peaks),
            count.reshape(batch),
        )
    return one(spec, threshold, start_idx, limit)


@partial(jax.jit, static_argnames=("min_gap",))
def cluster_peaks_device(
    idxs: jnp.ndarray,  # (..., mx) i32 ascending crossings, padded with nbins
    snrs: jnp.ndarray,  # (..., mx) f32
    nbins: jnp.ndarray,  # scalar i32: pad sentinel (any idx >= nbins is pad)
    *,
    min_gap: int = 30,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Exact ON-DEVICE port of identify_unique_peaks
    (peakfinder.hpp:27-56), vectorised over every leading cell.

    The reference walks crossings sequentially per spectrum; here a
    lax.scan walks the (small, static) compacted slot axis once while
    every (dm, level, accel) cell advances in parallel lanes — turning
    a 13k-call host loop into one device pass, and shrinking the
    device->host transfer to cluster peaks (tens) instead of raw
    crossings (hundreds). Quirk preserved: ``lastidx`` advances only
    when a higher snr is found, so a slow ramp of weak peaks can
    terminate a cluster early.

    Returns (cluster idxs (..., mx) i32 ascending padded with nbins,
    cluster snrs (..., mx) f32 0-padded, cluster count (...,) i32).
    """
    batch = idxs.shape[:-1]
    mx = idxs.shape[-1]
    flat_i = idxs.reshape(-1, mx).T  # (mx, lanes)
    flat_s = snrs.reshape(-1, mx).T
    lanes = flat_i.shape[1]
    # one trailing pad step flushes the final open cluster
    flat_i = jnp.concatenate(
        [flat_i, jnp.full((1, lanes), nbins, dtype=flat_i.dtype)]
    )
    flat_s = jnp.concatenate([flat_s, jnp.zeros((1, lanes), flat_s.dtype)])

    def step(carry, xs):
        open_, cpeak, cpeakidx, lastidx = carry
        idx, snr = xs
        is_pad = idx >= nbins
        close = open_ & (is_pad | (idx - lastidx >= min_gap))
        start = (~open_ | close) & ~is_pad
        update = open_ & ~close & ~is_pad & (snr > cpeak)
        take = start | update
        carry = (
            (open_ & ~is_pad) | start,
            jnp.where(take, snr, cpeak),
            jnp.where(take, idx, cpeakidx),
            jnp.where(take, idx, lastidx),
        )
        return carry, (close, cpeakidx, cpeak)

    # derive the init carry from the inputs so its sharding/varying
    # type matches the scan body's outputs under shard_map
    zero_i = flat_i[0] * 0
    init = (zero_i < -1, flat_s[0] * 0, zero_i, zero_i)
    _, (valid, eidx, esnr) = jax.lax.scan(step, init, (flat_i, flat_s))
    # compact the scattered emissions to the head, preserving order
    # (same -index top_k trick as find_peaks_device)
    valid = valid.T  # (lanes, mx+1)
    eidx = eidx.T
    esnr = esnr.T
    step_i = jnp.arange(mx + 1, dtype=jnp.int32)
    key = jnp.where(valid, -step_i, jnp.int32(-(mx + 2)))
    kv, ki = jax.lax.top_k(key, mx)
    ok = kv > -(mx + 2)
    cidx = jnp.where(
        ok, jnp.take_along_axis(eidx, ki, axis=-1), nbins
    ).astype(jnp.int32)
    csnr = jnp.where(ok, jnp.take_along_axis(esnr, ki, axis=-1), 0.0)
    ccount = valid.sum(axis=-1).astype(jnp.int32)
    return (
        cidx.reshape(*batch, mx),
        csnr.reshape(*batch, mx),
        ccount.reshape(batch),
    )


@partial(jax.jit, static_argnames=("total_pad",))
def compact_peaks_device(
    idxs: jnp.ndarray,  # (..., mp) peak slots (cluster or raw)
    snrs: jnp.ndarray,  # (..., mp)
    ccounts: jnp.ndarray,  # (...) valid slots per cell
    *,
    total_pad: int,  # power-of-two >= total valid entries
) -> jnp.ndarray:
    """Ragged device-side compaction for the D2H transfer: gather ONLY
    the valid (idx, snr) slots of every cell into one flat buffer
    ((2*total_pad,) i32, snrs bitcast), cells in C order, slots in
    order. The slot arrays are mostly padding (counts are data-
    dependent), and the host link is slow — this sends exactly the
    entries plus pow2 slack instead of cells*mp slots. The gather
    index map is built ON DEVICE from ccounts (cumsum + a histogram
    cumsum — jnp.searchsorted lowers to a scalar-core while loop on TPU
    and measured ~55 ms per call at production sizes), so the host only
    supplies the static padded total it learned from the counts
    transfer."""
    mp = idxs.shape[-1]
    cc = jnp.minimum(ccounts.reshape(-1), mp).astype(jnp.int32)
    ends = jnp.cumsum(cc)
    starts = ends - cc
    pos = jnp.arange(total_pad, dtype=jnp.int32)
    # cell[pos] = #{ends <= pos} (== searchsorted(ends, pos, 'right')
    # for sorted ends): scatter-add each end into a histogram, cumsum.
    # Empty cells contribute coincident ends; the add accumulates them.
    hist = jnp.zeros(total_pad + 1, jnp.int32).at[
        jnp.minimum(ends, total_pad)
    ].add(1)
    cell = jnp.minimum(
        jnp.cumsum(hist)[:total_pad], jnp.int32(cc.size - 1)
    )
    within = jnp.clip(pos - jnp.take(starts, cell), 0, mp - 1)
    flat = cell * mp + within
    valid = pos < ends[-1]
    # ONE 2-row gather instead of two flat gathers: TPU gathers pay a
    # large per-call cost, and the shared index vector amortises it
    # (measured 59 -> 7 ms/call at production shapes; bitwise equal —
    # zeroing the f32 payload before or after the bitcast is the same)
    stacked = jnp.stack(
        [
            idxs.reshape(-1).astype(jnp.int32),
            jax.lax.bitcast_convert_type(snrs.reshape(-1), jnp.int32),
        ]
    )
    out = jnp.where(valid, jnp.take(stacked, flat, axis=1), 0)
    return jnp.concatenate([out[0], out[1]])


@partial(jax.jit, static_argnames=("total_pad",))
def pack_chunk_results(
    idxs: jnp.ndarray,
    snrs: jnp.ndarray,
    counts: jnp.ndarray,
    ccounts: jnp.ndarray,
    *,
    total_pad: int,
) -> jnp.ndarray:
    """One-dispatch wave payload: [counts | ccounts | ragged stream].

    The search loop used to dispatch the counts concat and the
    compaction as separate programs; on a high-latency link every
    dispatched program and every fetch costs a round trip, so the whole
    chunk result is packed by ONE jitted call and fetched with one
    transfer."""
    return jnp.concatenate(
        [
            counts.reshape(-1).astype(jnp.int32),
            ccounts.reshape(-1).astype(jnp.int32),
            compact_peaks_device(idxs, snrs, ccounts, total_pad=total_pad),
        ]
    )


def cluster_peaks(
    idxs: np.ndarray, snrs: np.ndarray, count: int, min_gap: int = 30
) -> tuple[np.ndarray, np.ndarray]:
    """Exact port of identify_unique_peaks (peakfinder.hpp:27-56).

    Walks ascending indices; within a run where consecutive gaps stay
    below ``min_gap`` keeps the highest snr. Quirk preserved: ``lastidx``
    only advances when a higher snr is found, so a slow ramp of weak
    peaks can terminate a cluster early. Runs in the native C++ host
    runtime when available.
    """
    from .. import native

    res = native.cluster_peaks(np.asarray(idxs), np.asarray(snrs), count, min_gap)
    if res is not None:
        return res
    peak_idx = []
    peak_snr = []
    ii = 0
    count = int(min(count, len(idxs)))
    while ii < count:
        cpeak = snrs[ii]
        cpeakidx = idxs[ii]
        lastidx = idxs[ii]
        ii += 1
        while ii < count and (idxs[ii] - lastidx) < min_gap:
            if snrs[ii] > cpeak:
                cpeak = snrs[ii]
                cpeakidx = idxs[ii]
                lastidx = idxs[ii]
            ii += 1
        peak_idx.append(cpeakidx)
        peak_snr.append(cpeak)
    return np.asarray(peak_idx, dtype=np.int64), np.asarray(peak_snr, dtype=np.float64)


# --- audit registry (ShapeCtx hooks rebuild the peaks machinery at a
# periodicity bucket's production tile: one (dm_block, accel_pad,
# size_spec) level for the walk, the (dm_block, nlev, accel_pad,
# max_peaks) slot arrays for the compaction/packing — the shapes the
# wave loop in pipeline/search.py actually dispatches) ---
from .registry import register_program, sds  # noqa: E402


def _param_find_peaks(ctx):
    if ctx.fft_size <= 0 or ctx.accel_pad <= 0:
        return None
    return (
        find_peaks_device,
        (
            sds((ctx.dm_block, ctx.accel_pad, ctx.fft_size // 2 + 1),
                "float32"),
            sds((), "float32"),
            sds((), "int32"),
            sds((), "int32"),
        ),
        {"max_peaks": ctx.max_peaks, "block": 64},
    )


def _param_cluster_peaks(ctx):
    if ctx.fft_size <= 0 or ctx.accel_pad <= 0:
        return None
    return (
        cluster_peaks_device,
        (
            sds((ctx.dm_block, ctx.accel_pad, ctx.max_peaks), "int32"),
            sds((ctx.dm_block, ctx.accel_pad, ctx.max_peaks), "float32"),
            sds((), "int32"),
        ),
        {"min_gap": 30},
    )


def _param_compact_peaks(ctx):
    if ctx.fft_size <= 0 or ctx.accel_pad <= 0:
        return None
    cells = (ctx.dm_block, ctx.nharms + 1, ctx.accel_pad)
    return (
        compact_peaks_device,
        (
            sds((*cells, ctx.max_peaks), "int32"),
            sds((*cells, ctx.max_peaks), "float32"),
            sds(cells, "int32"),
        ),
        {"total_pad": 4096},
    )


def _param_pack_chunk(ctx):
    if ctx.fft_size <= 0 or ctx.accel_pad <= 0:
        return None
    cells = (ctx.dm_block, ctx.nharms + 1, ctx.accel_pad)
    return (
        pack_chunk_results,
        (
            sds((*cells, ctx.max_peaks), "int32"),
            sds((*cells, ctx.max_peaks), "float32"),
            sds(cells, "int32"),
            sds(cells, "int32"),
        ),
        {"total_pad": 4096},
    )


register_program(
    "ops.peaks.find_peaks_device",
    lambda: (
        find_peaks_device,
        (
            sds((2, 256), "float32"),
            sds((), "float32"),
            sds((), "int32"),
            sds((), "int32"),
        ),
        {"max_peaks": 64, "block": 64},
    ),
    param=_param_find_peaks,
)
register_program(
    "ops.peaks.cluster_peaks_device",
    lambda: (
        cluster_peaks_device,
        (sds((2, 64), "int32"), sds((2, 64), "float32"), sds((), "int32")),
        {"min_gap": 30},
    ),
    param=_param_cluster_peaks,
)
register_program(
    "ops.peaks.compact_peaks_device",
    lambda: (
        compact_peaks_device,
        (sds((2, 64), "int32"), sds((2, 64), "float32"), sds((2,), "int32")),
        {"total_pad": 128},
    ),
    param=_param_compact_peaks,
)
register_program(
    "ops.peaks.pack_chunk_results",
    lambda: (
        pack_chunk_results,
        (
            sds((2, 64), "int32"),
            sds((2, 64), "float32"),
            sds((2,), "int32"),
            sds((2,), "int32"),
        ),
        {"total_pad": 128},
    ),
    param=_param_pack_chunk,
)
