"""Fast Folding Algorithm (FFA) periodicity search, TPU-native.

The reference ships the CLI spec for an FFA pipeline ("Peasoup/FFAster
extension", include/utils/cmdline.hpp:35-50,211-292 — p_start/p_end/
min_dc over a DM grid) but its implementation (`ffa_pipeline.cu`,
Makefile:41) is absent from the tree. This module implements the
search for real, designed for XLA rather than translated:

* The radix-2 FFA butterfly is expressed as fixed-shape batched
  gathers + adds: a time series is folded at EVERY integer base
  period p0 in [128, 256) bins at once by vmapping one
  (log2(m) stages) x (m_pad, 256) program over the p0 axis — no
  per-period recompiles, no scalar loops. Longer periods are reached
  octave by octave, halving the time resolution each octave (the
  standard FFA staircase), so every octave reuses the same compiled
  shapes.
* Circular phase shifts use modulo-p0 gathers on a 256-wide padded
  profile axis (rolling the padded buffer would wrap through the pad).
* Profile significance is a circular boxcar matched filter over
  octave-spaced duty cycles >= min_dc, scored as
  (boxcar_sum - w*mean) / (sigma * sqrt(w)) with mean/sigma the
  white-noise moments of the folded profile's baseline.

FFA trial periods: folding N = m * p0 samples at base period p0, row
j of the transform corresponds to period p0 + j / (m - 1) samples
(each successive row lets the fold drift one more sample across the
whole observation).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

_PMIN = 128  # base-period bucket: every octave folds p0 in [128, 256)
_PMAX = 256


class FFAOctaveResult(NamedTuple):
    snr: jax.Array  # (P, m_pad) best boxcar S/N per (p0, shift row)
    width: jax.Array  # (P, m_pad) i32 best boxcar width (bins)
    phase: jax.Array  # (P, m_pad) i32 best boxcar start phase (bins)


def _fold_rows(x: jax.Array, p0: jax.Array, m_pad: int) -> jax.Array:
    """(N,) -> (m_pad, PMAX): row i = x[i*p0 : i*p0 + p0], zero padded
    past p0 columns and past the last complete row."""
    n = x.shape[0]
    i = jnp.arange(m_pad, dtype=jnp.int32)[:, None]
    j = jnp.arange(_PMAX, dtype=jnp.int32)[None, :]
    src = i * p0 + j
    valid = (j < p0) & (src < n)
    return jnp.where(valid, x[jnp.clip(src, 0, n - 1)], 0.0)


def _shift_rows(prof: jax.Array, shift: jax.Array, p0: jax.Array) -> jax.Array:
    """Circularly delay each (.., PMAX) profile by ``shift`` bins
    within its true period p0 (modulo-p0 gather; the pad stays put)."""
    j = jnp.arange(_PMAX, dtype=jnp.int32)
    src = jnp.where(j[None, :] < p0, (j[None, :] + shift) % p0, j[None, :])
    return jnp.take_along_axis(prof, jnp.broadcast_to(src, prof.shape), axis=-1)


def ffa_transform(x: jax.Array, p0: jax.Array, m_pad: int) -> jax.Array:
    """Radix-2 FFA of ``x`` at integer base period ``p0`` (traced).

    Returns (m_pad, PMAX) profiles; row j (j < m, the number of
    complete periods in x) is the sum of the m rows folded with a
    total end-to-end drift of j samples — i.e. the fold at period
    p0 + j/(m-1) samples. Rows >= m are zero-row-padded partial sums.
    """
    prof = _fold_rows(x, p0, m_pad)
    stages = int(np.log2(m_pad))
    assert 1 << stages == m_pad, "m_pad must be a power of two"
    for s in range(stages):
        blk = 1 << (s + 1)  # rows per merge group after this stage
        half = blk >> 1
        i = jnp.arange(m_pad, dtype=jnp.int32)
        g = i // blk  # group index
        j = i % blk  # target drift within group
        a = g * blk + (j >> 1)  # top half row: drift floor(j/2)
        b = a + half  # bottom half row
        shift = (j + 1) >> 1  # bottom half is delayed ceil(j/2)
        top = prof[a]
        bot = _shift_rows(prof[b], shift[:, None], p0)
        prof = top + bot
    return prof


def boxcar_snr(
    prof: jax.Array,  # (..., PMAX) folded profiles
    p0: jax.Array,  # scalar i32 true period (bins)
    widths: tuple[int, ...],
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Circular boxcar matched filter: for each width w, score
    (sum_w - w*mean) / (sigma*sqrt(w)) maximised over start phase,
    with mean/sigma estimated from the profile itself (excluding the
    pad). Windows wrap modulo the TRUE period p0, not the padded
    width. Returns (best snr, best width, best phase)."""
    j = jnp.arange(_PMAX, dtype=jnp.int32)
    inmask = (j < p0)[None, :] if prof.ndim > 1 else j < p0
    inmask = jnp.broadcast_to(inmask, prof.shape)
    p0f = p0.astype(jnp.float32)
    mean = jnp.sum(jnp.where(inmask, prof, 0.0), axis=-1, keepdims=True) / p0f
    var = (
        jnp.sum(jnp.where(inmask, (prof - mean) ** 2, 0.0), axis=-1,
                keepdims=True)
        / p0f
    )
    sigma = jnp.sqrt(jnp.maximum(var, 1e-20))
    # cumulative sums over one period; windows that cross the period
    # boundary are (total - head) + tail, NOT a read through the pad
    wrapped = jnp.where(inmask, prof - mean, 0.0)
    csum = jnp.cumsum(wrapped, axis=-1)
    zero = jnp.zeros_like(csum[..., :1])
    csum = jnp.concatenate([zero, csum], axis=-1)  # (..., PMAX+1)
    total = jnp.take_along_axis(
        csum, jnp.broadcast_to(p0, csum.shape[:-1])[..., None], axis=-1
    )

    best_snr = jnp.full(prof.shape[:-1], -jnp.inf, jnp.float32)
    best_w = jnp.zeros(prof.shape[:-1], jnp.int32)
    best_ph = jnp.zeros(prof.shape[:-1], jnp.int32)
    phases = jnp.arange(_PMAX, dtype=jnp.int32)
    for w in widths:
        end = phases + w
        head = jnp.take(csum, phases, axis=-1)
        nowrap = jnp.take(csum, jnp.minimum(end, _PMAX), axis=-1) - head
        tail = jnp.take(
            csum, jnp.clip(end - p0, 0, _PMAX), axis=-1
        )
        sums = jnp.where(end[None, :] <= p0, nowrap, (total - head) + tail)
        valid = (phases[None, :] < p0) & (w < p0)
        valid = jnp.broadcast_to(valid, sums.shape)
        snr_w = jnp.where(
            valid, sums / (sigma * np.float32(np.sqrt(w))), -jnp.inf
        )
        ph = jnp.argmax(snr_w, axis=-1).astype(jnp.int32)
        s_w = jnp.max(snr_w, axis=-1)
        better = s_w > best_snr
        best_snr = jnp.where(better, s_w, best_snr)
        best_w = jnp.where(better, w, best_w)
        best_ph = jnp.where(better, ph, best_ph)
    return best_snr, best_w, best_ph


def duty_cycle_widths(min_dc: float, pmax: int = _PMAX) -> tuple[int, ...]:
    """Octave-spaced boxcar widths from min_dc * pmax up to half the
    period (reference flag --min_dc, cmdline.hpp:276-278)."""
    w = max(1, int(round(min_dc * pmax)))
    out = []
    while w <= pmax // 2:
        out.append(w)
        w *= 2
    return tuple(out) or (1,)


@lru_cache(maxsize=None)
def _octave_fn(m_pad: int, widths: tuple[int, ...]):
    """One compiled program searches EVERY base period of an octave:
    vmap over the (P = PMAX - PMIN) p0 values of the fixed-shape
    transform + matched filter. Input may be a single series (N,) or a
    BLOCK of DM trials (D, N) — the whole block folds in one dispatch."""

    @jax.jit
    def run(x: jax.Array) -> FFAOctaveResult:
        p0s = jnp.arange(_PMIN, _PMAX, dtype=jnp.int32)

        def series(xi):
            def one(p0):
                prof = ffa_transform(xi, p0, m_pad)
                return boxcar_snr(prof, p0, widths)

            return jax.vmap(one)(p0s)

        if x.ndim == 2:
            snr, w, ph = jax.vmap(series)(x)  # (D, P, m_pad)
        else:
            snr, w, ph = series(x)
        return FFAOctaveResult(snr=snr, width=w, phase=ph)

    return run


class FFACandidate(NamedTuple):
    period: float  # seconds
    dm: float
    snr: float
    width: int  # boxcar bins (of the folded profile)
    dc: float  # duty cycle = width / period_bins


def _extract_octave(
    snr: np.ndarray,  # (P, m_pad) per-(p0, row) best S/N
    wid: np.ndarray,
    n: int,
    tcur: float,
    p_start: float,
    p_end: float,
    snr_min: float,
    dm: float,
    m_pad: int,
    out: list,
) -> None:
    for pi in range(snr.shape[0]):
        p0 = _PMIN + pi
        p_lo, p_hi = p0 * tcur, (p0 + 1) * tcur
        if p_hi < p_start or p_lo > p_end:
            continue
        m = min(max(n // p0, 2), m_pad)
        row = int(np.argmax(snr[pi, :m]))
        s = float(snr[pi, row])
        if s >= snr_min:
            period = (p0 + row / max(m - 1, 1)) * tcur
            if p_start <= period <= p_end:
                out.append(
                    FFACandidate(
                        period=period,
                        dm=dm,
                        snr=s,
                        width=int(wid[pi, row]),
                        dc=float(wid[pi, row]) / p0,
                    )
                )


def ffa_search_block(
    trials: np.ndarray,  # (D, N) f32 dedispersed time series
    tsamp: float,
    p_start: float,
    p_end: float,
    min_dc: float,
    dms,  # (D,) DM values for candidate tagging
    snr_min: float = 6.0,
    hbm_budget: int = 2_000_000_000,
    progress=None,  # optional callable(fraction in [0, 1])
) -> list[FFACandidate]:
    """Full staircase FFA search of a BLOCK of DM trials: each octave
    folds every trial in as few compiled dispatches as the working set
    allows (vs one dispatch per trial per octave). Downsamples by 2
    per octave so base periods stay in the [PMIN, PMAX) bucket."""
    X = np.asarray(trials, dtype=np.float32)
    X = X - X.mean(axis=1, keepdims=True)
    ds = max(1, int(p_start / tsamp / _PMIN))
    Xd = X[:, : X.shape[1] // ds * ds].reshape(X.shape[0], -1, ds).sum(axis=2)
    tcur = tsamp * ds
    if p_start < _PMIN * tcur:
        import warnings

        warnings.warn(
            f"FFA effective start period is {_PMIN * tcur:.4f} s "
            f"(requested {p_start}): base periods fold at >= {_PMIN} "
            f"bins of the {tcur:.6f} s downsampled series"
        )
    cands: list[FFACandidate] = []
    n_oct = max(
        1, int(np.ceil(np.log2(max(2.0, p_end / (_PMIN * tcur)))))
    )
    oct_i = 0
    while _PMIN * tcur < p_end:
        n = Xd.shape[1]
        m_pad = 1 << max(1, int(np.ceil(np.log2(max(2, n // _PMIN)))))
        widths = duty_cycle_widths(min_dc)
        # working set ~ (P, m_pad, PMAX) f32 profiles per trial
        per_trial = (_PMAX - _PMIN) * m_pad * _PMAX * 4 * 3
        d_blk = max(1, min(Xd.shape[0], hbm_budget // per_trial))
        fn = _octave_fn(m_pad, widths)
        for s0 in range(0, Xd.shape[0], d_blk):
            blk = Xd[s0 : s0 + d_blk]
            if blk.shape[0] < d_blk:  # fixed shape -> one compile
                blk = np.pad(blk, ((0, d_blk - blk.shape[0]), (0, 0)))
            res = fn(jnp.asarray(blk))
            snr = np.asarray(res.snr)
            wid = np.asarray(res.width)
            for d in range(min(d_blk, Xd.shape[0] - s0)):
                _extract_octave(
                    snr[d], wid[d], n, tcur, p_start, p_end, snr_min,
                    float(dms[s0 + d]), m_pad, cands,
                )
        oct_i += 1
        if progress is not None:
            progress(min(1.0, oct_i / n_oct))
        if Xd.shape[1] < 4 * _PMAX:
            if 2 * _PMIN * tcur < p_end:
                import warnings

                warnings.warn(
                    f"FFA stopped at {_PMAX * tcur:.3f} s (requested "
                    f"p_end {p_end}): the series is too short to fold "
                    f"longer periods meaningfully"
                )
            break
        Xd = Xd[:, : Xd.shape[1] // 2 * 2].reshape(
            Xd.shape[0], -1, 2
        ).sum(axis=2)
        tcur *= 2
    return collapse_periods(cands)


def ffa_search_series(
    x: np.ndarray,  # (N,) f32 dedispersed, whitened time series
    tsamp: float,
    p_start: float,
    p_end: float,
    min_dc: float,
    dm: float = 0.0,
    snr_min: float = 6.0,
) -> list[FFACandidate]:
    """Full staircase FFA search of one time series over [p_start,
    p_end] seconds (single-trial convenience over ffa_search_block)."""
    return ffa_search_block(
        np.asarray(x)[None, :], tsamp, p_start, p_end, min_dc,
        [dm], snr_min=snr_min,
    )


def collapse_periods(
    cands: list[FFACandidate], tol: float = 1e-3
) -> list[FFACandidate]:
    """Sort by S/N descending and keep the strongest candidate of
    each near-duplicate period cluster (relative tolerance)."""
    cands = sorted(cands, key=lambda c: -c.snr)
    out: list[FFACandidate] = []
    for c in cands:
        if all(abs(c.period - o.period) / o.period > tol for o in out):
            out.append(c)
    return out


# --- audit registry: one octave program over a tiny fold grid, plus
# a ShapeCtx hook at the FIRST octave's geometry for a bucket's
# dedispersed trial length (the staircase downsamples by 2 per octave,
# so the first octave is the largest program the bucket traces) ---
from .registry import register_program, sds  # noqa: E402


def _param_octave(ctx):
    n = ctx.out_nsamps
    if n < 2 * _PMIN:
        return None
    m_pad = 1 << max(1, int(np.ceil(np.log2(max(2, n // _PMIN)))))
    widths = duty_cycle_widths(0.01)
    d = max(1, min(2, ctx.dm_block))
    return (_octave_fn(m_pad, widths), (sds((d, n), "float32"),), {})


register_program(
    "ops.ffa.octave",
    lambda: (_octave_fn(8, (1, 2, 4)), (sds((2048,), "float32"),), {}),
    param=_param_octave,
)
