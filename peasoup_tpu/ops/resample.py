"""Time-domain acceleration resampling as an index-map gather.

Reference kernels: resample_kernelII (the search pipeline's version,
out[i] = in[rn(i + i*af*(i-N))], src/kernels.cu:314-346) and the
quadratic resample_kernel (used by the candidate folder,
out[i] = in[rn(i + af*((i-N/2)^2-(N/2)^2))], kernels.cu:308-332), with
af = a*tsamp/(2c) computed in f64 (kernels.cu:354).

TPU design: the reference does the index math per element in f64; TPU
f64 is emulated and slow, so we exploit that the output index is
integer + small shift: rn(i + s) == i + rn(s) for integer i away from
half-sample ties, and the shift s = af*i*(i-N) is computed accurately
in f32 because i and (i-N) are exactly representable (|i| < 2^24) and
af is tiny. Worst-case f32 error in s is ~1e-5 samples — tie-breaking
differences only. Batched over a leading axis of accelerations: one
gather per (accel, sample) tile, MXU-free but VPU/HBM friendly.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

SPEED_OF_LIGHT = 299792458.0


def accel_factor(accs: np.ndarray, tsamp: float) -> np.ndarray:
    """af = (a*tsamp) / (2c): the a*tsamp product is an F32 multiply in
    the reference (``float a, float tsamp``, kernels.cu:348-354), the
    division by 2c is f64."""
    prod = (np.asarray(accs, dtype=np.float32) * np.float32(tsamp)).astype(
        np.float32
    )
    return prod.astype(np.float64) / (2.0 * SPEED_OF_LIGHT)


@jax.jit
def resample_accel(x: jnp.ndarray, afs: jnp.ndarray) -> jnp.ndarray:
    """Resample a time series for each acceleration factor.

    Args:
      x: (N,) float32 time series.
      afs: (A,) float32 acceleration factors (a*tsamp/2c).

    Returns (A, N): out[a, i] = x[i + rint(afs[a]*i*(i-N))].
    """
    n = x.shape[-1]
    idx = jnp.arange(n, dtype=jnp.float32)
    quad = idx * (idx - jnp.float32(n))  # exact inputs, one f32 rounding

    def one(af: jnp.ndarray) -> jnp.ndarray:
        shift = jnp.rint(af * quad).astype(jnp.int32)
        src = jnp.clip(jnp.arange(n, dtype=jnp.int32) + shift, 0, n - 1)
        return jnp.take(x, src)

    return jax.vmap(one)(afs)


@partial(jax.jit, static_argnames=("smax",))
def resample_select(
    x: jnp.ndarray,  # (D, N) f32 time series per DM trial
    afs: jnp.ndarray,  # (D, A) f32 acceleration factors a*tsamp/2c
    *,
    smax: int,
) -> jnp.ndarray:
    """Gather-free resampling for small shift spans.

    For physical accelerations the shift s(i) = rint(af*i*(i-N)) spans
    only a handful of integer values over the WHOLE series
    (|s| <= |af|*N^2/4); each output is then a SELECT among 2*smax+1
    shifted copies of x — pure elementwise VPU work at full HBM
    bandwidth instead of a gather. Edge-padding reproduces the
    reference's index clip exactly (x[clip(i+s, 0, N-1)],
    src/kernels.cu:341-345), so results are bitwise identical to
    :func:`resample_accel`. ``smax`` must be >= max|afs|*N^2/4
    (see :func:`select_span`).

    Returns (D, A, N).
    """
    n = x.shape[-1]
    idx = jnp.arange(n, dtype=jnp.float32)
    quad = idx * (idx - jnp.float32(n))  # exact inputs, one f32 rounding
    shift = jnp.rint(afs[..., None] * quad).astype(jnp.int32)  # (D, A, N)
    xp = jnp.pad(x.astype(jnp.float32), ((0, 0), (smax, smax)), mode="edge")
    out = jnp.zeros(shift.shape, jnp.float32)
    for s in range(-smax, smax + 1):
        arm = jax.lax.dynamic_slice_in_dim(xp, smax + s, n, axis=1)  # (D, N)
        out = jnp.where(shift == jnp.int32(s), arm[:, None, :], out)
    return out


@partial(jax.jit, static_argnames=("smax",))
def resample_select_packed(
    x: jnp.ndarray,  # (D, N) f32 time series per DM trial
    afs: jnp.ndarray,  # (D, A) f32 acceleration factors a*tsamp/2c
    *,
    smax: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """:func:`resample_select` emitted directly as (even, odd) sample
    planes — the packed matmul rfft's complex deinterleave
    (ops/fft.py:packed_dft_z) costs a stride-2 relayout of the full
    (D, A, N) resample output (~21 ms/dense search on v5e); selecting
    into the two half-length planes costs the same select work and
    makes the relayout FREE (the per-trial input is tiny, so its own
    parity split is noise). Values are BITWISE those of
    resample_select: out_even[..., j] == out[..., 2j],
    out_odd[..., j] == out[..., 2j+1].

    Returns ((D, A, N//2), (D, A, N//2)).
    """
    n = x.shape[-1]
    m = n // 2
    idx = jnp.arange(n, dtype=jnp.float32)
    quad = idx * (idx - jnp.float32(n))  # exact inputs, one f32 rounding
    she = jnp.rint(afs[..., None] * quad[0::2]).astype(jnp.int32)
    sho = jnp.rint(afs[..., None] * quad[1::2]).astype(jnp.int32)
    xp = jnp.pad(x.astype(jnp.float32), ((0, 0), (smax, smax)), mode="edge")
    planes = (xp[:, 0::2], xp[:, 1::2])  # xp[2t], xp[2t+1]
    oute = jnp.zeros(she.shape, jnp.float32)
    outo = jnp.zeros(sho.shape, jnp.float32)
    for s in range(-smax, smax + 1):
        # even output j reads xp[smax + s + 2j]: parity of (smax+s)
        # picks the plane, its half-index the slice offset
        p = smax + s
        arm = jax.lax.dynamic_slice_in_dim(planes[p % 2], p // 2, m, axis=1)
        oute = jnp.where(she == jnp.int32(s), arm[:, None, :], oute)
        p = smax + s + 1  # odd output j reads xp[smax + s + 2j + 1]
        arm = jax.lax.dynamic_slice_in_dim(planes[p % 2], p // 2, m, axis=1)
        outo = jnp.where(sho == jnp.int32(s), arm[:, None, :], outo)
    return oute, outo


@partial(jax.jit, static_argnames=("smax", "n1", "n2"))
def resample_select_packed_planes(
    x: jnp.ndarray,  # (D, N) f32 time series per DM trial
    afs: jnp.ndarray,  # (D, A) f32 acceleration factors a*tsamp/2c
    *,
    smax: int,
    n1: int,
    n2: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """:func:`resample_select_packed` emitted directly as the fused DFT
    kernel's (D, A, n1, n2) input planes (flat sample j = j1*n2 + j2,
    row-major — ops/pallas/dftspec.py). Computing the select IN the
    4-D shape matters: a reshape between the 3-D select output and the
    kernel operand changes the XLA tile layout, which materialises as
    two full-plane relayout copy passes (~25 ms at the dense tutorial
    grid, traced r5 — the whole einsum-chain win eaten); here the
    select's one fused loop writes the kernel's tiled layout directly.
    Every arm must stay an index-map view (not a materialised array):
    a per-arm reshape of the flat slice makes XLA materialise the arm
    AND its (D, A, n1, n2) broadcast (traced r5: 12 broadcast passes,
    +12 ms), so the arms are instead STATIC slices of one overlapped-
    window base XB[d, j1, t] = plane[d, j1*n2 + t] (t < n2 + smax;
    built once per parity, ~plane-sized) — two small fusion operands,
    nineteen offsets. Values are BITWISE those of resample_select:
    out_even[..., j1, j2] == out[..., 2*(j1*n2+j2)], odd likewise."""
    n = x.shape[-1]
    m = n // 2
    if n1 * n2 != m:
        raise ValueError(f"bad plane factorisation {n1}x{n2} != {m}")
    idx = jnp.arange(n, dtype=jnp.float32)
    quad = idx * (idx - jnp.float32(n))  # exact inputs, one f32 rounding
    q4e = quad[0::2].reshape(n1, n2)
    q4o = quad[1::2].reshape(n1, n2)
    she = jnp.rint(afs[..., None, None] * q4e).astype(jnp.int32)
    sho = jnp.rint(afs[..., None, None] * q4o).astype(jnp.int32)
    xp = jnp.pad(x.astype(jnp.float32), ((0, 0), (smax, smax)), mode="edge")
    # overlapped row windows: arm offsets o = (smax+s[+1])//2 <= smax,
    # so XB width n2+smax covers every arm's [o, o+n2) row slice and
    # the build's max flat index is m+smax-1 — exactly the plane length
    win = jnp.arange(n1)[:, None] * n2 + jnp.arange(n2 + smax)[None, :]
    xbs = tuple(
        jnp.take(xp[:, par::2], win, axis=1) for par in (0, 1)
    )  # (D, n1, n2+smax) each
    oute = jnp.zeros(she.shape, jnp.float32)
    outo = jnp.zeros(sho.shape, jnp.float32)
    for s in range(-smax, smax + 1):
        # even output j reads xp[smax + s + 2j]: parity of (smax+s)
        # picks the plane, its half-index the slice offset
        p = smax + s
        arm = xbs[p % 2][:, :, p // 2 : p // 2 + n2]
        oute = jnp.where(she == jnp.int32(s), arm[:, None], oute)
        p = smax + s + 1  # odd output j reads xp[smax + s + 2j + 1]
        arm = xbs[p % 2][:, :, p // 2 : p // 2 + n2]
        outo = jnp.where(sho == jnp.int32(s), arm[:, None], outo)
    # one joint barrier, like packed_dft_z_parts': without it XLA's
    # priority fusion pre-materialises several arm broadcasts as
    # full-size (D, A, n1, n2) passes instead of emitting ONE select
    # loop (traced r5: 16.8 -> ~5 ms)
    return jax.lax.optimization_barrier((oute, outo))


def select_span(af_max: float, n: int, limit: int = 64) -> int:
    """Static shift bound for :func:`resample_select`: ceil of
    max|af|*N^2/4 plus one guard sample, or 0 when the span exceeds
    ``limit`` (caller should use the gather path instead)."""
    smax = int(np.ceil(af_max * (n / 2.0) ** 2)) + 1
    return smax if smax <= limit else 0


@jax.jit
def resample_accel_quadratic(x: jnp.ndarray, af: jnp.ndarray) -> jnp.ndarray:
    """The folder's variant: out[i] = x[i + rint(af*((i-N/2)^2-(N/2)^2))]
    (kernels.cu:308-332)."""
    n = x.shape[-1]
    half = jnp.float32(n) / 2.0
    idx = jnp.arange(n, dtype=jnp.float32)
    quad = (idx - half) ** 2 - half * half
    shift = jnp.rint(af * quad).astype(jnp.int32)
    src = jnp.clip(jnp.arange(n, dtype=jnp.int32) + shift, 0, n - 1)
    return jnp.take(x, src)


# --- audit registry (ShapeCtx hooks rebuild the resample programs at
# a periodicity bucket's (dm_block, accel_pad, fft_size) production
# tile, derived from the accel plan in perf.warmup.shape_ctx_for_
# bucket; non-periodicity ctxs decline) ---
from .registry import register_program, sds  # noqa: E402


def _param_resample_accel(ctx):
    if ctx.fft_size <= 0 or ctx.accel_pad <= 0:
        return None
    return (
        resample_accel,
        (sds((ctx.fft_size,), "float32"), sds((ctx.accel_pad,), "float32")),
        {},
    )


def _param_select(fn):
    def hook(ctx, fn=fn):
        # the gather-free select only dispatches when the span probe
        # admits it (pipeline/search.py); mirror that gate here
        if ctx.fft_size <= 0 or ctx.accel_pad <= 0 or ctx.select_smax <= 0:
            return None
        return (
            fn,
            (
                sds((ctx.dm_block, ctx.fft_size), "float32"),
                sds((ctx.dm_block, ctx.accel_pad), "float32"),
            ),
            {"smax": ctx.select_smax},
        )
    return hook


def _param_select_planes(ctx):
    base = _param_select(resample_select_packed_planes)(ctx)
    if base is None or ctx.fft_size & (ctx.fft_size - 1):
        return None
    from .pallas.dftspec import plane_factors

    n1, n2 = plane_factors(ctx.fft_size // 2)
    fn, args, kwargs = base
    return fn, args, {**kwargs, "n1": n1, "n2": n2}


register_program(
    "ops.resample.resample_accel",
    lambda: (resample_accel, (sds((256,), "float32"), sds((4,), "float32")), {}),
    param=_param_resample_accel,
)
def _param_resample_quadratic(ctx):
    # the jerk-trial variant resamples one series per scalar adot at
    # the same fft tile as the linear path
    if ctx.fft_size <= 0:
        return None
    return (
        resample_accel_quadratic,
        (sds((ctx.fft_size,), "float32"), sds((), "float32")),
        {},
    )


register_program(
    "ops.resample.resample_accel_quadratic",
    lambda: (
        resample_accel_quadratic,
        (sds((256,), "float32"), sds((), "float32")),
        {},
    ),
    param=_param_resample_quadratic,
)
register_program(
    "ops.resample.resample_select",
    lambda: (
        resample_select,
        (sds((4, 256), "float32"), sds((4, 3), "float32")),
        {"smax": 4},
    ),
    param=_param_select(resample_select),
)
register_program(
    "ops.resample.resample_select_packed",
    lambda: (
        resample_select_packed,
        (sds((4, 256), "float32"), sds((4, 3), "float32")),
        {"smax": 4},
    ),
    param=_param_select(resample_select_packed),
)
register_program(
    "ops.resample.resample_select_packed_planes",
    lambda: (
        resample_select_packed_planes,
        (sds((4, 256), "float32"), sds((4, 3), "float32")),
        {"smax": 4, "n1": 8, "n2": 16},
    ),
    param=_param_select_planes,
)
