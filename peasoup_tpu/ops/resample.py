"""Time-domain acceleration resampling as an index-map gather.

Reference kernels: resample_kernelII (the search pipeline's version,
out[i] = in[rn(i + i*af*(i-N))], src/kernels.cu:314-346) and the
quadratic resample_kernel (used by the candidate folder,
out[i] = in[rn(i + af*((i-N/2)^2-(N/2)^2))], kernels.cu:308-332), with
af = a*tsamp/(2c) computed in f64 (kernels.cu:354).

TPU design: the reference does the index math per element in f64; TPU
f64 is emulated and slow, so we exploit that the output index is
integer + small shift: rn(i + s) == i + rn(s) for integer i away from
half-sample ties, and the shift s = af*i*(i-N) is computed accurately
in f32 because i and (i-N) are exactly representable (|i| < 2^24) and
af is tiny. Worst-case f32 error in s is ~1e-5 samples — tie-breaking
differences only. Batched over a leading axis of accelerations: one
gather per (accel, sample) tile, MXU-free but VPU/HBM friendly.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

SPEED_OF_LIGHT = 299792458.0


def accel_factor(accs: np.ndarray, tsamp: float) -> np.ndarray:
    """af = a * tsamp / (2c) in f64 on the host (kernels.cu:354)."""
    return np.asarray(accs, dtype=np.float64) * tsamp / (2.0 * SPEED_OF_LIGHT)


@jax.jit
def resample_accel(x: jnp.ndarray, afs: jnp.ndarray) -> jnp.ndarray:
    """Resample a time series for each acceleration factor.

    Args:
      x: (N,) float32 time series.
      afs: (A,) float32 acceleration factors (a*tsamp/2c).

    Returns (A, N): out[a, i] = x[i + rint(afs[a]*i*(i-N))].
    """
    n = x.shape[-1]
    idx = jnp.arange(n, dtype=jnp.float32)
    quad = idx * (idx - jnp.float32(n))  # exact inputs, one f32 rounding

    def one(af: jnp.ndarray) -> jnp.ndarray:
        shift = jnp.rint(af * quad).astype(jnp.int32)
        src = jnp.clip(jnp.arange(n, dtype=jnp.int32) + shift, 0, n - 1)
        return jnp.take(x, src)

    return jax.vmap(one)(afs)


@jax.jit
def resample_accel_quadratic(x: jnp.ndarray, af: jnp.ndarray) -> jnp.ndarray:
    """The folder's variant: out[i] = x[i + rint(af*((i-N/2)^2-(N/2)^2))]
    (kernels.cu:308-332)."""
    n = x.shape[-1]
    half = jnp.float32(n) / 2.0
    idx = jnp.arange(n, dtype=jnp.float32)
    quad = (idx - half) ** 2 - half * half
    shift = jnp.rint(af * quad).astype(jnp.int32)
    src = jnp.clip(jnp.arange(n, dtype=jnp.int32) + shift, 0, n - 1)
    return jnp.take(x, src)
