from .dedisperse import dedisperse, dedisperse_block
from .spectrum import form_power, form_interpolated, spectrum_stats, normalise
from .rednoise import median_scrunch5, linear_stretch, running_median, deredden
from .zap import birdie_mask, zap_birdies
from .resample import resample_accel, resample_accel_quadratic, accel_factor
from .harmonics import harmonic_sums
from .peaks import find_peaks_device, cluster_peaks
from .singlepulse import (
    boxcar_best,
    default_widths,
    make_single_pulse_search_fn,
    matched_filter_snr,
    normalise_trials,
    width_scales,
)
from .fold import fold_time_series, fold_time_series_np
from .fold_optimise import FoldOptimiser
from .coincidence import coincidence_mask
from .correlate import baseline_pairs, find_delays
