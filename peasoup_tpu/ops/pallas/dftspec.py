"""Pallas TPU kernel: packed four-step DFT fused with untwist+interbin.

Replaces the XLA einsum chain (ops/fft.py packed_dft_z_parts) PLUS the
untwist+interbin+normalise kernel (ops/pallas/interbin.py) for the
production pow2 search sizes. The einsum chain is LAYOUT-bound, not
MXU-bound: XLA materialises both DFT stages through HBM and inserts
four full-array {3,2,1,0}<->{3,1,2,0} relayout copies around them
(compiled-HLO-verified, NOTES.md round-4 continuation) — einsums
29.2 ms + copies 9.2 ms + interbin kernel 7.6 ms at the dense tutorial
grid. Here one kernel does the whole chain per 8-row stripe in VMEM:

  planes (8, n1, n2) -> step1 DFT over j1 -> twiddle -> step2 DFT over
  j2 -> Z (k2, k1) in natural bin order -> mirror/untwist -> interbin
  -> normalise -> (8, npad) spectrum pre-padded for the harmonic
  mega-kernel.

Key structural tricks:
  * Both DFT stages contract dim 0 of both operands (the MXU's
    transposed-lhs form), so the four-step's classic middle transpose
    NEVER materialises: step1 emits Ct (j2, l) from A (j1, j2) against
    the symmetric W1 (j1, l), and step2 emits Et (k2, k1) from
    Tt (j2, k1) against W2 (j2, k2) — flat (k2, k1) IS bin order
    k = k1 + n1*k2, so the output reshape is a free bitcast.
  * f32 x f32 matmuls run as an explicit THREE-PASS bf16 term
    expansion (x = xh+xm by exact 16-bit word truncation, w likewise;
    passes xm*wh, xh*wm, xh*wh summed small-to-large) — the same
    accuracy class as XLA's Precision.HIGH (~1.5e-5 rel), which the
    golden-recall gate accepts END TO END: the PEASOUP_FFT_PRECISION=
    high experiment measured recall 1.0 with exact ranks and ~0 dS/N
    deltas (NOTES.md round-4 continuation). A full six-pass
    HIGHEST-class variant was built and measured — 41 ms vs the
    chain's 46, all of the win eaten by split/pass overhead — so the
    shipped kernel is the 3-pass form (21.8 ms standalone). Gating is
    TWO-LAYERED (probe_pallas_dftspec): (a) a STRUCTURAL per-bin gate
    against :func:`dft_untwist_interbin_twin` — a pure-jnp replay of
    the kernel built from the SAME helper functions with the SAME term
    grouping, so beyond Mosaic-vs-XLA accumulation-order noise
    (measured <= 8.9e-6 of the 3e-5 envelope) the two differ only if
    Mosaic mis-lowers something (roll off by a lane, bad flip, wrong
    clamp); and (b) an
    ACCURACY-CLASS gate against the exact HIGHEST einsum chain:
    per-bin |amp - amp_ref| / (|amp_ref| + rms) max <= 1e-3 and
    99.9%-quantile <= 2e-4 (measured 3.7e-4 / 5.7e-5; the golden-
    recall gate remains the end-to-end arbiter). PEASOUP_FUSED_DFT=0
    restores the einsum + interbin-kernel chain (exact HIGHEST).
  * The mirror term Z[M-k] is built with one-hot reversals: plane
    order by an anti-identity dot on the sublane dim, lane order by
    the aligned-slice + ANTI-128 dot (interbin.py's _rev_lanes
    argument), both at the same 2-term class as the DFT (the one-hot
    side is exact; term-separate flips skip one split); the k1=0
    column is patched from a plane-shifted column-0 extract whose
    CIRCULAR roll supplies the k=0 wrap to Z[0], and the Nyquist bin
    is a (1,1) store (Mosaic cannot broadcast (1,1) across both
    sublanes and lanes, even staged).

Reference chain: cuFFT R2C -> bin_interbin_series -> normalise
(src/kernels.cu:231-304 + 469-494); same bin conventions as
ops/pallas/interbin.py.

VMEM: ~2 MB/plane operands (x2 double-buffered), (8, n1, n2) x2 Z
scratch, (8, npad) output — gated to m <= 2^17 (the benchmark sizes);
survey-scale m falls back to the einsum + interbin-kernel path via the
shape gate in the caller.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# toolchain compat: TPUCompilerParams -> CompilerParams rename; both
# accept vmem_limit_bytes. PSK203 pins this against the toolchain.
_COMPILER_PARAMS = (
    getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
)

_SUB = 8  # rows per stripe (f32 sublane quantum)
_MAX_M = 1 << 17  # VMEM gate: per-plane stripe buffer = 8*m*4 bytes

_MSK32 = np.uint32(0xFFFF0000)


def _split3_np(x: np.ndarray):
    """Exact 3-term bf16 split by 16-bit word truncation (hi+mid+lo
    == x in f32; each term exactly bf16-representable)."""
    xi = x.view(np.uint32)
    hi = (xi & _MSK32).view(np.float32)
    r1 = x - hi
    mid = (r1.view(np.uint32) & _MSK32).view(np.float32)
    lo = r1 - mid
    return hi, mid, lo


def _split3(x: jnp.ndarray):
    """The same split traced (kernel or jnp twin)."""
    m = jnp.uint32(0xFFFF0000)
    xi = jax.lax.bitcast_convert_type(x, jnp.uint32)
    hi = jax.lax.bitcast_convert_type(xi & m, jnp.float32)
    r1 = x - hi
    mid = jax.lax.bitcast_convert_type(
        jax.lax.bitcast_convert_type(r1, jnp.uint32) & m, jnp.float32
    )
    lo = r1 - mid
    return hi, mid, lo


_DN0 = (((0,), (0,)), ((), ()))  # contract dim0 x dim0 (xT @ y form)


def _bd(a, b, dn=_DN0):
    return jax.lax.dot_general(
        a, b, dn, preferred_element_type=jnp.float32
    ).astype(jnp.float32)


def _dot3(xs, ws, dn=_DN0):
    """Three-pass bf16-split f32 matmul (xm*wh + xh*wm + xh*wh,
    small-to-large): Precision.HIGH-class accuracy (~1.5e-5 rel), the
    class the golden gate accepts for the FFT chain."""
    xh, xm = xs
    wh, wm = ws
    return (_bd(xm, wh, dn) + _bd(xh, wm, dn)) + _bd(xh, wh, dn)


def _b16(x):
    return x.astype(jnp.bfloat16)


def _split2_b16(x):
    h, m_, _l = _split3(x)
    return _b16(h), _b16(m_)


@lru_cache(maxsize=None)
def _consts(n: int):
    """Kernel constants for series length n (m = n//2 = n1*n2):
    pre-split bf16 DFT matrices, transposed twiddles, untwist phasor in
    (k2, k1) plane space, and the two anti-identities."""
    m = n // 2
    n1, n2 = plane_factors(m)
    j1 = np.arange(n1)
    j2 = np.arange(n2)
    w1 = np.exp(-2j * np.pi * np.outer(j1, j1) / n1)  # symmetric
    w2 = np.exp(-2j * np.pi * np.outer(j2, j2) / n2)  # symmetric
    tw = np.exp(-2j * np.pi * np.outer(j1, j2) / m)
    out = {"n1": n1, "n2": n2}
    for name, mat in (
        ("w1r", w1.real), ("w1i", w1.imag),
        ("w2r", w2.real), ("w2i", w2.imag),
    ):
        # hi+mid terms only (3-pass class); stored f32 (exactly bf16-
        # representable), cast to bf16 at trace time (exact)
        out[name] = np.stack(
            _split3_np(np.ascontiguousarray(mat, np.float32))[:2]
        )
    out["twtr"] = np.ascontiguousarray(tw.real.T, np.float32)  # (j2, l)
    out["twti"] = np.ascontiguousarray(tw.imag.T, np.float32)
    out["anti_n2"] = np.eye(n2, dtype=np.float32)[::-1].copy()
    out["anti128"] = np.eye(128, dtype=np.float32)[::-1].copy()
    return out


def _flip2(z, anti_rows, anti128, n1, n2):
    """Both-dims reversal P[k2,k1] = z[n2-1-k2, n1-1-k1] at the 2-term
    class: lane order by aligned 128-slices + one-hot ANTI-128 dots
    applied PER TERM (flipping a term is exact, so no re-split between
    the stages), then plane order by the anti-identity from the left
    on a fresh 2-term split of the lane-flipped value."""
    g = n1 // 128
    dnl = (((2,), (0,)), ((), ()))
    a128 = _b16(anti128)

    def fl(t):
        xg = jnp.concatenate(
            [t[:, i * 128 : (i + 1) * 128] for i in reversed(range(g))],
            axis=1,
        )
        return _bd(xg.reshape(n2, g, 128), a128, dnl).reshape(n2, n1)

    h, m_ = _split2_b16(z)
    lf = fl(h) + fl(m_)
    h2, m2 = _split2_b16(lf)
    dn0 = (((1,), (0,)), ((), ()))
    ab = _b16(anti_rows)
    return _bd(ab, h2, dn0) + _bd(ab, m2, dn0)


def _rev_rows2(z, anti_rows):
    """Reverse dim0 (sublane planes) of (n, w) at the 2-term class:
    one-hot anti-identity matmul from the left."""
    zs = _split2_b16(z)
    a = _b16(anti_rows)
    dn = (((1,), (0,)), ((), ()))  # ANTI (rev, j) @ z (j, w)
    return _bd(a, zs[0], dn) + _bd(a, zs[1], dn)


def _row_dft_tail(ctr, cti, w2s, w2is, twtr, twti):
    """Steps 2+3 of one plane's DFT from its step-1 result Ct (j2, l):
    twiddle, then the j2 contraction emitting Z as (k2, k1)."""
    # step 2 twiddle in transposed (j2, l) space
    ttr = ctr * twtr - cti * twti
    tti = ctr * twti + cti * twtr
    # step 3 (contract j2): Et (k2, k1) = sum_j2 W2[j2,k2] Tt[j2,k1]
    ttrs = _split2_b16(ttr)
    ttis = _split2_b16(tti)
    zr = _dot3(w2s, ttrs) - _dot3(w2is, ttis)
    zi = _dot3(w2s, ttis) + _dot3(w2is, ttrs)
    return zr, zi


_DNB = (((1,), (0,)), ((), ()))  # contract j1 of (S, n1, n2) with w dim0


def _stripe_dft_step1(xe3, xo3, w1s, w1is):
    """Step 1 for a whole (S, n1, n2) stripe, BATCHED: contracting j1
    against W1 makes each dot M = S*n2 rows instead of n2 (better MXU
    utilisation at these small tiles), with the complex
    (W1r + iW1i)(ar + i*ai) result emitted naturally (S, j2, l).
    Shared VERBATIM by the kernel and the twin (same _dot3 contract,
    just the _DNB dimension numbers) so batched-matmul accumulation
    blocking can never open a kernel/twin gap."""
    ars = _split2_b16(xe3)
    ais = _split2_b16(xo3)
    ctr = _dot3(ars, w1s, _DNB) - _dot3(ais, w1is, _DNB)
    cti = _dot3(ais, w1s, _DNB) + _dot3(ars, w1is, _DNB)
    return ctr, cti


def _row_spectrum(
    zr, zi, unc, uns, anti_n, anti128, mean, std, *, n1, n2, roll
):
    """One plane's untwist + interbin + normalise: Z (k2, k1) -> the
    (n2, n1) main spectrum block plus the (1, 1) Nyquist bin. ``roll``
    is ``pltpu.roll`` inside the kernel and ``jnp.roll`` in the twin
    (identical circular semantics); everything else is the same traced
    ops in the same order."""
    lane = jax.lax.broadcasted_iota(jnp.int32, (n2, n1), 1)
    plane = jax.lax.broadcasted_iota(jnp.int32, (n2, n1), 0)
    first = (lane == 0) & (plane == 0)
    # mirror zm[k] = Z[M-k]: for k1 >= 1 it is P[k2, k1-1] with
    # P = flip_planes(flip_lanes(Z)); for k1 == 0 (k2 >= 1) it is
    # Z[n2-k2, 0] = plane-shifted flip of column 0; (0,0) -> Z[0]
    pr = _flip2(zr, anti_n, anti128, n1, n2)
    pi = _flip2(zi, anti_n, anti128, n1, n2)
    prr = roll(pr, 1, 1)
    pir = roll(pi, 1, 1)
    # column 0 fix: zm(k2, 0) = Z[n2-k2, 0] = roll_planes(flipped
    # col0, 1); flipped col0 [k2] = Z[n2-1-k2, 0]. The roll is
    # CIRCULAR, so (0,0) wraps to flipped[n2-1] = Z[0,0] — exactly
    # the k=0 mirror (zm[0] = Z[0]); no separate override needed
    # (and none is possible: Mosaic refuses (1,1)->both-dims
    # broadcasts, even staged — it fuses the chain back together)
    c0r = roll(_rev_rows2(zr[:, 0:1], anti_n), 1, 0)
    c0i = roll(_rev_rows2(zi[:, 0:1], anti_n), 1, 0)
    zmr = jnp.where(lane == 0, c0r, prr)
    zmi = jnp.where(lane == 0, c0i, pir)
    # untwist (ops/fft.py formulas, identical to interbin.py)
    arr_ = 0.5 * (zr + zmr)
    aii = 0.5 * (zi - zmi)
    br = zr - zmr
    bi = zi + zmi
    xr = arr_ + 0.5 * (unc * bi - uns * br)
    xi = aii - 0.5 * (unc * br + uns * bi)
    # interbin shift X[k-1]: lane roll + previous-plane column fix
    xr_l = roll(xr, 1, 1)
    xi_l = roll(xi, 1, 1)
    cl_r = roll(xr[:, n1 - 1 : n1], 1, 0)
    cl_i = roll(xi[:, n1 - 1 : n1], 1, 0)
    xr_l = jnp.where(lane == 0, cl_r, xr_l)
    xi_l = jnp.where(lane == 0, cl_i, xi_l)
    xr_l = jnp.where(first, 0.0, xr_l)
    xi_l = jnp.where(first, 0.0, xi_l)
    ampsq = xr * xr + xi * xi
    dsq = 0.5 * ((xr - xr_l) ** 2 + (xi - xi_l) ** 2)
    amp = jnp.sqrt(jnp.maximum(ampsq, dsq))
    main = (amp - mean) / std
    # Nyquist bin m: X[m] = ReZ[0] - ImZ[0] (real; the untwist
    # identities), X[m-1] = X[n2-1, n1-1]
    xnr = zr[0:1, 0:1] - zi[0:1, 0:1]
    xml_r = xr[n2 - 1 : n2, n1 - 1 : n1]
    xml_i = xi[n2 - 1 : n2, n1 - 1 : n1]
    namp = jnp.sqrt(
        jnp.maximum(
            xnr * xnr, 0.5 * ((xnr - xml_r) ** 2 + xml_i * xml_i)
        )
    )
    return main, (namp - mean) / std


def _kernel(
    w1_ref, w2_ref, twtr_ref, twti_ref, unc_ref, uns_ref, antin_ref,
    anti128_ref, mean_ref, std_ref, xe_ref, xo_ref, out_ref, zr3, zi3,
    *, n1, n2, m, kpad,
):
    w1s = tuple(_b16(w1_ref[t]) for t in range(2))
    w1is = tuple(_b16(w1_ref[t + 2]) for t in range(2))
    w2s = tuple(_b16(w2_ref[t]) for t in range(2))
    w2is = tuple(_b16(w2_ref[t + 2]) for t in range(2))
    twtr = twtr_ref[:]
    twti = twti_ref[:]

    ctr3, cti3 = _stripe_dft_step1(
        xe_ref[:], xo_ref[:], w1s, w1is
    )  # (S, n2, n1) each
    for r in range(_SUB):
        zr3[r], zi3[r] = _row_dft_tail(
            ctr3[r], cti3[r], w2s, w2is, twtr, twti
        )

    # ---- untwist + interbin + normalise over the whole stripe ----
    anti_n = antin_ref[:]
    anti128 = anti128_ref[:]
    unc = unc_ref[:]
    uns = uns_ref[:]

    for r in range(_SUB):
        # mean/std arrive as SMEM scalars: scalar SPLATS against 2-D
        # values are supported where (1,1)-array broadcasts are not
        row = pl.program_id(0) * _SUB + r
        main, nyq = _row_spectrum(
            zr3[r], zi3[r], unc, uns, anti_n, anti128,
            mean_ref[row], std_ref[row], n1=n1, n2=n2, roll=pltpu.roll,
        )
        out_ref[r, :n2, :] = main
        # the pad planes past the Nyquist stay zero and the single real
        # bin is a (1,1) store — no broadcast
        out_ref[r, n2:, :] = jnp.zeros((kpad - n2, n1), jnp.float32)
        out_ref[r, n2 : n2 + 1, 0:1] = nyq


# ---- shared two-layer oracle (single source for probe_pallas_dftspec
# AND tests/test_pallas.py, so the production gate and CI can't drift) --
STRUCT_ENV_REL = 3e-5  # per-bin envelope factor vs the twin
ACC_MAX_REL = 1e-3  # accuracy class vs the HIGHEST chain: per-bin max
ACC_Q999_REL = 2e-4  # ... and 99.9%-quantile


def twin_envelope(twin: np.ndarray) -> np.ndarray:
    """Per-bin structural tolerance |got - twin| <=
    STRUCT_ENV_REL * (|twin| + row rms): Mosaic-vs-XLA accumulation
    order (TPU probe) and cross-host FMA codegen (CI, cached
    executables) both measure well inside it, while a broken lowering
    perturbs bins by O(rms) — five orders above — and fails every bin
    it breaks. Shared by the interbin oracle (same numeric class)."""
    scale = np.sqrt((twin**2).mean(axis=-1, keepdims=True))
    return STRUCT_ENV_REL * (np.abs(twin) + scale)


def oracle_data(n: int, r: int = 9, seed: int = 0):
    """The tone+noise case both gates run on: interbin's max() takes
    both branches and the accuracy gate sees the cancellation-heavy
    bins adjacent to the tone. Returns (x, xe, xo, mean, std) as
    numpy."""
    rng = np.random.default_rng(seed)
    t = np.arange(n)
    x = (
        rng.normal(size=(r, n)) + 3.0 * np.sin(2 * np.pi * t * 0.1317)
    ).astype(np.float32)
    xe = np.ascontiguousarray(x[:, 0::2])
    xo = np.ascontiguousarray(x[:, 1::2])
    mean = rng.normal(size=r).astype(np.float32)
    std = (0.5 + rng.random(r)).astype(np.float32)
    return x, xe, xo, mean, std


def accuracy_rel(
    got: np.ndarray, ref: np.ndarray, mean: np.ndarray, std: np.ndarray,
    m: int,
) -> np.ndarray:
    """Per-bin accuracy-class residual vs the exact chain:
    |amp - amp_ref| / (|amp_ref| + row rms) on the un-normalised
    amplitudes (gate: max <= ACC_MAX_REL, q99.9 <= ACC_Q999_REL;
    measured 3.7e-4 / 5.7e-5 — the max sits at untwist-cancellation
    bins, inherent to any HIGH-class DFT)."""
    stdn = std[:, None]
    meann = mean[:, None]
    amp_g = got[:, : m + 1] * stdn + meann
    amp_r = ref * stdn + meann
    scale = np.sqrt((amp_r**2).mean(axis=1, keepdims=True))
    return np.abs(amp_g - amp_r) / (np.abs(amp_r) + scale)


def plane_factors(m: int) -> tuple[int, int]:
    """The kernel's DFT factorisation m = n1 * n2 (n1 = the pow2 at or
    below sqrt(m)); producers that emit (.., n1, n2) planes directly
    (ops/resample.py:resample_select_packed_planes) use this so the
    select writes the kernel's tile layout with no relayout pass."""
    n1 = 1 << ((m.bit_length() - 1) // 2)
    return n1, m // n1


def _geometry(m: int, npad: int) -> tuple[int, int, int]:
    """Validate the kernel's shape preconditions for half-length ``m``
    and output pad ``npad``; returns (n1, n2, kpad) or raises."""
    if m <= 0 or m & (m - 1):
        raise ValueError(f"fused DFT kernel needs pow2 m, got {m}")
    if m > _MAX_M:
        raise ValueError(f"fused DFT kernel gated to m <= {_MAX_M}, got {m}")
    n1, n2 = plane_factors(m)
    if npad % n1 or npad <= m or n1 % 128 or n2 % 8:
        raise ValueError(f"bad dftspec geometry {m=} {npad=} {n1=} {n2=}")
    return n1, n2, npad // n1


def dftspec_supported(size: int, npad: int) -> bool:
    """Shape gate for the driver: True iff the fused kernel's geometry
    preconditions hold for series length ``size`` and output pad
    ``npad`` (survey-scale m falls back to the einsum chain here, not
    via a trace-time ValueError)."""
    if size <= 0 or size % 2:
        return False
    try:
        _geometry(size // 2, npad)
    except ValueError:
        return False
    return True


def _phasor(n: int, n1: int, n2: int):
    """Untwist phasor in (k2, k1) plane space: bin k = k1 + n1*k2 < m."""
    k = (np.arange(n2)[:, None] * n1 + np.arange(n1)[None, :]).astype(
        np.float64
    )
    un = np.exp(-2j * np.pi * k / n)
    return (
        jnp.asarray(un.real.astype(np.float32)),
        jnp.asarray((-un.imag).astype(np.float32)),
    )


@lru_cache(maxsize=None)
def _build(rpad: int, n: int, npad: int, interpret: bool):
    c = _consts(n)
    n1, n2 = c["n1"], c["n2"]
    m = n1 * n2
    kpad = npad // n1
    kernel = partial(_kernel, n1=n1, n2=n2, m=m, kpad=kpad)
    cspec = lambda shape: pl.BlockSpec(shape, lambda r: tuple(0 for _ in shape))
    return pl.pallas_call(
        kernel,
        grid=(rpad // _SUB,),
        in_specs=[
            cspec((4, n1, n1)),  # w1 parts (r/i x 2 terms)
            cspec((4, n2, n2)),  # w2 parts
            cspec((n2, n1)),  # twtr
            cspec((n2, n1)),  # twti
            cspec((n2, n1)),  # unc
            cspec((n2, n1)),  # uns
            cspec((n2, n2)),  # anti_n (plane reversal)
            cspec((128, 128)),  # anti128 (lane reversal)
            pl.BlockSpec(memory_space=pltpu.SMEM),  # mean (rpad,)
            pl.BlockSpec(memory_space=pltpu.SMEM),  # std (rpad,)
            pl.BlockSpec((_SUB, n1, n2), lambda r: (r, 0, 0)),  # xe
            pl.BlockSpec((_SUB, n1, n2), lambda r: (r, 0, 0)),  # xo
        ],
        out_specs=pl.BlockSpec((_SUB, kpad, n1), lambda r: (r, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((rpad, kpad, n1), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((_SUB, n2, n1), jnp.float32),
            pltpu.VMEM((_SUB, n2, n1), jnp.float32),
        ],
        compiler_params=_COMPILER_PARAMS(
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        interpret=interpret,
    )


def _plane_view(xe, npad):
    """Resolve the input view: (R, m) flat planes are reshaped to the
    kernel's (R, n1, n2); (R, n1, n2) pre-shaped planes (the zero-copy
    producer path) are validated and passed through."""
    if xe.ndim == 3:
        r, a1, a2 = xe.shape
        m = a1 * a2
        n1, n2, kpad = _geometry(m, npad)
        if (a1, a2) != (n1, n2):
            raise ValueError(
                f"pre-shaped planes {a1}x{a2} != kernel factorisation "
                f"{n1}x{n2}"
            )
        return xe, r, m, n1, n2, kpad
    r, m = xe.shape
    n1, n2, kpad = _geometry(m, npad)
    return xe.reshape(r, n1, n2), r, m, n1, n2, kpad


def dft_untwist_interbin(
    xe: jnp.ndarray,  # (R, m) f32 even-sample planes — or (R, n1, n2)
    xo: jnp.ndarray,  # (R, m) f32 odd-sample planes — or (R, n1, n2)
    mean: jnp.ndarray,  # (R,)
    std: jnp.ndarray,  # (R,)
    *,
    npad: int,  # output width, a multiple of n1 and > m
    interpret: bool = False,
) -> jnp.ndarray:
    """(R, npad) f32 normalised interbin spectrum of the real series
    whose even/odd sample planes are xe/xo — the fused equivalent of
    packed_dft_z_parts + untwist_interbin_normalise. bins k in [0, m]
    real, the rest zero. Producers should pass (R, n1, n2) pre-shaped
    planes (plane_factors): the flat (R, m) form costs two full-plane
    relayout copy passes at the XLA/Mosaic tile boundary."""
    xe3, r, m, n1, n2, kpad = _plane_view(xe, npad)
    xo3 = _plane_view(xo, npad)[0]
    n = 2 * m
    c = _consts(n)
    unc, uns = _phasor(n, n1, n2)
    rpad = -(-r // _SUB) * _SUB
    mean2 = mean.astype(jnp.float32)
    std2 = std.astype(jnp.float32)
    if rpad != r:
        pad3 = [(0, rpad - r), (0, 0), (0, 0)]
        xe3 = jnp.pad(xe3, pad3)
        xo3 = jnp.pad(xo3, pad3)
        mean2 = jnp.pad(mean2, (0, rpad - r))
        # std pads with ONES so pad rows never divide by zero
        std2 = jnp.pad(std2, (0, rpad - r), constant_values=1.0)
    fn = _build(rpad, n, npad, interpret)
    out = fn(
        jnp.asarray(np.concatenate([c["w1r"], c["w1i"]])),
        jnp.asarray(np.concatenate([c["w2r"], c["w2i"]])),
        jnp.asarray(c["twtr"]), jnp.asarray(c["twti"]),
        unc, uns,
        jnp.asarray(c["anti_n2"]),
        jnp.asarray(c["anti128"]),
        mean2, std2, xe3, xo3,
    )
    return out.reshape(rpad, npad)[:r]


def dft_untwist_interbin_twin(
    xe: jnp.ndarray,  # (R, m) f32 even-sample planes
    xo: jnp.ndarray,  # (R, m) f32 odd-sample planes
    mean: jnp.ndarray,  # (R,)
    std: jnp.ndarray,  # (R,)
    *,
    npad: int,
) -> jnp.ndarray:
    """Pure-jnp contraction-exact replay of :func:`dft_untwist_interbin`:
    the SAME helper functions (_stripe_dft_step1 / _row_dft_tail /
    _row_spectrum) run outside Pallas, with ``jnp.roll`` standing in
    for ``pltpu.roll`` (identical circular semantics) and the kernel's
    exact stripe batching so every dot has the kernel's operand
    shapes. On a given backend the op sequence — bf16 splits,
    three-pass dots, one-hot flips, rolls — is identical term for
    term, so beyond accumulation-order noise
    (Mosaic MXU vs XLA dots: measured <= 8.9e-6 of the 3e-5 per-bin
    envelope on v5e; bitwise 0 under fresh same-backend CPU compiles)
    any kernel/twin difference is a broken Mosaic lowering. Used by
    probe_pallas_dftspec (on TPU) and the interpret-mode tests (on
    CPU); test-only — O(rows) trace size."""
    xe3, r, m, n1, n2, kpad = _plane_view(xe, npad)
    xo3 = _plane_view(xo, npad)[0]
    n = 2 * m
    c = _consts(n)
    unc, uns = _phasor(n, n1, n2)
    w1cat = jnp.asarray(np.concatenate([c["w1r"], c["w1i"]]))
    w2cat = jnp.asarray(np.concatenate([c["w2r"], c["w2i"]]))
    w1s = tuple(_b16(w1cat[t]) for t in range(2))
    w1is = tuple(_b16(w1cat[t + 2]) for t in range(2))
    w2s = tuple(_b16(w2cat[t]) for t in range(2))
    w2is = tuple(_b16(w2cat[t + 2]) for t in range(2))
    twtr = jnp.asarray(c["twtr"])
    twti = jnp.asarray(c["twti"])
    anti_n = jnp.asarray(c["anti_n2"])
    anti128 = jnp.asarray(c["anti128"])
    xe3 = xe3.astype(jnp.float32)
    xo3 = xo3.astype(jnp.float32)
    mean2 = mean.astype(jnp.float32)
    std2 = std.astype(jnp.float32)
    # replicate the kernel's _SUB-row stripes exactly, including the
    # BATCHED step-1 dot per stripe (shared _stripe_dft_step1): the
    # batched matmul's accumulation blocking is then identical by
    # construction, not by hope
    rpad = -(-r // _SUB) * _SUB
    if rpad != r:
        pad3 = [(0, rpad - r), (0, 0), (0, 0)]
        xe3 = jnp.pad(xe3, pad3)
        xo3 = jnp.pad(xo3, pad3)
    rows = []
    for st in range(rpad // _SUB):
        sl = slice(st * _SUB, (st + 1) * _SUB)
        ctr3, cti3 = _stripe_dft_step1(xe3[sl], xo3[sl], w1s, w1is)
        for i in range(_SUB):
            gr = st * _SUB + i
            if gr >= r:
                break
            zr, zi = _row_dft_tail(
                ctr3[i], cti3[i], w2s, w2is, twtr, twti
            )
            main, nyq = _row_spectrum(
                zr, zi, unc, uns, anti_n, anti128, mean2[gr], std2[gr],
                n1=n1, n2=n2, roll=jnp.roll,
            )
            blk = jnp.zeros((kpad, n1), jnp.float32)
            blk = blk.at[:n2].set(main)
            blk = blk.at[n2, 0].set(nyq[0, 0])
            rows.append(blk.reshape(npad))
    return jnp.stack(rows)
