"""Pallas TPU kernel: fused threshold + compaction + peak clustering.

Replaces the find_peaks_device -> cluster_peaks_device pair
(ops/peaks.py) with ONE sequential pass per spectrum row. Reference
semantics preserved exactly: Thrust copy_if thresholding
(src/kernels.cu:384-416) followed by the identify_unique_peaks walk
(include/transforms/peakfinder.hpp:27-56), including the
lastidx-advances-only-on-new-max quirk.

Why a kernel: XLA's lax.top_k — the only fast sized-compaction
primitive — lowers on TPU to a full per-lane sort whose cost is
independent of k (~400 ms per search chunk at production shapes), and
the separate cluster scan pays another pass. Crossings are sparse
(hundreds per 65k-bin spectrum at a 9-sigma threshold), so a single
streaming pass that walks blocks sequentially and handles crossings
one at a time is ~10x cheaper, AND its output is CLUSTER peaks — the
compaction size no longer needs to cover raw crossings, so the
adaptive-size escalation only ever re-dispatches for cluster-count
overflow (rare).

Design:
  rows are processed in stripes of ``_SUB`` rows (a multiple of the
  f32 sublane quantum 8; default 24 — see the tuning comment at the
  definition): grid = (row stripes, bin blocks), sequential
  ("arbitrary") order, so for each stripe the kernel sees blocks of
  ``_BLOCK`` bins left to right. The identify_unique_peaks state
  machine runs as _SUB independent rows of (cursor, raw count, open,
  cpeak, cpeakidx, lastidx) vectors living in VMEM scratch across
  grid steps. Per block: vector threshold mask; a stripe whose block
  has no crossing pays only the mask+check. Otherwise a fori_loop
  walks crossings oldest-first in every row at once (masked min per
  sublane); cluster emissions write the (_SUB, mx) output block
  through a one-hot select (no dynamic-index stores). Output blocks
  stay VMEM-resident for the whole stripe (their BlockSpec index
  ignores the bin axis).

Outputs per row: cluster idxs (mx,) i32 ascending padded with
``nbins``; cluster snrs (mx,) f32 zero-padded; counts (2,) i32 =
(raw crossings, clusters). Matches the (idxs, snrs, ccounts)
convention of cluster_peaks_device; clusters beyond ``mx`` are
dropped but still counted (callers escalate on counts[1] > mx).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import os as _os

from ...obs.log import get_logger as _get_logger
from ...obs.telemetry import current as _current_telemetry

_log = _get_logger("ops.pallas.peaks")

# How the stripe height was resolved, for telemetry/debugging: the
# probe subprocess used to run silently, leaving "why is this machine
# on _SUB=8?" undiagnosable. Keys: sub (the resolved height), source
# (env|probe), and for probed resolutions cache (hit|miss|skip) and
# verdict (ok|bad|notpu|cpu-platform|inconclusive*). The peasoup CLI
# forwards this dict as a ``pallas_peaks_sub`` telemetry event.
SUB_RESOLUTION: dict = {}

PEAKS_BLOCK = int(_os.environ.get("PEASOUP_PEAKS_BLOCK", "4096"))
# bins per grid step (128-lane multiple); 4096 measured best on v5e
# (fewer grid steps beats the larger per-step vector work; r3 scan:
# 512/1024/8704/17408 give 112/94/99/135 ms vs 87 ms for 2048-4096 at
# production shapes).  Overridable for tuning via PEASOUP_PEAKS_BLOCK
# (read once at import); harmonic_sums(block_align=PEAKS_BLOCK) keeps
# its level padding in lockstep with this value.
if PEAKS_BLOCK <= 0 or PEAKS_BLOCK % 128:
    raise ValueError(
        f"PEASOUP_PEAKS_BLOCK must be a positive multiple of 128, got "
        f"{PEAKS_BLOCK}"
    )
_BLOCK = PEAKS_BLOCK
# rows per stripe (multiple of the f32 sublane quantum 8): taller
# stripes cut the number of grid steps — the window-merged walk (r4)
# made the per-step fixed work (per-level threshold mask + count) the
# dominant cost, and it row-vectorises for free. 24 measured best on
# v5e with the harmonic mega-kernel (dense tutorial search 140.1 ->
# 113.3 ms device; 16 gives 119.9, 8 gives 140.1). 32+ fails the
# Mosaic compile: on THIS toolchain that surfaces as a catchable
# remote-compile error the probes turn into a jnp fallback, but other
# toolchains have SIGABRTed the whole process on bad _SUB values (see
# probe_pallas_interbin's note) — an in-process probe CANNOT protect
# against that, so the 24 default is resolved through a subprocess-
# isolated, disk-cached probe (_sub24_default_safe below): a toolchain
# that aborts on 24 kills the CHILD, and this process degrades to the
# everywhere-validated 8. An explicit PEASOUP_PEAKS_SUB override skips
# the probe (the operator owns the risk — and the fix, deleting
# ~/.cache/peasoup_tpu/peaks_sub24.* after a transient probe failure).


def _sub24_default_safe() -> bool:
    """Can THIS toolchain compile+run the peaks kernel at the fast
    default _SUB=24? Probed in a SUBPROCESS so a Mosaic SIGABRT lands
    there, with the verdict cached on disk per (jax, jaxlib) so the
    cost is once per machine, not per process. The child's compile
    also lands in the persistent XLA cache, so the in-process oracle
    probes that follow recompile from cache.

    The PARENT never initialises jax here — on standard TPU runtimes
    holding the client would starve the child of the device and turn
    every probe into a false 'bad'. The CHILD decides the platform,
    and distinguishes a machine with NO TPU hardware (exit 3: no
    Mosaic compile risk anywhere, 24 is safe — persisted as 'ok' so
    non-TPU machines pay the child exactly once) from a TPU that
    exists but could not be acquired, e.g. the parent's client
    already holds it (exit 4: the probe CANNOT validate the fast
    default, so it must not ship it). Verdicts: exit 0 -> 'ok'
    persisted; signal death (SIGABRT-class, the failure this probe
    exists for) -> 'bad' persisted; exit 4 / other nonzero (locked
    TPU, import error, timeout) is INCONCLUSIVE — fall back to 8 for
    this process only, warn, persist nothing, so a transient failure
    can't pin the slow path forever. (Production drivers import this
    module via the oracle probes AFTER the parent client exists; on
    single-client runtimes they land on exit 4 unless a verdict was
    cached earlier — run any CLI once, or `python -c "import
    peasoup_tpu.ops.pallas.peaks"`, to seed the cache, or set
    PEASOUP_PEAKS_SUB explicitly.)"""
    import hashlib
    import subprocess
    import sys
    import warnings

    # raw env forms of the geometry knobs (the module constants _SBW/
    # _WSTEPS are defined below this resolution point; the child
    # inherits the same env, so these pin the probed geometry)
    _SBW_ENV = _os.environ.get("PEASOUP_PEAKS_SBW", "0")
    _WSTEPS_ENV = _os.environ.get("PEASOUP_PEAKS_WSTEPS", "2")

    # explicit cpu-only env (the test suite's conftest) — same verdict
    # the child would return, without paying its jax import
    if _os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        SUB_RESOLUTION.update(cache="skip", verdict="cpu-platform")
        return True
    def _ver(pkg):
        try:
            from importlib.metadata import version

            return version(pkg)
        except Exception:
            return "none"

    def _tpu_hw_markers() -> bool:
        # cheap jax-free TPU-hardware sniff, IDENTICAL to the child's:
        # libtpu wheel, accelerator device nodes, or a TPU env
        import glob
        import importlib.util

        return bool(
            importlib.util.find_spec("libtpu") is not None
            or glob.glob("/dev/accel*")
            or glob.glob("/dev/vfio/*")
            or _os.environ.get("TPU_NAME")
        )

    # libtpu ships as its own wheel: the Mosaic toolchain can change
    # under a fixed jax/jaxlib, so it must be part of the verdict key —
    # as must the kernel-geometry knobs the child compiles with
    # (PEASOUP_PEAKS_BLOCK/SBW/WSTEPS): a verdict probed at one block
    # geometry says nothing about another
    key = (
        f"24-{_ver('jax')}-{_ver('jaxlib')}-{_ver('libtpu')}"
        f"-{PEAKS_BLOCK}-{_SBW_ENV}-{_WSTEPS_ENV}"
    )
    cache_dir = _os.path.join(
        _os.environ.get(
            "XDG_CACHE_HOME", _os.path.expanduser("~/.cache")
        ),
        "peasoup_tpu",
    )
    path = _os.path.join(
        cache_dir,
        "peaks_sub24." + hashlib.sha1(key.encode()).hexdigest()[:12],
    )
    try:
        with open(path) as fh:
            verdict = fh.read().strip()
        if verdict == "ok":
            SUB_RESOLUTION.update(cache="hit", verdict="ok")
            return True
        if verdict == "bad":
            SUB_RESOLUTION.update(cache="hit", verdict="bad")
            return False
        # 'notpu' was recorded on a machine with no TPU hardware: honor
        # it only while that is still true (a shared/NFS cache reaching
        # a real TPU machine must re-probe, not ship 24 unvalidated)
        if verdict == "notpu" and not _tpu_hw_markers():
            SUB_RESOLUTION.update(cache="hit", verdict="notpu")
            return True
    except OSError:
        pass
    SUB_RESOLUTION["cache"] = "miss"
    pkg_root = _os.path.dirname(  # .../peasoup_tpu/ops/pallas -> repo
        _os.path.dirname(_os.path.dirname(_os.path.dirname(__file__)))
    )
    script = (
        "import os, sys, glob\n"
        "os.environ['PEASOUP_PEAKS_SUB'] = '24'\n"
        "import importlib.util\n"
        "import jax\n"
        "if jax.default_backend() != 'tpu':\n"
        "    # no-TPU machine (exit 3) vs TPU hardware present but\n"
        "    # unacquirable, e.g. locked by the parent (exit 4): the\n"
        "    # latter must stay inconclusive — libtpu/accel devices or\n"
        "    # a TPU-ish plugin env mean a tpu backend was expected\n"
        "    has_hw = (\n"
        "        importlib.util.find_spec('libtpu') is not None\n"
        "        or glob.glob('/dev/accel*') or glob.glob('/dev/vfio/*')\n"
        "        or os.environ.get('TPU_NAME')\n"
        "    )\n"
        "    sys.exit(4 if has_hw else 3)\n"
        "import numpy as np, jax.numpy as jnp\n"
        "from peasoup_tpu.utils.cache import enable_compilation_cache\n"
        "enable_compilation_cache()\n"
        "from peasoup_tpu.ops.pallas.peaks import find_cluster_peaks_multi\n"
        "s = jnp.asarray(np.zeros((24, %d), np.float32))\n"
        "w = jnp.asarray(np.asarray([[0, 100]], np.int32))\n"
        "out = find_cluster_peaks_multi(\n"
        "    [s], w, threshold=5.0, max_peaks=32, scales=(1.0,),\n"
        "    nbins=%d,\n"
        ")\n"
        "[np.asarray(a) for a in out]\n" % (PEAKS_BLOCK, PEAKS_BLOCK - 7)
    )
    env = dict(_os.environ)
    env["PYTHONPATH"] = (
        pkg_root + _os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH")
        else pkg_root
    )
    err_tail = ""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", script],
            timeout=900, capture_output=True, env=env,
        )
        rc = proc.returncode
        err_tail = proc.stderr.decode("utf-8", "replace")[-400:]
    except Exception as exc:
        rc = 1
        err_tail = f"{type(exc).__name__}: {exc}"
    if rc > 0 and rc != 3:
        # inconclusive (locked TPU / import error / timeout):
        # conservative for this process, nothing persisted; the child's
        # stderr tail makes the cause diagnosable from logs
        SUB_RESOLUTION.update(verdict="inconclusive", exit_code=rc)
        warnings.warn(
            "PEASOUP_PEAKS_SUB probe subprocess could not validate the "
            f"fast stripe height (exit {rc}); using the conservative 8 "
            "for this process. Seed the verdict cache from a process "
            "that does not yet hold the TPU (e.g. `python -c \"import "
            "peasoup_tpu.ops.pallas.peaks\"`) or set "
            f"PEASOUP_PEAKS_SUB=24 explicitly. Child stderr: {err_tail}"
        )
        return False
    # rc 0: validated on TPU -> 'ok'. rc 3: no TPU hardware on this
    # machine -> 'notpu' (24 is risk-free here — compiled Mosaic
    # kernels are gated off by backend_supports_pallas — but a TPU
    # machine reading this cache re-probes; see the read side).
    # Signal death: only ABORT-class signals (the Mosaic fault this
    # probe exists for) persist 'bad' — an operator's Ctrl-C or the
    # OOM-killer mid-probe must stay inconclusive, or it would pin the
    # slow path on this machine forever.
    import signal

    if rc < 0 and -rc not in (
        signal.SIGABRT, signal.SIGSEGV, signal.SIGILL, signal.SIGFPE,
        signal.SIGBUS,
    ):
        SUB_RESOLUTION.update(
            verdict="inconclusive-signal", signal=-rc
        )
        warnings.warn(
            f"PEASOUP_PEAKS_SUB probe subprocess was killed (signal "
            f"{-rc}); treating as inconclusive — using 8 for this "
            "process, nothing persisted."
        )
        return False
    ok = rc in (0, 3)
    SUB_RESOLUTION.update(
        verdict="ok" if rc == 0 else "notpu" if rc == 3 else "bad"
    )
    try:
        _os.makedirs(cache_dir, exist_ok=True)
        with open(path, "w") as fh:
            fh.write("ok" if rc == 0 else "notpu" if rc == 3 else "bad")
    except OSError:
        pass  # read-only home: re-probe per process
    return ok


_sub_env = _os.environ.get("PEASOUP_PEAKS_SUB")
if _sub_env is not None:
    _SUB = int(_sub_env)
    SUB_RESOLUTION.update(sub=_SUB, source="env")
else:
    _SUB = 24 if _sub24_default_safe() else 8
    SUB_RESOLUTION.update(sub=_SUB, source="probe")
if _SUB <= 0 or _SUB % 8:
    raise ValueError(f"PEASOUP_PEAKS_SUB must be a positive multiple of 8: {_SUB}")
# surface the (formerly silent) resolution: a debug log line always,
# plus a telemetry event when a run's telemetry is already active (the
# peasoup CLI re-emits SUB_RESOLUTION into its own manifest, since this
# module usually resolves before the run's telemetry is activated)
_log.debug("peaks stripe height resolved: %s", SUB_RESOLUTION)
_current_telemetry().event("pallas_peaks_sub", **SUB_RESOLUTION)
# crossing-walk subblock width (lanes). r3 chose 512 to shrink
# per-TRIP vector work; with the r4 window-merged walk trips are few
# and the per-SUBBLOCK guards (a sum reduction + scalar branch each,
# x nlev per grid step) dominate instead, so the default is now the
# full block (one guard per level per step; measured 41.1 -> 35.5 ms
# at the dense tutorial grid).
_SBW = int(_os.environ.get("PEASOUP_PEAKS_SBW", "0")) or _BLOCK
if _SBW <= 0 or _SBW % 128 or _BLOCK % _SBW:
    raise ValueError(
        "PEASOUP_PEAKS_SBW must be a positive multiple of 128 dividing "
        f"PEASOUP_PEAKS_BLOCK: {_SBW}"
    )
# unrolled machine steps per while-loop trip (the walk is trip-latency
# bound; each step is one close/emit + one window merge); must be >= 1
# or the walk loop would never clear crossings (infinite device loop)
_WSTEPS = int(_os.environ.get("PEASOUP_PEAKS_WSTEPS", "2"))
if _WSTEPS < 1:
    raise ValueError(f"PEASOUP_PEAKS_WSTEPS must be >= 1, got {_WSTEPS}")
_BIG = 1 << 30  # "no crossing" sentinel for the masked min reduction


def _level_machine(
    lvl, s, *, win_ref, idx_ref, snr_ref, cnt_ref, istate, fstate, mstate,
    b, nb, gidx, slot, mx, threshold, min_gap, scale,
):
    """One harmonic level's threshold + identify_unique_peaks walk for
    the current (stripe, block) grid step. ``s`` is the level's (VMEM-
    resident) value block — loaded from an operand by the peaks kernel,
    computed in VMEM by the harmonic mega-kernel (harmpeaks.py). State
    lives in shared scratch columns [lvl*8, lvl*8+5); outputs go to
    slices [lvl*mx, (lvl+1)*mx) of idx/snr and [2*lvl, 2*lvl+2) of
    cnt."""
    c0 = lvl * 8  # this level's state column base
    o0, o1 = lvl * mx, (lvl + 1) * mx
    lo = win_ref[lvl, 0]
    hi = win_ref[lvl, 1]
    if scale != 1.0:
        s = s * jnp.float32(scale)
    mask = (gidx >= lo) & (gidx < hi) & (s > jnp.float32(threshold))
    cnt = jnp.sum(mask.astype(jnp.int32), axis=1, keepdims=True)
    istate[:, c0 + 1 : c0 + 2] = istate[:, c0 + 1 : c0 + 2] + cnt

    def emit(do, cursor, cpeakidx, cpeak):
        hot = do & (slot == cursor) & (cursor < mx)
        idx_ref[:, o0:o1] = jnp.where(hot, cpeakidx, idx_ref[:, o0:o1])
        snr_ref[:, o0:o1] = jnp.where(hot, cpeak, snr_ref[:, o0:o1])

    @pl.when(jnp.max(cnt) > 0)
    def _(mask=mask, s=s, emit=emit, c0=c0):
        mstate[:] = mask.astype(jnp.int32)

        # walk the block's crossings SUBBLOCK by subblock (left to
        # right, so the cluster machine sees the same ascending
        # crossing sequence). All slices are STATIC (python
        # unroll), so no dynamic lane indexing reaches Mosaic.
        #
        # WINDOW-MERGED walk (r4): the walk is TRIP-LATENCY-bound
        # (~8.7 us/trip regardless of vector width — r3 measured
        # subblock shrinking and block-size scans flat), so the
        # lever is trip COUNT. Each trip processes the first
        # remaining crossing through the full close/emit/take
        # machine, then MERGES every further crossing j in the
        # close-free window (idx, lastidx' + min_gap) in one vector
        # step: for such j, close cannot fire (lastidx only
        # advances, so j - lastidx_at_j < min_gap), and a close-free
        # sequence of takes reduces to "final cpeak = max(cpeak,
        # window max); lastidx/cpeakidx move to the FIRST position
        # of the window max iff it strictly beats cpeak" — exactly
        # the identify_unique_peaks quirk (lastidx advances only on
        # new max, peakfinder.hpp:27-56), because intermediate
        # non-emitting takes leave no other trace. A contiguous
        # ~min_gap-wide cluster run collapses from ~30 trips to ~2.
        for lo_l in range(0, _BLOCK, _SBW):
            mask_sb = mask[:, lo_l : lo_l + _SBW]
            gidx_sb = gidx[:, lo_l : lo_l + _SBW]
            s_sb = s[:, lo_l : lo_l + _SBW]
            # at full-block _SBW the enclosing cnt guard already
            # established crossings exist: reuse its (cheaper,
            # lane-reduced) sum as the loop seed and drop the
            # (always-true) inner guard entirely at trace time
            tot_sb = (
                jnp.sum(cnt)
                if _SBW == _BLOCK
                else jnp.sum(mask_sb.astype(jnp.int32))
            )
            guard = (
                (lambda f: f())
                if _SBW == _BLOCK
                else pl.when(tot_sb > 0)
            )

            @guard
            def _(mask_sb=mask_sb, gidx_sb=gidx_sb, s_sb=s_sb,
                  tot_sb=tot_sb, lo_l=lo_l, emit=emit, c0=c0):
                def body(rem):
                    msk = mstate[:, lo_l : lo_l + _SBW] > 0
                    cursor = istate[:, c0 : c0 + 1]
                    open_ = istate[:, c0 + 2 : c0 + 3]
                    cpeakidx = istate[:, c0 + 3 : c0 + 4]
                    lastidx = istate[:, c0 + 4 : c0 + 5]
                    cpeak = fstate[:, c0 : c0 + 1]
                    # _WSTEPS unrolled machine steps per trip: the
                    # loop is trip-latency-bound, so more vector
                    # work per trip is nearly free
                    for _ in range(_WSTEPS):
                        idx = jnp.min(
                            jnp.where(msk, gidx_sb, jnp.int32(_BIG)),
                            axis=1, keepdims=True,
                        )
                        act = idx < jnp.int32(_BIG)
                        snr = jnp.max(
                            jnp.where(
                                msk & (gidx_sb == idx), s_sb, -jnp.inf
                            ),
                            axis=1,
                            keepdims=True,
                        )
                        close = (
                            act
                            & (open_ == 1)
                            & (idx - lastidx >= min_gap)
                        )
                        emit(close, cursor, cpeakidx, cpeak)
                        cursor = jnp.where(close, cursor + 1, cursor)
                        start = act & ((open_ == 0) | close)
                        take = start | (act & (snr > cpeak))
                        cpeakidx = jnp.where(take, idx, cpeakidx)
                        lastidx = jnp.where(take, idx, lastidx)
                        cpeak = jnp.where(take, snr, cpeak)
                        open_ = jnp.where(act, 1, open_)
                        # close-free window past the first element:
                        # one masked max + first-argmax stands in
                        # for every crossing the sequential machine
                        # could only take, never close on
                        wmask = (
                            msk
                            & (gidx_sb > idx)
                            & (gidx_sb < lastidx + jnp.int32(min_gap))
                        )
                        wmax = jnp.max(
                            jnp.where(wmask, s_sb, -jnp.inf),
                            axis=1, keepdims=True,
                        )
                        wfirst = jnp.min(
                            jnp.where(
                                wmask & (s_sb == wmax), gidx_sb,
                                jnp.int32(_BIG),
                            ),
                            axis=1, keepdims=True,
                        )
                        wtake = act & (wmax > cpeak)
                        cpeakidx = jnp.where(wtake, wfirst, cpeakidx)
                        lastidx = jnp.where(wtake, wfirst, lastidx)
                        cpeak = jnp.where(wtake, wmax, cpeak)
                        msk = msk & ~((gidx_sb == idx) | wmask)
                    nst = msk.astype(jnp.int32)
                    mstate[:, lo_l : lo_l + _SBW] = nst
                    istate[:, c0 : c0 + 1] = cursor
                    istate[:, c0 + 2 : c0 + 3] = open_
                    istate[:, c0 + 3 : c0 + 4] = cpeakidx
                    istate[:, c0 + 4 : c0 + 5] = lastidx
                    fstate[:, c0 : c0 + 1] = cpeak
                    return jnp.sum(nst)

                jax.lax.while_loop(lambda rem: rem > 0, body, tot_sb)

    @pl.when(b == nb - 1)
    def _(emit=emit, c0=c0, lvl=lvl):
        open_ = istate[:, c0 + 2 : c0 + 3]
        emit(
            open_ == 1, istate[:, c0 : c0 + 1],
            istate[:, c0 + 3 : c0 + 4], fstate[:, c0 : c0 + 1],
        )
        cnt_ref[:, 2 * lvl : 2 * lvl + 1] = istate[:, c0 + 1 : c0 + 2]
        cnt_ref[:, 2 * lvl + 1 : 2 * lvl + 2] = (
            istate[:, c0 : c0 + 1] + open_
        )


def _kernel_multi(*refs, nlev, mx, nbins, threshold, min_gap, scales):
    """All nlev levels' threshold+cluster machines in ONE grid walk:
    each (stripe, block) step streams every level's block and runs nlev
    independent identify_unique_peaks machines via the shared
    _level_machine. One kernel dispatch and one fifth the grid steps of
    the per-level version — the per-step DMA latency was the dominant
    cost, not the bytes."""
    win_ref = refs[0]
    s_refs = refs[1 : 1 + nlev]
    idx_ref, snr_ref, cnt_ref = refs[1 + nlev : 4 + nlev]
    istate, fstate, mstate = refs[4 + nlev : 7 + nlev]
    b = pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(b == 0)
    def _():
        istate[:] = jnp.zeros((_SUB, 128), jnp.int32)
        fstate[:] = jnp.zeros((_SUB, 128), jnp.float32)
        idx_ref[:] = jnp.full((_SUB, nlev * mx), nbins, jnp.int32)
        snr_ref[:] = jnp.zeros((_SUB, nlev * mx), jnp.float32)

    gidx = b * _BLOCK + jax.lax.broadcasted_iota(jnp.int32, (_SUB, _BLOCK), 1)
    slot = jax.lax.broadcasted_iota(jnp.int32, (_SUB, mx), 1)

    for lvl in range(nlev):
        _level_machine(
            lvl, s_refs[lvl][:], win_ref=win_ref, idx_ref=idx_ref,
            snr_ref=snr_ref, cnt_ref=cnt_ref, istate=istate, fstate=fstate,
            mstate=mstate, b=b, nb=nb, gidx=gidx, slot=slot, mx=mx,
            threshold=threshold, min_gap=min_gap, scale=scales[lvl],
        )


@lru_cache(maxsize=None)
def _build_multi(
    rows: int, npad: int, nlev: int, mx: int, nbins: int,
    threshold: float, min_gap: int, scales: tuple, interpret: bool,
):
    kernel = partial(
        _kernel_multi, nlev=nlev, mx=mx, nbins=nbins, threshold=threshold,
        min_gap=min_gap, scales=scales,
    )
    nblk = npad // _BLOCK
    return pl.pallas_call(
        kernel,
        grid=(rows // _SUB, nblk),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [
            pl.BlockSpec((_SUB, _BLOCK), lambda r, b: (r, b))
            for _ in range(nlev)
        ],
        out_specs=[
            pl.BlockSpec((_SUB, nlev * mx), lambda r, b: (r, 0)),
            pl.BlockSpec((_SUB, nlev * mx), lambda r, b: (r, 0)),
            pl.BlockSpec((_SUB, nlev * 2), lambda r, b: (r, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, nlev * mx), jnp.int32),
            jax.ShapeDtypeStruct((rows, nlev * mx), jnp.float32),
            jax.ShapeDtypeStruct((rows, nlev * 2), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((_SUB, 128), jnp.int32),
            pltpu.VMEM((_SUB, 128), jnp.float32),
            pltpu.VMEM((_SUB, _BLOCK), jnp.int32),
        ],
        interpret=interpret,
    )


def find_cluster_peaks_multi(
    levels,  # sequence of nlev (..., nbins) f32 spectra (level 0 = base)
    windows: jnp.ndarray,  # (nlev, 2) i32 [start, limit) per level
    *,
    threshold: float,
    max_peaks: int,
    scales: tuple,  # per-level in-VMEM factors (1.0 for pre-scaled)
    min_gap: int = 30,
    interpret: bool = False,
    nbins: int | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One-dispatch equivalent of nlev find_cluster_peaks_pallas calls.
    Returns (idxs (..., nlev, max_peaks), snrs, raw counts (..., nlev),
    cluster counts (..., nlev)).  ``nbins`` is the TRUE bin count (the
    idx pad sentinel) when the level arrays arrive pre-padded past it
    (harmonic_sums block_align) — the pad region must be masked by the
    windows' hi bounds."""
    nlev = len(levels)
    nbins_in = levels[0].shape[-1]
    nbins = nbins if nbins is not None else nbins_in
    # the pad region past the true nbins is GARBAGE (harmonic sums
    # gather real low bins there), so no window may reach into it —
    # clamp rather than trust every caller's window construction
    windows = jnp.stack(
        [windows[:, 0], jnp.minimum(windows[:, 1], nbins)], axis=1
    )
    batch = levels[0].shape[:-1]
    rows = 1
    for d in batch:
        rows *= d
    npad = -(-nbins_in // _BLOCK) * _BLOCK
    rpad = -(-rows // _SUB) * _SUB
    flats = []
    for s in levels:
        flat = s.reshape(rows, nbins_in)
        if npad != nbins_in or rpad != rows:
            flat = jnp.pad(flat, ((0, rpad - rows), (0, npad - nbins_in)))
        flats.append(flat)
    fn = _build_multi(
        rpad, npad, nlev, max_peaks, nbins, float(threshold), min_gap,
        tuple(float(x) for x in scales), interpret,
    )
    cidx, csnr, counts = fn(windows.astype(jnp.int32), *flats)
    cidx = cidx[:rows].reshape(*batch, nlev, max_peaks)
    csnr = csnr[:rows].reshape(*batch, nlev, max_peaks)
    counts = counts[:rows].reshape(*batch, nlev, 2)
    return cidx, csnr, counts[..., 0], counts[..., 1]


def find_cluster_peaks_pallas(
    spec: jnp.ndarray,  # (..., nbins) f32 normalised spectrum/harmonic sum
    windows: jnp.ndarray,  # (nlev, 2) i32 [start, limit) per level
    lvl: int,
    *,
    threshold: float,
    max_peaks: int,
    min_gap: int = 30,
    interpret: bool = False,
    scale: float = 1.0,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fused equivalent of find_peaks_device + cluster_peaks_device for
    one harmonic level: a thin nlev=1 wrapper over the multi-level
    kernel so the cluster state machine exists in exactly one place.
    Returns (cluster idxs (..., max_peaks), cluster snrs, raw count
    (...,), cluster count (...,)). With ``scale`` != 1 the spectrum is
    multiplied by it in VMEM before thresholding (for unscaled
    cumulative harmonic sums)."""
    cidx, csnr, counts, ccounts = find_cluster_peaks_multi(
        [spec], windows[lvl : lvl + 1],
        threshold=threshold, max_peaks=max_peaks, scales=(scale,),
        min_gap=min_gap, interpret=interpret,
    )
    return (
        cidx[..., 0, :],
        csnr[..., 0, :],
        counts[..., 0],
        ccounts[..., 0],
    )
