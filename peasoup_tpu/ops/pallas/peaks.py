"""Pallas TPU kernel: fused threshold + compaction + peak clustering.

Replaces the find_peaks_device -> cluster_peaks_device pair
(ops/peaks.py) with ONE sequential pass per spectrum row. Reference
semantics preserved exactly: Thrust copy_if thresholding
(src/kernels.cu:384-416) followed by the identify_unique_peaks walk
(include/transforms/peakfinder.hpp:27-56), including the
lastidx-advances-only-on-new-max quirk.

Why a kernel: XLA's lax.top_k — the only fast sized-compaction
primitive — lowers on TPU to a full per-lane sort whose cost is
independent of k (~400 ms per search chunk at production shapes), and
the separate cluster scan pays another pass. Crossings are sparse
(hundreds per 65k-bin spectrum at a 9-sigma threshold), so a single
streaming pass that walks blocks sequentially and handles crossings
one at a time is ~10x cheaper, AND its output is CLUSTER peaks — the
compaction size no longer needs to cover raw crossings, so the
adaptive-size escalation only ever re-dispatches for cluster-count
overflow (rare).

Design:
  rows are processed in stripes of ``_SUB`` = 8 (the f32 sublane
  quantum): grid = (row stripes, bin blocks), sequential ("arbitrary")
  order, so for each stripe the kernel sees blocks of ``_BLOCK`` bins
  left to right. The identify_unique_peaks state machine runs as 8
  independent lanes of (cursor, raw count, open, cpeak, cpeakidx,
  lastidx) vectors living in VMEM scratch across grid steps. Per
  block: vector threshold mask; a stripe whose block has no crossing
  pays only the mask+check. Otherwise a fori_loop walks crossings
  oldest-first in every row lane at once (masked min per sublane);
  cluster emissions write the (8, mx) output block through a one-hot
  select (no dynamic-index stores). Output blocks stay VMEM-resident
  for the whole stripe (their BlockSpec index ignores the bin axis).

Outputs per row: cluster idxs (mx,) i32 ascending padded with
``nbins``; cluster snrs (mx,) f32 zero-padded; counts (2,) i32 =
(raw crossings, clusters). Matches the (idxs, snrs, ccounts)
convention of cluster_peaks_device; clusters beyond ``mx`` are
dropped but still counted (callers escalate on counts[1] > mx).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BLOCK = 4096  # bins per grid step (128-lane multiple); 4096 measured
# best on v5e (fewer grid steps beats the larger per-step vector work:
# 34 -> 29 ms per level-call at production shapes; 8192 regresses)
_SUB = 8  # rows per stripe (f32 sublane quantum)
_BIG = 1 << 30  # "no crossing" sentinel for the masked min reduction


def _kernel(
    win_ref,  # SMEM (nlev, 2) i32 [start, limit) rows
    s_ref,  # VMEM (SUB, B) f32 spectrum stripe block
    idx_ref,  # VMEM (SUB, mx) i32 out, stripe-resident
    snr_ref,  # VMEM (SUB, mx) f32 out, stripe-resident
    cnt_ref,  # VMEM (SUB, 2) i32 out (raw, clusters)
    istate,  # VMEM scratch (SUB, 128) i32: cursor/raw/open/cpeakidx/lastidx
    fstate,  # VMEM scratch (SUB, 128) f32: cpeak
    mstate,  # VMEM scratch (SUB, B) i32: crossing mask being consumed
    *,
    lvl: int,
    mx: int,
    nbins: int,
    threshold: float,
    min_gap: int,
):
    b = pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(b == 0)
    def _():
        istate[:, :5] = jnp.zeros((_SUB, 5), jnp.int32)
        fstate[:, :1] = jnp.zeros((_SUB, 1), jnp.float32)
        idx_ref[:] = jnp.full((_SUB, mx), nbins, jnp.int32)
        snr_ref[:] = jnp.zeros((_SUB, mx), jnp.float32)

    lo = win_ref[lvl, 0]
    hi = win_ref[lvl, 1]
    s = s_ref[:]
    gidx = b * _BLOCK + jax.lax.broadcasted_iota(jnp.int32, (_SUB, _BLOCK), 1)
    mask = (gidx >= lo) & (gidx < hi) & (s > jnp.float32(threshold))
    cnt = jnp.sum(mask.astype(jnp.int32), axis=1, keepdims=True)  # (SUB, 1)
    istate[:, 1:2] = istate[:, 1:2] + cnt

    slot = jax.lax.broadcasted_iota(jnp.int32, (_SUB, mx), 1)

    def emit(do, cursor, cpeakidx, cpeak):
        # one-hot write of each emitting lane's cluster peak
        hot = do & (slot == cursor) & (cursor < mx)
        idx_ref[:] = jnp.where(hot, cpeakidx, idx_ref[:])
        snr_ref[:] = jnp.where(hot, cpeak, snr_ref[:])

    @pl.when(jnp.max(cnt) > 0)
    def _():
        # Mosaic's loop regions only legalize scalar carries: the loop
        # counts down the worst row lane's crossings while ALL mutable
        # state (remaining-crossings mask + cluster machine) lives in
        # VMEM scratch refs.
        mstate[:] = mask.astype(jnp.int32)

        def body(it):
            m = mstate[:] > 0
            cursor = istate[:, 0:1]
            open_ = istate[:, 2:3]
            cpeakidx = istate[:, 3:4]
            lastidx = istate[:, 4:5]
            cpeak = fstate[:, 0:1]
            idx = jnp.min(
                jnp.where(m, gidx, jnp.int32(_BIG)), axis=1, keepdims=True
            )
            act = idx < jnp.int32(_BIG)  # lanes with a crossing left
            snr = jnp.max(
                jnp.where(m & (gidx == idx), s, -jnp.inf),
                axis=1,
                keepdims=True,
            )
            close = act & (open_ == 1) & (idx - lastidx >= min_gap)
            emit(close, cursor, cpeakidx, cpeak)
            cursor = jnp.where(close, cursor + 1, cursor)
            start = act & ((open_ == 0) | close)
            take = start | (act & (snr > cpeak))
            mstate[:] = jnp.where(gidx == idx, 0, mstate[:])
            istate[:, 0:1] = cursor
            istate[:, 2:3] = jnp.where(act, 1, open_)
            istate[:, 3:4] = jnp.where(take, idx, cpeakidx)
            istate[:, 4:5] = jnp.where(take, idx, lastidx)
            fstate[:, 0:1] = jnp.where(take, snr, cpeak)
            return it - 1

        jax.lax.while_loop(lambda it: it > 0, body, jnp.max(cnt))

    @pl.when(b == nb - 1)
    def _():
        # flush the final open cluster of each row lane
        open_ = istate[:, 2:3]
        emit(open_ == 1, istate[:, 0:1], istate[:, 3:4], fstate[:, 0:1])
        cnt_ref[:, 0:1] = istate[:, 1:2]
        cnt_ref[:, 1:2] = istate[:, 0:1] + open_


@lru_cache(maxsize=None)
def _build(
    rows: int, npad: int, nlev: int, lvl: int, mx: int, nbins: int,
    threshold: float, min_gap: int, interpret: bool,
):
    kernel = partial(
        _kernel, lvl=lvl, mx=mx, nbins=nbins, threshold=threshold,
        min_gap=min_gap,
    )
    nblk = npad // _BLOCK
    return pl.pallas_call(
        kernel,
        grid=(rows // _SUB, nblk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # windows table
            pl.BlockSpec((_SUB, _BLOCK), lambda r, b: (r, b)),
        ],
        out_specs=[
            pl.BlockSpec((_SUB, mx), lambda r, b: (r, 0)),
            pl.BlockSpec((_SUB, mx), lambda r, b: (r, 0)),
            pl.BlockSpec((_SUB, 2), lambda r, b: (r, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, mx), jnp.int32),
            jax.ShapeDtypeStruct((rows, mx), jnp.float32),
            jax.ShapeDtypeStruct((rows, 2), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((_SUB, 128), jnp.int32),
            pltpu.VMEM((_SUB, 128), jnp.float32),
            pltpu.VMEM((_SUB, _BLOCK), jnp.int32),
        ],
        interpret=interpret,
    )


def find_cluster_peaks_pallas(
    spec: jnp.ndarray,  # (..., nbins) f32 normalised spectrum/harmonic sum
    windows: jnp.ndarray,  # (nlev, 2) i32 [start, limit) per level
    lvl: int,
    *,
    threshold: float,
    max_peaks: int,
    min_gap: int = 30,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fused equivalent of find_peaks_device + cluster_peaks_device for
    one harmonic level. Returns (cluster idxs (..., max_peaks), cluster
    snrs, raw count (...,), cluster count (...,))."""
    nbins = spec.shape[-1]
    batch = spec.shape[:-1]
    rows = 1
    for d in batch:
        rows *= d
    flat = spec.reshape(rows, nbins)
    npad = -(-nbins // _BLOCK) * _BLOCK
    rpad = -(-rows // _SUB) * _SUB
    if npad != nbins or rpad != rows:
        # pad bins/rows never cross: pad gidx >= nbins >= window limit,
        # and pad-row values 0 <= threshold
        flat = jnp.pad(flat, ((0, rpad - rows), (0, npad - nbins)))
    fn = _build(
        rpad, npad, int(windows.shape[0]), lvl, max_peaks, nbins,
        float(threshold), min_gap, interpret,
    )
    cidx, csnr, counts = fn(windows.astype(jnp.int32), flat)
    return (
        cidx[:rows].reshape(*batch, max_peaks),
        csnr[:rows].reshape(*batch, max_peaks),
        counts[:rows, 0].reshape(batch),
        counts[:rows, 1].reshape(batch),
    )
