"""Pallas TPU kernels for the hot ops.

Each kernel has a pure-jnp twin in ``peasoup_tpu.ops`` used as the
oracle in tests (interpret mode on CPU) and as the fallback on
non-TPU backends or when a kernel's preconditions don't hold.
"""

from __future__ import annotations

from functools import lru_cache

import jax


def backend_supports_pallas() -> bool:
    """Compiled Mosaic kernels need a real TPU backend; everywhere else
    the kernels still run via the interpreter (tests) or fall back."""
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


@lru_cache(maxsize=1)
def probe_pallas_resample() -> bool:
    """One-time REAL compile+run probe of the resample kernel.

    The kernels are interpret-tested everywhere, but Mosaic's compiled
    feature set differs per backend/toolchain; a production search must
    degrade to the jnp twin rather than crash, so eligibility is
    established by actually running a tiny kernel once per process.
    """
    if not backend_supports_pallas():
        return False
    try:
        import numpy as np
        import jax.numpy as jnp

        from .resample import resample_block_pallas

        n = 1024
        x = jnp.asarray(np.arange(2 * n, dtype=np.float32).reshape(2, n))
        afs = jnp.asarray(np.full((2, 2), 1e-9, dtype=np.float32))
        out = np.asarray(resample_block_pallas(x, afs, block=128))
        return bool(np.isfinite(out).all()) and out.shape == (2, 2, n)
    except Exception as exc:  # any Mosaic/compile failure -> jnp path
        import warnings

        warnings.warn(f"Pallas resample kernel unavailable, using jnp "
                      f"fallback: {type(exc).__name__}: {exc}")
        return False


from .resample import resample_block_pallas, resample_block  # noqa: E402
