"""Pallas TPU kernels for the hot ops.

Each kernel has a pure-jnp twin in ``peasoup_tpu.ops`` used as the
oracle in tests (interpret mode on CPU) and as the fallback on
non-TPU backends or when a kernel's preconditions don't hold.
"""

from __future__ import annotations

from functools import lru_cache

import jax


def backend_supports_pallas() -> bool:
    """Compiled Mosaic kernels need a real TPU backend; everywhere else
    the kernels still run via the interpreter (tests) or fall back."""
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


@lru_cache(maxsize=None)
def probe_pallas_resample(n: int, block: int) -> bool:
    """REAL compile+run probe of the resample kernel at the shape the
    caller is about to use (cached per (n, block)).

    The kernels are interpret-tested everywhere, but Mosaic's compiled
    feature set differs per backend/toolchain; a production search must
    degrade to the jnp twin rather than crash, so eligibility is
    established by actually compiling and running the kernel with the
    production n and block (grid trimmed to one DM x one accel trial —
    the VMEM window, DMA shapes, and roll lowering are what vary with
    shape, and those are set by (n, block))."""
    if not backend_supports_pallas() or block <= 0:
        return False
    try:
        import numpy as np
        import jax
        import jax.numpy as jnp

        from .resample import resample_block_pallas
        from ..resample import resample_accel

        # af near the choose_block precondition limit: the shift walks
        # through every select arm, so a wrong pltpu.roll lowering (off
        # by a lane, wrong direction) cannot return oracle-equal data
        af = 1.9 / (float(n) * block)
        x = jnp.asarray(np.arange(n, dtype=np.float32).reshape(1, n))
        afs = jnp.asarray(np.asarray([[af, -af]], dtype=np.float32))
        out = np.asarray(resample_block_pallas(x, afs, block=block))
        if out.shape != (1, 2, n):
            return False
        # the kernel's index math is the same f32 ops as the jnp twin:
        # anything but bitwise equality means a broken lowering
        ref = np.asarray(resample_accel(x[0], afs[0]))
        return bool(np.array_equal(out[0], ref))
    except Exception as exc:  # any Mosaic/compile failure -> jnp path
        import warnings

        warnings.warn(f"Pallas resample kernel unavailable at n={n}, "
                      f"block={block}; using jnp fallback: "
                      f"{type(exc).__name__}: {exc}")
        return False


@lru_cache(maxsize=None)
def probe_pallas_peaks(nbins: int, nlev: int, max_peaks: int) -> bool:
    """REAL compile+run probe of the fused threshold+cluster kernel at
    the production bin count (cached). Oracle-checked against the jnp
    find_peaks_device + cluster_peaks_device pair on data that
    exercises crossings, clusters, gaps, and window edges."""
    if not backend_supports_pallas():
        return False
    try:
        import numpy as np
        import jax.numpy as jnp

        from .peaks import find_cluster_peaks_multi
        from ..peaks import cluster_peaks_device, find_peaks_device

        rng = np.random.default_rng(0)
        # sub-threshold noise + a planted comb: the crossing count is
        # set by the comb alone (a few hundred), so the jnp oracle's
        # fixed raw compaction below never overflows at ANY nbins —
        # a chi-squared noise floor would overflow it for long
        # observations and silently fail the probe
        s = np.abs(rng.normal(size=(9, nbins))).astype(np.float32)
        s[::3, :: max(1, nbins // 97)] += 30.0  # comb of crossings
        s[1, nbins // 2 : nbins // 2 + 400 : 4] += 20.0  # dense cluster run
        lo, hi = nbins // 10, nbins - nbins // 16
        windows = np.tile(
            np.asarray([[lo, hi]], np.int32), (nlev, 1)
        )
        # probe the PRODUCTION input configuration: levels arrive
        # block-aligned with a GARBAGE tail past the true nbins
        # (harmonic_sums block_align) plus the explicit nbins override —
        # the pad region carries huge values so a masking/sentinel
        # regression in the kernel fails the probe, not production
        from .peaks import PEAKS_BLOCK

        npad = -(-nbins // PEAKS_BLOCK) * PEAKS_BLOCK
        sp = jnp.asarray(
            np.pad(s, ((0, 0), (0, npad - nbins)), constant_values=1e9)
        )
        # probe the MULTI-level kernel (the production path): every
        # level gets a scaled view of the same data, in-kernel scales
        # matching the jnp oracle's pre-scaled inputs bitwise
        scales = tuple(
            1.0 if lv == 0 else 2.0 ** (-lv / 2.0) for lv in range(nlev)
        )
        ci, cs, rc, cc = find_cluster_peaks_multi(
            [sp] * nlev, jnp.asarray(windows),
            threshold=9.0, max_peaks=max_peaks, scales=scales,
            nbins=nbins,
        )
        sp = sp[:, :nbins]  # the jnp oracle below sees the true bins
        ci, cs, rc, cc = map(np.asarray, (ci, cs, rc, cc))
        ok = True
        for lv in range(nlev):
            if not ok:
                break
            sc = jnp.asarray(sp * jnp.float32(scales[lv]))
            i_, s_, c_ = find_peaks_device(
                sc, jnp.float32(9.0), jnp.int32(lo), jnp.int32(hi),
                max_peaks=1 << 14,
            )
            ji, js, jc = cluster_peaks_device(i_, s_, jnp.int32(nbins))
            ji, js, jc, c_ = map(np.asarray, (ji, js, jc, c_))
            ok = np.array_equal(rc[:, lv], c_) and np.array_equal(
                cc[:, lv], jc
            )
            for r in range(s.shape[0]):
                if not ok:
                    break
                k = min(int(jc[r]), max_peaks)
                ok = np.array_equal(
                    ci[r, lv, :k], ji[r, :k]
                ) and np.array_equal(cs[r, lv, :k], js[r, :k])
        if not ok:
            import warnings

            warnings.warn(
                f"Pallas peaks kernel FAILED the oracle check at "
                f"nbins={nbins}; using jnp fallback"
            )
        return ok
    except Exception as exc:  # any Mosaic/compile failure -> jnp path
        import warnings

        warnings.warn(
            f"Pallas peaks kernel unavailable at nbins={nbins}; using "
            f"jnp fallback: {type(exc).__name__}: {exc}"
        )
        return False


@lru_cache(maxsize=None)
def probe_pallas_interbin(size: int, block: int) -> bool:
    """REAL compile+run probe of the fused untwist+interbin+normalise
    kernel (ops/pallas/interbin.py) at a small pow2 shape, gated on
    BITWISE equality with the jnp twin chain (rfft_pow2_matmul_parts ->
    form_interpolated_parts -> normalise): the kernel replays exactly
    the same f32 formulas, so any difference means a broken lowering
    (roll off by a lane, bad carry, wrong clamp). The features that
    vary by toolchain (static pltpu.roll, clamped block index maps,
    VMEM carry scratch) are shape-independent, so a small probe gates
    every production shape — at the PRODUCTION block width (Mosaic
    failures can be block-geometry-specific, e.g. the documented
    PEASOUP_PEAKS_SUB SIGABRT), with the probe's m scaled up to fit."""
    if not backend_supports_pallas():
        return False
    try:
        import numpy as np
        import jax.numpy as jnp

        from .interbin import untwist_interbin_normalise
        from ..fft import rfft_pow2_matmul_parts
        from ..spectrum import form_interpolated_parts, normalise

        blk = block
        m = 8192 if 8192 % blk == 0 else 2 * blk
        n = 2 * m
        npad = m + blk
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(9, n)).astype(np.float32))
        mean = jnp.asarray(rng.normal(size=9).astype(np.float32))
        std = jnp.asarray((1.0 + rng.random(9)).astype(np.float32))
        from ..fft import packed_dft_z

        zr, zi = packed_dft_z(x)
        got = np.asarray(
            untwist_interbin_normalise(zr, zi, mean, std, npad=npad, block=blk)
        )
        ref = np.asarray(
            normalise(
                form_interpolated_parts(*rfft_pow2_matmul_parts(x)),
                mean, std,
            )
        )
        ok = (
            got.shape == (9, npad)
            and np.array_equal(got[:, : m + 1], ref)
            and not got[:, m + 1 :].any()
        )
        if not ok:
            import warnings

            warnings.warn(
                "Pallas interbin kernel FAILED the bitwise oracle check; "
                "using the unfused path"
            )
        return ok
    except Exception as exc:  # any Mosaic/compile failure -> unfused path
        import warnings

        warnings.warn(
            f"Pallas interbin kernel unavailable; using the unfused "
            f"path: {type(exc).__name__}: {exc}"
        )
        return False


@lru_cache(maxsize=None)
def probe_pallas_harmpeaks(nbins: int, nharms: int, max_peaks: int) -> bool:
    """REAL compile+run probe of the harmonic+peaks mega-kernel
    (ops/pallas/harmpeaks.py) at the production bin count, oracle-
    checked BITWISE against harmonic_sums(method="take") + the jnp
    find_peaks_device/cluster_peaks_device pair: the kernel's one-hot
    MXU gathers and in-VMEM accumulation replay exactly the same f32
    chain, so any difference means a broken lowering (bad stream index
    map, inexact dot, mis-sliced window)."""
    if not backend_supports_pallas():
        return False
    try:
        import numpy as np
        import jax.numpy as jnp

        from .harmpeaks import find_harmonic_cluster_peaks
        from .peaks import PEAKS_BLOCK
        from ..harmonics import harmonic_sums
        from ..peaks import cluster_peaks_device, find_peaks_device

        nlev = nharms + 1
        rng = np.random.default_rng(0)
        # sub-threshold noise + planted combs (see probe_pallas_peaks);
        # values vary across the full spectrum so every stream's gather
        # path is data-sensitive
        s = np.abs(rng.normal(size=(9, nbins))).astype(np.float32)
        s[::3, :: max(1, nbins // 97)] += 30.0
        s[1, nbins // 2 : nbins // 2 + 400 : 4] += 20.0
        lo, hi = nbins // 10, nbins - nbins // 16
        windows = np.tile(np.asarray([[lo, hi]], np.int32), (nlev, 1))
        npad = -(-nbins // PEAKS_BLOCK) * PEAKS_BLOCK
        # pad region: huge garbage, like the production fused path can
        # carry past the true bins — must be masked by the hi clamp
        sp = jnp.asarray(
            np.pad(s, ((0, 0), (0, npad - nbins)), constant_values=1e9)
        )
        scales = tuple(
            1.0 if lv == 0 else 2.0 ** (-lv / 2.0) for lv in range(nlev)
        )
        ci, cs, rc, cc = find_harmonic_cluster_peaks(
            sp, jnp.asarray(windows), nharms=nharms, threshold=9.0,
            max_peaks=max_peaks, scales=scales, nbins=nbins,
        )
        ci, cs, rc, cc = map(np.asarray, (ci, cs, rc, cc))
        levels = [jnp.asarray(s)] + harmonic_sums(
            jnp.asarray(s), nharms=nharms, method="take", scaled=True
        )
        ok = True
        for lv in range(nlev):
            if not ok:
                break
            i_, s_, c_ = find_peaks_device(
                levels[lv], jnp.float32(9.0), jnp.int32(lo), jnp.int32(hi),
                max_peaks=1 << 14,
            )
            ji, js, jc = cluster_peaks_device(i_, s_, jnp.int32(nbins))
            ji, js, jc, c_ = map(np.asarray, (ji, js, jc, c_))
            ok = np.array_equal(rc[:, lv], c_) and np.array_equal(
                cc[:, lv], jc
            )
            for r in range(s.shape[0]):
                if not ok:
                    break
                k = min(int(jc[r]), max_peaks)
                ok = np.array_equal(
                    ci[r, lv, :k], ji[r, :k]
                ) and np.array_equal(cs[r, lv, :k], js[r, :k])
        if not ok:
            import warnings

            warnings.warn(
                f"Pallas harmonic+peaks mega-kernel FAILED the bitwise "
                f"oracle check at nbins={nbins}; using the conv+peaks path"
            )
        return ok
    except Exception as exc:  # any Mosaic/compile failure -> conv path
        import warnings

        warnings.warn(
            f"Pallas harmonic+peaks mega-kernel unavailable at "
            f"nbins={nbins}; using the conv+peaks path: "
            f"{type(exc).__name__}: {exc}"
        )
        return False


@lru_cache(maxsize=None)
def probe_pallas_dftspec(n: int, npad: int) -> bool:
    """REAL compile+run probe of the fused four-step DFT + untwist +
    interbin + normalise kernel (ops/pallas/dftspec.py) at the
    PRODUCTION (n, npad) — the DFT factorisation (n1, n2) is shape-
    dependent, so unlike the other probes this one runs the exact
    production geometry. Two deliberate gates (the kernel is 3-pass
    HIGH-class, so a single bitwise-vs-exact-chain gate is impossible
    by construction):

    (a) STRUCTURAL, per bin vs dft_untwist_interbin_twin — the same
        helpers with the same term grouping run outside Pallas — at
        |got - twin| <= 3e-5 (|twin| + rms): Mosaic's MXU accumulation
        order differs from XLA's by at most 8.9e-6 of that envelope
        (measured, v5e, production shape), while a broken lowering
        (roll off by a lane, bad flip, wrong clamp) perturbs bins by
        O(rms) — five orders above the gate — and fails every bin it
        breaks.
    (b) ACCURACY CLASS, vs the exact Precision.HIGHEST einsum chain on
        tone+noise data: per-bin |amp - amp_ref| / (|amp_ref| + rms)
        max <= 1e-3 and 99.9%-quantile <= 2e-4 (measured 3.7e-4 /
        5.7e-5; the max sits at untwist-cancellation bins adjacent to
        the tone, inherent to any HIGH-class DFT). The golden-recall
        gate (tests/test_recall.py) remains the end-to-end arbiter.
    """
    if not backend_supports_pallas():
        return False
    try:
        import numpy as np
        import jax.numpy as jnp

        from .dftspec import (
            ACC_MAX_REL, ACC_Q999_REL, accuracy_rel,
            dft_untwist_interbin, dft_untwist_interbin_twin,
            dftspec_supported, oracle_data, twin_envelope,
        )
        from ..fft import rfft_pow2_matmul_parts
        from ..spectrum import form_interpolated_parts, normalise

        if not dftspec_supported(n, npad):
            return False
        m = n // 2
        x, xe, xo, mean, std = oracle_data(n)
        xe, xo = jnp.asarray(xe), jnp.asarray(xo)
        meanj, stdj = jnp.asarray(mean), jnp.asarray(std)
        got = np.asarray(
            dft_untwist_interbin(xe, xo, meanj, stdj, npad=npad)
        )
        tw = np.asarray(
            dft_untwist_interbin_twin(xe, xo, meanj, stdj, npad=npad)
        )
        ok = got.shape == (9, npad) and bool(
            (np.abs(got - tw) <= twin_envelope(tw)).all()
        )
        if ok:
            ref = np.asarray(
                normalise(
                    form_interpolated_parts(
                        *rfft_pow2_matmul_parts(jnp.asarray(x))
                    ),
                    meanj, stdj,
                )
            )
            rel = accuracy_rel(got, ref, mean, std, m)
            ok = (
                float(rel.max()) <= ACC_MAX_REL
                and float(np.quantile(rel, 0.999)) <= ACC_Q999_REL
                and not got[:, m + 1 :].any()
            )
        if not ok:
            import warnings

            warnings.warn(
                f"Pallas fused-DFT kernel FAILED the oracle gates at "
                f"n={n}; using the einsum + interbin-kernel chain"
            )
        return ok
    except Exception as exc:  # any Mosaic/compile failure -> einsum chain
        import warnings

        warnings.warn(
            f"Pallas fused-DFT kernel unavailable at n={n}: "
            f"{type(exc).__name__}: {exc}; using the einsum + "
            f"interbin-kernel chain"
        )
        return False


@lru_cache(maxsize=None)
def probe_pallas_boxcar(n_widths: int, span: int) -> bool:
    """REAL compile+run probe of the single-pulse boxcar sweep kernel
    (ops/pallas/boxcar.py) at the production width count and tile span,
    gated on BITWISE equality with the jnp twin
    (ops.singlepulse.boxcar_best_twin): both consume the same padded
    prefix-sum rows and replay the same f32 subtract/scale/mask/max
    chain, so any difference means a broken lowering (roll off by a
    lane, bad SMEM scalar read, mis-clamped window). The features that
    vary by toolchain (dynamic-offset 1-D DMA, dynamic pltpu.roll,
    scalar-prefetch SMEM reads) are exercised at a reduced trial count
    with the production (n_widths, span) geometry."""
    if not backend_supports_pallas() or span <= 0:
        return False
    try:
        import numpy as np
        import jax.numpy as jnp

        from .boxcar import boxcar_best_pallas
        from ..singlepulse import (
            boxcar_best_twin,
            default_widths,
            prefix_sum_padded,
            width_extent,
            width_scales,
        )

        widths = default_widths(n_widths)
        scales = width_scales(widths)
        tpad = 2 * span
        wext = width_extent(widths)
        rng = np.random.default_rng(0)
        nvalid = tpad - span // 2  # exercise the validity tail mask
        norm = rng.normal(size=(3, nvalid)).astype(np.float32)
        # a planted bright pulse makes the argmax width data-sensitive
        norm[1, nvalid // 3 : nvalid // 3 + 16] += 25.0
        csum = prefix_sum_padded(jnp.asarray(norm), tpad, wext)
        got_b, got_w = boxcar_best_pallas(
            csum, widths, scales, nvalid, tpad, span=span
        )
        ref_b, ref_w = boxcar_best_twin(csum, widths, scales, nvalid, tpad)
        ok = bool(
            np.array_equal(np.asarray(got_b), np.asarray(ref_b))
            and np.array_equal(np.asarray(got_w), np.asarray(ref_w))
        )
        if not ok:
            import warnings

            warnings.warn(
                f"Pallas boxcar kernel FAILED the bitwise oracle check "
                f"at n_widths={n_widths}, span={span}; using jnp twin"
            )
        return ok
    except Exception as exc:  # any Mosaic/compile failure -> jnp twin
        import warnings

        warnings.warn(
            f"Pallas boxcar kernel unavailable at n_widths={n_widths}, "
            f"span={span}; using jnp twin: {type(exc).__name__}: {exc}"
        )
        return False


@lru_cache(maxsize=None)
def probe_pallas_spchain(n_widths: int, span: int, dec: int) -> bool:
    """REAL compile+run probe of the fused single-pulse chain tail
    (ops/pallas/spchain.py: boxcar sweep + dec-fold in one VMEM pass)
    at the production width count, tile span and decimation, gated on
    BITWISE equality with the jnp twin
    (ops.singlepulse.boxcar_dec_best_twin). Beyond the boxcar kernel's
    feature set this needs the (span/dec, dec) retile of the sweep
    tile, whose Mosaic support varies by toolchain — exactly what the
    probe arbitrates before the driver may route to the kernel."""
    if not backend_supports_pallas() or span <= 0 or dec <= 0:
        return False
    if span % dec:
        return False
    try:
        import numpy as np
        import jax.numpy as jnp

        from .spchain import boxcar_dec_best_pallas
        from ..singlepulse import (
            boxcar_dec_best_twin,
            default_widths,
            prefix_sum_padded,
            width_extent,
            width_scales,
        )

        widths = default_widths(n_widths)
        scales = width_scales(widths)
        tpad = 2 * span
        wext = width_extent(widths)
        rng = np.random.default_rng(0)
        nvalid = tpad - span // 2  # exercise the validity tail mask
        norm = rng.normal(size=(3, nvalid)).astype(np.float32)
        # a planted bright pulse makes argmax/width data-sensitive; a
        # duplicated value exercises the first-max tie rule
        norm[1, nvalid // 3 : nvalid // 3 + 16] += 25.0
        norm[2, 100] = norm[2, 100 + dec // 2] = 30.0
        csum = prefix_sum_padded(jnp.asarray(norm), tpad, wext)
        got = boxcar_dec_best_pallas(
            csum, widths, scales, nvalid, tpad, dec, span=span
        )
        ref = boxcar_dec_best_twin(csum, widths, scales, nvalid, tpad, dec)
        ok = all(
            np.array_equal(np.asarray(g), np.asarray(r))
            for g, r in zip(got, ref)
        )
        if not ok:
            import warnings

            warnings.warn(
                f"Pallas single-pulse chain kernel FAILED the bitwise "
                f"oracle check at n_widths={n_widths}, span={span}, "
                f"dec={dec}; using the unfused path"
            )
        return ok
    except Exception as exc:  # any Mosaic/compile failure -> unfused path
        import warnings

        warnings.warn(
            f"Pallas single-pulse chain kernel unavailable at "
            f"n_widths={n_widths}, span={span}, dec={dec}; using the "
            f"unfused path: {type(exc).__name__}: {exc}"
        )
        return False


@lru_cache(maxsize=None)
def probe_pallas_specchain() -> bool:
    """REAL compile+run probe of the fused deredden+zap+interbin kernel
    (ops/pallas/specchain.py) at a small shape, gated on BITWISE
    equality with the jnp twin (ops.spectrum.interp_deredden_zap): the
    kernel replays the same f32 divide/select/square/max/sqrt chain,
    so any difference means a broken lowering (carry off by a tile,
    roll off by a lane, bad mask). The varying features (static
    pltpu.roll, VMEM carry scratch, scalar-prefetch bins count) are
    shape-independent, so one probe at the production SPEC_BLOCK
    gates every production shape."""
    if not backend_supports_pallas():
        return False
    try:
        import numpy as np
        import jax.numpy as jnp

        from .specchain import SPEC_BLOCK, interp_deredden_zap_pallas
        from ..spectrum import interp_deredden_zap

        rng = np.random.default_rng(0)
        nbins = SPEC_BLOCK + SPEC_BLOCK // 2 + 1  # odd, forces the pad
        d = 9  # forces the row pad
        re = jnp.asarray(rng.normal(size=(d, nbins)).astype(np.float32))
        im = jnp.asarray(rng.normal(size=(d, nbins)).astype(np.float32))
        med = jnp.asarray(
            (0.5 + rng.random((d, nbins))).astype(np.float32)
        )
        zap = np.zeros(nbins, dtype=bool)
        zap[40:44] = True
        zap[2] = True  # a birdie inside the zeroed low bins
        zap[SPEC_BLOCK - 1 : SPEC_BLOCK + 1] = True  # tile boundary
        zapj = jnp.asarray(zap)
        got = interp_deredden_zap_pallas(re, im, med, zapj)
        ref = interp_deredden_zap(re, im, med, zapj)
        # parts are pure select/divide chains: BITWISE. The amplitude
        # carries the mul+add sums whose only legitimate deviation is
        # FMA-contraction codegen: per-bin envelope (s0_envelope), the
        # dftspec/interbin discipline — a structural fault (bad carry,
        # shifted lane) perturbs bins by O(rms), orders above it
        from .specchain import s0_envelope

        s0_got, s0_ref = np.asarray(got[2]), np.asarray(ref[2])
        ok = all(
            np.array_equal(np.asarray(g), np.asarray(r))
            for g, r in zip(got[:2], ref[:2])
        ) and bool(
            (np.abs(s0_got - s0_ref) <= s0_envelope(s0_ref)).all()
        )
        if not ok:
            import warnings

            warnings.warn(
                "Pallas spectrum chain kernel FAILED the bitwise oracle "
                "check; using the unfused path"
            )
        return ok
    except Exception as exc:  # any Mosaic/compile failure -> unfused path
        import warnings

        warnings.warn(
            f"Pallas spectrum chain kernel unavailable; using the "
            f"unfused path: {type(exc).__name__}: {exc}"
        )
        return False


from .resample import resample_block_pallas, resample_block  # noqa: E402


@lru_cache(maxsize=None)
def probe_pallas_dedisperse() -> bool:
    """REAL compile+run probe of the dedispersion kernel (cached per
    process). Small-shape oracle check: the features that vary by
    toolchain (dynamic-offset 1-D DMA, dynamic pltpu.roll, SMEM scalar
    reads) are shape-independent, so one small probe gates the kernel
    for every production shape."""
    if not backend_supports_pallas():
        return False
    try:
        import numpy as np
        import jax.numpy as jnp

        from .dedisperse import dedisperse_pallas
        from ..dedisperse import dedisperse_block

        rng = np.random.default_rng(0)
        t, c, d = 8192, 16, 8
        fil = rng.integers(0, 4, size=(t, c)).astype(np.uint8)
        # irregular delays exercise every rem/roll combination
        delays = np.sort(
            rng.integers(0, 3000, size=(d, c)).astype(np.int32), axis=0
        )
        kill = (rng.random(c) > 0.2).astype(np.int32)
        out_nsamps = t - int(delays.max())
        got = np.asarray(
            dedisperse_pallas(fil, delays, kill, out_nsamps, scale=0.9)
        )
        ref = np.asarray(
            dedisperse_block(
                jnp.asarray(fil), jnp.asarray(delays), jnp.asarray(kill),
                out_nsamps=out_nsamps, scale=0.9,
            )
        )
        ok = bool(np.array_equal(got, ref))
        if not ok:
            import warnings

            warnings.warn(
                "Pallas dedispersion kernel FAILED the oracle check; "
                "using the jnp path"
            )
        return ok
    except Exception as exc:  # any Mosaic/compile failure -> jnp path
        import warnings

        warnings.warn(
            f"Pallas dedispersion kernel unavailable; using jnp path: "
            f"{type(exc).__name__}: {exc}"
        )
        return False
