"""Pallas TPU kernels for the hot ops.

Each kernel has a pure-jnp twin in ``peasoup_tpu.ops`` used as the
oracle in tests (interpret mode on CPU) and as the fallback on
non-TPU backends or when a kernel's preconditions don't hold.
"""

from __future__ import annotations

from functools import lru_cache

import jax


def backend_supports_pallas() -> bool:
    """Compiled Mosaic kernels need a real TPU backend; everywhere else
    the kernels still run via the interpreter (tests) or fall back."""
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


@lru_cache(maxsize=None)
def probe_pallas_resample(n: int, block: int) -> bool:
    """REAL compile+run probe of the resample kernel at the shape the
    caller is about to use (cached per (n, block)).

    The kernels are interpret-tested everywhere, but Mosaic's compiled
    feature set differs per backend/toolchain; a production search must
    degrade to the jnp twin rather than crash, so eligibility is
    established by actually compiling and running the kernel with the
    production n and block (grid trimmed to one DM x one accel trial —
    the VMEM window, DMA shapes, and roll lowering are what vary with
    shape, and those are set by (n, block))."""
    if not backend_supports_pallas() or block <= 0:
        return False
    try:
        import numpy as np
        import jax
        import jax.numpy as jnp

        from .resample import resample_block_pallas
        from ..resample import resample_accel

        # af near the choose_block precondition limit: the shift walks
        # through every select arm, so a wrong pltpu.roll lowering (off
        # by a lane, wrong direction) cannot return oracle-equal data
        af = 1.9 / (float(n) * block)
        x = jnp.asarray(np.arange(n, dtype=np.float32).reshape(1, n))
        afs = jnp.asarray(np.asarray([[af, -af]], dtype=np.float32))
        out = np.asarray(resample_block_pallas(x, afs, block=block))
        if out.shape != (1, 2, n):
            return False
        # the kernel's index math is the same f32 ops as the jnp twin:
        # anything but bitwise equality means a broken lowering
        ref = np.asarray(resample_accel(x[0], afs[0]))
        return bool(np.array_equal(out[0], ref))
    except Exception as exc:  # any Mosaic/compile failure -> jnp path
        import warnings

        warnings.warn(f"Pallas resample kernel unavailable at n={n}, "
                      f"block={block}; using jnp fallback: "
                      f"{type(exc).__name__}: {exc}")
        return False


from .resample import resample_block_pallas, resample_block  # noqa: E402
