"""Pallas TPU kernels for the hot ops.

Each kernel has a pure-jnp twin in ``peasoup_tpu.ops`` used as the
oracle in tests (interpret mode on CPU) and as the fallback on
non-TPU backends or when a kernel's preconditions don't hold.
"""

from __future__ import annotations

import jax


def backend_supports_pallas() -> bool:
    """Compiled Mosaic kernels need a real TPU backend; everywhere else
    the kernels still run via the interpreter (tests) or fall back."""
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


from .resample import resample_block_pallas, resample_block  # noqa: E402
