"""Registry of the Pallas TPU kernels: the twin/probe/fallback contract.

Every kernel in :mod:`peasoup_tpu.ops.pallas` ships as a TRIPLE — the
kernel itself, a bitwise (or envelope-gated) **jnp twin** used as the
oracle and the fallback implementation, and a **compile-and-run probe**
in ``ops/pallas/__init__.py`` that arbitrates, per toolchain and per
production shape, whether the driver may route to the kernel at all.
The convention was enforced by review only; this registry makes it a
machine-checked contract: the audit's kernel engine
(:mod:`peasoup_tpu.analysis.kernels`) cross-references every entry
(PSK202), lowers every kernel under interpret mode at the registered
tiny geometry (PSK203), attempts Mosaic lowering where the toolchain
allows (PSK208), and flags any ``pl.pallas_call`` module that skips
registration (PSK201).

``build`` thunks close over all static/python arguments and expose only
array operands, so the audit can ``jax.jit(...).lower(...)`` them
without concretising statics; they are lazy — nothing imports jax until
a consumer runs them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable


@dataclass(frozen=True)
class KernelSpec:
    """One registered Pallas kernel.

    ``probe`` names the ``probe_pallas_*`` gate in
    ``ops/pallas/__init__.py``; ``twin`` is the dotted path of the jnp
    oracle the probe must compare against; ``fallback`` documents the
    ladder the driver descends when the probe rejects.
    ``scalar_prefetch`` is the kernel's ``num_scalar_prefetch`` count
    (0 = no scalar-prefetch grid), cross-checked against the module AST
    (PSK206). ``retile_fallback`` marks kernels that retile the lane
    dimension in-kernel (the ``(span/dec, dec)`` reshape family) and
    therefore MUST sit behind a probe-gated retile ladder (PSK207
    flags lane retiles in kernels without it).
    """

    name: str
    module: str  # dotted module holding the entry point
    entry: str  # public entry-point function
    probe: str  # probe_pallas_* gate in ops/pallas/__init__.py
    twin: str  # dotted path of the jnp oracle / fallback
    fallback: str  # human description of the fallback ladder
    # build(interpret) -> (fn, array_args, kwargs); interpret=False
    # builds the Mosaic-lowered variant for TPU toolchain checks
    build: Callable[..., tuple[Callable, tuple, dict[str, Any]]]
    scalar_prefetch: int = 0
    retile_fallback: bool = False


def _build_dedisperse(interpret: bool = True):
    import numpy as np

    from .dedisperse import dedisperse_pallas

    t, c, d = 2048, 8, 4
    fil = np.zeros((t, c), dtype=np.uint8)
    # delay table and killmask are host-side plan inputs (the entry
    # does host math on them), so the thunk closes over them and only
    # the filterbank is a traced operand
    delays = np.tile(
        np.arange(d, dtype=np.int32)[:, None] * 16, (1, c)
    )
    kill = np.ones(c, dtype=np.int32)
    out = t - int(delays.max())
    return (
        lambda f: dedisperse_pallas(
            f, delays, kill, out, scale=0.9, interpret=interpret
        ),
        (fil,),
        {},
    )


def _build_resample(interpret: bool = True):
    import numpy as np

    from .resample import resample_block_pallas

    n, block = 4096, 512
    x = np.zeros((1, n), dtype=np.float32)
    afs = np.asarray([[1e-9, -1e-9]], dtype=np.float32)
    return (
        lambda xx, aa: resample_block_pallas(
            xx, aa, block=block, interpret=interpret
        ),
        (x, afs),
        {},
    )


def _build_boxcar(interpret: bool = True):
    from ..singlepulse import (
        default_widths,
        plan_pad,
        prefix_sum_padded,
        width_extent,
        width_scales,
    )
    from .boxcar import boxcar_best_pallas

    import jax.numpy as jnp

    t = 2048
    widths = default_widths(4)
    tpad, span = plan_pad(t)
    wext = width_extent(widths)
    scales = width_scales(widths)
    csum = prefix_sum_padded(jnp.zeros((1, t), jnp.float32), tpad, wext)
    return (
        lambda cs: boxcar_best_pallas(
            cs, widths, scales, t, tpad, span=span, interpret=interpret
        ),
        (csum,),
        {},
    )


def _build_spchain(interpret: bool = True):
    from ..singlepulse import (
        default_widths,
        prefix_sum_padded,
        width_extent,
        width_scales,
    )
    from .spchain import boxcar_dec_best_pallas

    import jax.numpy as jnp

    span, dec = 1024, 32
    tpad = 2 * span
    widths = default_widths(6)
    wext = width_extent(widths)
    scales = width_scales(widths)
    nvalid = tpad - span // 2
    csum = prefix_sum_padded(
        jnp.zeros((1, nvalid), jnp.float32), tpad, wext
    )
    return (
        lambda cs: boxcar_dec_best_pallas(
            cs, widths, scales, nvalid, tpad, dec, span=span,
            interpret=interpret,
        ),
        (csum,),
        {},
    )


def _build_specchain(interpret: bool = True):
    import numpy as np

    from .specchain import SPEC_BLOCK, interp_deredden_zap_pallas

    nbins, d = SPEC_BLOCK + 129, 3
    re = np.zeros((d, nbins), dtype=np.float32)
    im = np.zeros((d, nbins), dtype=np.float32)
    med = np.ones((d, nbins), dtype=np.float32)
    zap = np.zeros(nbins, dtype=bool)
    return (
        lambda r, i, m, z: interp_deredden_zap_pallas(
            r, i, m, z, interpret=interpret
        ),
        (re, im, med, zap),
        {},
    )


def _build_interbin(interpret: bool = True):
    import numpy as np

    from .interbin import untwist_interbin_normalise

    block = 128
    m = 2 * block  # packed-DFT half length; must be a block multiple
    npad = m + block
    r = 2
    zr = np.zeros((r, m), dtype=np.float32)
    zi = np.zeros((r, m), dtype=np.float32)
    mean = np.zeros(r, dtype=np.float32)
    std = np.ones(r, dtype=np.float32)
    return (
        lambda a, b, mu, sd: untwist_interbin_normalise(
            a, b, mu, sd, npad=npad, block=block, interpret=interpret
        ),
        (zr, zi, mean, std),
        {},
    )


def _build_dftspec(interpret: bool = True):
    import numpy as np

    from .dftspec import dft_untwist_interbin, dftspec_supported

    n = 1 << 15  # geometry floor: n1 must be a multiple of 128
    m = n // 2
    npad = m + 128
    if not dftspec_supported(n, npad):  # pragma: no cover - static geo
        raise ValueError(f"dftspec geometry unsupported: n={n}")
    r = 2
    xe = np.zeros((r, m), dtype=np.float32)
    xo = np.zeros((r, m), dtype=np.float32)
    mean = np.zeros(r, dtype=np.float32)
    std = np.ones(r, dtype=np.float32)
    return (
        lambda a, b, mu, sd: dft_untwist_interbin(
            a, b, mu, sd, npad=npad, interpret=interpret
        ),
        (xe, xo, mean, std),
        {},
    )


def _build_peaks(interpret: bool = True):
    import numpy as np

    import jax.numpy as jnp

    from .peaks import PEAKS_BLOCK, find_cluster_peaks_multi

    nlev, nbins = 2, PEAKS_BLOCK
    sp = jnp.zeros((2, nbins), jnp.float32)
    windows = np.tile(
        np.asarray([[8, nbins - 8]], np.int32), (nlev, 1)
    )
    return (
        lambda s, w: find_cluster_peaks_multi(
            [s] * nlev, w, threshold=9.0, max_peaks=16,
            scales=(1.0, 0.5), nbins=nbins, interpret=interpret,
        ),
        (sp, jnp.asarray(windows)),
        {},
    )


def _build_harmpeaks(interpret: bool = True):
    import numpy as np

    import jax.numpy as jnp

    from .harmpeaks import find_harmonic_cluster_peaks
    from .peaks import PEAKS_BLOCK

    nharms = 2
    nlev = nharms + 1
    nbins = PEAKS_BLOCK
    sp = jnp.zeros((2, nbins), jnp.float32)
    windows = np.tile(
        np.asarray([[8, nbins - 8]], np.int32), (nlev, 1)
    )
    return (
        lambda s, w: find_harmonic_cluster_peaks(
            s, w, nharms=nharms, threshold=9.0, max_peaks=16,
            scales=(1.0, 0.5, 0.25), nbins=nbins, interpret=interpret,
        ),
        (sp, jnp.asarray(windows)),
        {},
    )


_KERNELS: tuple[KernelSpec, ...] = (
    KernelSpec(
        name="pallas.dedisperse",
        module="peasoup_tpu.ops.pallas.dedisperse",
        entry="dedisperse_pallas",
        probe="probe_pallas_dedisperse",
        twin="peasoup_tpu.ops.dedisperse.dedisperse_block",
        fallback="jnp gather scan (ops.dedisperse.dedisperse_block)",
        build=_build_dedisperse,
        scalar_prefetch=0,
    ),
    KernelSpec(
        name="pallas.resample",
        module="peasoup_tpu.ops.pallas.resample",
        entry="resample_block_pallas",
        probe="probe_pallas_resample",
        twin="peasoup_tpu.ops.resample.resample_accel",
        fallback="vmapped jnp resample (ops.resample.resample_accel)",
        build=_build_resample,
        scalar_prefetch=0,
    ),
    KernelSpec(
        name="pallas.boxcar",
        module="peasoup_tpu.ops.pallas.boxcar",
        entry="boxcar_best_pallas",
        probe="probe_pallas_boxcar",
        twin="peasoup_tpu.ops.singlepulse.boxcar_best_twin",
        fallback="jnp twin sweep (ops.singlepulse.boxcar_best_twin)",
        build=_build_boxcar,
        scalar_prefetch=3,
    ),
    KernelSpec(
        name="pallas.spchain",
        module="peasoup_tpu.ops.pallas.spchain",
        entry="boxcar_dec_best_pallas",
        probe="probe_pallas_spchain",
        twin="peasoup_tpu.ops.singlepulse.boxcar_dec_best_twin",
        fallback=(
            "retiled fused spans -> boxcar kernel + jnp dec-fold -> "
            "jnp twin (pipeline.single_pulse.select_sp_kernels ladder)"
        ),
        build=_build_spchain,
        scalar_prefetch=3,
        retile_fallback=True,
    ),
    KernelSpec(
        name="pallas.specchain",
        module="peasoup_tpu.ops.pallas.specchain",
        entry="interp_deredden_zap_pallas",
        probe="probe_pallas_specchain",
        twin="peasoup_tpu.ops.spectrum.interp_deredden_zap",
        fallback="unfused deredden->zap->interbin stanza (jnp twin)",
        build=_build_specchain,
        scalar_prefetch=1,  # the true-bins count rides SMEM prefetch
    ),
    KernelSpec(
        name="pallas.interbin",
        module="peasoup_tpu.ops.pallas.interbin",
        entry="untwist_interbin_normalise",
        probe="probe_pallas_interbin",
        twin="peasoup_tpu.ops.spectrum.form_interpolated_parts",
        fallback=(
            "packed-matmul rfft parts -> form_interpolated_parts -> "
            "normalise (the unfused jnp chain)"
        ),
        build=_build_interbin,
        scalar_prefetch=0,
    ),
    KernelSpec(
        name="pallas.dftspec",
        module="peasoup_tpu.ops.pallas.dftspec",
        entry="dft_untwist_interbin",
        probe="probe_pallas_dftspec",
        twin="peasoup_tpu.ops.pallas.dftspec.dft_untwist_interbin_twin",
        fallback="einsum four-step DFT + interbin kernel chain",
        build=_build_dftspec,
        scalar_prefetch=0,
        retile_fallback=True,
    ),
    KernelSpec(
        name="pallas.peaks",
        module="peasoup_tpu.ops.pallas.peaks",
        entry="find_cluster_peaks_multi",
        probe="probe_pallas_peaks",
        twin="peasoup_tpu.ops.peaks.find_peaks_device",
        fallback=(
            "jnp find_peaks_device + cluster_peaks_device per level"
        ),
        build=_build_peaks,
        scalar_prefetch=0,
    ),
    KernelSpec(
        name="pallas.harmpeaks",
        module="peasoup_tpu.ops.pallas.harmpeaks",
        entry="find_harmonic_cluster_peaks",
        probe="probe_pallas_harmpeaks",
        twin="peasoup_tpu.ops.harmonics.harmonic_sums",
        fallback=(
            "harmonic_sums(method='take') + jnp peaks pair per level"
        ),
        build=_build_harmpeaks,
        scalar_prefetch=0,
        # the MXU one-hot gather retiles its (SUB*K, BLOCK) dot output
        # back to the (SUB, BLOCK) tile; the probe + conv+peaks path
        # is the ladder that absorbs toolchains rejecting it
        retile_fallback=True,
    ),
)


def kernel_specs() -> tuple[KernelSpec, ...]:
    """All registered kernels (import-cheap: thunks are lazy)."""
    return _KERNELS


def spec_for_module(stem: str) -> KernelSpec | None:
    """The registered spec whose module basename is ``stem``."""
    for spec in _KERNELS:
        if spec.module.rsplit(".", 1)[-1] == stem:
            return spec
    return None
