"""Pallas TPU kernel for time-domain acceleration resampling.

Reference: resample_kernelII, out[i] = in[rint(i + i*af*(i-N))]
(src/kernels.cu:314-346) — a per-element gather in CUDA. BASELINE.md
names this op as a Pallas target.

TPU design — NO gather at all. The shift s(i) = rint(af*i*(i-N)) is a
slowly varying step function: its slope |d s/d i| = |af*(2i-N)| <=
|af|*N is tiny for physical accelerations (~1e-7..1e-4 samples/sample).
Pick a block size BLK with |af|*N*BLK <= 2; then within one output
block the shift takes at most 4 distinct values, so the block is a
SELECT among 4 shifted copies of one contiguous window:

  HBM --async DMA--> VMEM window [ws, ws+W), W = BLK + 2*MARGIN
  out[j] = select(s(i0+j) - s_base, window[j+v], ..., window[j+v+3])

which is pure vector ops + one dynamic-offset DMA per block — the
gather is traded for HBM streaming at full bandwidth.

Boundary handling: the input is padded with a MARGIN-sample leading
apron (+ tail slack) so the window start ws = i0 + s(i0) is ALWAYS in
range — no clamping, so the select never misaligns at the array ends
(an earlier clamped-window design silently corrupted the first/last
blocks once |af|*N*BLK approached 1). Reads clipped to sample 0 by the
reference's index clip land exactly on x[0] through the apron. The
index arithmetic uses the same f32 ops as the jnp twin
(ops/resample.py), so results are bitwise identical.

Window-start validity under the precondition |af|*N*BLK <= 2
(enforced by choose_block): |s(i0)| <= |af|*i0*(N-i0) < i0 for i0 > 0
(since |af|*N < 1), so ws = i0 + s(i0) >= 0, and ws <= N - BLK + 2 so
ws + W <= N_pad. In-block local offsets vs = src + MARGIN - ws - j lie
in [MARGIN - 2 - spread, MARGIN + 2 + spread] with spread <= 3.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_MARGIN = 64  # leading apron; also window slack each side of a block
_SELECT_SPAN = 4  # distinct shift values handled per block
_PAD_TAIL = 3 * _MARGIN  # trailing slack: ws + W <= n + 2 + 2*MARGIN


def choose_block(af_max: float, n: int) -> int:
    """Largest power-of-two block with shift spread <= SELECT_SPAN-1,
    clamped to [128, 2048]. Returns 0 if no valid block exists (caller
    must use the jnp fallback). This is the single source of truth for
    the kernel's preconditions."""
    if af_max < 0:
        raise ValueError("af_max must be >= 0")
    limit = 2.0 / (af_max * n) if af_max > 0 else float("inf")
    blk = 128
    if blk > limit or n % blk or n < blk + 2 * _MARGIN:
        return 0
    while (
        blk * 2 <= min(limit, 2048)
        and n % (blk * 2) == 0
        and n >= blk * 2 + 2 * _MARGIN
    ):
        blk *= 2
    return blk


def _kernel(af_ref, x_ref, out_ref, win_ref, sem, *, n: int, blk: int):
    d = pl.program_id(0)
    t = pl.program_id(2)
    w = blk + 2 * _MARGIN
    af = af_ref[0, 0]
    nf = jnp.float32(n)
    i0 = t * blk
    i0f = jnp.float32(i0)
    s0 = jnp.rint(af * (i0f * (i0f - nf))).astype(jnp.int32)
    ws = i0 + s0  # window origin in the PADDED array; in range by above

    copy = pltpu.make_async_copy(
        x_ref.at[d, pl.ds(ws, w)], win_ref.at[0], sem
    )
    copy.start()

    j = jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1)
    ivec = (i0 + j).astype(jnp.float32)  # exact: i < 2^24
    quad = ivec * (ivec - nf)  # same single f32 rounding as jnp twin
    shift = jnp.rint(af * quad).astype(jnp.int32)
    src = jnp.clip(i0 + j + shift, 0, n - 1)  # reference's index clip
    vs = src + _MARGIN - ws - j  # local window offset minus j, >= 0
    vmin = jnp.min(vs)

    copy.wait()
    acc = jnp.zeros((1, blk), jnp.float32)
    for s in range(_SELECT_SPAN):
        shifted = win_ref[0:1, pl.ds(vmin + s, blk)]
        acc = jnp.where(vs == vmin + s, shifted, acc)
    out_ref[0, 0, :] = acc[0]


@lru_cache(maxsize=None)
def _build(d: int, a: int, n: int, blk: int, interpret: bool):
    w = blk + 2 * _MARGIN
    kernel = partial(_kernel, n=n, blk=blk)
    return pl.pallas_call(
        kernel,
        grid=(d, a, n // blk),
        in_specs=[
            pl.BlockSpec(
                (1, 1), lambda dd, aa, tt: (dd, aa),
                memory_space=pltpu.SMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, blk), lambda dd, aa, tt: (dd, aa, tt),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((d, a, n), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((1, w), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )


def resample_block_pallas(
    x: jnp.ndarray,  # (D, N) f32 time series per DM trial
    afs: jnp.ndarray,  # (D, A) f32 acceleration factors a*tsamp/2c
    *,
    block: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """(D, A, N) resampled series; ``block`` must come from
    choose_block (guarantees max|afs|*N*block <= 2)."""
    d, n = x.shape
    a = afs.shape[1]
    if n % block or n < block + 2 * _MARGIN:
        raise ValueError(f"N={n} incompatible with block={block}")
    # leading apron: clipped-to-0 reads resolve to x[0]; tail slack
    # keeps every window DMA in bounds without clamping (see module doc)
    xp = jnp.pad(x.astype(jnp.float32), ((0, 0), (_MARGIN, _PAD_TAIL)))
    fn = _build(d, a, n, block, interpret)
    return fn(afs.astype(jnp.float32), xp)


def resample_block(
    x: jnp.ndarray, afs: jnp.ndarray, af_max: float, *, interpret: bool = False
) -> jnp.ndarray:
    """Dispatch: Pallas kernel when choose_block accepts and we're on
    TPU (or interpreting); else the jnp gather twin."""
    from ..resample import resample_accel
    from . import backend_supports_pallas

    _, n = x.shape
    blk = choose_block(af_max, n)
    if blk and (interpret or backend_supports_pallas()):
        return resample_block_pallas(x, afs, block=blk, interpret=interpret)
    return jax.vmap(resample_accel)(x, afs)
