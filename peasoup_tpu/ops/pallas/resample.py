"""Pallas TPU kernel for time-domain acceleration resampling.

Reference: resample_kernelII, out[i] = in[rint(i + i*af*(i-N))]
(src/kernels.cu:314-346) — a per-element gather in CUDA. BASELINE.md
names this op as a Pallas target.

TPU design — NO gather at all. The shift s(i) = rint(af*i*(i-N)) is a
slowly varying step function: its slope |d s/d i| = |af*(2i-N)| <=
|af|*N is tiny for physical accelerations (~1e-7..1e-4 samples/sample).
Pick a block size BLK with |af|*N*BLK <= 2; then within one output
block the shift takes at most 4 distinct values, so the block is a
SELECT among 4 shifted copies of one contiguous window:

  HBM --async DMA--> VMEM window, then
  out[j] = select(s(i0+j) - s_base, window[j+v], ..., window[j+v+3])

which is pure vector ops + one dynamic-offset DMA per tile — the
gather is traded for HBM streaming at full bandwidth.

Mosaic DMA/layout constraints (discovered on real v5e lowering) shape
the implementation:
  * dynamic-offset DMA slices are only unrestricted for 1-D refs, and
    1-D refs are tiled in 1024-lane quanta: both the slice length and
    the start offset must be multiples of 1024 (asserted to the
    compiler with pl.multiple_of). The input is therefore passed as a
    FLAT 1-D array of 1024-aligned padded rows, and the window start
    is quantized down to a 1024 boundary; the remainder is absorbed by
    the in-VMEM chunk+roll below.
  * VMEM vector loads need provably-128-aligned starts, so the select
    arms load a 128-aligned chunk covering [vmin, vmin+3+BLK) and
    lane-rotate it with pltpu.roll (dynamic shift).
  * output block shapes must end in (8k, 128m), so one invocation
    computes a SUPER=8 stack of consecutive BLK-blocks as an (8, BLK)
    tile of a (D, A, N/BLK, BLK) output (reshaped to (D, A, N) by the
    caller — free, same contiguous layout). All 8 sub-blocks share ONE
    window DMA: across a super-block the shift drifts by at most
    |af|*N*8*BLK <= 16 samples.

Correctness bounds, under the choose_block precondition
|af|*N*BLK <= 2 (so |af|*N < 1):
  * p = i0 + s(i0) is in [0, N - 8*BLK + 16]: |s(i0)| <= |af|*i0*(N-i0)
    < i0, i0 + s(i0) is increasing in i0 (derivative
    1 + af*(2*i0 - N) > 0), and |s(i0)| <= |af|*N*8*BLK <= 16 at
    i0 = N - 8*BLK.
  * window coverage: reads span x positions [max(0, p-3), p + 8*BLK
    + 18]; the window [q, q + W) with q = floor((dS + p)/1024)*1024,
    W = 8*BLK + _WIN_EXTRA (= 8*BLK + 4096) covers them with >= 61
    lanes of head slack, and q + W stays inside the padded row since
    the row stride is >= n + M + _WIN_EXTRA + 2 (dS = row start,
    M = 64 apron).
  * in-window select offsets vs = rem_q + M + (src - p) - j lie in
    [0, 7*BLK + 1106], so the 1024-aligned chunk [base, base + clen)
    with base = floor(vmin/1024)*1024 and clen = roundup(BLK + 1026,
    1024) <= BLK + 2048 ends at most at 8*BLK + 3154 < W — inside
    the window.
The index arithmetic uses the same f32 ops as the jnp twin
(ops/resample.py), so results are bitwise identical to it.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# toolchain compat: the memory-space enum was renamed TPUMemorySpace ->
# MemorySpace (and gained an HBM member — older toolchains spell the
# off-chip space ANY). The audit's kernel engine (PSK203) pins this.
_MEMSPACE = getattr(pltpu, "MemorySpace", None) or pltpu.TPUMemorySpace
_HBM = getattr(_MEMSPACE, "HBM", _MEMSPACE.ANY)

_MARGIN = 64  # head apron per padded row
_SELECT_SPAN = 4  # distinct shift values handled per sub-block
_SUPER = 8  # sub-blocks per kernel invocation (TPU sublane quantum)
_QUANT = 1024  # 1-D tiling quantum (lanes): DMA and VMEM loads alike
_WIN_EXTRA = 4 * _QUANT  # window slack beyond SUPER*BLK (coverage proof above)


def _row_stride(n: int) -> int:
    # room for quantization (1024) + margin + drift, rounded to 1024
    return -(-(n + _MARGIN + _WIN_EXTRA + 2) // _QUANT) * _QUANT


def _window_len(blk: int) -> int:
    # single source of truth for the DMA length AND the scratch size
    return _SUPER * blk + _WIN_EXTRA


def choose_block(af_max: float, n: int) -> int:
    """Largest power-of-two sub-block with shift spread <= SELECT_SPAN-1,
    clamped to [128, 2048]. Returns 0 if no valid block exists (caller
    must use the jnp fallback). This is the single source of truth for
    the kernel's preconditions."""
    if af_max < 0:
        raise ValueError("af_max must be >= 0")
    limit = 2.0 / (af_max * n) if af_max > 0 else float("inf")
    blk = 128
    if blk > limit or n % (_SUPER * blk):
        return 0
    while blk * 2 <= min(limit, 2048) and n % (_SUPER * blk * 2) == 0:
        blk *= 2
    return blk


def _kernel(
    af_ref, x_ref, out_ref, win_ref, sem, *, n: int, blk: int, interpret: bool
):
    d = pl.program_id(0)
    a = pl.program_id(1)
    t = pl.program_id(2)
    sup = _SUPER * blk
    w = _window_len(blk)
    stride = _row_stride(n)
    af = af_ref[d, a]
    nf = jnp.float32(n)
    i0 = t * sup
    i0f = jnp.float32(i0)
    s0 = jnp.rint(af * (i0f * (i0f - nf))).astype(jnp.int32)
    p = i0 + s0  # window anchor in x coords; in [0, n - sup + 2]
    u = d * stride + p  # unquantized window start (flat padded coords)
    q = pl.multiple_of((u // _QUANT) * _QUANT, _QUANT)
    rem_q = u - q  # in [0, 1024)

    copy = pltpu.make_async_copy(x_ref.at[pl.ds(q, w)], win_ref, sem)
    copy.start()

    j = jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1)
    # 1-D VMEM loads share the 1024 tiling rule: round the chunk start
    # down to 1024 and its length up; the roll absorbs the remainder
    clen = -(-(blk + _QUANT + _SELECT_SPAN - 2) // _QUANT) * _QUANT
    # all index math is independent of the window data — do it while
    # the DMA is in flight
    sel = []
    for r in range(_SUPER):
        base_i = i0 + r * blk
        ivec = (base_i + j).astype(jnp.float32)  # exact: i < 2^24
        quad = ivec * (ivec - nf)  # same single f32 rounding as jnp twin
        shift = jnp.rint(af * quad).astype(jnp.int32)
        src = jnp.clip(base_i + j + shift, 0, n - 1)  # reference's clip
        # flat offset of src in window, minus lane index
        vs = rem_q + _MARGIN + (src - p) - j
        vmin = jnp.min(vs)
        base = pl.multiple_of((vmin // _QUANT) * _QUANT, _QUANT)
        sel.append((vs, vmin, base, vmin - base))
    copy.wait()
    rows = []
    for vs, vmin, base, rem in sel:
        chunk = win_ref[pl.ds(base, clen)].reshape(1, clen)
        acc = jnp.zeros((1, blk), jnp.float32)
        for s in range(_SELECT_SPAN):
            if interpret:
                arm = jax.lax.dynamic_slice(chunk, (0, rem + s), (1, blk))
            else:
                arm = pltpu.roll(chunk, clen - (rem + s), axis=1)[:, :blk]
            acc = jnp.where(vs == vmin + s, arm, acc)
        rows.append(acc)
    out_ref[:] = jnp.concatenate(rows, axis=0)


@lru_cache(maxsize=None)
def _build(d: int, a: int, n: int, blk: int, interpret: bool):
    w = _window_len(blk)
    kernel = partial(_kernel, n=n, blk=blk, interpret=interpret)
    return pl.pallas_call(
        kernel,
        grid=(d, a, n // (_SUPER * blk)),
        in_specs=[
            # whole (D, A) table in SMEM: TPU lowering rejects (1, 1)
            # blocks; the kernel indexes it by program_id instead
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=_HBM),
        ],
        out_specs=pl.BlockSpec(
            # (8, blk) tile keeps the block tail TPU-compliant; the
            # squeezed (dm, accel) dims are indexed by the grid
            (None, None, _SUPER, blk), lambda dd, aa, tt: (dd, aa, tt, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((d, a, n // blk, blk), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((w,), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )


def resample_block_pallas(
    x: jnp.ndarray,  # (D, N) f32 time series per DM trial
    afs: jnp.ndarray,  # (D, A) f32 acceleration factors a*tsamp/2c
    *,
    block: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """(D, A, N) resampled series; ``block`` must come from
    choose_block (guarantees max|afs|*N*block <= 2)."""
    d, n = x.shape
    a = afs.shape[1]
    if n % (_SUPER * block):
        raise ValueError(f"N={n} incompatible with block={block}")
    stride = _row_stride(n)
    # flat 1024-aligned padded rows: [MARGIN apron][x row][tail slack]
    xp = jnp.pad(
        x.astype(jnp.float32), ((0, 0), (_MARGIN, stride - n - _MARGIN))
    ).reshape(-1)
    fn = _build(d, a, n, block, interpret)
    return fn(afs.astype(jnp.float32), xp).reshape(d, a, n)


def resample_block(
    x: jnp.ndarray, afs: jnp.ndarray, af_max: float, *, interpret: bool = False
) -> jnp.ndarray:
    """Dispatch: Pallas kernel when choose_block accepts and the
    backend proves it can compile it (or we're interpreting); else the
    jnp gather twin."""
    from ..resample import resample_accel
    from . import probe_pallas_resample

    _, n = x.shape
    blk = choose_block(af_max, n)
    if blk and (interpret or probe_pallas_resample(n, blk)):
        return resample_block_pallas(x, afs, block=blk, interpret=interpret)
    return jax.vmap(resample_accel)(x, afs)
