"""Pallas TPU kernel: fused rfft-untwist + interbin + normalise.

Completes the packed four-step matmul rfft (ops/fft.py): the two MXU
einsums emit the half-length complex DFT Z[k] in natural order, and
this kernel turns Z straight into the NORMALISED interbin spectrum the
search consumes (reference chain: cuFFT R2C -> bin_interbin_series
-> normalise, src/kernels.cu:231-304 + 469-494) in ONE streaming pass.

Why a kernel: the untwist needs conj(Z[M-k]) — in pure XLA that is a
rev + concat per (re, im) plus separate interbin-shift concats and a
normalise pass, ~6 full HBM round trips that ate the matmul FFT's
standalone 1.75x win in-pipeline (NOTES.md round 3). Here the mirror
term needs NO materialised reversal at all (r4; the XLA rev copy it
replaces ran at ~300 GB/s for 9.9 ms in-pipeline): the mirrored
operands are the FORWARD zr/zi arrays fetched at the mirrored block
index (nbz-1-b), reversed in VMEM — group order by 128-aligned lane
slices (pure vreg renames) and within-group by one anti-identity MXU
dot (one-hot, so bitwise-exact) — and the shift-by-one patterns
(mirror + interbin's X[k-1]) are carried lane boundaries in VMEM
scratch across a sequential k-block grid, so the whole chain is
einsums -> one fused pass.

Bin layout (matches the jnp path's pad convention): output (R, npad)
f32 with bins k = 0..m real, k > m zeroed (npad = the peaks kernel's
block alignment so no separate pad pass is spent downstream).

Special bins, from the real-input untwist identities:
  X[0] = Re Z[0] + Im Z[0]   (mirror wraps to Z[0] itself)
  X[m] = Re Z[0] - Im Z[0]   (Nyquist; Z[0] carried from block 0)
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_SUB = 8  # rows per stripe (f32 sublane quantum)


def _rev_lanes(x: jnp.ndarray, anti: jnp.ndarray, block: int) -> jnp.ndarray:
    """Reverse the lane axis of an (_SUB, block) VMEM value exactly:
    group order via 128-aligned static slices (vreg renames), then
    within-group via one anti-identity MXU dot (one-hot products are
    exact, so the result is bitwise the reversed input). Measured
    ~1 ms per 742 MB over a plain copy — vs 6.8 ms for XLA's rev."""
    g = block // 128
    xg = jnp.concatenate(
        [x[:, i * 128 : (i + 1) * 128] for i in reversed(range(g))], axis=1
    )
    z = jax.lax.dot_general(
        xg.reshape(_SUB, g, 128), anti, (((2,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    return z.reshape(_SUB, block)


def _untwist_block(zr, zi, zrv, ziv, carry, c, s, mean, std, lane, gk,
                   m, roll):
    """One (stripe, k-block) step's untwist + interbin + normalise at
    FIXED term grouping — shared VERBATIM by the Pallas kernel
    (roll=pltpu.roll) and the jnp twin (roll=jnp.roll), so the twin is
    a contraction-order-exact replay (see dftspec.py's _row_spectrum
    for the same pattern). ``carry`` = (zrv_last, ziv_last, xr_last,
    xi_last, z0r, z0i) as values; returns (out, xr_last', xi_last').

    Steps: forward term Z[k] (wrapping the Nyquist k == m to the
    carried Z[0]); mirror term Z[M-k] = zrev[k-1] from the reversed
    mirrored block via in-block right-shift + carried boundary lane;
    the untwist X[k] = (Z[k]+conj(Zm))/2 - i/2 e^{-2pi i k/n}
    (Z[k]-conj(Zm)) (ops/fft.py formulas); interbin X[k-1] by the same
    shift pattern (kernels.cu:231-252); normalise (kernels.cu:469-494)
    + zero the pad past the true bins."""
    nyq = gk == m
    zr = jnp.where(nyq, carry[4], zr)
    zi = jnp.where(nyq, carry[5], zi)
    zmr = jnp.where(lane == 0, carry[0], roll(zrv, 1, 1))
    zmi = jnp.where(lane == 0, carry[1], roll(ziv, 1, 1))
    arr = 0.5 * (zr + zmr)
    aii = 0.5 * (zi - zmi)
    br = zr - zmr
    bi = zi + zmi
    xr = arr + 0.5 * (c * bi - s * br)
    xi = aii - 0.5 * (c * br + s * bi)
    xr_l = jnp.where(lane == 0, carry[2], roll(xr, 1, 1))
    xi_l = jnp.where(lane == 0, carry[3], roll(xi, 1, 1))
    ampsq = xr * xr + xi * xi
    dsq = 0.5 * ((xr - xr_l) ** 2 + (xi - xi_l) ** 2)
    amp = jnp.sqrt(jnp.maximum(ampsq, dsq))
    out = (amp - mean) / std
    out = jnp.where(gk <= m, out, 0.0)
    return out, xr[:, -1:], xi[:, -1:]


def _kernel(
    anti_ref, unc_ref, uns_ref, mean_ref, std_ref, zr_ref, zi_ref,
    zmr_ref, zmi_ref, out_ref, state, *, block, m,
):
    b = pl.program_id(1)
    zr = zr_ref[:]
    zi = zi_ref[:]

    @pl.when(b == 0)
    def _():
        # carries: [zrv_last, ziv_last, xr_last, xi_last, z0r, z0i]
        # k=0's mirror wraps to Z[0]; X[-1] = 0 (the interbin kernel's
        # idx==0 branch, kernels.cu:242)
        state[:, 0:1] = zr[:, 0:1]
        state[:, 1:2] = zi[:, 0:1]
        state[:, 2:3] = jnp.zeros((_SUB, 1), jnp.float32)
        state[:, 3:4] = jnp.zeros((_SUB, 1), jnp.float32)
        state[:, 4:5] = zr[:, 0:1]
        state[:, 5:6] = zi[:, 0:1]

    lane = jax.lax.broadcasted_iota(jnp.int32, (_SUB, block), 1)
    gk = b * block + lane  # global bin index
    # mirror operands: the mirrored-index FORWARD block (zm*_ref, block
    # nbz-1-b) reversed in VMEM gives this block of zrev = flip(Z)
    zrv = _rev_lanes(zmr_ref[:], anti_ref[:], block)
    ziv = _rev_lanes(zmi_ref[:], anti_ref[:], block)
    carry = tuple(state[:, i : i + 1] for i in range(6))
    out, xr_last, xi_last = _untwist_block(
        zr, zi, zrv, ziv, carry, unc_ref[:], uns_ref[:],
        mean_ref[:, 0:1], std_ref[:, 0:1], lane, gk, m, roll=pltpu.roll,
    )
    out_ref[:] = out
    # advance carries: zrev's last lane == the mirrored forward block's
    # FIRST lane, so the carry needs no reversed value at all
    state[:, 0:1] = zmr_ref[:, 0:1]
    state[:, 1:2] = zmi_ref[:, 0:1]
    state[:, 2:3] = xr_last
    state[:, 3:4] = xi_last


@lru_cache(maxsize=None)
def _build(rpad: int, m: int, npad: int, block: int, interpret: bool):
    nbz = m // block  # z blocks (m is a multiple of block by gating)
    zspec = pl.BlockSpec(
        (_SUB, block), lambda r, b: (r, jnp.minimum(b, nbz - 1))
    )
    # mirrored fetch: block b of flip(Z) is the REVERSE of forward
    # block nbz-1-b; for b >= nbz (the pad block) clamp to block 0,
    # matching the old zrv spec's min(b, nbz-1) on the flipped array
    mspec = pl.BlockSpec(
        (_SUB, block), lambda r, b: (r, jnp.maximum(nbz - 1 - b, 0))
    )
    return pl.pallas_call(
        partial(_kernel, block=block, m=m),
        grid=(rpad // _SUB, npad // block),
        in_specs=[
            pl.BlockSpec((128, 128), lambda r, b: (0, 0)),  # anti
            pl.BlockSpec((1, block), lambda r, b: (0, b)),  # unc
            pl.BlockSpec((1, block), lambda r, b: (0, b)),  # uns
            pl.BlockSpec((_SUB, 128), lambda r, b: (r, 0)),  # mean
            pl.BlockSpec((_SUB, 128), lambda r, b: (r, 0)),  # std
            zspec, zspec, mspec, mspec,  # zr, zi, mirrored zr, zi
        ],
        out_specs=pl.BlockSpec((_SUB, block), lambda r, b: (r, b)),
        out_shape=jax.ShapeDtypeStruct((rpad, npad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((_SUB, 128), jnp.float32)],
        interpret=interpret,
    )


def untwist_interbin_normalise(
    zr: jnp.ndarray,  # (R, m) f32 packed-DFT real part, natural order
    zi: jnp.ndarray,  # (R, m) f32 imaginary part
    mean: jnp.ndarray,  # (R,) f32 per-row spectrum mean
    std: jnp.ndarray,  # (R,) f32 per-row spectrum std
    *,
    npad: int,  # output width (multiple of ``block``, > m)
    block: int = 4096,
    interpret: bool = False,
) -> jnp.ndarray:
    """(R, npad) f32 normalised interbin spectrum of the real series
    whose packed half-length DFT is Z = zr + i*zi; bins k in [0, m]
    real, the rest zero. ``m`` must be a multiple of ``block`` and
    ``npad`` a strictly larger multiple."""
    r, m = zr.shape
    if m % block or npad % block or npad <= m:
        raise ValueError(f"bad interbin kernel geometry {m=} {npad=} {block=}")
    n = 2 * m
    k = np.arange(npad, dtype=np.float64)
    un = np.exp(-2j * np.pi * np.minimum(k, m) / n)
    unc = jnp.asarray(un.real[None, :].astype(np.float32))
    uns = jnp.asarray((-un.imag)[None, :].astype(np.float32))
    rpad = -(-r // _SUB) * _SUB
    mean2 = jnp.broadcast_to(mean[:, None], (r, 128))
    std2 = jnp.broadcast_to(std[:, None], (r, 128))
    if rpad != r:
        pad = [(0, rpad - r), (0, 0)]
        zr, zi = (jnp.pad(a, pad) for a in (zr, zi))
        # std pads with ONES so the pad rows' normalise never divides
        # by zero (their outputs are dropped)
        mean2 = jnp.pad(mean2, pad)
        std2 = jnp.pad(std2, pad, constant_values=1.0)
    anti = jnp.asarray(np.eye(128, dtype=np.float32)[::-1].copy())
    fn = _build(rpad, m, npad, block, interpret)
    out = fn(anti, unc, uns, mean2, std2, zr, zi, zr, zi)
    return out[:r]


def untwist_interbin_normalise_twin(
    zr: jnp.ndarray,
    zi: jnp.ndarray,
    mean: jnp.ndarray,
    std: jnp.ndarray,
    *,
    npad: int,
    block: int = 4096,
) -> jnp.ndarray:
    """Pure-jnp contraction-exact replay of
    :func:`untwist_interbin_normalise`: the kernel's per-(stripe, block)
    grid walk — mirrored-block fetch, _rev_lanes one-hot reversal,
    carry lanes, untwist, interbin, normalise — run outside Pallas with
    ``jnp.roll`` for ``pltpu.roll`` and Python loops for the grid, so
    every expression tree matches the kernel term for term. Kernel and
    twin agree bitwise when both compile fresh; when the persistent
    compile cache serves a cross-host executable the residual is pure
    FMA-contraction codegen (measured max 5.2e-6 rel), so the CI
    oracle asserts a per-bin 1e-5 envelope that still fails every bin
    a structural half-lane fault breaks — without TPU hardware (the
    on-TPU probe gates bitwise against the differently-grouped jnp
    chain instead). Test-only — O(grid) trace size."""
    r, m = zr.shape
    if m % block or npad % block or npad <= m:
        raise ValueError(f"bad interbin kernel geometry {m=} {npad=} {block=}")
    n = 2 * m
    k = np.arange(npad, dtype=np.float64)
    un = np.exp(-2j * np.pi * np.minimum(k, m) / n)
    unc = jnp.asarray(un.real[None, :].astype(np.float32))
    uns = jnp.asarray((-un.imag)[None, :].astype(np.float32))
    rpad = -(-r // _SUB) * _SUB
    mean2 = jnp.broadcast_to(mean.astype(jnp.float32)[:, None], (r, 1))
    std2 = jnp.broadcast_to(std.astype(jnp.float32)[:, None], (r, 1))
    if rpad != r:
        pad = [(0, rpad - r), (0, 0)]
        zr, zi = (jnp.pad(a, pad) for a in (zr, zi))
        mean2 = jnp.pad(mean2, pad)
        std2 = jnp.pad(std2, pad, constant_values=1.0)
    anti = jnp.asarray(np.eye(128, dtype=np.float32)[::-1].copy())
    nbz = m // block
    stripes = []
    for st in range(rpad // _SUB):
        sl = slice(st * _SUB, (st + 1) * _SUB)
        zrs, zis = zr[sl], zi[sl]
        mean_s, std_s = mean2[sl], std2[sl]
        # carries: [zrv_last, ziv_last, xr_last, xi_last, z0r, z0i]
        zero = jnp.zeros((_SUB, 1), jnp.float32)
        carry = [zrs[:, 0:1], zis[:, 0:1], zero, zero,
                 zrs[:, 0:1], zis[:, 0:1]]
        blocks = []
        for b in range(npad // block):
            # the kernel's BlockSpec index maps as python slices
            zb = min(b, nbz - 1) * block
            mb = max(nbz - 1 - b, 0) * block
            zmr_b = zrs[:, mb : mb + block]
            zmi_b = zis[:, mb : mb + block]
            lane = jax.lax.broadcasted_iota(jnp.int32, (_SUB, block), 1)
            zrv = _rev_lanes(zmr_b, anti, block)
            ziv = _rev_lanes(zmi_b, anti, block)
            out, xr_last, xi_last = _untwist_block(
                zrs[:, zb : zb + block], zis[:, zb : zb + block],
                zrv, ziv, tuple(carry),
                unc[:, b * block : (b + 1) * block],
                uns[:, b * block : (b + 1) * block],
                mean_s, std_s, lane, b * block + lane, m, roll=jnp.roll,
            )
            blocks.append(out)
            carry = [zmr_b[:, 0:1], zmi_b[:, 0:1], xr_last, xi_last,
                     carry[4], carry[5]]
        stripes.append(jnp.concatenate(blocks, axis=1))
    return jnp.concatenate(stripes, axis=0)[:r]
