"""Pallas TPU kernel for incoherent dedispersion.

Reference: the shift-and-sum the external ``dedisp`` CUDA library does
inside ``dedisp_execute`` (used at /root/reference/include/transforms/
dedisperser.hpp:98-113): out[d, t] = sum_c x[t + delay[d, c], c].

The jnp twin (ops/dedisperse.py:_dedisperse_core) scans channels with a
(D, T_out) HBM-resident accumulator: every channel step re-reads and
re-writes the whole accumulator, and every per-channel shift is a
dynamic slice. This kernel removes both costs:

  * the output block accumulates in VMEM scratch across the channel
    grid axis (written to HBM once, at the last channel step);
  * each channel window arrives by ONE dynamic-offset async DMA shared
    by all 8 trials of the block — adjacent DM trials' delays differ by
    at most SPREAD samples (computed from the actual delay table), so
    one window [min-delay .. min-delay + B + SPREAD) covers the whole
    trial chunk, and each trial's residual shift is one in-VMEM
    pltpu.roll (dynamic lane rotate).

Layout (same conventions as ops/pallas/resample.py, which established
the Mosaic rules on this toolchain): the filterbank is passed as a FLAT
1-D f32 array of 1024-aligned padded CHANNEL rows (killmask
pre-multiplied); DMA starts are quantized down to 1024 lanes and the
remainder absorbed by the roll.

Summation order is channel-ascending per output element — identical to
the jnp twin, and for <=8-bit inputs channel sums are exact integers in
f32, so results are bitwise equal either way (tests assert equality).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_DT = 8  # DM trials per output block (f32 sublane quantum)
_CC = 16  # channels per grid step (windows DMA'd per step)
_QUANT = 1024  # 1-D tiling quantum (lanes): DMA starts/lengths


def _window_len(b: int, spread: int) -> int:
    # covers rem (<1024) + per-trial shift (<=spread) + B output lanes
    return b + (-(-(spread + _QUANT + 1) // _QUANT)) * _QUANT


def _row_stride(t_in: int, b: int, spread: int) -> int:
    # window starts reach (t_out_pad - B) + max_delay <= t_in - B; add
    # the window length and round to the 1024 quantum
    return -(-(t_in + _window_len(b, spread) + 1) // _QUANT) * _QUANT


def _kernel(
    del_ref,  # SMEM (DT, C) i32 delays for this trial chunk (all channels)
    x_ref,  # HBM flat padded channel rows
    out_ref,  # VMEM (DT, B) f32 output block (accumulated across c)
    acc_ref,  # VMEM scratch (DT, B) f32
    win_ref,  # VMEM scratch (CC*W,) f32 channel windows, flat 1-D
    # (single rows of a 2-D scratch are not sliceable: Mosaic requires
    # 8-aligned slices on the sublane dim; 1-D refs tile in 1024-lane
    # quanta and W is a 1024 multiple)
    sems,  # DMA semaphores (CC,)
    *,
    b: int,
    w: int,
    stride: int,
    cc_count: int,
    interpret: bool,
):
    t = pl.program_id(1)
    c = pl.program_id(2)
    nc = pl.num_programs(2)
    t0 = t * b

    @pl.when(c == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    copies = []
    for cc in range(cc_count):
        chan = c * cc_count + cc
        d0 = del_ref[0, chan]  # delays ascend with trial index
        u = chan * stride + t0 + d0
        q = pl.multiple_of((u // _QUANT) * _QUANT, _QUANT)
        cp = pltpu.make_async_copy(
            x_ref.at[pl.ds(q, w)],
            win_ref.at[pl.ds(cc * w, w)],
            sems.at[cc],
        )
        cp.start()
        copies.append((cp, u - q, chan))

    # per-trial row accumulators live as VALUES across the channel
    # loop: one concatenate + one acc_ref add per grid step instead of
    # one per channel
    rows = [jnp.zeros((1, b), jnp.float32) for _ in range(_DT)]
    for cc, (cp, rem, chan) in enumerate(copies):
        cp.wait()
        d0 = del_ref[0, chan]
        chunk = win_ref[pl.ds(cc * w, w)].reshape(1, w)
        for di in range(_DT):
            shift = rem + (del_ref[di, chan] - d0)
            if interpret:
                arm = jax.lax.dynamic_slice(chunk, (0, shift), (1, b))
            else:
                arm = pltpu.roll(chunk, w - shift, axis=1)[:, :b]
            rows[di] = rows[di] + arm
    acc_ref[:] += jnp.concatenate(rows, axis=0)

    @pl.when(c == nc - 1)
    def _():
        out_ref[:] = acc_ref[:]


@lru_cache(maxsize=None)
def _build(
    d: int, t_out: int, c: int, b: int, spread: int, stride: int,
    interpret: bool,
):
    w = _window_len(b, spread)
    kernel = partial(
        _kernel, b=b, w=w, stride=stride, cc_count=_CC, interpret=interpret
    )
    return pl.pallas_call(
        kernel,
        grid=(d // _DT, t_out // b, c // _CC),
        in_specs=[
            # full channel width per trial chunk (SMEM blocks must have
            # their last dim equal to the array's); 8 x C x 4 B = 32 KB
            # at 1024 channels
            pl.BlockSpec(
                (_DT, c), lambda dd, tt, cc: (dd, 0),
                memory_space=pltpu.SMEM,
            ),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        ],
        out_specs=pl.BlockSpec(
            (_DT, b), lambda dd, tt, cc: (dd, tt), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((d, t_out), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((_DT, b), jnp.float32),
            pltpu.VMEM((_CC * w,), jnp.float32),
            pltpu.SemaphoreType.DMA((_CC,)),
        ],
        interpret=interpret,
    )


def plan_spread(delays: np.ndarray) -> int:
    """Max in-chunk delay spread: max over channels and aligned _DT-trial
    chunks of delay[last, c] - delay[first, c] (delays ascend with DM)."""
    d = np.asarray(delays)
    spread = 0
    for lo in range(0, d.shape[0], _DT):
        blk = d[lo : lo + _DT]
        spread = max(spread, int((blk.max(axis=0) - blk.min(axis=0)).max()))
    return spread


def pallas_hbm_bytes(
    t_in: int, c: int, d: int, out_nsamps: int, spread: int | None = None
) -> int:
    """Rough peak HBM need of dedisperse_pallas: the padded f32 flat
    filterbank + the full f32 output (+ the caller-held input). Used by
    dedisperse_device to keep near-limit trial sets on the blocked jnp
    path, whose working set is one trial block. Pass the REAL delay
    ``spread`` (plan_spread(delays)) when the caller holds the table —
    the one-block fallback bound undercounts when coarse high-DM steps
    spread further than one block (ADVICE r1)."""
    b = min(16384, max(_QUANT, -(-out_nsamps // _QUANT) * _QUANT))
    t_out = -(-out_nsamps // b) * b
    cpad = -(-c // _CC) * _CC
    dpad = -(-d // _DT) * _DT
    stride = _row_stride(t_in, b, max(spread, b) if spread else b)
    return 4 * (cpad * stride + dpad * t_out) + t_in * c


def dedisperse_pallas(
    fil_tc,  # (T, C) u8/f32 filterbank (numpy or device array)
    delays: np.ndarray,  # (D, C) int32
    killmask: np.ndarray,  # (C,)
    out_nsamps: int,
    *,
    quantize: bool = True,
    scale: float = 1.0,
    block: int = 16384,
    interpret: bool = False,
) -> jax.Array:
    """All DM trials in ONE kernel dispatch, bitwise equal to the jnp
    twin. Trials/channels pad to the (8, 16) grid quanta with repeated/
    zero rows; output time pads to ``block`` lanes and is trimmed."""
    delays = np.asarray(delays, dtype=np.int32)
    d, c = delays.shape
    t_in = fil_tc.shape[0]
    # don't let a small search pay a full survey-sized block: the padded
    # tail beyond out_nsamps is computed and trimmed (row padding keeps
    # every window in range regardless — see _row_stride)
    b = min(block, max(_QUANT, -(-out_nsamps // _QUANT) * _QUANT))
    t_out = -(-out_nsamps // b) * b
    spread = plan_spread(delays)
    stride = _row_stride(t_in, b, spread)

    dpad = -(-d // _DT) * _DT
    cpad = -(-c // _CC) * _CC
    if dpad > d:
        # repeat the last trial: keeps delays ascending within chunks
        delays = np.concatenate(
            [delays, np.repeat(delays[-1:], dpad - d, axis=0)]
        )
    if cpad > c:
        # extra channels: zero data rows at the max existing delay so
        # windows stay in range and contribute exact zeros
        delays = np.concatenate(
            [delays, np.tile(delays[:, -1:], (1, cpad - c))], axis=1
        )

    run = _jit_full(
        dpad, t_out, cpad, b, spread, stride, d, c, t_in, out_nsamps,
        quantize, float(scale), interpret,
    )
    return run(jnp.asarray(fil_tc), jnp.asarray(delays),
               jnp.asarray(np.asarray(killmask)))


@lru_cache(maxsize=None)
def _jit_full(
    dpad, t_out, cpad, b, spread, stride, d, c, t_in, out_nsamps,
    quantize, scale, interpret,
):
    """Prep (mask, f32, pad/transpose/flatten), the kernel, and the
    trim/scale/quantize tail as ONE jitted program: each eager op is a
    separately dispatched executable, and on a high-latency link the
    half-dozen dispatches cost more than the kernel itself."""
    fn = _build(dpad, t_out, cpad, b, spread, stride, interpret)

    @jax.jit
    def run(fil_tc, delays, killmask):
        x = fil_tc.astype(jnp.float32) * killmask.astype(jnp.float32)[None, :]
        # flat padded channel rows (tail zeros; never selected)
        xp = jnp.pad(x.T, ((0, cpad - c), (0, stride - t_in))).reshape(-1)
        out = fn(delays, xp)[:d, :out_nsamps]
        if scale != 1.0:
            out = out * jnp.float32(scale)
        if quantize:
            out = jnp.clip(jnp.rint(out), 0, 255).astype(jnp.uint8)
        return out

    return run
