"""Pallas TPU kernel for incoherent dedispersion.

Reference: the shift-and-sum the external ``dedisp`` CUDA library does
inside ``dedisp_execute`` (used at /root/reference/include/transforms/
dedisperser.hpp:98-113): out[d, t] = sum_c x[t + delay[d, c], c].

The jnp twin (ops/dedisperse.py:_dedisperse_core) scans channels with a
(D, T_out) HBM-resident accumulator: every channel step re-reads and
re-writes the whole accumulator, and every per-channel shift is a
dynamic slice. This kernel removes both costs:

  * the output block accumulates in VMEM scratch across the channel
    grid axis (written to HBM once, at the last channel step);
  * each channel window arrives by ONE dynamic-offset async DMA shared
    by all 8 trials of the block — adjacent DM trials' delays differ by
    at most SPREAD samples (computed from the actual delay table), so
    one window [min-delay .. min-delay + B + SPREAD) covers the whole
    trial chunk, and each trial's residual shift is one in-VMEM
    pltpu.roll (dynamic lane rotate).

Layout (round 2, blocked-roll rewrite): the filterbank is passed as a
(C, TR, 128) BLOCKED array of padded channel rows (killmask
pre-multiplied); window DMA starts are quantized down to 128-sample
row boundaries, and each trial's residual alignment decomposes into a
row offset (select among statically row-rolled window versions) plus a
lane shift (one dynamic lane roll + row-boundary select), so every
vector op runs at full (8, 128) vreg width.

Summation order is channel-ascending per output element — identical to
the jnp twin, and for <=8-bit inputs channel sums are exact integers in
f32, so results are bitwise equal either way (tests assert equality).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# toolchain compat: TPUMemorySpace -> MemorySpace rename; older
# toolchains spell the off-chip space ANY (no HBM member). PSK203 pins
# this against the installed toolchain.
_MEMSPACE = getattr(pltpu, "MemorySpace", None) or pltpu.TPUMemorySpace
_HBM = getattr(_MEMSPACE, "HBM", _MEMSPACE.ANY)

_DT = 8  # DM trials per output block (f32 sublane quantum)
_CC = 16  # channels per grid step (windows DMA'd per step)
_QUANT = 1024  # output block-size quantum (keeps t_out a lane multiple)


def _nbw(nb: int, k_max: int) -> int:
    # window rows: nb output rows + k_max per-trial row offset + 1 for
    # the lane-boundary next-row, rounded to the sublane quantum
    return -(-(nb + k_max + 1) // 8) * 8


def _tr_rows(t_in: int, nb: int, k_max: int) -> int:
    # blocked channel row count: data rows + window slack (zero rows)
    return -(-t_in // 128) + _nbw(nb, k_max) + 1


def _kernel(
    del_ref,  # SMEM (DT, C) i32 delays for this trial chunk (all channels)
    x_ref,  # HBM (C, TR, 128) blocked padded channel rows
    out_ref,  # VMEM (DT, nb, 128) output block (accumulated across c)
    acc_ref,  # VMEM scratch (DT, nb, 128) f32
    win_ref,  # VMEM scratch (CC, NBW, 128) f32 channel windows
    sems,  # DMA semaphores (CC,)
    *,
    nb: int,
    nbw: int,
    k_max: int,
    cc_count: int,
    interpret: bool,
):
    """Blocked shift-and-sum: one shared (NBW, 128) window per channel
    per 8-trial chunk, per-trial alignment resolved as
    (row offset k_i, lane shift s_i) with k_i handled by selecting
    among k_max+1 statically row-rolled window versions (computed once
    per channel) and s_i by one dynamic lane roll + row-boundary
    select — every vector op runs at full (8, 128) vreg width, unlike
    the round-1 kernel's (1, W) single-sublane rolls (measured ~5x).
    Channel sums accumulate ascending per trial, so results stay
    bitwise equal to the jnp twin for integer inputs."""
    t = pl.program_id(1)
    c = pl.program_id(2)
    nc = pl.num_programs(2)
    t0 = t * (nb * 128)

    @pl.when(c == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def roll(x, shift, axis):
        if interpret:
            return jnp.roll(x, shift, axis=axis)
        return pltpu.roll(x, shift, axis=axis)

    copies = []
    for cc in range(cc_count):
        chan = c * cc_count + cc
        d0 = del_ref[0, chan]  # chunk-min delay (delays ascend with trial)
        u0 = t0 + d0
        q0 = u0 // 128
        cp = pltpu.make_async_copy(
            x_ref.at[chan, pl.ds(q0, nbw)],
            win_ref.at[cc],
            sems.at[cc],
        )
        cp.start()
        copies.append((cp, u0 - q0 * 128, chan))

    lane = jax.lax.broadcasted_iota(jnp.int32, (nb, 128), 1)
    for cc, (cp, base, chan) in enumerate(copies):
        cp.wait()
        wnd = win_ref[cc]  # (NBW, 128)
        d0 = del_ref[0, chan]
        # versions[k][r] = wnd[r + k]: static sublane rolls, shared by
        # all 8 trials of the chunk
        versions = [
            wnd if k == 0 else roll(wnd, nbw - k, axis=0)
            for k in range(k_max + 1)
        ]
        for di in range(_DT):
            rel = base + (del_ref[di, chan] - d0)  # in [0, 127 + spread]
            k_i = rel // 128
            s_i = rel % 128
            sel = versions[0]
            for k in range(1, k_max + 1):
                sel = jnp.where(k_i == k, versions[k], sel)
            a = roll(sel, 128 - s_i, axis=1)  # a[r, l] = sel[r, l+s mod 128]
            nxt = roll(a, nbw - 1, axis=0)  # nxt[r] = a[r + 1]
            arm = jnp.where(lane < 128 - s_i, a[:nb], nxt[:nb])
            acc_ref[di] += arm

    @pl.when(c == nc - 1)
    def _():
        out_ref[:] = acc_ref[:]


@lru_cache(maxsize=None)
def _build(
    d: int, t_out: int, c: int, b: int, spread: int, interpret: bool,
):
    nb = b // 128
    k_max = (127 + spread) // 128
    nbw = _nbw(nb, k_max)
    kernel = partial(
        _kernel, nb=nb, nbw=nbw, k_max=k_max, cc_count=_CC,
        interpret=interpret,
    )
    tb = t_out // 128
    return pl.pallas_call(
        kernel,
        grid=(d // _DT, tb // nb, c // _CC),
        in_specs=[
            # full channel width per trial chunk (SMEM blocks must have
            # their last dim equal to the array's); 8 x C x 4 B = 32 KB
            # at 1024 channels
            pl.BlockSpec(
                (_DT, c), lambda dd, tt, cc: (dd, 0),
                memory_space=pltpu.SMEM,
            ),
            pl.BlockSpec(memory_space=_HBM),
        ],
        out_specs=pl.BlockSpec(
            (_DT, nb, 128), lambda dd, tt, cc: (dd, tt, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((d, tb, 128), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((_DT, nb, 128), jnp.float32),
            pltpu.VMEM((_CC, nbw, 128), jnp.float32),
            pltpu.SemaphoreType.DMA((_CC,)),
        ],
        interpret=interpret,
    )


def plan_spread(delays: np.ndarray) -> int:
    """Max in-chunk delay spread: max over channels and aligned _DT-trial
    chunks of delay[last, c] - delay[first, c] (delays ascend with DM)."""
    d = np.asarray(delays)
    spread = 0
    for lo in range(0, d.shape[0], _DT):
        blk = d[lo : lo + _DT]
        spread = max(spread, int((blk.max(axis=0) - blk.min(axis=0)).max()))
    return spread


def pallas_hbm_bytes(
    t_in: int, c: int, d: int, out_nsamps: int, spread: int | None = None
) -> int:
    """Rough peak HBM need of dedisperse_pallas: the padded f32 flat
    filterbank + the full f32 output (+ the caller-held input). Used by
    dedisperse_device to keep near-limit trial sets on the blocked jnp
    path, whose working set is one trial block. Pass the REAL delay
    ``spread`` (plan_spread(delays)) when the caller holds the table —
    the one-block fallback bound undercounts when coarse high-DM steps
    spread further than one block (ADVICE r1)."""
    b = min(16384, max(_QUANT, -(-out_nsamps // _QUANT) * _QUANT))
    t_out = -(-out_nsamps // b) * b
    cpad = -(-c // _CC) * _CC
    dpad = -(-d // _DT) * _DT
    sp = spread if spread is not None else _QUANT
    tr = _tr_rows(t_in, b // 128, (127 + sp) // 128)
    return 4 * (cpad * tr * 128 + dpad * t_out) + t_in * c


def dedisperse_pallas(
    fil_tc,  # (T, C) u8/f32 filterbank (numpy or device array)
    delays: np.ndarray,  # (D, C) int32
    killmask: np.ndarray,  # (C,)
    out_nsamps: int,
    *,
    quantize: bool = True,
    scale: float = 1.0,
    block: int = 16384,
    interpret: bool = False,
    spread: int | None = None,
) -> jax.Array:
    """All DM trials in ONE kernel dispatch, bitwise equal to the jnp
    twin. Trials/channels pad to the (8, 16) grid quanta with repeated/
    zero rows; output time pads to ``block`` lanes and is trimmed.
    Pass ``spread`` (plan_spread(delays)) when the caller already
    computed it — the O(D*C) host scan is not free at survey scale."""
    delays = np.asarray(delays, dtype=np.int32)
    d, c = delays.shape
    t_in = fil_tc.shape[0]
    # don't let a small search pay a full survey-sized block: the padded
    # tail beyond out_nsamps is computed and trimmed (window slack rows
    # keep every DMA in range regardless)
    b = min(block, max(_QUANT, -(-out_nsamps // _QUANT) * _QUANT))
    t_out = -(-out_nsamps // b) * b
    if spread is None:
        spread = plan_spread(delays)

    dpad = -(-d // _DT) * _DT
    cpad = -(-c // _CC) * _CC
    if dpad > d:
        # repeat the last trial: keeps delays ascending within chunks
        delays = np.concatenate(
            [delays, np.repeat(delays[-1:], dpad - d, axis=0)]
        )
    if cpad > c:
        # extra channels: zero data rows at the max existing delay so
        # windows stay in range and contribute exact zeros
        delays = np.concatenate(
            [delays, np.tile(delays[:, -1:], (1, cpad - c))], axis=1
        )

    run = _jit_full(
        dpad, t_out, cpad, b, spread, d, c, t_in, out_nsamps,
        quantize, float(scale), interpret,
    )
    return run(jnp.asarray(fil_tc), jnp.asarray(delays),
               jnp.asarray(np.asarray(killmask)))


@lru_cache(maxsize=None)
def _jit_full(
    dpad, t_out, cpad, b, spread, d, c, t_in, out_nsamps,
    quantize, scale, interpret,
):
    """Prep (mask, f32, pad/transpose/block), the kernel, and the
    trim/scale/quantize tail as ONE jitted program: each eager op is a
    separately dispatched executable, and on a high-latency link the
    half-dozen dispatches cost more than the kernel itself."""
    fn = _build(dpad, t_out, cpad, b, spread, interpret)
    k_max = (127 + spread) // 128
    tr = _tr_rows(t_in, b // 128, k_max)

    @jax.jit
    def run(fil_tc, delays, killmask):
        x = fil_tc.astype(jnp.float32) * killmask.astype(jnp.float32)[None, :]
        # (C, TR, 128) blocked channel rows (tail zero rows = window
        # slack; never selected into real output samples)
        xp = jnp.pad(
            x.T, ((0, cpad - c), (0, tr * 128 - t_in))
        ).reshape(cpad, tr, 128)
        out = fn(delays, xp).reshape(dpad, t_out)[:d, :out_nsamps]
        if scale != 1.0:
            out = out * jnp.float32(scale)
        if quantize:
            out = jnp.clip(jnp.rint(out), 0, 255).astype(jnp.uint8)
        return out

    return run
