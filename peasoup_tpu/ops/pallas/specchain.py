"""Pallas TPU kernel for the fused spectrum-chain tail:
deredden -> zap -> interbin in one pass over the spectrum.

The unfused once-per-DM-trial stanza (pipeline/accel_search.py
_preprocess_trial) walks the (D, nbins) spectrum batch once per op:
deredden reads and rewrites the complex parts, zap reads and rewrites
them again, and the interbin amplitude pass reads them a third time.
This kernel streams each (row-block, column-tile) once through VMEM
and emits all three results — the dereddened+zapped parts (the irfft
input) and the interbinned amplitude (the stats input) — with the
interbin's left-neighbour dependency carried across column tiles in a
VMEM scratch (the column grid axis iterates sequentially per row
block, like ops/pallas/interbin.py's carry).

The arithmetic is the identical f32 chain as the jnp twin
(ops.spectrum.interp_deredden_zap): divide, select, square, max, sqrt
— so outputs are BITWISE equal to it, and the probe
(ops.pallas.probe_pallas_specchain) gates on exactly that. Columns at
or past ``nbins`` (the pad to the tile quantum) emit zeros.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

SPEC_BLOCK = 512  # column tile (lanes); rows tile in sublane groups
_ROWS = 8


def s0_envelope(twin: np.ndarray) -> np.ndarray:
    """Per-bin deviation bound for interpret-mode s0 comparisons: the
    kernel replays the twin's exact term grouping, so the only
    legitimate deviation is FMA-contraction codegen in the
    ``re*re + im*im`` / ``0.5*((dre)^2 + (dim)^2)`` sums — a few ULP of
    the bin magnitude (the dereddened+zapped parts carry no mul+add
    adjacency and stay bitwise). Mirrors ops/pallas/dftspec.py's
    twin_envelope discipline; the on-TPU probe stays bitwise."""
    t = np.asarray(twin)
    rms = np.sqrt(np.mean(t * t, axis=-1, keepdims=True))
    return 1e-6 * (np.abs(t) + rms)


def _kernel(
    nbins_ref,  # (1,) i32 SMEM (scalar prefetch)
    re_ref,  # (ROWS, BLK) f32 VMEM in tile
    im_ref,
    med_ref,
    zap_ref,  # (1, BLK) i32 in tile (birdie mask as 0/1)
    reo_ref,  # (ROWS, BLK) f32 VMEM out tiles
    imo_ref,
    s0_ref,
    carry_ref,  # (ROWS, 2) f32 VMEM scratch: last column's (re_d, im_d)
    *,
    blk: int,
    interpret: bool,
):
    c = pl.program_id(1)
    nbins = nbins_ref[0]
    rows = re_ref.shape[0]
    j = c * blk + jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 1)
    valid = j < nbins
    re = re_ref[...]
    im = im_ref[...]
    med = med_ref[...]
    zap = zap_ref[...] != 0  # (1, BLK) broadcasts over rows
    low5 = j < 5
    re_d = jnp.where(low5, jnp.float32(0.0), re / med)
    im_d = jnp.where(low5, jnp.float32(0.0), im / med)
    re_d = jnp.where(zap, jnp.float32(1.0), re_d)
    im_d = jnp.where(zap, jnp.float32(0.0), im_d)
    re_d = jnp.where(valid, re_d, jnp.float32(0.0))
    im_d = jnp.where(valid, im_d, jnp.float32(0.0))

    def roll(x, shift):
        if interpret:
            return jnp.roll(x, shift, axis=1)
        return pltpu.roll(x, shift, axis=1)

    # left neighbour: lane roll within the tile, tile-boundary lane
    # from the carry (zero at the first tile — the twin's k=0 zero)
    lane = jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 1)
    first = lane == 0
    re_l = jnp.where(
        first,
        jnp.where(c == 0, jnp.float32(0.0), carry_ref[:, 0:1]),
        roll(re_d, 1),
    )
    im_l = jnp.where(
        first,
        jnp.where(c == 0, jnp.float32(0.0), carry_ref[:, 1:2]),
        roll(im_d, 1),
    )
    ampsq = re_d * re_d + im_d * im_d
    ampsq_diff = 0.5 * ((re_d - re_l) ** 2 + (im_d - im_l) ** 2)
    s0 = jnp.sqrt(jnp.maximum(ampsq, ampsq_diff))
    carry_ref[:, 0:1] = re_d[:, blk - 1 :]
    carry_ref[:, 1:2] = im_d[:, blk - 1 :]
    reo_ref[...] = re_d
    imo_ref[...] = im_d
    s0_ref[...] = jnp.where(valid, s0, jnp.float32(0.0))


@lru_cache(maxsize=None)
def _build(d: int, npad: int, blk: int, interpret: bool):
    kernel = partial(_kernel, blk=blk, interpret=interpret)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        # rows outer, columns inner: the carry walks each row block's
        # columns in order
        grid=(d // _ROWS, npad // blk),
        in_specs=[
            pl.BlockSpec(
                (_ROWS, blk), lambda dd, cc, *_: (dd, cc),
                memory_space=pltpu.VMEM,
            )
            for _ in range(3)
        ]
        + [
            pl.BlockSpec(
                (None, blk), lambda dd, cc, *_: (0, cc),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=[
            pl.BlockSpec(
                (_ROWS, blk), lambda dd, cc, *_: (dd, cc),
                memory_space=pltpu.VMEM,
            )
            for _ in range(3)
        ],
        scratch_shapes=[pltpu.VMEM((_ROWS, 2), jnp.float32)],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((d, npad), jnp.float32) for _ in range(3)
        ],
        interpret=interpret,
    )


def interp_deredden_zap_pallas(
    re: jnp.ndarray,  # (D, nbins) f32 raw spectrum parts
    im: jnp.ndarray,
    med: jnp.ndarray,  # (D, nbins) f32 running median
    zapmask,  # (nbins,) bool
    *,
    block: int = SPEC_BLOCK,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fused deredden+zap+interbin over a trial batch; bitwise equal to
    ops.spectrum.interp_deredden_zap. Rows pad to the sublane group and
    columns to the tile quantum (median pads with ones so the pad
    division is finite; every pad output is zeroed)."""
    d, nbins = re.shape
    dpad = -(-d // _ROWS) * _ROWS
    npad = -(-nbins // block) * block
    if dpad > d or npad > nbins:
        re = jnp.pad(re, ((0, dpad - d), (0, npad - nbins)))
        im = jnp.pad(im, ((0, dpad - d), (0, npad - nbins)))
        med = jnp.pad(
            med, ((0, dpad - d), (0, npad - nbins)), constant_values=1.0
        )
    zap = jnp.pad(
        jnp.asarray(zapmask).astype(jnp.int32), (0, npad - nbins)
    ).reshape(1, npad)
    fn = _build(dpad, npad, block, interpret)
    reo, imo, s0 = fn(
        jnp.asarray(np.asarray([nbins], dtype=np.int32)), re, im, med, zap
    )
    return reo[:d, :nbins], imo[:d, :nbins], s0[:d, :nbins]
