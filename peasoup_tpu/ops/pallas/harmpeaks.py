"""Pallas TPU mega-kernel: harmonic summing fused into the peaks walk.

Replaces the harmonic_sums(method="conv") -> find_cluster_peaks_multi
pair for the production search. The conv formulation is XLA-optimal
but HBM-bound BY CONSTRUCTION: the cumulative val chain is 31 (nharms=5)
or 15 (nharms=4) separate conv+add HLOs, each of which must round-trip
the full (rows, npad) accumulator through HBM — measured 38.7 GB /
51.6 ms at the dense tutorial grid, plus ~18 ms of layout copies
between the conv outputs and the peaks custom call and a further level
write+read for the walk (NOTES.md round-4 trace). Here the whole
chain — gather, accumulate, scale, threshold, cluster-walk — runs in
VMEM; HBM traffic drops to the spectrum block reads (~sum(k/2^h)+1
passes) and the tiny peak outputs.

Harmonic gather in VMEM (reference math: harmonic_sum_kernel,
src/kernels.cu:33-208; same exact integer index map as
ops/harmonics.py): for stream (h, k odd < 2^h) the source index of
output bin i is (i*k + 2^(h-1)) >> h. Per bin block b of width B the
sources live in [b*Bq, (b+1)*Bq] with Bq = B*k >> h (exact: 2^h | B*k),
fetched as one (SUB, Bq) operand at block index b plus two (SUB, 128)
edge operands at lanes (b+1)*Bq and (b+1)*Bq + 128. Writing
i = g*128 + r the local source is g*s + c_r with s = 128*k >> h and
c_r = (r*k + 2^(h-1)) >> h <= s < 128; each 128-lane group's window is
carved from VMEM as an ALIGNED 256-wide slice (pure vreg renames) plus
one pltpu.roll by the group's phase g*s mod 128 (Mosaic CRASHES on
misaligned 128-slices — probed r4), then all G groups are gathered by
one shared constant one-hot (128, 128) MXU dot. One-hot matmul is an
exact gather (harmonics.py "conv"/"mxu" argument; Mosaic rejects
per-operand precision, and at plain HIGHEST the one-hot side's extra
split terms are exact zeros), so accumulated level values are BITWISE
identical to method="take" and the walk outputs are bitwise identical
to find_cluster_peaks_multi on conv-produced levels.

Accumulation order per element matches the reference exactly: base
spectrum, then levels h ascending, odd k ascending within each level —
one `+` at a time (harmonics.py harmonic_sums contract).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .peaks import _BIG, _BLOCK, _SUB, _level_machine  # shared walk machine


def _streams(nharms: int) -> list[tuple[int, int]]:
    """(h, k) per fresh gather, in the reference's accumulation order."""
    return [
        (h, k) for h in range(1, nharms + 1) for k in range(1, 1 << h, 2)
    ]


@lru_cache(maxsize=None)
def _gather_consts(nharms: int) -> np.ndarray:
    """(nstreams*128, 128) stacked one-hot gather matrices: block si
    holds C[c, r] = 1 iff (r*k + 2^(h-1)) >> h == c for stream si."""
    mats = []
    for h, k in _streams(nharms):
        r = np.arange(128)
        c_r = (r * k + (1 << (h - 1))) >> h
        C = np.zeros((128, 128), dtype=np.float32)
        C[c_r, r] = 1.0
        mats.append(C)
    return np.concatenate(mats, axis=0)


def _kernel_harm(*refs, nharms, mx, nbins, threshold, min_gap, scales):
    ns = len(_streams(nharms))
    nlev = nharms + 1
    win_ref, c_ref, base_ref = refs[:3]
    mains = refs[3 : 3 + ns]
    edges1 = refs[3 + ns : 3 + 2 * ns]
    edges2 = refs[3 + 2 * ns : 3 + 3 * ns]
    idx_ref, snr_ref, cnt_ref = refs[3 + 3 * ns : 6 + 3 * ns]
    istate, fstate, mstate = refs[6 + 3 * ns : 9 + 3 * ns]
    b = pl.program_id(1)
    nb = pl.num_programs(1)

    @pl.when(b == 0)
    def _():
        istate[:] = jnp.zeros((_SUB, 128), jnp.int32)
        fstate[:] = jnp.zeros((_SUB, 128), jnp.float32)
        idx_ref[:] = jnp.full((_SUB, nlev * mx), nbins, jnp.int32)
        snr_ref[:] = jnp.zeros((_SUB, nlev * mx), jnp.float32)

    gidx = b * _BLOCK + jax.lax.broadcasted_iota(jnp.int32, (_SUB, _BLOCK), 1)
    slot = jax.lax.broadcasted_iota(jnp.int32, (_SUB, mx), 1)
    G = _BLOCK // 128

    def machine(lvl, val):
        _level_machine(
            lvl, val, win_ref=win_ref, idx_ref=idx_ref, snr_ref=snr_ref,
            cnt_ref=cnt_ref, istate=istate, fstate=fstate, mstate=mstate,
            b=b, nb=nb, gidx=gidx, slot=slot, mx=mx,
            threshold=threshold, min_gap=min_gap, scale=scales[lvl],
        )

    val = base_ref[:]
    machine(0, val)
    si = 0
    for h in range(1, nharms + 1):
        for k in range(1, 1 << h, 2):
            s_ = (128 * k) >> h
            inb = jnp.concatenate(
                [mains[si][:], edges1[si][:], edges2[si][:]], axis=1
            )
            # group g's window inb[g*s_ : g*s_+128] is MISALIGNED
            # (g*s_ mod 128 != 0) and Mosaic crashes lowering such
            # slices: carve an aligned 256-wide slice (vreg renames)
            # and phase-align it with one cheap lane roll instead
            wnds = []
            for g in range(G):
                a = (g * s_) // 128 * 128
                ph = g * s_ - a
                w = inb[:, a : a + 256]
                if ph:
                    w = pltpu.roll(w, 256 - ph, 1)
                wnds.append(w[:, :128])
            x = jnp.stack(wnds, axis=1)  # (SUB, G, 128), natural order
            chk = c_ref[si * 128 : (si + 1) * 128, :]
            # Mosaic rejects per-operand dot precision (the XLA conv
            # path's (HIGHEST, DEFAULT) trick) and HIGHEST-both-sides
            # pays dead extra passes against the one-hot operand, so
            # split the data side into an exact 3-term bf16 sum and run
            # three 1-pass bf16 dots. The split TRUNCATES via bit
            # masking (each term = the next 16 bits of the f32 word,
            # always exactly representable in bf16; each residual
            # subtraction is exact by cancellation) rather than
            # round-trip casts, which compilers may elide under
            # --xla_allow_excess_precision (observed: the rounding
            # split collapses to r1 == 0 in interpret mode). Each dot's
            # output is the exact gather of its term (one-hot), and
            # (hi+mid)+lo reconstructs x[src] bitwise — measured equal
            # to the HIGHEST dot on v5e and ~9% faster
            msk = jnp.uint32(0xFFFF0000)
            xi = jax.lax.bitcast_convert_type(x, jnp.uint32)
            hi_f = jax.lax.bitcast_convert_type(xi & msk, jnp.float32)
            r1 = x - hi_f
            r1i = jax.lax.bitcast_convert_type(r1, jnp.uint32)
            mid_f = jax.lax.bitcast_convert_type(r1i & msk, jnp.float32)
            lo_f = r1 - mid_f
            chkb = chk.astype(jnp.bfloat16)  # 0/1: exact in bf16

            def dd(a):
                # a is exactly bf16-representable, so the cast is
                # exact; the f32 output cast is a no-op on TPU (MXU
                # accumulates f32) and keeps interpret backends that
                # return bf16 exact (single one-hot term per output)
                return jax.lax.dot_general(
                    a.astype(jnp.bfloat16), chkb, (((2,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ).astype(jnp.float32)

            gat = (dd(hi_f) + dd(mid_f)) + dd(lo_f)
            val = val + gat.reshape(_SUB, _BLOCK)
            si += 1
        machine(h, val)


@lru_cache(maxsize=None)
def _build_harm(
    rows: int, npad: int, nharms: int, mx: int, nbins: int,
    threshold: float, min_gap: int, scales: tuple, interpret: bool,
):
    streams = _streams(nharms)
    nlev = nharms + 1
    kernel = partial(
        _kernel_harm, nharms=nharms, mx=mx, nbins=nbins,
        threshold=threshold, min_gap=min_gap, scales=scales,
    )
    nblk = npad // _BLOCK
    main_specs, edge1_specs, edge2_specs = [], [], []
    nmax = npad // 128 - 1
    for h, k in streams:
        bq = (_BLOCK * k) >> h  # lane width of one main block (mult of 128)
        main_specs.append(
            pl.BlockSpec((_SUB, bq), lambda r, b: (r, b))
        )
        e = bq // 128  # edge block index stride, in 128-lane units
        # two trailing 128-lane edge blocks cover the aligned 256-wide
        # window carve-out past the main block; the in-bounds clamp can
        # only bind for windows whose outputs lie in the masked pad
        # region (real-bin sources stay < nbins <= npad - npad/2^h)
        edge1_specs.append(
            pl.BlockSpec(
                (_SUB, 128),
                lambda r, b, e=e: (r, jnp.minimum((b + 1) * e, nmax)),
            )
        )
        edge2_specs.append(
            pl.BlockSpec(
                (_SUB, 128),
                lambda r, b, e=e: (r, jnp.minimum((b + 1) * e + 1, nmax)),
            )
        )
    return pl.pallas_call(
        kernel,
        grid=(rows // _SUB, nblk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # windows
            pl.BlockSpec(
                (len(streams) * 128, 128), lambda r, b: (0, 0)
            ),  # one-hot gather constants
            pl.BlockSpec((_SUB, _BLOCK), lambda r, b: (r, b)),  # base
        ]
        + main_specs
        + edge1_specs
        + edge2_specs,
        out_specs=[
            pl.BlockSpec((_SUB, nlev * mx), lambda r, b: (r, 0)),
            pl.BlockSpec((_SUB, nlev * mx), lambda r, b: (r, 0)),
            pl.BlockSpec((_SUB, nlev * 2), lambda r, b: (r, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, nlev * mx), jnp.int32),
            jax.ShapeDtypeStruct((rows, nlev * mx), jnp.float32),
            jax.ShapeDtypeStruct((rows, nlev * 2), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((_SUB, 128), jnp.int32),
            pltpu.VMEM((_SUB, 128), jnp.float32),
            pltpu.VMEM((_SUB, _BLOCK), jnp.int32),
        ],
        interpret=interpret,
    )


def find_harmonic_cluster_peaks(
    spec,  # (..., npad) f32 normalised spectrum, pre-padded to _BLOCK
    windows: jnp.ndarray,  # (nharms+1, 2) i32 [start, limit) per level
    *,
    nharms: int,
    threshold: float,
    max_peaks: int,
    scales: tuple,  # per-level in-VMEM factors (level 0 first)
    min_gap: int = 30,
    interpret: bool = False,
    nbins: int | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One-dispatch equivalent of harmonic_sums(method="conv",
    scaled=False, block_align=_BLOCK) + find_cluster_peaks_multi.
    Returns (idxs (..., nlev, max_peaks), snrs, raw counts (..., nlev),
    cluster counts (..., nlev)); nlev = nharms + 1, level 0 the base
    spectrum. ``nbins`` is the TRUE bin count (idx pad sentinel);
    windows' hi bounds are clamped to it, masking both the pad region
    and the pad-region harmonic values (which gather real low bins,
    exactly like the conv path's block_align garbage).
    """
    if not 0 < nharms <= 5:
        raise ValueError("nharms must be in 1..5")
    nbins_in = spec.shape[-1]
    if nbins_in % _BLOCK:
        raise ValueError(
            f"spec last axis must be a multiple of the peaks block "
            f"({_BLOCK}); got {nbins_in} — pad upstream"
        )
    nlev = nharms + 1
    if len(scales) != nlev or windows.shape[0] != nlev:
        raise ValueError("scales/windows must cover nharms+1 levels")
    nbins = nbins if nbins is not None else nbins_in
    windows = jnp.stack(
        [windows[:, 0], jnp.minimum(windows[:, 1], nbins)], axis=1
    )
    batch = spec.shape[:-1]
    rows = 1
    for d in batch:
        rows *= d
    rpad = -(-rows // _SUB) * _SUB
    flat = spec.reshape(rows, nbins_in)
    if rpad != rows:
        flat = jnp.pad(flat, ((0, rpad - rows), (0, 0)))
    fn = _build_harm(
        rpad, nbins_in, nharms, max_peaks, nbins, float(threshold),
        min_gap, tuple(float(x) for x in scales), interpret,
    )
    consts = jnp.asarray(_gather_consts(nharms))
    ns = len(_streams(nharms))
    args = [windows.astype(jnp.int32), consts, flat]
    args += [flat] * ns  # main stream views (index-mapped slices)
    args += [flat] * (2 * ns)  # two edge views per stream
    cidx, csnr, counts = fn(*args)
    cidx = cidx[:rows].reshape(*batch, nlev, max_peaks)
    csnr = csnr[:rows].reshape(*batch, nlev, max_peaks)
    counts = counts[:rows].reshape(*batch, nlev, 2)
    return cidx, csnr, counts[..., 0], counts[..., 1]
