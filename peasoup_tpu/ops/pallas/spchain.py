"""Pallas TPU kernel for the fused single-pulse chain tail:
boxcar width sweep + dec-fold best-plane decimation in one VMEM pass.

The unfused pair (ops/pallas/boxcar.py, then the jnp reshape/max/argmax
decimation in ops.singlepulse.make_single_pulse_search_fn) writes the
full (D, tpad) best-S/N and best-width planes to HBM only for the very
next op to re-read and crush them ``dec``-fold. This kernel keeps the
whole tail resident: per (dm, tile) grid step one dynamic-offset DMA
brings in the prefix-sum window, the width sweep runs as lane-rolls of
that window exactly like the boxcar kernel, and the dec-fold
(block max, in-block argmax, width at the argmax) happens on the VMEM
tile before anything touches HBM — the planes that leave the chip are
``dec``x smaller.

Index math is the identical f32/i32 chain as the jnp twin
(ops.singlepulse.boxcar_dec_best_twin): subtract, scale, mask,
strict-> running max, then first-max argmax via a lane-iota min — so
outputs are BITWISE equal to it; the probe
(ops.pallas.probe_pallas_spchain) gates on exactly that. The dec-fold
retile of the (1, span) sweep into (span/dec, dec) sublane x lane form
is the one feature beyond ops/pallas/boxcar.py's set, and Mosaic
support for it varies by toolchain — which is precisely why the probe
compiles and runs the real kernel before the driver may route to it.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_QUANT = 1024


def _kernel(
    widths_ref,  # (W,) i32 SMEM (scalar prefetch)
    scales_ref,  # (W,) f32 SMEM (scalar prefetch)
    nvalid_ref,  # (1,) i32 SMEM (scalar prefetch)
    csum_ref,  # flat (D * row_stride,) f32 HBM
    bmax_ref,  # (1, span // dec) f32 VMEM out tile
    barg_ref,  # (1, span // dec) i32 VMEM out tile (in-block argmax)
    bw_ref,  # (1, span // dec) i32 VMEM out tile (width at argmax)
    win_ref,  # (span + wext,) f32 VMEM scratch
    sem,
    *,
    span: int,
    wext: int,
    dec: int,
    row_stride: int,
    n_widths: int,
    interpret: bool,
):
    d = pl.program_id(0)
    g = pl.program_id(1)
    clen = span + wext
    u = d * row_stride + g * span  # 1024-aligned: both terms are
    copy = pltpu.make_async_copy(
        csum_ref.at[pl.ds(pl.multiple_of(u, _QUANT), clen)], win_ref, sem
    )
    copy.start()
    j = g * span + jax.lax.broadcasted_iota(jnp.int32, (1, span), 1)
    nvalid = nvalid_ref[0]
    neg_inf = jnp.float32(-jnp.inf)
    copy.wait()
    chunk = win_ref[...].reshape(1, clen)
    lo = chunk[:, :span]
    best = jnp.full((1, span), neg_inf, jnp.float32)
    bw = jnp.zeros((1, span), jnp.int32)
    for k in range(n_widths):
        w = widths_ref[k]
        scale = scales_ref[k]
        if interpret:
            hi = jax.lax.dynamic_slice(chunk, (0, w), (1, span))
        else:
            hi = pltpu.roll(chunk, clen - w, axis=1)[:, :span]
        snr = jnp.where(j + w <= nvalid, (hi - lo) * scale, neg_inf)
        better = snr > best
        best = jnp.where(better, snr, best)
        bw = jnp.where(better, jnp.int32(k), bw)
    # dec-fold on the resident tile: block max, FIRST-max argmax (the
    # jnp twin's jnp.argmax semantics) via a lane-iota min, and the
    # width index at that argmax via a one-hot sum
    nbd = span // dec
    blk = best.reshape(nbd, dec)
    bw_blk = bw.reshape(nbd, dec)
    bmax = jnp.max(blk, axis=1, keepdims=True)  # (nbd, 1)
    lane = jax.lax.broadcasted_iota(jnp.int32, (nbd, dec), 1)
    barg = jnp.min(
        jnp.where(blk == bmax, lane, jnp.int32(dec)), axis=1, keepdims=True
    )
    wsel = jnp.sum(
        jnp.where(lane == barg, bw_blk, jnp.int32(0)), axis=1, keepdims=True
    )
    bmax_ref[:] = bmax.reshape(-1)
    barg_ref[:] = barg.reshape(-1)
    bw_ref[:] = wsel.reshape(-1)


@lru_cache(maxsize=None)
def _build(
    d: int, tpad: int, span: int, wext: int, dec: int, n_widths: int,
    interpret: bool,
):
    row_stride = tpad + wext  # a _QUANT multiple (plan_pad/width_extent)
    kernel = partial(
        _kernel,
        span=span,
        wext=wext,
        dec=dec,
        row_stride=row_stride,
        n_widths=n_widths,
        interpret=interpret,
    )
    nbd = span // dec
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(d, tpad // span),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=[
            pl.BlockSpec(
                (None, nbd), lambda dd, gg, *_: (dd, gg),
                memory_space=pltpu.VMEM,
            )
            for _ in range(3)
        ],
        scratch_shapes=[
            pltpu.VMEM((span + wext,), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((d, tpad // dec), jnp.float32),
            jax.ShapeDtypeStruct((d, tpad // dec), jnp.int32),
            jax.ShapeDtypeStruct((d, tpad // dec), jnp.int32),
        ],
        interpret=interpret,
    )


def boxcar_dec_best_pallas(
    csum_pad: jnp.ndarray,  # (D, tpad + wext) from prefix_sum_padded
    widths: tuple[int, ...],
    scales: np.ndarray,
    nvalid: int,
    tpad: int,
    dec: int,
    *,
    span: int,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fused width sweep + dec-fold; bitwise equal to
    ops.singlepulse.boxcar_dec_best_twin. Returns (block max S/N
    (D, tpad/dec) f32, in-block argmax (D, tpad/dec) i32, width index
    at the argmax (D, tpad/dec) i32). ``span`` must divide ``tpad``
    and ``dec`` must divide ``span``."""
    d, row = csum_pad.shape
    wext = row - tpad
    if (
        tpad % span
        or span % dec
        or row % _QUANT
        or wext <= int(max(widths))
    ):
        raise ValueError(
            f"boxcar_dec_best_pallas: incompatible geometry tpad={tpad} "
            f"span={span} dec={dec} wext={wext} widths<={max(widths)}"
        )
    fn = _build(d, tpad, span, wext, dec, len(widths), interpret)
    return fn(
        jnp.asarray(widths, dtype=jnp.int32),
        jnp.asarray(scales, dtype=jnp.float32),
        jnp.asarray([nvalid], dtype=jnp.int32),
        csum_pad.reshape(-1),
    )
