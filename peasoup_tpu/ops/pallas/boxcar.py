"""Pallas TPU kernel for the single-pulse boxcar width sweep.

The jnp twin (ops/singlepulse.boxcar_best_twin) reads the padded
prefix-sum rows W + 1 times from HBM (one shifted stream per width plus
the base). This kernel streams each prefix-sum tile into VMEM ONCE and
runs the whole width sweep there: per (dm, tile) grid step, one
dynamic-offset DMA brings in ``span + wext`` contiguous samples, and
every boxcar width becomes a lane-roll of that resident window —
W shifted reads of VMEM instead of W passes over HBM.

The width list and its 1/sqrt(w) scales ride in as SCALAR-PREFETCH
operands (SMEM), so one compiled kernel serves every width
configuration of the same count: the sweep loop is unrolled statically
over the width COUNT while each width VALUE is a runtime scalar read.

Lowering constraints follow ops/pallas/resample.py: the input is a
flat 1-D array of 1024-aligned padded rows (1-D dynamic-offset DMA
slices must start/size on 1024-lane quanta — here both the row stride
and the tile span are 1024 multiples, so window starts are aligned by
construction), and the dynamic per-width shift uses pltpu.roll on the
VMEM window (dynamic_slice in interpret mode).

Index math is the identical f32 chain as the twin — subtract, scale,
mask, strict-> running max — so outputs are BITWISE equal to it; the
probe (ops.pallas.probe_pallas_boxcar) gates on exactly that.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_QUANT = 1024


def _kernel(
    widths_ref,  # (W,) i32 SMEM (scalar prefetch)
    scales_ref,  # (W,) f32 SMEM (scalar prefetch)
    nvalid_ref,  # (1,) i32 SMEM (scalar prefetch)
    csum_ref,  # flat (D * row_stride,) f32 HBM
    best_ref,  # (1, span) f32 VMEM out tile
    bw_ref,  # (1, span) i32 VMEM out tile
    win_ref,  # (span + wext,) f32 VMEM scratch
    sem,
    *,
    span: int,
    wext: int,
    row_stride: int,
    n_widths: int,
    interpret: bool,
):
    d = pl.program_id(0)
    g = pl.program_id(1)
    clen = span + wext
    u = d * row_stride + g * span  # 1024-aligned: both terms are
    copy = pltpu.make_async_copy(
        csum_ref.at[pl.ds(pl.multiple_of(u, _QUANT), clen)], win_ref, sem
    )
    copy.start()
    j = g * span + jax.lax.broadcasted_iota(jnp.int32, (1, span), 1)
    nvalid = nvalid_ref[0]
    neg_inf = jnp.float32(-jnp.inf)
    copy.wait()
    chunk = win_ref[...].reshape(1, clen)
    lo = chunk[:, :span]
    best = jnp.full((1, span), neg_inf, jnp.float32)
    bw = jnp.zeros((1, span), jnp.int32)
    for k in range(n_widths):
        w = widths_ref[k]
        scale = scales_ref[k]
        if interpret:
            hi = jax.lax.dynamic_slice(chunk, (0, w), (1, span))
        else:
            hi = pltpu.roll(chunk, clen - w, axis=1)[:, :span]
        snr = jnp.where(j + w <= nvalid, (hi - lo) * scale, neg_inf)
        better = snr > best
        best = jnp.where(better, snr, best)
        bw = jnp.where(better, jnp.int32(k), bw)
    best_ref[:] = best.reshape(-1)
    bw_ref[:] = bw.reshape(-1)


@lru_cache(maxsize=None)
def _build(
    d: int, tpad: int, span: int, wext: int, n_widths: int, interpret: bool
):
    row_stride = tpad + wext  # already a _QUANT multiple (plan_pad/width_extent)
    kernel = partial(
        _kernel,
        span=span,
        wext=wext,
        row_stride=row_stride,
        n_widths=n_widths,
        interpret=interpret,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(d, tpad // span),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=[
            pl.BlockSpec(
                (None, span), lambda dd, gg, *_: (dd, gg),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (None, span), lambda dd, gg, *_: (dd, gg),
                memory_space=pltpu.VMEM,
            ),
        ],
        scratch_shapes=[
            pltpu.VMEM((span + wext,), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((d, tpad), jnp.float32),
            jax.ShapeDtypeStruct((d, tpad), jnp.int32),
        ],
        interpret=interpret,
    )


def boxcar_best_pallas(
    csum_pad: jnp.ndarray,  # (D, tpad + wext) from prefix_sum_padded
    widths: tuple[int, ...],
    scales: np.ndarray,
    nvalid: int,
    tpad: int,
    *,
    span: int,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """VMEM-resident width sweep; bitwise equal to boxcar_best_twin.
    ``span`` must divide ``tpad`` (both from ops.singlepulse.plan_pad);
    the row length tpad + wext doubles as the (1024-aligned) flat row
    stride."""
    d, row = csum_pad.shape
    wext = row - tpad
    if tpad % span or row % _QUANT or wext <= int(max(widths)):
        raise ValueError(
            f"boxcar_best_pallas: incompatible geometry tpad={tpad} "
            f"span={span} wext={wext} widths<={max(widths)}"
        )
    fn = _build(d, tpad, span, wext, len(widths), interpret)
    return fn(
        jnp.asarray(widths, dtype=jnp.int32),
        jnp.asarray(scales, dtype=jnp.float32),
        jnp.asarray([nvalid], dtype=jnp.int32),
        csum_pad.reshape(-1),
    )
