"""Cross-beam delay finding via frequency-domain cross-correlation.

Reference: ``DelayFinder::find_delays`` (include/transforms/correlator.hpp:44-92)
FFTs beam ``ii``, conjugates it (device_conjugate, src/kernels.cu:1104-1120),
then for every later beam ``jj`` FFTs it, multiplies in place
(device_cuCmulf_inplace, kernels.cu:1122-1139), inverse-FFTs, copies the
first and last ``max_delay`` lag bins to the host and takes the argmax of
their powers. (``FringeFinder`` is an empty stub in the reference,
correlator.hpp:18-23 — not reproduced.)

TPU design: the reference recomputes FFT(y) for every pair — O(B^2) FFTs.
Here every beam is FFT'd ONCE, the conjugate products for all baselines
are formed as one batched elementwise multiply, and one batched inverse
FFT + windowed argmax finishes the job on-device. The +/-max_delay lag
window is gathered with static slices, so the whole thing is a single
jitted program.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class DelayResult(NamedTuple):
    """Per-baseline cross-correlation peaks.

    pairs: (P, 2) int32 beam-index pairs (ii, jj) with ii < jj.
    distance: (P,) int32 argmax position inside the 2*max_delay lag
      window — identical to the reference's printed "Distance"
      (correlator.hpp:85-86): [0, max_delay) are lags 0..max_delay-1,
      [max_delay, 2*max_delay) are lags -max_delay..-1.
    lag: (P,) int32 signed sample delay of the correlation peak.
    power: (P,) float32 |cc|^2 at the peak.
    """

    pairs: np.ndarray
    distance: jax.Array
    lag: jax.Array
    power: jax.Array


def baseline_pairs(nbeams: int) -> np.ndarray:
    """All (ii, jj) with ii < jj, in the reference's loop order
    (correlator.hpp:62-69)."""
    return np.asarray(
        [(i, j) for i in range(nbeams) for j in range(i + 1, nbeams)],
        dtype=np.int32,
    ).reshape(-1, 2)


@partial(jax.jit, static_argnames=("max_delay",))
def _find_delays(beams: jax.Array, pairs: jax.Array, *, max_delay: int):
    spectra = jnp.fft.fft(beams, axis=-1)  # one FFT per beam, not per pair
    prod = jnp.conj(spectra[pairs[:, 0]]) * spectra[pairs[:, 1]]
    cc = jnp.fft.ifft(prod, axis=-1)  # (P, N) cross-correlations
    # +/-max_delay lag window, ordered like the reference's two D2H
    # copies (correlator.hpp:77-78): positive lags then negative lags
    window = jnp.concatenate([cc[:, :max_delay], cc[:, -max_delay:]], axis=-1)
    power = window.real**2 + window.imag**2
    distance = jnp.argmax(power, axis=-1).astype(jnp.int32)
    lag = jnp.where(distance < max_delay, distance, distance - 2 * max_delay)
    peak = jnp.take_along_axis(power, distance[:, None].astype(jnp.int32), -1)
    return distance, lag, peak[:, 0].astype(jnp.float32)


def find_delays(beams, max_delay: int) -> DelayResult:
    """Cross-correlate every beam pair and locate the peak lag.

    Args:
      beams: (B, N) real or complex time series (the reference's packed
        complex chars arrive here already unpacked to complex64).
      max_delay: lag search half-window in samples.

    Returns a DelayResult over all B*(B-1)/2 baselines.
    """
    beams = jnp.asarray(beams)
    if not jnp.iscomplexobj(beams):
        beams = beams.astype(jnp.complex64)
    if beams.ndim != 2:
        raise ValueError("beams must be (nbeams, nsamps)")
    nbeams, nsamps = beams.shape
    if not 0 < 2 * max_delay <= nsamps:
        raise ValueError("max_delay must be in (0, nsamps/2]")
    pairs = baseline_pairs(nbeams)
    distance, lag, power = _find_delays(
        beams, jnp.asarray(pairs), max_delay=max_delay
    )
    return DelayResult(pairs=pairs, distance=distance, lag=lag, power=power)


# --- audit registry: representative shape plus a ShapeCtx hook at a
# bucket's trial length (beam delay correlation runs over the same
# per-beam series the coincidencer consumes) ---
from .registry import register_program, sds  # noqa: E402


def _param_find_delays(ctx):
    n = ctx.out_nsamps
    if n <= 8:
        return None
    return (
        _find_delays,
        (sds((3, n), "float32"), sds((3, 2), "int32")),
        {"max_delay": max(1, min(256, n // 2))},
    )


register_program(
    "ops.correlate.find_delays",
    lambda: (
        _find_delays,
        (sds((3, 64), "float32"), sds((3, 2), "int32")),
        {"max_delay": 4},
    ),
    param=_param_find_delays,
)
