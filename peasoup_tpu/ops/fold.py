"""Time-series folding into (subint, phase-bin) profiles.

Reference: fold_time_series_kernel — one CUDA block per subint builds a
shared-memory phase histogram with atomicAdd, phase from
frac(jj*tsamp/period)*nbins in f64, and a count array initialised to 1
(an off-by-one bias kept for parity; src/kernels.cu:597-651).

TPU design: the phase->bin map is data-independent integer-valued
metadata; it is computed EXACTLY in host f64 (TPU f64 is emulated and
slow) and shipped as an i32 array, while the fold itself is an on-device
segment-sum — which batches naturally over many candidates (the
reference's abandoned fold_subintegration_kernel intent,
src/folding_kernels.cu).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def fold_bins_np(
    nsamps: int, tsamp: float, period: float, nbins: int, nints: int
) -> np.ndarray:
    """Exact (f64) flattened (subint*nbins + phase_bin) index per sample.

    Samples beyond nints*(nsamps//nints) are dropped, like the kernel's
    per-block ranges. Returns (nints*(nsamps//nints),) int32.
    """
    nsps = nsamps // nints
    used = nsps * nints
    jj = np.arange(used, dtype=np.float64)
    frac = np.mod(jj * (tsamp / period), 1.0)
    bins = np.floor(frac * nbins).astype(np.int32)
    subs = (np.arange(used) // nsps).astype(np.int32)
    return subs * nbins + bins


@partial(jax.jit, static_argnames=("nbins", "nints"))
def fold_time_series(
    x: jnp.ndarray,  # (..., used_nsamps) resampled time series
    flat_bins: jnp.ndarray,  # (..., used_nsamps) int32 from fold_bins_np
    *,
    nbins: int,
    nints: int,
) -> jnp.ndarray:
    """Segment-sum fold -> (..., nints, nbins), value = sum/(1+hits)."""
    nseg = nints * nbins

    def one(xi, bi):
        sums = jax.ops.segment_sum(xi, bi, num_segments=nseg)
        counts = jax.ops.segment_sum(jnp.ones_like(xi), bi, num_segments=nseg)
        return (sums / (counts + 1.0)).reshape(nints, nbins)

    batch = x.shape[:-1]
    if batch:
        flat = x.reshape(-1, x.shape[-1])
        fb = flat_bins.reshape(-1, x.shape[-1])
        out = jax.vmap(one)(flat, fb)
        return out.reshape(*batch, nints, nbins)
    return one(x, flat_bins)


def fold_time_series_np(
    x: np.ndarray, nsamps: int, tsamp: float, period: float, nbins: int, nints: int
) -> np.ndarray:
    """NumPy f64 oracle of the CUDA fold, count-bias included."""
    flat = fold_bins_np(nsamps, tsamp, period, nbins, nints)
    used = len(flat)
    sums = np.bincount(flat, weights=x[:used].astype(np.float64), minlength=nints * nbins)
    counts = np.bincount(flat, minlength=nints * nbins) + 1.0
    return (sums / counts).reshape(nints, nbins)


# --- audit registry: representative shape plus a ShapeCtx hook at a
# bucket's fold geometry (pipeline.folder.fold_geometry rides the ctx
# as fold_nsamps/fold_nbins/fold_nints) ---
from .registry import register_program, sds  # noqa: E402


def _param_fold_time_series(ctx):
    if ctx.fold_nsamps <= 0:
        return None
    used = ctx.fold_nints * (ctx.fold_nsamps // ctx.fold_nints)
    if used <= 0:
        return None
    return (
        fold_time_series,
        (sds((used,), "float32"), sds((used,), "int32")),
        {"nbins": ctx.fold_nbins, "nints": ctx.fold_nints},
    )


register_program(
    "ops.fold.fold_time_series",
    lambda: (
        fold_time_series,
        (sds((1024,), "float32"), sds((1024,), "int32")),
        {"nbins": 16, "nints": 4},
    ),
    param=_param_fold_time_series,
)
