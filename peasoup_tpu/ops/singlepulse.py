"""Single-pulse search device ops: per-trial normalisation + boxcar
matched filtering over the dedispersed DM-time plane.

The reference pipeline has NO single-pulse stage — it searches
periodicity only. This module is the framework's new workload
(ROADMAP: "opens a new workload"), following the canonical shape of
GPU single-pulse pipelines (Heimdall; GSP, arXiv:2110.12749; the
auto-tuned dedispersion survey work, arXiv:1601.01165): each DM
trial's time series is baseline/variance normalised, convolved with a
bank of ~12 log-spaced boxcar filters via cumulative-sum differencing,
and thresholded in S/N.

TPU design: everything is one jitted program over a (dm_block, nsamps)
trial block — static shapes, no scalar loops.

* The boxcar bank collapses to a per-sample BEST-width plane:
  ``best[d, t] = max_w snr_w[d, t]`` and ``argw[d, t]``. This is the
  W-fold memory reduction that makes the sweep device-friendly (the
  full (D, W, T) S/N cube never exists in HBM), and per-sample best
  width is exactly what single-pulse candidates report.
* S/N extraction reuses the periodicity search's static-shape peak
  machinery (ops/peaks.find_peaks_device) on a ``dec``-fold
  max-decimated view of the best plane, with the true sample index
  recovered from the in-block argmax — crossings are bounded by
  run-length/dec, so a bright broad pulse cannot overflow the
  compaction the way raw per-sample crossings would.
* An optional Pallas kernel (ops/pallas/boxcar.py) keeps the width
  sweep VMEM-resident with a scalar-prefetch width list; it is gated
  by a compile+run bitwise oracle probe and falls back to the jnp
  twin here, exactly like the other Pallas ops.

The boxcar at sample ``t`` with width ``w`` covers ``[t, t + w)``:
``snr_w[t] = (csum[t + w] - csum[t]) * scale[w]`` with
``scale[w] = 1/sqrt(w)`` on the normalised series — the matched-filter
S/N for a top-hat pulse in unit-variance noise.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from .peaks import find_peaks_device

# 1-D tiling quantum shared with the Pallas kernel (lane granularity of
# flat refs; see ops/pallas/resample.py for the lowering constraints)
_QUANT = 1024
_SPAN_MAX = 8192  # samples per kernel invocation (VMEM window ~45 KB)

# Std retained by a +-3 sigma clipped Gaussian:
# sqrt(1 - 6*phi(3)/(2*Phi(3)-1)). The robust clipping passes estimate
# sigma from clipped samples; dividing by the retention unbiases it so
# reported S/N matches the matched-filter expectation on pure noise.
CLIP3_STD_RETENTION = 0.9865835
DEFAULT_N_WIDTHS = 12


def default_widths(n_widths: int = DEFAULT_N_WIDTHS, max_width: int = 0):
    """Octave-spaced boxcar widths 1, 2, 4, ... (samples). ``max_width``
    > 0 additionally caps the largest width (the driver caps at a
    fraction of the trial length so the filter never outgrows the
    data)."""
    widths = []
    for k in range(max(1, n_widths)):
        w = 1 << k
        if max_width and w > max_width:
            break
        widths.append(w)
    return tuple(widths)


def width_scales(widths) -> np.ndarray:
    """Matched-filter normalisation 1/sqrt(w) per width, rounded once
    to f32 (the single source both the jnp twin and the Pallas kernel
    multiply by, keeping them bitwise comparable)."""
    return (1.0 / np.sqrt(np.asarray(widths, dtype=np.float64))).astype(
        np.float32
    )


def plan_pad(nsamps: int) -> tuple[int, int]:
    """(tpad, span): trial rows pad to ``tpad`` samples processed in
    ``span``-sample kernel tiles; both are _QUANT multiples and span
    divides tpad (Mosaic 1-D refs tile in 1024-lane quanta)."""
    span = _SPAN_MAX if nsamps >= _SPAN_MAX else -(-nsamps // _QUANT) * _QUANT
    tpad = -(-nsamps // span) * span
    return tpad, span


def width_extent(widths) -> int:
    """Window slack past a tile for the largest boxcar, rounded to the
    tiling quantum (the kernel's DMA length is span + this)."""
    return -(-(int(max(widths)) + 2) // _QUANT) * _QUANT


@partial(jax.jit, static_argnames=("clip_sigma", "n_rounds"))
def normalise_trials(
    x: jnp.ndarray, *, clip_sigma: float = 3.0, n_rounds: int = 2
) -> jnp.ndarray:
    """Per-trial baseline/variance normalisation with iterative
    sigma-clipped moment re-estimation: moments over the full trial,
    then ``n_rounds`` passes over samples within ``clip_sigma`` of the
    running estimate, so a bright pulse does not inflate its own noise
    estimate (a single pass is not enough — the pulse inflates the
    FIRST std, so its clip bound sits far above clip_sigma true sigmas
    and the truncation correction below would over-correct). The
    clipped std is unbiased by the Gaussian truncation retention
    (CLIP3_STD_RETENTION) each round, so the clip bound converges to
    clip_sigma TRUE sigmas and pure noise normalises to unit variance
    without bias."""
    x = x.astype(jnp.float32)
    n = x.shape[-1]
    corr = np.float32(CLIP3_STD_RETENTION if clip_sigma == 3.0 else 1.0)
    mean = jnp.sum(x, axis=-1, keepdims=True) / n
    var = jnp.sum((x - mean) ** 2, axis=-1, keepdims=True) / n
    std = jnp.sqrt(jnp.maximum(var, 1e-12))
    for _ in range(max(1, n_rounds)):
        keep = jnp.abs(x - mean) <= clip_sigma * std
        nkeep = jnp.maximum(jnp.sum(keep, axis=-1, keepdims=True), 1)
        mean = jnp.sum(jnp.where(keep, x, 0.0), axis=-1, keepdims=True) / nkeep
        var = (
            jnp.sum(
                jnp.where(keep, (x - mean) ** 2, 0.0), axis=-1, keepdims=True
            )
            / nkeep
        )
        std = jnp.sqrt(jnp.maximum(var, 1e-12)) / corr
    return (x - mean) / std


def prefix_sum_padded(norm: jnp.ndarray, tpad: int, wext: int) -> jnp.ndarray:
    """(D, tpad + wext) exclusive prefix sum rows: csum[d, t] =
    sum(norm[d, :t]) for t <= nsamps, zero-padded past it. Built ONCE
    and consumed identically by the jnp twin and the Pallas kernel
    (identical bits in -> bitwise-comparable sweeps out)."""
    d, n = norm.shape
    csum = jnp.cumsum(norm, axis=-1, dtype=jnp.float32)
    lead = jnp.zeros((d, 1), jnp.float32)
    return jnp.pad(
        jnp.concatenate([lead, csum], axis=-1), ((0, 0), (0, tpad + wext - n - 1))
    )


def boxcar_best_twin(
    csum_pad: jnp.ndarray,  # (D, tpad + wext) from prefix_sum_padded
    widths: tuple[int, ...],
    scales: np.ndarray,  # f32 from width_scales
    nvalid: int,
    tpad: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Pure-jnp width sweep: (best S/N (D, tpad) f32, best width index
    (D, tpad) i32). Boxcars starting past ``nvalid - w`` are -inf (and
    therefore never the argmax). Width ties keep the NARROWEST width
    (strict > in the running max), matching the kernel's loop."""
    j = jnp.arange(tpad, dtype=jnp.int32)
    lo = csum_pad[:, :tpad]
    neg_inf = jnp.float32(-jnp.inf)
    best = jnp.full(lo.shape, neg_inf, jnp.float32)
    bw = jnp.zeros(lo.shape, jnp.int32)
    for k, w in enumerate(widths):
        hi = csum_pad[:, w : w + tpad]
        snr = jnp.where(
            j + w <= nvalid, (hi - lo) * jnp.float32(scales[k]), neg_inf
        )
        better = snr > best
        best = jnp.where(better, snr, best)
        bw = jnp.where(better, jnp.int32(k), bw)
    return best, bw


def boxcar_dec_best_twin(
    csum_pad: jnp.ndarray,  # (D, tpad + wext) from prefix_sum_padded
    widths: tuple[int, ...],
    scales: np.ndarray,
    nvalid: int,
    tpad: int,
    dec: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pure-jnp twin of the fused sweep + dec-fold chain tail
    (ops/pallas/spchain.py): the boxcar width sweep followed by the
    ``dec``-fold best-plane decimation — (block max S/N (D, tpad/dec),
    in-block argmax (D, tpad/dec) i32, width index at the argmax).
    Composes :func:`boxcar_best_twin` with exactly the reshape/max/
    argmax/take chain the search program historically ran, so the
    fused routing is bitwise-invisible to candidates."""
    best, bw = boxcar_best_twin(csum_pad, widths, scales, nvalid, tpad)
    d = best.shape[0]
    nbd = tpad // dec
    blocks = best.reshape(d, nbd, dec)
    bmax = jnp.max(blocks, axis=-1)
    barg = jnp.argmax(blocks, axis=-1).astype(jnp.int32)
    bwidx = jnp.take_along_axis(
        bw.reshape(d, nbd, dec), barg[..., None], axis=-1
    )[..., 0]
    return bmax, barg, bwidx


def boxcar_dec_best(
    norm: jnp.ndarray,  # (D, nsamps) normalised trials
    widths: tuple[int, ...],
    dec: int,
    *,
    pallas_span: int = 0,  # >0: Pallas BOXCAR kernel for the sweep
    fused_span: int = 0,  # >0: fused sweep+dec-fold Pallas kernel
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Dispatch the fused chain tail: the sweep+dec-fold mega-kernel
    when the caller resolved ``fused_span`` (probe passed), else the
    plain sweep (Pallas boxcar kernel or jnp twin) followed by the jnp
    decimation — all three routes bitwise identical."""
    n = norm.shape[-1]
    tpad, _ = plan_pad(n)
    wext = width_extent(widths)
    scales = width_scales(widths)
    csum_pad = prefix_sum_padded(norm, tpad, wext)
    if fused_span:
        from .pallas.spchain import boxcar_dec_best_pallas

        return boxcar_dec_best_pallas(
            csum_pad, widths, scales, n, tpad, dec, span=fused_span,
            interpret=interpret,
        )
    if pallas_span:
        from .pallas.boxcar import boxcar_best_pallas

        best, bw = boxcar_best_pallas(
            csum_pad, widths, scales, n, tpad, span=pallas_span,
            interpret=interpret,
        )
    else:
        best, bw = boxcar_best_twin(csum_pad, widths, scales, n, tpad)
    d = best.shape[0]
    nbd = tpad // dec
    blocks = best.reshape(d, nbd, dec)
    bmax = jnp.max(blocks, axis=-1)
    barg = jnp.argmax(blocks, axis=-1).astype(jnp.int32)
    bwidx = jnp.take_along_axis(
        bw.reshape(d, nbd, dec), barg[..., None], axis=-1
    )[..., 0]
    return bmax, barg, bwidx


def boxcar_best(
    norm: jnp.ndarray,  # (D, nsamps) normalised trials
    widths: tuple[int, ...],
    *,
    pallas_span: int = 0,  # 0 = jnp twin; >0 = Pallas tile span
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Dispatch the width sweep: the Pallas kernel when the caller
    resolved a span (probe passed), else the jnp twin. Returns
    (best (D, tpad), argw (D, tpad)) with tpad from plan_pad."""
    n = norm.shape[-1]
    tpad, span = plan_pad(n)
    wext = width_extent(widths)
    scales = width_scales(widths)
    csum_pad = prefix_sum_padded(norm, tpad, wext)
    if pallas_span:
        from .pallas.boxcar import boxcar_best_pallas

        return boxcar_best_pallas(
            csum_pad, widths, scales, n, tpad, span=pallas_span,
            interpret=interpret,
        )
    return boxcar_best_twin(csum_pad, widths, scales, n, tpad)


@lru_cache(maxsize=16)
def make_single_pulse_search_fn(
    widths: tuple[int, ...],
    threshold: float,
    max_events: int,
    dec: int,
    pallas_span: int,
    fused_span: int = 0,
):
    """One jitted program: u8/f32 trial block -> per-trial single-pulse
    events. Returns fn(trials (D, nsamps)) ->
    (samples (D, K) i32, width_idx (D, K) i32, snrs (D, K) f32,
    counts (D,) i32) with K = max_events; ``counts`` may exceed K
    (overflow — the driver logs and keeps the first K, which arrive in
    ascending time order). Events are ``dec``-fold max-decimated block
    peaks of the best-width plane; the sample index is exact (argmax
    within the block). The normalise -> boxcar -> dec-fold -> compact
    chain is ONE jitted program; with ``fused_span`` (probe-gated) the
    sweep + dec-fold middle runs as the single Pallas mega-kernel
    (ops/pallas/spchain.py) — bitwise-identical events either way."""

    def run(trials: jnp.ndarray):
        n = trials.shape[-1]
        tpad, _ = plan_pad(n)
        if tpad % dec:
            raise ValueError(
                f"decimate={dec} must divide the padded trial length "
                f"{tpad} (use a power of two <= {_QUANT})"
            )
        norm = normalise_trials(trials)
        bmax, barg, bwidx = boxcar_dec_best(
            norm, widths, dec, pallas_span=pallas_span,
            fused_span=fused_span,
        )
        nbd = tpad // dec
        pidx, psnr, pcount = find_peaks_device(
            bmax, jnp.float32(threshold), jnp.int32(0), jnp.int32(nbd),
            max_peaks=max_events,
        )
        valid = pidx < nbd
        safe = jnp.minimum(pidx, nbd - 1)
        samples = safe * dec + jnp.take_along_axis(barg, safe, axis=-1)
        widx = jnp.take_along_axis(bwidx, safe, axis=-1)
        samples = jnp.where(valid, samples, -1)
        widx = jnp.where(valid, widx, 0)
        return samples, widx, psnr, pcount

    return jax.jit(run)


def matched_filter_snr(amplitude: float, width: int, sigma: float) -> float:
    """Analytic boxcar matched-filter S/N for a top-hat pulse of
    per-sample ``amplitude`` and ``width`` samples in noise of std
    ``sigma`` — the oracle the injection-recovery test checks against:
    S/N = amplitude * sqrt(width) / sigma."""
    return float(amplitude) * float(np.sqrt(width)) / float(sigma)


# --- audit registry: the per-block search program the spsearch driver
# dispatches (jnp twin path), plus the normaliser standalone; the
# ShapeCtx hooks rebuild both at a campaign bucket's production
# geometry (dm_block x out_nsamps, the bucket's width bank) so AOT
# warmup compiles the programs the driver will actually dispatch ---
from .registry import register_program, sds  # noqa: E402


def _param_search(ctx):
    if not ctx.widths:  # periodicity-only ctx: no boxcar bank
        return None
    return (
        make_single_pulse_search_fn(
            tuple(int(w) for w in ctx.widths), float(ctx.min_snr),
            int(ctx.max_events), int(ctx.decimate), int(ctx.pallas_span),
            int(ctx.sp_fused_span),
        ),
        (sds((ctx.dm_block, ctx.out_nsamps), "uint8"),),
        {},
    )


def _param_normalise(ctx):
    if not ctx.widths:
        return None
    return (
        normalise_trials,
        (sds((ctx.dm_block, ctx.out_nsamps), "float32"),),
        {},
    )


register_program(
    "ops.singlepulse.normalise_trials",
    lambda: (normalise_trials, (sds((4, 1024), "float32"),), {}),
    param=_param_normalise,
)
register_program(
    "ops.singlepulse.single_pulse_search",
    lambda: (
        make_single_pulse_search_fn((1, 2, 4, 8), 7.0, 64, 8, 0),
        (sds((2, 2048), "float32"),),
        {},
    ),
    param=_param_search,
)
