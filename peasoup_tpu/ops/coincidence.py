"""Multibeam coincidence matching.

Reference: coincidence_kernel counts, per sample, how many beams exceed
a threshold; the output mask is 1 where fewer than ``beam_thresh``
beams fired (src/kernels.cu:1073-1100). TPU design: beams live on a
(possibly sharded) leading axis; the count is a sum over that axis —
``jax.lax.psum`` over the mesh's beam axis when sharded (see
peasoup_tpu.parallel.coincidence).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def coincidence_mask(
    beams: jnp.ndarray, thresh: float, beam_thresh: int,
    axis_name: str | None = None,
) -> jnp.ndarray:
    """beams: (B, N) -> (N,) float mask, 1.0 = keep (not multibeam RFI).

    Inside shard_map, pass ``axis_name`` to reduce exceed-counts across
    the sharded beam axis with a psum.
    """
    count = jnp.sum(beams > thresh, axis=0)
    if axis_name is not None:
        count = jax.lax.psum(count, axis_name=axis_name)
    return (count < beam_thresh).astype(jnp.float32)


# --- audit registry: thresh/beam_thresh traced as scalars (they are
# data in the sharded driver too); the ShapeCtx hook rebuilds over a
# bucket's dedispersed trial length (the multibeam veto consumes the
# single-pulse stream at exactly that geometry) ---
from .registry import register_program, sds  # noqa: E402


def _param_coincidence(ctx):
    if ctx.out_nsamps <= 0:
        return None
    return (
        coincidence_mask,
        (
            sds((4, ctx.out_nsamps), "float32"),
            sds((), "float32"),
            sds((), "int32"),
        ),
        {},
    )


register_program(
    "ops.coincidence.coincidence_mask",
    lambda: (
        coincidence_mask,
        (sds((3, 64), "float32"), sds((), "float32"), sds((), "int32")),
        {},
    ),
    param=_param_coincidence,
)
