"""Running-median red-noise estimation and dereddening.

Reference: Heimdall-derived median_scrunch5 / linear_stretch kernels
(src/kernels.cu:867-1011) composed into a three-scale piecewise median
spline by Dereddener::calculate_median
(include/transforms/dereddener.hpp:41-62); the complex spectrum is then
divided by the median with the first five bins zeroed
(kernels.cu:1013-1034).

TPU design: median-of-5 is a reshape + small sort along a unit axis
(vectorises on the VPU); the linear stretch is a gather + lerp. All
batched over leading axes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def median_scrunch5(x: jnp.ndarray) -> jnp.ndarray:
    """Median of non-overlapping blocks of 5 along the last axis.

    Truncates the tail like the reference (kernels.cu:972-979). For
    inputs shorter than 5 the reference degenerates to mean/median of
    what is there (kernels.cu:954-970).
    """
    n = x.shape[-1]
    if n == 1:
        return x
    if n == 2:
        return jnp.mean(x, axis=-1, keepdims=True)
    if n in (3, 4):
        # median4 averages the two central values (bitwise identical
        # to jnp.median's 0.5/0.5 linear interpolation at q=0.5)
        s = jnp.sort(x[..., :n], axis=-1)
        if n == 3:
            return s[..., 1:2]
        return 0.5 * (s[..., 1:2] + s[..., 2:3])
    m = n // 5
    blocks = x[..., : m * 5].reshape(*x.shape[:-1], m, 5)
    # sort-and-take instead of jnp.median: the quantile position math
    # runs in the weak float width (f64 under x64) and trips the
    # audit's f64 contract; the middle order statistic is exact
    return jnp.sort(blocks, axis=-1)[..., 2]


def linear_stretch(x: jnp.ndarray, out_count: int) -> jnp.ndarray:
    """Linear interpolation of the last axis up to ``out_count`` points.

    Matches linear_stretch_functor (kernels.cu:983-996): step is
    (in_count-1)/(out_count-1); fractional parts below 1e-5 snap to the
    left sample.
    """
    in_count = x.shape[-1]
    step = jnp.float32(in_count - 1) / jnp.float32(out_count - 1)
    pos = jnp.arange(out_count, dtype=jnp.float32) * step
    j = pos.astype(jnp.int32)  # floor for non-negative
    frac = pos - j.astype(jnp.float32)
    j1 = jnp.minimum(j + 1, in_count - 1)
    left = jnp.take(x, j, axis=-1)
    right = jnp.take(x, j1, axis=-1)
    return jnp.where(frac > 1e-5, left + frac * (right - left), left)


@partial(jax.jit, static_argnames=("pos5", "pos25"))
def running_median(powers: jnp.ndarray, *, pos5: int, pos25: int) -> jnp.ndarray:
    """Three-scale running median of an amplitude spectrum.

    Splices stretched medians of block size 5/25/125: bins [0,pos5) from
    the x5 median, [pos5,pos25) from x25, [pos25,end) from x125
    (dereddener.hpp:41-62). ``pos5``/``pos25`` are the bin positions of
    the boundary frequencies (0.05 Hz and 0.5 Hz by default).
    """
    size = powers.shape[-1]
    med5 = median_scrunch5(powers)
    med25 = median_scrunch5(med5)
    med125 = median_scrunch5(med25)
    s5 = linear_stretch(med5, size)
    s25 = linear_stretch(med25, size)
    s125 = linear_stretch(med125, size)
    idx = jnp.arange(size)
    return jnp.where(idx < pos5, s5, jnp.where(idx < pos25, s25, s125))


def deredden(fseries: jnp.ndarray, median: jnp.ndarray) -> jnp.ndarray:
    """Divide the complex spectrum by the running median; zero bins 0-4
    (kernels.cu:1013-1023)."""
    out = fseries / median.astype(fseries.real.dtype)
    idx = jnp.arange(fseries.shape[-1])
    return jnp.where(idx < 5, 0.0 + 0.0j, out)


def whiten_fseries(x: jnp.ndarray, *, pos5: int, pos25: int) -> jnp.ndarray:
    """rfft -> amplitude -> running median -> dereddened Fourier series.

    The shared stanza of the search worker (pipeline_multi.cu:174-186),
    the candidate folder (folder.hpp:385-388) and the coincidencer
    (coincidencer.cpp:167-171).
    """
    from .spectrum import form_power  # local import avoids a cycle

    fser = jnp.fft.rfft(x.astype(jnp.float32))
    med = running_median(form_power(fser), pos5=pos5, pos25=pos25)
    return deredden(fser, med)


# --- audit registry: representative shapes plus ShapeCtx hooks at a
# periodicity bucket's spectrum length and whitening boundaries (the
# driver's pos5/pos25 ride the ctx so bucket-ladder contracts trace
# the exact static configuration the search would compile) ---
from .registry import register_program, sds  # noqa: E402


def _param_running_median(ctx):
    if ctx.fft_size <= 0 or ctx.pos25 <= 0:
        return None
    m = ctx.fft_size // 2 + 1
    if ctx.pos25 >= m:
        return None
    return (
        running_median,
        (sds((m,), "float32"),),
        {"pos5": ctx.pos5, "pos25": ctx.pos25},
    )


def _param_whiten_fseries(ctx):
    if ctx.fft_size <= 0 or ctx.pos25 <= 0:
        return None
    if ctx.pos25 >= ctx.fft_size // 2 + 1:
        return None
    pos5, pos25 = ctx.pos5, ctx.pos25
    return (
        lambda x: whiten_fseries(x, pos5=pos5, pos25=pos25),
        (sds((ctx.fft_size,), "float32"),),
        {},
    )


register_program(
    "ops.rednoise.running_median",
    lambda: (
        running_median,
        (sds((1024,), "float32"),),
        {"pos5": 32, "pos25": 256},
    ),
    param=_param_running_median,
)
register_program(
    "ops.rednoise.whiten_fseries",
    # pos5/pos25 must stay static through the jit wrap (running_median
    # takes them as static_argnames), so close over them
    lambda: (
        lambda x: whiten_fseries(x, pos5=8, pos25=64),
        (sds((512,), "float32"),),
        {},
    ),
    param=_param_whiten_fseries,
)
