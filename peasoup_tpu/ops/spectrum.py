"""Power-spectrum forming, statistics and normalisation.

Reference kernels: power_series_kernel (amplitude via z*rsqrt(z)) and
bin_interbin_series_kernel (Fourier interpolation by nearest-bin
difference), src/kernels.cu:215-304; stats/normalise kernels
src/kernels.cu:420-494 and include/utils/stats.hpp.

All functions are pure jnp, batched over leading axes.
"""

from __future__ import annotations

import jax.numpy as jnp


def form_power(fseries: jnp.ndarray) -> jnp.ndarray:
    """Amplitude spectrum |X_k| (the reference's "power series").

    The reference computes z*rsqrt(z) = sqrt(z) with z = re^2+im^2
    (kernels.cu:223-224); jnp.abs is the same quantity without the
    z=0 -> NaN hazard of rsqrt.
    """
    return jnp.abs(fseries).astype(jnp.float32)


def form_interpolated_parts(re: jnp.ndarray, im: jnp.ndarray) -> jnp.ndarray:
    """form_interpolated on explicit (re, im) f32 parts — lets a lazy
    elementwise producer (the matmul rfft's untwist) fuse straight into
    the interbin pass without materialising a complex array."""
    re_l = jnp.concatenate([jnp.zeros_like(re[..., :1]), re[..., :-1]], axis=-1)
    im_l = jnp.concatenate([jnp.zeros_like(im[..., :1]), im[..., :-1]], axis=-1)
    ampsq = re * re + im * im
    ampsq_diff = 0.5 * ((re - re_l) ** 2 + (im - im_l) ** 2)
    return jnp.sqrt(jnp.maximum(ampsq, ampsq_diff))


def form_interpolated(fseries: jnp.ndarray) -> jnp.ndarray:
    """Interbinned amplitude: sqrt(max(|X_k|^2, 0.5|X_k - X_{k-1}|^2)).

    Recovers power for signals midway between Fourier bins
    (kernels.cu:231-252). X_{-1} is taken as 0 like the kernel's idx==0
    branch. Operates along the last axis.
    """
    return form_interpolated_parts(
        jnp.real(fseries).astype(jnp.float32),
        jnp.imag(fseries).astype(jnp.float32),
    )


def interp_deredden_zap(
    re: jnp.ndarray,  # (..., nbins) f32 real part of the raw spectrum
    im: jnp.ndarray,  # (..., nbins) f32 imaginary part
    med: jnp.ndarray,  # (..., nbins) f32 running median (rednoise)
    zapmask: jnp.ndarray,  # (nbins,) bool birdie mask
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The fused spectrum-chain tail as ONE elementwise pass over
    explicit f32 parts: deredden (divide by the running median, zero
    bins 0-4, rednoise.deredden), zap birdies to 1+0j (zap.zap_birdies)
    and Fourier-interpolate the amplitude (form_interpolated_parts) —
    the jnp twin of the Pallas kernel (ops/pallas/specchain.py), which
    replays these exact f32 formulas so the probe can gate on bitwise
    equality. Returns (re_d, im_d, s0): the dereddened+zapped parts
    (the irfft input) and the interbinned amplitude (the stats input).

    The unfused chain walks the spectrum once per op; this is the
    pipeline's hot once-per-DM-trial stanza, so one pass matters at
    survey DM counts."""
    idx = jnp.arange(re.shape[-1])
    low5 = idx < 5
    re_d = jnp.where(low5, 0.0, re / med)
    im_d = jnp.where(low5, 0.0, im / med)
    re_d = jnp.where(zapmask, 1.0, re_d)
    im_d = jnp.where(zapmask, 0.0, im_d)
    return re_d, im_d, form_interpolated_parts(re_d, im_d)


def spectrum_stats(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(mean, rms, std) over the last axis; std = sqrt(rms^2 - mean^2)
    (stats.hpp:20-23)."""
    n = x.shape[-1]
    mean = jnp.sum(x, axis=-1) / n
    rms = jnp.sqrt(jnp.sum(x * x, axis=-1) / n)
    std = jnp.sqrt(rms * rms - mean * mean)
    return mean, rms, std


def normalise(x: jnp.ndarray, mean: jnp.ndarray, std: jnp.ndarray) -> jnp.ndarray:
    """(x - mean) / std with broadcasting (kernels.cu:469-494)."""
    return (x - mean[..., None]) / std[..., None]


# --- audit registry: these building blocks are pure jnp; the contract
# engine stages each one standalone over a tiny shape set. The ShapeCtx
# hooks rebuild them at a periodicity bucket's production batch — the
# (dm_block, accel_pad, size_spec) tile the accel-search chain actually
# traces (derived from the accel plan in perf.warmup.shape_ctx_for_
# bucket) — so warmup/contracts/microbench see production shapes, not
# the tiny representatives ---
from .registry import register_program, sds  # noqa: E402


def _spec_tile(ctx):
    """The periodicity chain's (dm_block, accel_pad, size_spec) tile,
    or None for non-periodicity ctxs (spsearch/stream buckets)."""
    if ctx.fft_size <= 0 or ctx.accel_pad <= 0:
        return None
    return (ctx.dm_block, ctx.accel_pad, ctx.fft_size // 2 + 1)


def _param_form_power(ctx):
    t = _spec_tile(ctx)
    return None if t is None else (form_power, (sds(t, "complex64"),), {})


def _param_form_interpolated(ctx):
    t = _spec_tile(ctx)
    if t is None:
        return None
    return (form_interpolated, (sds(t, "complex64"),), {})


def _param_form_interpolated_parts(ctx):
    t = _spec_tile(ctx)
    if t is None:
        return None
    return (
        form_interpolated_parts,
        (sds(t, "float32"), sds(t, "float32")),
        {},
    )


def _param_spectrum_stats(ctx):
    t = _spec_tile(ctx)
    return None if t is None else (spectrum_stats, (sds(t, "float32"),), {})


def _param_normalise(ctx):
    t = _spec_tile(ctx)
    if t is None:
        return None
    return (
        normalise,
        (sds(t, "float32"), sds(t[:2], "float32"), sds(t[:2], "float32")),
        {},
    )


register_program(
    "ops.spectrum.form_power",
    lambda: (form_power, (sds((128,), "complex64"),), {}),
    param=_param_form_power,
)
register_program(
    "ops.spectrum.form_interpolated",
    lambda: (form_interpolated, (sds((128,), "complex64"),), {}),
    param=_param_form_interpolated,
)
register_program(
    "ops.spectrum.form_interpolated_parts",
    lambda: (
        form_interpolated_parts,
        (sds((128,), "float32"), sds((128,), "float32")),
        {},
    ),
    param=_param_form_interpolated_parts,
)
def _param_interp_deredden_zap(ctx):
    # the once-per-DM-trial fused chain runs over the (dm_block, nbins)
    # batch BEFORE the accel axis exists
    if ctx.fft_size <= 0:
        return None
    nbins = ctx.fft_size // 2 + 1
    t = (ctx.dm_block, nbins)
    return (
        interp_deredden_zap,
        (
            sds(t, "float32"), sds(t, "float32"), sds(t, "float32"),
            sds((nbins,), "bool"),
        ),
        {},
    )


register_program(
    "ops.spectrum.interp_deredden_zap",
    lambda: (
        interp_deredden_zap,
        (
            sds((4, 128), "float32"), sds((4, 128), "float32"),
            sds((4, 128), "float32"), sds((128,), "bool"),
        ),
        {},
    ),
    param=_param_interp_deredden_zap,
)
register_program(
    "ops.spectrum.spectrum_stats",
    lambda: (spectrum_stats, (sds((4, 128), "float32"),), {}),
    param=_param_spectrum_stats,
)
register_program(
    "ops.spectrum.normalise",
    lambda: (
        normalise,
        (sds((4, 128), "float32"), sds((4,), "float32"), sds((4,), "float32")),
        {},
    ),
    param=_param_normalise,
)
