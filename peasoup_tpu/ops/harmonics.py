"""Incoherent harmonic summing of power spectra.

Reference: harmonic_sum_kernel (src/kernels.cu:33-208) produces, for
fold level h in 1..5, sum_{k=1..2^h} p[(int)(i*k/2^h + 0.5)] scaled by
rsqrt(2^h), accumulating across levels (level h reuses level h-1's sum
and adds only the odd-k/2^h gathers).

TPU design. The reference's float index expression (int)(i*k/2^h + 0.5)
is EXACT integer math: (i*k + 2^(h-1)) >> h (the double value is exactly
representable, truncation == floor). Two implementations:

* ``method="take"``: direct batched jnp.take gathers — the oracle.
* ``method="mxu"`` (default): the gather index map is PERIODIC in the
  output index: writing i = q*2^h + r, src(i) = q*k + c_r with
  c_r = (r*k + 2^(h-1)) >> h a compile-time constant <= k. So the
  whole level-h harmonic-k gather is

      out.reshape(Q, 2^h) = X @ C,   X[q, c] = p[q*k + c] (c <= k),
      C[c, r] = [c == c_r]  (one column-wise one-hot per r)

  where X is two dense strided reshapes/slices of p (contiguous
  vector loads) and C is a tiny constant (k+1, 2^h) matrix: the
  irregular gather becomes an MXU matmul. Because each C column is
  one-hot, the matmul result is the exact gather value (zeros add
  exactly), so "mxu" and "take" agree bitwise in f32 (tests assert
  equality; Precision.HIGHEST keeps f32 exactness on the MXU).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np


@lru_cache(maxsize=None)
def _onehot_matrix(k: int, h: int) -> np.ndarray:
    """(k+1, 2^h) f32 with C[c, r] = 1 iff (r*k + 2^(h-1)) >> h == c."""
    r = np.arange(1 << h)
    c_r = (r * k + (1 << (h - 1))) >> h
    C = np.zeros((k + 1, 1 << h), dtype=np.float32)
    C[c_r, r] = 1.0
    return C


def _gather_mxu(p: jnp.ndarray, nbins_pad: int, k: int, h: int) -> jnp.ndarray:
    """out[..., i] = p[..., (i*k + 2^(h-1)) >> h] for i < nbins_pad via
    strided reshapes + a constant one-hot matmul (p is pre-padded so all
    slices below are in range)."""
    q_count = nbins_pad >> h
    body = p[..., : q_count * k].reshape(*p.shape[:-1], q_count, k)
    # edge column c == k: p[(q+1)*k], hit when k <= 2^(h-1)
    edge = p[..., k : k * (q_count + 1) : k][..., None]
    x = jnp.concatenate([body, edge], axis=-1)  # (..., Q, k+1)
    C = jnp.asarray(_onehot_matrix(k, h))
    out = jnp.einsum(
        "...qc,cr->...qr", x, C, precision=jax.lax.Precision.HIGHEST
    )
    return out.reshape(*p.shape[:-1], nbins_pad)


@partial(jax.jit, static_argnames=("nharms", "method"))
def harmonic_sums(
    p: jnp.ndarray, *, nharms: int = 4, method: str = "mxu"
) -> list[jnp.ndarray]:
    """Cumulative fractional-harmonic sums of a spectrum.

    Args:
      p: (..., nbins) float32 spectrum (normalised).
      nharms: number of fold levels (<= 5, like the unrolled kernel).
      method: "mxu" (strided-reshape + one-hot matmul) or "take"
        (direct gather); bitwise-identical results.

    Returns a list of ``nharms`` arrays shaped like ``p``; entry h-1 is
    the 2^h-harmonic sum scaled by rsqrt(2^h).
    """
    if not 0 < nharms <= 5:
        raise ValueError("nharms must be in 1..5")
    nbins = p.shape[-1]
    if method == "take":
        i = jnp.arange(nbins, dtype=jnp.int32)
        out = []
        val = p
        for h in range(1, nharms + 1):
            half = 1 << (h - 1)
            for k in range(1, 1 << h, 2):  # odd: new gathers this level
                src = (i * k + half) >> h
                val = val + jnp.take(p, src, axis=-1)
            out.append(val * jnp.float32(2.0 ** (-h / 2.0)))
        return out
    if method != "mxu":
        raise ValueError(f"unknown method {method!r}")

    align = 1 << nharms
    nbins_pad = (nbins + align - 1) // align * align
    # strided slices below reach at most nbins_pad + align source bins;
    # src indices for i < nbins stay < nbins, so the zero pad is inert
    pp = jnp.pad(p, [(0, 0)] * (p.ndim - 1) + [(0, nbins_pad + align - nbins)])
    out = []
    val = p
    for h in range(1, nharms + 1):
        for k in range(1, 1 << h, 2):
            val = val + _gather_mxu(pp, nbins_pad, k, h)[..., :nbins]
        out.append(val * jnp.float32(2.0 ** (-h / 2.0)))
    return out
