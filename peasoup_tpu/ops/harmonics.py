"""Incoherent harmonic summing of power spectra.

Reference: harmonic_sum_kernel (src/kernels.cu:33-208) produces, for
fold level h in 1..5, sum_{k=1..2^h} p[(int)(i*k/2^h + 0.5)] scaled by
rsqrt(2^h), accumulating across levels (level h reuses level h-1's sum
and adds only the odd-k/2^h gathers).

TPU design. The reference's float index expression (int)(i*k/2^h + 0.5)
is EXACT integer math: (i*k + 2^(h-1)) >> h (the double value is exactly
representable, truncation == floor). Four implementations:

* ``method="take"``: direct batched jnp.take gathers — the oracle.
* ``method="mxu"``: the gather index map is PERIODIC in the
  output index: writing i = q*2^h + r, src(i) = q*k + c_r with
  c_r = (r*k + 2^(h-1)) >> h a compile-time constant <= k. So the
  whole level-h harmonic-k gather is

      out.reshape(Q, 2^h) = X @ C,   X[q, c] = p[q*k + c] (c <= k),
      C[c, r] = [c == c_r]  (one column-wise one-hot per r)

  where X is two dense strided reshapes/slices of p (contiguous
  vector loads) and C is a tiny constant (k+1, 2^h) matrix: the
  irregular gather becomes an MXU matmul. Because each C column is
  one-hot, the matmul result is the exact gather value (zeros add
  exactly), so "mxu" and "take" agree bitwise in f32 (tests assert
  equality).
* ``method="conv"`` (default): every (h, k) gather is a STRIDED 1-D
  CONVOLUTION. At output period P = 128 (one full lane vector),
  i = q*P + r: src(i) = q*s + c_r with s = P*k >> h (integral for
  h <= 7) and c_r = (r*k + 2^(h-1)) >> h <= s. So the gather is
  conv_general_dilated(p[None, :, None], W, stride=s, VALID) with the
  (s+1, 1, P) one-hot taps W[c_r, 0, r] = 1: conv windows overlap
  natively (no materialized X, no edge-column hack), the MXU
  contraction is the window (s+1 <= 121), and the (Q, P) output
  merges to natural bin order for FREE because P is exactly the lane
  width. Gathers are summed one `+` at a time in reference order, so
  "conv" is bitwise-identical to "take"/"mxu" (tests assert it).
  Measured 3.3x faster than "mxu" at production shapes on v5e.
* ``method="fused"``: "mxu" wastes >85% of the 128-deep MXU
  contraction (k+1 <= 16 per matmul, 15 matmuls for nharms=4). At the
  coarser output period 2^H (H = nharms), EVERY (h, k) gather shares
  one row decomposition: writing i = q*2^H + r (r < 2^H),
  src(i) = q*s + c_r with stride s = k*2^(H-h) and
  c_r = (r*k + 2^(h-1)) >> h <= s (the split is exact because
  q*2^H*k is divisible by 2^h). Stacking the per-(h,k) windows
  X_hk[q, c] = p[q*s + c] (c <= s) along the contraction axis and the
  one-hot columns into a block-diagonal-ish constant C with one output
  column group of width 2^H per LEVEL gives all nharms levels'
  fresh-gather sums in ONE matmul with contraction
  sum(s_hk + 1) (= 135 for nharms=4) — near-full MXU depth. A cumsum
  over the tiny level axis then forms the reference's cumulative sums.
  Per-level results differ from "take" only by f32 summation order
  (each level's odd-k gathers are summed in the MXU accumulator
  instead of one `+` at a time).

Both matmul methods need Precision.HIGHEST: only the 3-term bf16
operand split (24 mantissa bits) keeps products with the 0/1
constants — and therefore the gathered values — exact; HIGH's 2-term
split loses the low 8 mantissa bits (measured ~5e-6 rel error).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np


@lru_cache(maxsize=None)
def _onehot_matrix(k: int, h: int) -> np.ndarray:
    """(k+1, 2^h) f32 with C[c, r] = 1 iff (r*k + 2^(h-1)) >> h == c."""
    r = np.arange(1 << h)
    c_r = (r * k + (1 << (h - 1))) >> h
    C = np.zeros((k + 1, 1 << h), dtype=np.float32)
    C[c_r, r] = 1.0
    return C


def _gather_mxu(p: jnp.ndarray, nbins_pad: int, k: int, h: int) -> jnp.ndarray:
    """out[..., i] = p[..., (i*k + 2^(h-1)) >> h] for i < nbins_pad via
    strided reshapes + a constant one-hot matmul (p is pre-padded so all
    slices below are in range)."""
    q_count = nbins_pad >> h
    body = p[..., : q_count * k].reshape(*p.shape[:-1], q_count, k)
    # edge column c == k: p[(q+1)*k], hit when k <= 2^(h-1)
    edge = p[..., k : k * (q_count + 1) : k][..., None]
    x = jnp.concatenate([body, edge], axis=-1)  # (..., Q, k+1)
    C = jnp.asarray(_onehot_matrix(k, h))
    out = jnp.einsum(
        "...qc,cr->...qr", x, C, precision=jax.lax.Precision.HIGHEST
    )
    return out.reshape(*p.shape[:-1], nbins_pad)


_CONV_P = 128  # conv output period = the f32 lane width


@lru_cache(maxsize=None)
def _conv_taps(k: int, h: int) -> np.ndarray:
    """(s+1, 1, P) one-hot conv filter with W[c_r, 0, r] = 1,
    c_r = (r*k + 2^(h-1)) >> h, s = P*k >> h."""
    s = (_CONV_P * k) >> h
    r = np.arange(_CONV_P)
    c_r = (r * k + (1 << (h - 1))) >> h
    W = np.zeros((s + 1, 1, _CONV_P), dtype=np.float32)
    W[c_r, 0, r] = 1.0
    return W


def _gather_conv(x: jnp.ndarray, Q: int, k: int, h: int) -> jnp.ndarray:
    """out[..., i] = p[..., (i*k + 2^(h-1)) >> h] for i < Q*P via one
    strided conv. ``x`` is the padded spectrum as (rows, >=Q*s+1, 1)."""
    s = (_CONV_P * k) >> h
    # per-operand precision: the spectrum operand needs the full bf16x3
    # split (HIGHEST) for exactness, but the TAPS are one-hot — exactly
    # representable in ONE bf16 term — so DEFAULT on that side halves
    # the MXU pass count while staying BITWISE equal (each output is a
    # single 1.0*x product; measured equal on v5e, gated by the
    # bitwise ==take/mxu twin tests)
    g = jax.lax.conv_general_dilated(
        x, jnp.asarray(_conv_taps(k, h)),
        window_strides=(s,), padding="VALID",
        dimension_numbers=("NWC", "WIO", "NWC"),
        precision=(jax.lax.Precision.HIGHEST, jax.lax.Precision.DEFAULT),
    )
    return g[:, :Q]  # (rows, Q, P)


@lru_cache(maxsize=None)
def _fused_blocks(nharms: int) -> tuple[tuple[tuple[int, int, int], ...], np.ndarray]:
    """Contraction-block layout for the fused formulation.

    Returns (blocks, C): blocks is a tuple of (h, k, s) with
    s = k*2^(nharms-h) for every level h in 1..nharms and odd k < 2^h;
    C is the (sum(s+1), nharms*2^nharms) f32 constant with
    C[base_hk + c, (h-1)*2^nharms + r] = 1 iff (r*k + 2^(h-1)) >> h == c.
    """
    H = nharms
    blocks = []
    for h in range(1, H + 1):
        for k in range(1, 1 << h, 2):
            blocks.append((h, k, k << (H - h)))
    K = sum(s + 1 for _, _, s in blocks)
    C = np.zeros((K, H << H), dtype=np.float32)
    base = 0
    r = np.arange(1 << H)
    for h, k, s in blocks:
        c_r = (r * k + (1 << (h - 1))) >> h
        C[base + c_r, ((h - 1) << H) + r] = 1.0
        base += s + 1
    return tuple(blocks), C


def _fused_level_sums(p: jnp.ndarray, nharms: int) -> jnp.ndarray:
    """(..., nharms, nbins_pad) per-LEVEL fresh-gather sums
    sum_{k odd < 2^h} p[(i*k + 2^(h-1)) >> h] via one MXU matmul.
    ``p`` must be padded so indices up to Q*max(s) are in range."""
    blocks, C = _fused_blocks(nharms)
    H = nharms
    nbins_pad = (p.shape[-1] - 1) >> H << H  # caller pads to mult + 1
    Q = nbins_pad >> H
    cols = []
    for _, _, s in blocks:
        # window X_hk[q, c] = p[q*s + c], c in [0, s]: a contiguous
        # reshape for c < s plus one strided slice for the edge c == s
        cols.append(p[..., : Q * s].reshape(*p.shape[:-1], Q, s))
        cols.append(p[..., s : s * Q + 1 : s][..., None])
    x = jnp.concatenate(cols, axis=-1)  # (..., Q, K)
    out = jnp.einsum(
        "...qc,cr->...qr", x, jnp.asarray(C),
        # one-hot C is exact in a single bf16 term (see _gather_conv)
        precision=(jax.lax.Precision.HIGHEST, jax.lax.Precision.DEFAULT),
    )  # (..., Q, H*2^H)
    out = out.reshape(*p.shape[:-1], Q, H, 1 << H)
    out = jnp.moveaxis(out, -2, -3)  # (..., H, Q, 2^H)
    return out.reshape(*p.shape[:-1], H, nbins_pad)


@partial(jax.jit, static_argnames=("nharms", "method", "scaled", "block_align"))
def harmonic_sums(
    p: jnp.ndarray, *, nharms: int = 4, method: str = "conv",
    scaled: bool = True, block_align: int = 0,
) -> list[jnp.ndarray]:
    """Cumulative fractional-harmonic sums of a spectrum.

    Args:
      p: (..., nbins) float32 spectrum (normalised).
      nharms: number of fold levels (<= 5, like the unrolled kernel).
      method: "conv" (one strided conv per (level, harmonic); fastest),
        "mxu" (one one-hot matmul per (level, harmonic)), "take"
        (direct gather) — all three bitwise-identical — or "fused"
        (all levels in one near-full-depth MXU matmul; differs only
        in f32 summation order).
      scaled: apply the reference's rsqrt(2^h) per-level factor here.
        False skips it (one full HBM pass per level) for consumers that
        scale downstream, e.g. the Pallas peaks kernel scaling in VMEM.
      block_align: conv method only — when > 0, levels come back PADDED
        to a multiple of this (garbage past ``nbins``: the pad region's
        gathers read real low bins) so a downstream blocked consumer
        (the Pallas peaks kernel) needs no per-level pad pass; bins
        below ``nbins`` are bitwise identical to the unpadded result.

    Returns a list of ``nharms`` arrays shaped like ``p`` (last axis
    padded when ``block_align``); entry h-1 is the 2^h-harmonic sum,
    scaled by rsqrt(2^h) unless ``scaled=False``.
    """
    if not 0 < nharms <= 5:
        raise ValueError("nharms must be in 1..5")
    nbins = p.shape[-1]

    def lvl_out(val, h):
        return val * jnp.float32(2.0 ** (-h / 2.0)) if scaled else val

    if method == "conv":
        P = _CONV_P
        align = max(P, block_align)
        npad = -(-nbins // align) * align
        Q = npad // P
        # src indices for i < nbins stay < nbins, so zero pad is inert
        # for the real bins (pad-region outputs gather real low bins —
        # garbage the caller masks or slices away)
        pp = jnp.pad(p, [(0, 0)] * (p.ndim - 1) + [(0, npad + 1 - nbins)])
        x = pp.reshape(-1, pp.shape[-1], 1)
        # accumulate IN THE CONV OUTPUT BLOCK SPACE (rows, Q, P): every
        # (h, k) conv emits the same (q, lane) -> bin q*P+lane order, so
        # the val chain needs no per-gather reshape/slice — XLA fuses
        # each add into its conv — and only the nharms level outputs pay
        # a (free, contiguous) flatten.  Add ORDER per element is
        # unchanged, so results stay bitwise identical to "take".
        val = pp[..., :npad].reshape(-1, Q, P)
        out = []
        for h in range(1, nharms + 1):
            for k in range(1, 1 << h, 2):  # odd: new gathers this level
                val = val + _gather_conv(x, Q, k, h)
            flat = val.reshape(*p.shape[:-1], npad)
            if not block_align:
                flat = flat[..., :nbins]
            out.append(lvl_out(flat, h))
        return out
    if method == "take":
        i = jnp.arange(nbins, dtype=jnp.int32)
        out = []
        val = p
        for h in range(1, nharms + 1):
            half = 1 << (h - 1)
            for k in range(1, 1 << h, 2):  # odd: new gathers this level
                src = (i * k + half) >> h
                val = val + jnp.take(p, src, axis=-1)
            out.append(lvl_out(val, h))
        return out

    align = 1 << nharms
    nbins_pad = (nbins + align - 1) // align * align
    # strided slices below reach at most nbins_pad + align source bins;
    # src indices for i < nbins stay < nbins, so the zero pad is inert
    pp = jnp.pad(p, [(0, 0)] * (p.ndim - 1) + [(0, nbins_pad + align - nbins)])

    if method == "fused":
        fresh = _fused_level_sums(pp, nharms)  # (..., H, nbins_pad)
        cum = p[..., None, :] + jnp.cumsum(fresh[..., :nbins], axis=-2)
        if scaled:
            scales = jnp.asarray(
                [2.0 ** (-h / 2.0) for h in range(1, nharms + 1)],
                jnp.float32,
            )
            cum = cum * scales[:, None]
        return [cum[..., h, :] for h in range(nharms)]
    if method != "mxu":
        raise ValueError(f"unknown method {method!r}")

    out = []
    val = p
    for h in range(1, nharms + 1):
        for k in range(1, 1 << h, 2):
            val = val + _gather_mxu(pp, nbins_pad, k, h)[..., :nbins]
        out.append(lvl_out(val, h))
    return out


# --- audit registry (the ShapeCtx hook rebuilds the conv chain at a
# periodicity bucket's production tile and fold count) ---
from .registry import register_program, sds  # noqa: E402


def _param_harmonic_sums(ctx):
    if ctx.fft_size <= 0 or ctx.accel_pad <= 0:
        return None
    return (
        harmonic_sums,
        (
            sds(
                (ctx.dm_block, ctx.accel_pad, ctx.fft_size // 2 + 1),
                "float32",
            ),
        ),
        {"nharms": min(5, max(1, ctx.nharms))},
    )


register_program(
    "ops.harmonics.harmonic_sums",
    lambda: (harmonic_sums, (sds((512,), "float32"),), {"nharms": 4}),
    param=_param_harmonic_sums,
)
