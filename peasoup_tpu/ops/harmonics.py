"""Incoherent harmonic summing of power spectra.

Reference: harmonic_sum_kernel (src/kernels.cu:33-208) produces, for
fold level h in 1..5, sum_{k=1..2^h} p[(int)(i*k/2^h + 0.5)] scaled by
rsqrt(2^h), accumulating across levels (level h reuses level h-1's sum
and adds only the odd-k/2^h gathers).

TPU design: the reference's float index expression (int)(i*k/2^h + 0.5)
is EXACT integer math: (i*k + 2^(h-1)) >> h (the double value is exactly
representable, truncation == floor). We therefore compute gather indices
with integer ops on-device — bit-identical to the CUDA index map, with
no f64. Gathers are batched over the accel-trial axis; XLA fuses the
adds between gathers.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("nharms",))
def harmonic_sums(p: jnp.ndarray, *, nharms: int = 4) -> list[jnp.ndarray]:
    """Cumulative fractional-harmonic sums of a spectrum.

    Args:
      p: (..., nbins) float32 spectrum (normalised).
      nharms: number of fold levels (<= 5, like the unrolled kernel).

    Returns a list of ``nharms`` arrays shaped like ``p``; entry h-1 is
    the 2^h-harmonic sum scaled by rsqrt(2^h).
    """
    if not 0 < nharms <= 5:
        raise ValueError("nharms must be in 1..5")
    nbins = p.shape[-1]
    i = jnp.arange(nbins, dtype=jnp.int32)
    out = []
    val = p
    for h in range(1, nharms + 1):
        denom_log2 = h
        half = 1 << (h - 1)
        for k in range(1, 1 << h, 2):  # odd numerators only: new this level
            src = (i * k + half) >> denom_log2
            val = val + jnp.take(p, src, axis=-1)
        out.append(val * jnp.float32(2.0 ** (-h / 2.0)))
    return out
