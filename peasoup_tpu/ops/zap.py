"""RFI "birdie" zapping of known-interference frequency ranges.

Reference: zap_birdies_kernel (src/kernels.cu:1036-1069) sets spectrum
bins in [(f-w)/bw_floor, (f+w)/bw_ceil) to 1+0j. TPU design: the bin
mask is precomputed on the host from the (freq, width) list (it only
depends on the plan, not the data) and applied as a select — no scatter
needed.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np


def birdie_mask(
    freqs: np.ndarray, widths: np.ndarray, bin_width: float, nbins: int
) -> np.ndarray:
    """Boolean (nbins,) mask, True where the spectrum must be replaced by 1.

    Bin ranges replicate the kernel exactly: low = floor((f-w)/bw)
    clamped to 0, high = ceil((f+w)/bw) clamped to nbins-1, half-open
    [low, high) — including the quirk that a range clipped at the top
    stops at nbins-2 (kernels.cu:1047-1057).
    """
    mask = np.zeros(nbins, dtype=bool)
    for f, w in zip(np.asarray(freqs, float), np.asarray(widths, float)):
        low = math.floor(np.float32(np.float32(f - w) / np.float32(bin_width)))
        high = math.ceil(np.float32(np.float32(f + w) / np.float32(bin_width)))
        if low < 0:
            low = 0
        if low >= nbins:
            continue
        if high >= nbins:
            high = nbins - 1
        mask[low:high] = True
    return mask


def zap_birdies(fseries: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Replace masked bins of the complex spectrum with 1+0j."""
    return jnp.where(mask, jnp.asarray(1.0 + 0.0j, dtype=fseries.dtype), fseries)


# --- audit registry: representative shape plus a ShapeCtx hook at a
# periodicity bucket's spectrum length (the mask is plan-static, so
# the traced shape is all that varies per rung) ---
from .registry import register_program, sds  # noqa: E402


def _param_zap_birdies(ctx):
    if ctx.fft_size <= 0:
        return None
    m = ctx.fft_size // 2 + 1
    return (
        zap_birdies,
        (sds((m,), "complex64"), sds((m,), "bool")),
        {},
    )


register_program(
    "ops.zap.zap_birdies",
    lambda: (zap_birdies, (sds((128,), "complex64"), sds((128,), "bool")), {}),
    param=_param_zap_birdies,
)
