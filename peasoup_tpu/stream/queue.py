"""Bounded ingest queue with explicit backpressure policy.

The reader thread (source -> queue) and the search loop (queue ->
device) are decoupled by a bounded FIFO of :class:`StreamBlock`\\ s.
When the search falls behind and the queue fills, the configured
policy decides what gives:

* ``"block"`` — the reader blocks until the search drains a slot.
  Backpressure propagates to the source: a replay source simply
  pauses; a live ring-buffer source falls behind real time (visible
  as ``chunks_behind`` in the status heartbeat) and may overrun
  upstream of us, which is the operator's capacity signal.
* ``"drop_oldest"`` — the OLDEST queued block is dropped to admit the
  new one, keeping latency bounded at the cost of sensitivity: the
  search loop zero-fills the gap (the drop is accounted per block and
  per sample, and emitted as a telemetry event by the driver). This
  is the live-trigger posture: stale data is worth less than fresh
  data when the point is catching a pulse as it arrives.

Drop accounting lives here (``drops`` property); gap *repair* (zero
filling) lives in the driver, which knows the sample geometry.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass

POLICIES = ("block", "drop_oldest")


@dataclass
class DropStats:
    blocks: int = 0
    samples: int = 0

    def to_doc(self) -> dict:
        return {"blocks": self.blocks, "samples": self.samples}


class BoundedBlockQueue:
    """Thread-safe bounded FIFO of StreamBlocks with a drop policy."""

    def __init__(self, capacity: int, policy: str = "block"):
        if policy not in POLICIES:
            raise ValueError(
                f"unknown backpressure policy {policy!r} "
                f"(expected one of {POLICIES})"
            )
        self.capacity = max(1, int(capacity))
        self.policy = policy
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._closed = False
        self._drops = DropStats()
        self._put_total = 0

    # --- producer side ------------------------------------------------
    def put(self, block) -> bool:
        """Enqueue a block under the policy. Returns False when the
        block (or an older one) was dropped to admit it."""
        with self._lock:
            self._put_total += 1
            if self.policy == "block":
                while len(self._q) >= self.capacity and not self._closed:
                    self._not_full.wait(0.1)
                if self._closed:
                    return False
                self._q.append(block)
                self._not_empty.notify()
                return True
            dropped = False
            while len(self._q) >= self.capacity:
                old = self._q.popleft()
                self._drops.blocks += 1
                self._drops.samples += int(old.nvalid)
                dropped = True
            self._q.append(block)
            self._not_empty.notify()
            return not dropped

    def close(self) -> None:
        """No more blocks will be put (source exhausted or reader
        died); wakes any waiting consumer."""
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    # --- consumer side ------------------------------------------------
    def get(self, timeout: float | None = None):
        """Dequeue the next block, or None when the queue is closed
        and drained (or ``timeout`` elapsed)."""
        with self._lock:
            if timeout is None:
                while not self._q and not self._closed:
                    self._not_empty.wait(0.1)
            elif not self._q and not self._closed:
                self._not_empty.wait(timeout)
            if not self._q:
                return None
            block = self._q.popleft()
            self._not_full.notify()
            return block

    # --- introspection ------------------------------------------------
    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._q)

    @property
    def queued_samples(self) -> int:
        with self._lock:
            return sum(int(b.nvalid) for b in self._q)

    @property
    def drops(self) -> DropStats:
        with self._lock:
            return DropStats(self._drops.blocks, self._drops.samples)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed and not self._q
