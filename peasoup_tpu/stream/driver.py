"""Streaming real-time single-pulse search driver.

The batch pipeline is a job: read everything, search, write, exit. This
driver is a SERVICE loop (the GSP/CRAFTS commensal shape,
arXiv:2110.12749): a reader thread ingests fixed-size blocks from a
:class:`~peasoup_tpu.io.stream_source.StreamSource` into a bounded
queue with an explicit backpressure policy; the main loop assembles
overlapping fixed-shape input windows, dedisperses each with the SAME
compiled program every chunk, runs the stateful streaming boxcar sweep
(ops/streaming.py) with the carried tail, incrementally confirms
friends-of-friends clusters whose time horizon has passed, and emits
them as triggers within a configurable latency budget.

Invariants the design buys:

* **fixed shapes everywhere** — input window ``(chunk + max_delay,
  nchans)``, dedispersed chunk ``(ndm, chunk)``, search window
  ``(ndm, hold + chunk)``; every per-chunk variation (validity span,
  emit range) is a traced scalar, so after the first chunk ZERO XLA
  programs compile (asserted via the telemetry compile counters, the
  same contract campaign warm buckets carry);
* **boundary exactness** — the carried ``hold`` tail (>= the widest
  boxcar) plus deferred emission means every event is searched with
  full context: replaying a recorded observation yields the batch
  ``spsearch`` candidate set (S/N differs only by the chunk-local
  normalisation moments);
* **bounded lag, accounted loss** — the queue's ``drop_oldest`` mode
  trades sensitivity for latency explicitly: dropped blocks are
  zero-filled (keeping the stream's sample clock intact) and accounted
  per block/sample in telemetry, the status heartbeat, and the final
  manifest.

Observability: the run's ``status.json`` heartbeat gains a
``streaming`` section (input rate, queue depth, end-to-end chunk
latency p50/p95 against the SLO, drop/gap tallies, chunks behind real
time, steady-state recompile count); the same section lands in the
telemetry manifest on drain, and the flight recorder captures it on
abort like any other run state.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..io.masks import read_killfile
from ..obs import get_logger
from ..obs.telemetry import current as current_telemetry
from ..ops.dedisperse import dedisperse_block, output_scale
from ..ops.singlepulse import default_widths
from ..ops.streaming import make_stream_chunk_fn, stream_geometry
from ..pipeline.single_pulse import (
    _EVENT_DTYPE,
    candidates_from_clusters,
    cluster_events_fof,
)
from ..plan.dm_plan import DMPlan
from .queue import BoundedBlockQueue
from .triggers import TriggerSink

log = get_logger("stream.driver")

STREAM_STATUS_VERSION = 1


@dataclass
class StreamConfig:
    """Streaming search knobs (DM/width/threshold knobs mirror
    SinglePulseConfig so a replayed stream is comparable to a batch
    ``spsearch`` of the same recording)."""

    outdir: str = "."
    killfilename: str = ""
    dm_start: float = 0.0
    dm_end: float = 100.0
    dm_tol: float = 1.10
    dm_pulse_width: float = 64.0
    min_snr: float = 6.0
    n_widths: int = 12
    max_width: int = 0
    max_events: int = 256
    decimate: int = 32
    time_link: float = 1.0
    dm_link: int = 2
    limit: int = 1000  # rolling .singlepulse table size
    # streaming geometry
    chunk_samples: int = 16384  # dedispersed samples per chunk (L)
    hold_samples: int = 0  # carried tail (H); 0 = auto from widths
    # ingest / backpressure
    queue_blocks: int = 8  # bounded queue capacity (source blocks)
    policy: str = "block"  # or "drop_oldest"
    latency_slo_s: float = 2.0  # per-chunk arrival->events budget
    max_chunks: int = 0  # stop after N chunks (0 = stream end only)
    # performance
    warmup: bool = True  # AOT-compile the chunk programs before ingest
    flush_every: int = 1  # rolling-table rewrite cadence (chunks)
    # fleet observability: append-only time series (obs/metrics.py) of
    # chunk latency / queue depth / triggers — "" disables
    metrics_jsonl: str = ""


@dataclass
class StreamResult:
    """What a drained stream leaves behind (plus the on-disk trigger
    stream the sink wrote while it ran)."""

    candidates: list
    dm_list: np.ndarray
    widths: tuple[int, ...]
    n_chunks: int = 0
    n_triggers: int = 0
    n_events: int = 0
    n_overflowed: int = 0
    total_out_samples: int = 0
    drops: dict = field(default_factory=dict)
    latency: dict = field(default_factory=dict)
    timers: dict = field(default_factory=dict)
    jit_programs_first_chunk: int = 0
    jit_programs_steady: int = 0


def _percentile(sorted_xs: list, frac: float) -> float | None:
    if not sorted_xs:
        return None
    i = min(len(sorted_xs) - 1, int(frac * len(sorted_xs)))
    return sorted_xs[i]


class StreamingSearch:
    """Consume a StreamSource chunk by chunk and emit live triggers."""

    def __init__(self, config: StreamConfig):
        self.config = config
        self._lock = threading.Lock()
        # aggregates read by the status-section provider (heartbeat
        # thread) while the main loop writes them
        self._latencies: list[float] = []
        self._slo_misses = 0
        self._gap_samples = 0
        self._chunks_done = 0
        self._n_events = 0
        self._n_overflowed = 0
        self._received_samples = 0
        self._first_arrival: float | None = None
        self._last_arrival: float | None = None
        self._jit_first = 0
        self._jit_steady = 0
        self._spans: list[tuple[int, int, float]] = []  # (lo, hi, t_ready)
        self._pending = np.zeros(0, dtype=_EVENT_DTYPE)
        self._queue: BoundedBlockQueue | None = None
        self._sink: TriggerSink | None = None
        self._reader_error: BaseException | None = None

    # --- planning -----------------------------------------------------
    def plan_for(self, fmt) -> DMPlan:
        cfg = self.config
        killmask = None
        if cfg.killfilename:
            killmask = read_killfile(cfg.killfilename, fmt.nchans)
        return DMPlan.create(
            nsamps=cfg.chunk_samples,  # out_nsamps is unused here
            nchans=fmt.nchans,
            tsamp=fmt.tsamp,
            fch1=fmt.fch1,
            foff=fmt.foff,
            dm_start=cfg.dm_start,
            dm_end=cfg.dm_end,
            pulse_width=cfg.dm_pulse_width,
            tol=cfg.dm_tol,
            killmask=killmask,
        )

    def widths_for(self) -> tuple[int, ...]:
        """The stream's boxcar bank: octave-spaced, capped at a quarter
        chunk (mirroring the batch quarter-trial cap) and by
        cfg.max_width."""
        cfg = self.config
        cap = max(1, cfg.chunk_samples // 4)
        if cfg.max_width:
            cap = min(cap, cfg.max_width)
        return default_widths(cfg.n_widths, max_width=cap)

    def shape_ctx(self, fmt, plan: DMPlan, widths, hold: int):
        """The production ShapeCtx of this stream's chunk programs, for
        AOT warmup and the perf tooling."""
        from ..ops.registry import ShapeCtx

        cfg = self.config
        return ShapeCtx(
            nsamps=cfg.chunk_samples + plan.max_delay,
            nchans=fmt.nchans,
            nbits=fmt.nbits,
            ndm=plan.ndm,
            out_nsamps=cfg.chunk_samples,
            dm_block=plan.ndm,
            dedisp_block=plan.ndm,
            widths=tuple(int(w) for w in widths),
            min_snr=float(cfg.min_snr),
            max_events=int(cfg.max_events),
            decimate=int(cfg.decimate),
            pallas_span=0,
            stream_chunk=int(cfg.chunk_samples),
            stream_hold=int(hold),
        )

    # --- reader thread ------------------------------------------------
    def _read(self, source, q: BoundedBlockQueue, tel) -> None:
        from ..resilience import guard_thread

        def _pump() -> None:
            for blk in source.blocks():
                q.put(blk)

        try:
            # the crash guard emits the structured thread_crashed
            # event (and flips the resilience section to degraded);
            # the error still surfaces in the main loop — a stream
            # cannot continue without its source
            exc = guard_thread(
                "peasoup-stream-reader", _pump, telemetry=tel
            )
            if exc is not None:
                self._reader_error = exc
                tel.event("stream_reader_error", error=f"{exc!s:.300}")
        finally:
            q.close()

    # --- status section (heartbeat + manifest) ------------------------
    def _status_section(self) -> dict:
        cfg = self.config
        q = self._queue
        with self._lock:
            lats = sorted(self._latencies)
            doc = {
                "version": STREAM_STATUS_VERSION,
                "policy": cfg.policy,
                "chunk_samples": cfg.chunk_samples,
                "chunks_done": self._chunks_done,
                "events": self._n_events,
                "pending_events": len(self._pending),
                "input_samples": self._received_samples,
                "gap_samples": self._gap_samples,
                "jit_programs_first_chunk": self._jit_first,
                "jit_programs_steady": self._jit_steady,
            }
            first, last = self._first_arrival, self._last_arrival
        if first is not None and last is not None and last > first:
            doc["input_rate_sps"] = round(
                self._received_samples / (last - first), 3
            )
        else:
            doc["input_rate_sps"] = None
        if q is not None:
            doc["queue_depth_blocks"] = q.depth
            doc["queue_capacity_blocks"] = q.capacity
            doc["chunks_behind"] = round(
                q.queued_samples / max(1, cfg.chunk_samples), 3
            )
            doc["drops"] = q.drops.to_doc()
        if self._sink is not None:
            doc["triggers"] = self._sink.n_emitted
        doc["latency_s"] = {
            "slo": cfg.latency_slo_s,
            "p50": _percentile(lats, 0.50),
            "p95": _percentile(lats, 0.95),
            "max": lats[-1] if lats else None,
            "misses": self._slo_misses,
        }
        return doc

    # --- incremental confirmation --------------------------------------
    def _confirm(
        self, frontier: float, widths, dm_list, tsamp: float
    ) -> list:
        """Confirm (and remove from the pending set) every
        friends-of-friends cluster no future event can still join: a
        new event's sample is >= ``frontier``, and linking reaches at
        most ``time_link * max(width) + decimate`` samples back."""
        cfg = self.config
        with self._lock:
            pending = self._pending
        if not len(pending):
            return []
        clusters = cluster_events_fof(
            pending, widths, time_link=cfg.time_link,
            dm_link=cfg.dm_link, dec=cfg.decimate,
        )
        horizon = frontier - (
            cfg.time_link * float(max(widths)) + cfg.decimate
        )
        done = [
            cl for cl in clusters
            if pending[cl]["sample"].max() < horizon
        ]
        if not done:
            return []
        cands = candidates_from_clusters(
            pending, done, widths, dm_list, tsamp
        )
        drop = np.concatenate(done)
        keep = np.ones(len(pending), dtype=bool)
        keep[drop] = False
        with self._lock:
            self._pending = pending[keep]
        return sorted(cands, key=lambda c: c.sample)

    def _latency_for_sample(self, sample: int, now: float) -> float | None:
        """End-to-end latency of a trigger: emission time minus the
        arrival of the newest block its chunk's search needed."""
        with self._lock:
            for lo, hi, t_ready in self._spans:
                if lo <= sample < hi:
                    return now - t_ready
        return None

    # --- the run ------------------------------------------------------
    def run(self, source) -> StreamResult:
        cfg = self.config
        tel = current_telemetry()
        timers: dict[str, float] = {
            "dedispersion": 0.0, "searching": 0.0, "clustering": 0.0,
        }
        t_total = time.perf_counter()
        fmt = source.format

        # --- plan ------------------------------------------------------
        tel.set_stage("plan")
        t0 = time.perf_counter()
        plan = self.plan_for(fmt)
        widths = self.widths_for()
        dec = cfg.decimate
        chunk = cfg.chunk_samples
        hold = stream_geometry(widths, chunk, dec, cfg.hold_samples)
        md = plan.max_delay
        w_in = chunk + md
        w = hold + chunk
        ndm = plan.ndm
        scale = output_scale(fmt.nbits, int(plan.killmask.sum()))
        timers["plan"] = time.perf_counter() - t0
        tel.set_context(
            stream_chunk_samples=chunk, stream_hold_samples=hold,
            stream_policy=cfg.policy, stream_slo_s=cfg.latency_slo_s,
        )
        tel.gauge("stream.ndm", ndm)
        tel.gauge("stream.slo_s", cfg.latency_slo_s)
        tel.event(
            "stream_plan", ndm=ndm, chunk=chunk, hold=hold,
            max_delay=md, widths=[int(x) for x in widths],
            block_samples=int(source.block_samples), policy=cfg.policy,
        )
        log.info(
            "streaming plan: %d DM trials, chunk %d (+%d hold), "
            "max delay %d, widths %s", ndm, chunk, hold, md,
            [int(x) for x in widths],
        )

        # --- AOT warmup (persistent cache; overlaps nothing yet, but a
        # warmed cache makes even the FIRST chunk compile-free) --------
        if cfg.warmup:
            tel.set_stage("warmup")
            t0 = time.perf_counter()
            from ..perf.warmup import warm_registry

            rep = warm_registry(
                ctx=self.shape_ctx(fmt, plan, widths, hold),
                programs=[
                    "ops.dedisperse.dedisperse_block",
                    "ops.streaming.stream_chunk_search",
                ],
            )
            timers["warmup"] = time.perf_counter() - t0
            tel.event(
                "stream_warmup", seconds=round(timers["warmup"], 3),
                compiled=rep.compiled, cache_hits=rep.cache_hits,
                errors=[p.name for p in rep.errors],
            )

        # --- devices-resident constants & programs ---------------------
        delays_dev = jnp.asarray(plan.delay_samples())
        kill_dev = jnp.asarray(plan.killmask.astype(np.float32))
        chunk_fn = make_stream_chunk_fn(
            widths, float(cfg.min_snr), cfg.max_events, dec, hold, chunk
        )
        tail = jnp.zeros((ndm, hold), jnp.uint8)

        # --- ingest ----------------------------------------------------
        from ..obs.metrics import MetricsRecorder

        metrics = MetricsRecorder(
            cfg.metrics_jsonl or os.path.join(cfg.outdir, "metrics.jsonl"),
            enabled=bool(cfg.metrics_jsonl),
        )
        sink = TriggerSink(cfg.outdir, limit=cfg.limit, run_id=tel.run_id)
        self._sink = sink
        q = BoundedBlockQueue(cfg.queue_blocks, cfg.policy)
        self._queue = q
        tel.set_status_section("streaming", self._status_section)
        # the reader runs under a copy of this thread's context so the
        # run's ambient telemetry (and with it fault-injection /
        # retry event attribution from the resilience layer) crosses
        # the thread boundary; the reader does no device work, so no
        # JIT stats can leak in from it
        import contextvars

        _reader_ctx = contextvars.copy_context()
        reader = threading.Thread(
            target=lambda: _reader_ctx.run(self._read, source, q, tel),
            name="peasoup-stream-reader", daemon=True,
        )
        reader.start()
        tel.set_stage("streaming")

        nchans = fmt.nchans
        buf = np.zeros((0, nchans), dtype=np.uint8)
        expected = 0  # next absolute input sample the reader owes us
        valid_in = None  # total input samples (known once final block seen)
        ended = False
        drop_reported = 0
        k = 0
        t_last_status = 0.0

        while True:
            # --- assemble the input window [k*chunk, k*chunk + w_in) --
            t_ready = None
            while buf.shape[0] < w_in and not ended:
                blk = q.get(timeout=0.25)
                if blk is None:
                    if q.closed:
                        ended = True
                    continue
                with self._lock:
                    if self._first_arrival is None:
                        self._first_arrival = blk.t_arrival_s
                    self._last_arrival = blk.t_arrival_s
                    self._received_samples += int(blk.nvalid)
                t_ready = blk.t_arrival_s
                if blk.start_sample > expected:
                    gap = blk.start_sample - expected
                    with self._lock:
                        self._gap_samples += gap
                    tel.event(
                        "stream_gap_fill", samples=int(gap),
                        at_sample=int(expected),
                    )
                    log.warning(
                        "gap of %d samples at %d (dropped upstream); "
                        "zero-filling", gap, expected,
                    )
                    buf = np.concatenate(
                        [buf, np.zeros((gap, nchans), np.uint8)]
                    )
                    expected += gap
                data = blk.data[: blk.nvalid]
                if blk.start_sample < expected:  # overlap: trim stale rows
                    data = data[expected - blk.start_sample :]
                buf = np.concatenate([buf, data]) if len(data) else buf
                expected = max(expected, blk.start_sample + blk.nvalid)
                if blk.final:
                    valid_in = blk.start_sample + blk.nvalid
                drops = q.drops
                if drops.blocks > drop_reported:
                    tel.event(
                        "stream_drop", blocks=int(drops.blocks),
                        samples=int(drops.samples), policy=cfg.policy,
                    )
                    drop_reported = drops.blocks
            if self._reader_error is not None:
                raise RuntimeError(
                    "stream reader failed"
                ) from self._reader_error
            if valid_in is None and ended:
                valid_in = expected
            final = ended and buf.shape[0] < w_in
            total_out = None
            if valid_in is not None:
                total_out = max(0, valid_in - md)
            origin = k * chunk - hold  # absolute sample of window[0]
            valid_lo = hold if k == 0 else 0
            nvalid = w
            if final:
                if total_out is None or total_out - origin <= valid_lo:
                    break  # nothing valid left to emit
                nvalid = min(w, total_out - origin)
            if cfg.max_chunks and k + 1 >= cfg.max_chunks:
                final = True
            if t_ready is None:
                t_ready = time.perf_counter()

            # --- one chunk through the two compiled programs ----------
            window_in = buf[:w_in]
            if window_in.shape[0] < w_in:
                window_in = np.concatenate(
                    [
                        window_in,
                        np.zeros(
                            (w_in - window_in.shape[0], nchans), np.uint8
                        ),
                    ]
                )
            t0 = time.perf_counter()
            new = dedisperse_block(
                jnp.asarray(window_in), delays_dev, kill_dev,
                out_nsamps=chunk, quantize=True, scale=scale,
            )
            # NO barrier between the dedisperse and sweep dispatches:
            # both enqueue back to back and XLA overlaps this chunk's
            # dedispersion with whatever is still in flight (the
            # previous chunk's sweep) — the dedisperse->sweep hop used
            # to serialise here per chunk. The dedispersion timer now
            # records dispatch wall only; device completion lands in
            # "searching" at the np.asarray sync below.
            # PEASOUP_SYNC_DEDISP=1 restores the old barrier.
            if os.environ.get("PEASOUP_SYNC_DEDISP"):
                jax.block_until_ready(new)
            t1 = time.perf_counter()
            timers["dedispersion"] += t1 - t0
            emit_lo = valid_lo // dec
            emit_hi = (w // dec) if final else (chunk // dec)
            ss, sw, ssn, sc = chunk_fn(
                tail, new, jnp.int32(valid_lo), jnp.int32(nvalid),
                jnp.int32(emit_lo), jnp.int32(emit_hi),
            )
            ss = np.asarray(ss)
            sw = np.asarray(sw)
            ssn = np.asarray(ssn)
            sc = np.asarray(sc)
            timers["searching"] += time.perf_counter() - t1
            tail = new[:, chunk - hold :]
            buf = buf[chunk:]
            t_done = time.perf_counter()

            # --- event extraction (absolute samples) ------------------
            recs = []
            kmax = ss.shape[1]
            for d in range(ndm):
                c = int(sc[d])
                if c > kmax:
                    with self._lock:
                        self._n_overflowed += 1
                for i in range(min(c, kmax)):
                    recs.append(
                        (d, origin + int(ss[d, i]), int(sw[d, i]),
                         float(ssn[d, i]))
                    )
            if recs:
                with self._lock:
                    self._pending = np.concatenate(
                        [
                            self._pending,
                            np.asarray(recs, dtype=_EVENT_DTYPE),
                        ]
                    )
            emit_hi_abs = origin + emit_hi * dec
            with self._lock:
                self._n_events += len(recs)
                self._chunks_done = k + 1
                self._spans.append((origin, emit_hi_abs, t_ready))
                if len(self._spans) > 64:
                    self._spans = self._spans[-64:]
                lat = t_done - t_ready
                self._latencies.append(lat)
                if len(self._latencies) > 1024:
                    self._latencies = self._latencies[-1024:]
                if lat > cfg.latency_slo_s:
                    self._slo_misses += 1
                    miss = self._slo_misses
                else:
                    miss = 0
            if miss:
                tel.event(
                    "stream_slo_miss", chunk=k,
                    latency_s=round(lat, 4), slo_s=cfg.latency_slo_s,
                    misses=miss,
                )
            metrics.observe("chunk_latency_seconds", lat)
            # the chunk-latency SLO feed (obs/alerts.py burn-rate
            # rules): cumulative traffic + miss counters
            metrics.counter("chunks_total")
            if miss:
                metrics.counter("chunk_slo_miss_total")

            # --- compile accounting (the zero-recompile contract) -----
            from ..campaign.runner import jit_programs_compiled

            compiled = jit_programs_compiled(tel)
            if k == 0:
                self._jit_first = compiled
            else:
                steady = compiled - self._jit_first
                if steady > self._jit_steady:
                    tel.event(
                        "stream_steady_recompile", chunk=k,
                        programs=steady - self._jit_steady,
                    )
                    log.warning(
                        "chunk %d recompiled %d program(s) in steady "
                        "state — a shape leaked", k,
                        steady - self._jit_steady,
                    )
                self._jit_steady = steady

            # --- confirm + emit triggers ------------------------------
            t0 = time.perf_counter()
            frontier = float("inf") if final else float(emit_hi_abs)
            confirmed = self._confirm(
                frontier, widths, plan.dm_list, fmt.tsamp
            )
            now = time.perf_counter()
            for cand in confirmed:
                rec = sink.emit(
                    cand,
                    latency_s=self._latency_for_sample(cand.sample, now),
                )
                tel.event(
                    "stream_trigger", seq=rec["seq"],
                    dm=rec["dm"], snr=rec["snr"],
                    sample=rec["sample"], width=rec["width"],
                    latency_s=rec["latency_s"],
                )
            if confirmed:
                metrics.counter("triggers_total", len(confirmed))
            if confirmed or (k % max(1, cfg.flush_every)) == 0:
                sink.flush_table()
            timers["clustering"] += time.perf_counter() - t0
            tel.set_progress(k + 1, unit="chunks")
            if t_done - t_last_status > 1.0:
                t_last_status = t_done
                st = self._status_section()
                metrics.gauge(
                    "queue_depth_blocks",
                    st.get("queue_depth_blocks", 0) or 0,
                )
                tel.gauge("stream.queue_depth", st.get(
                    "queue_depth_blocks", 0
                ))
                tel.gauge("stream.triggers", sink.n_emitted)
                tel.gauge(
                    "stream.drop_samples",
                    st["drops"]["samples"] + st["gap_samples"]
                    if "drops" in st else st["gap_samples"],
                )
            k += 1
            if final:
                break

        # --- drain ------------------------------------------------------
        tel.set_stage("drain")
        confirmed = self._confirm(
            float("inf"), widths, plan.dm_list, fmt.tsamp
        )
        now = time.perf_counter()
        for cand in confirmed:
            rec = sink.emit(
                cand, latency_s=self._latency_for_sample(cand.sample, now)
            )
            tel.event(
                "stream_trigger", seq=rec["seq"], dm=rec["dm"],
                snr=rec["snr"], sample=rec["sample"],
                width=rec["width"], latency_s=rec["latency_s"],
            )
        sink.close()
        source.close()
        timers["total"] = time.perf_counter() - t_total

        drops = q.drops
        st = self._status_section()
        total_out_final = int(total_out or 0)
        tel.gauge("stream.chunks", self._chunks_done)
        tel.gauge("stream.triggers", sink.n_emitted)
        tel.gauge("stream.events", self._n_events)
        tel.gauge("stream.drop_blocks", drops.blocks)
        tel.gauge("stream.drop_samples", drops.samples)
        tel.gauge("stream.gap_samples", self._gap_samples)
        tel.gauge("stream.slo_misses", self._slo_misses)
        tel.gauge("stream.jit_programs_steady", self._jit_steady)
        if self._n_overflowed:
            log.warning(
                "%d chunk-trials overflowed the %d-event compaction",
                self._n_overflowed, cfg.max_events,
            )
            tel.event(
                "sp_event_overflow", trials=self._n_overflowed,
                max_events=cfg.max_events,
            )
        tel.event(
            "stream_drained", chunks=self._chunks_done,
            triggers=sink.n_emitted, events=self._n_events,
            drops=drops.to_doc(), gap_samples=self._gap_samples,
            slo_misses=self._slo_misses,
            jit_programs_steady=self._jit_steady,
        )
        log.info(
            "stream drained: %d chunks, %d events, %d triggers, "
            "%d dropped blocks, %d steady-state recompiles",
            self._chunks_done, self._n_events, sink.n_emitted,
            drops.blocks, self._jit_steady,
        )
        return StreamResult(
            candidates=sink.candidates,
            dm_list=plan.dm_list,
            widths=widths,
            n_chunks=self._chunks_done,
            n_triggers=sink.n_emitted,
            n_events=self._n_events,
            n_overflowed=self._n_overflowed,
            total_out_samples=total_out_final,
            drops={**drops.to_doc(), "gap_samples": self._gap_samples},
            latency=st["latency_s"],
            timers=timers,
            jit_programs_first_chunk=self._jit_first,
            jit_programs_steady=self._jit_steady,
        )
