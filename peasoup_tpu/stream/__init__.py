"""Streaming real-time search: bounded-latency chunked ingest with
backpressure, drop accounting, and live triggers.

See :mod:`peasoup_tpu.stream.driver` for the service loop,
:mod:`peasoup_tpu.io.stream_source` for the block sources, and the
README "Streaming mode" section for the architecture sketch.
"""

from .driver import StreamConfig, StreamingSearch, StreamResult
from .queue import BoundedBlockQueue, DropStats
from .triggers import TRIGGER_SCHEMA, TriggerSink

__all__ = [
    "TRIGGER_SCHEMA",
    "BoundedBlockQueue",
    "DropStats",
    "StreamConfig",
    "StreamResult",
    "StreamingSearch",
    "TriggerSink",
]
