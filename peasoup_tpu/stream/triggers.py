"""Incremental trigger sink: confirmed candidates, as they happen.

Two output forms, both updated while the stream runs (the batch
pipeline's write-at-the-end contract is exactly what a real-time
search cannot have):

* ``triggers.jsonl`` — one JSON object per confirmed candidate,
  appended and flushed as each cluster is confirmed. Line-oriented so
  a downstream consumer (``tail -f``, a VOEvent broker shim, a test)
  can react with no framing protocol; each record carries the full
  candidate plus emission metadata (monotonic trigger seq, wall-clock
  emission time, end-to-end latency from block arrival to emission).
* ``candidates.singlepulse`` — the rolling top-``limit`` (by S/N)
  confirmed so far, atomically rewritten (tmp + os.replace, same
  discipline as status.json) in the batch ``.singlepulse`` column
  format, so every existing parser/report tool works on a live run's
  output directory unchanged.
"""

from __future__ import annotations

import json
import os
import time

from ..io.output import write_singlepulse

TRIGGER_SCHEMA = "peasoup_tpu.trigger"
TRIGGER_VERSION = 1


class TriggerSink:
    """Append-only JSONL trigger stream + rolling .singlepulse table."""

    def __init__(self, outdir: str, limit: int = 1000, run_id: str = ""):
        self.outdir = outdir
        self.limit = int(limit)
        self.run_id = run_id
        os.makedirs(outdir, exist_ok=True)
        self.jsonl_path = os.path.join(outdir, "triggers.jsonl")
        self.table_path = os.path.join(outdir, "candidates.singlepulse")
        self._jsonl = open(self.jsonl_path, "a", encoding="ascii")
        self._best: list = []  # confirmed candidates, unsorted
        self.n_emitted = 0
        self._dirty = False

    def emit(self, cand, latency_s: float | None = None) -> dict:
        """Emit one confirmed SinglePulseCandidate as a trigger."""
        self.n_emitted += 1
        rec = {
            "schema": TRIGGER_SCHEMA,
            "version": TRIGGER_VERSION,
            "seq": self.n_emitted,
            "run_id": self.run_id,
            "emitted_unix": time.time(),
            "latency_s": (
                round(latency_s, 6) if latency_s is not None else None
            ),
            "dm": round(float(cand.dm), 6),
            "dm_idx": int(cand.dm_idx),
            "snr": round(float(cand.snr), 4),
            "time_s": round(float(cand.time_s), 9),
            "sample": int(cand.sample),
            "width": int(cand.width),
            "width_idx": int(cand.width_idx),
            "members": int(cand.members),
            "sample_lo": int(cand.sample_lo),
            "sample_hi": int(cand.sample_hi),
            "dm_idx_lo": int(cand.dm_idx_lo),
            "dm_idx_hi": int(cand.dm_idx_hi),
        }
        self._jsonl.write(json.dumps(rec) + "\n")
        self._jsonl.flush()
        self._best.append(cand)
        if len(self._best) > 4 * max(1, self.limit):
            self._best = sorted(self._best, key=lambda c: -c.snr)[
                : self.limit
            ]
        self._dirty = True
        return rec

    def flush_table(self) -> None:
        """Atomically rewrite the rolling .singlepulse table."""
        if not self._dirty:
            return
        top = sorted(self._best, key=lambda c: -c.snr)[: self.limit]
        tmp = self.table_path + ".tmp"
        write_singlepulse(tmp, top)
        os.replace(tmp, self.table_path)
        self._dirty = False

    @property
    def candidates(self) -> list:
        """Confirmed candidates so far, S/N-descending, limited."""
        return sorted(self._best, key=lambda c: -c.snr)[: self.limit]

    def close(self) -> None:
        # always leave a table behind, even for a zero-trigger run
        self._dirty = self._dirty or not os.path.exists(self.table_path)
        self.flush_table()
        self._jsonl.close()
