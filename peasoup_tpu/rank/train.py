"""Deterministic training + calibration on the injection machinery.

No labelled survey data exists at bootstrap, but the repo already owns
an injection recipe (obs/health.py sentinels, the smoke/chaos
harnesses): synthetic dispersed pulsars and RFI foils with known
ground truth. This module generates labelled *fold products* with the
same physics vocabulary — persistent gaussian pulses peaking at their
own DM for pulsars; zero-DM-peaked, intermittent or broadband
structure for RFI; pure noise — extracts features through the
registered device program, trains the small MLP with plain seeded
full-batch gradient descent (pure JAX, no new dependencies), and fits
an isotonic-style (pool-adjacent-violators) calibration so scores read
as comparable probabilities across observations.

Everything is deterministic from the seed: same seed, same artifact,
same fingerprint — pinned by tests/test_rank.py.
"""

from __future__ import annotations

import numpy as np

from ..obs import get_logger
from ..ops.candidate_features import (
    DM_CURVE_FRACTIONS,
    FEATURE_NAMES,
    NFEATURES,
)
from .model import (
    MODEL_SCHEMA,
    MODEL_VERSION,
    RankModel,
    model_fingerprint,
    score_tier,
)
from .score import extract_features

log = get_logger("rank.train")


# --------------------------------------------------------------------------
# the injected ground-truth set
# --------------------------------------------------------------------------

def _circular_pulse(nbins: int, phase: float, width: float) -> np.ndarray:
    """A wrapped gaussian pulse over phase bins."""
    bins = np.arange(nbins, dtype=np.float64) / nbins
    d = np.abs(bins - phase)
    d = np.minimum(d, 1.0 - d) * nbins
    return np.exp(-0.5 * (d / max(width, 0.5)) ** 2)


def synth_fold_products(
    n: int,
    seed: int,
    *,
    nbins: int = 64,
    nints: int = 16,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, list[str]]:
    """``(prof, subints, dm_curve, labels, kinds)`` for ``n`` injected
    examples: ~40% pulsars (label 1), ~40% RFI foils, ~20% noise
    (label 0). The DM curve carries the fold significance at
    :data:`DM_CURVE_FRACTIONS` of the candidate DM — pulsars peak at
    their own DM, terrestrial foils at zero."""
    rng = np.random.default_rng(seed)
    fr = np.asarray(DM_CURVE_FRACTIONS, dtype=np.float64)
    ndm = len(fr)
    subints = np.empty((n, nints, nbins), dtype=np.float32)
    dm_curve = np.empty((n, ndm), dtype=np.float32)
    labels = np.zeros(n, dtype=np.int32)
    kinds: list[str] = []
    for i in range(n):
        u = rng.uniform()
        noise = rng.normal(0.0, 1.0, size=(nints, nbins))
        if u < 0.4:
            kind = "pulsar"
        elif u < 0.6:
            kind = "rfi_zerodm"
        elif u < 0.8:
            kind = "rfi_broad"
        else:
            kind = "noise"
        kinds.append(kind)
        if kind == "pulsar":
            labels[i] = 1
            phase = rng.uniform()
            width = rng.uniform(1.0, nbins / 10.0)
            amp = rng.uniform(4.0, 25.0)
            shape = _circular_pulse(nbins, phase, width)
            per = amp * rng.uniform(0.6, 1.4, size=nints)
            sub = noise + per[:, None] * shape[None, :]
            sigma = rng.uniform(0.25, 0.5)
            curve = amp * np.exp(-(((1.0 - fr) / sigma) ** 2))
            curve = curve + rng.normal(0.0, 0.5, size=ndm)
        elif kind == "rfi_zerodm":
            # impulsive terrestrial interference: bright in a random
            # subset of subints, fold significance peaking at DM 0
            phase = rng.uniform()
            width = rng.uniform(0.8, nbins / 8.0)
            amp = rng.uniform(5.0, 30.0)
            shape = _circular_pulse(nbins, phase, width)
            mask = rng.uniform(size=nints) < rng.uniform(0.1, 0.45)
            if not mask.any():
                mask[int(rng.integers(nints))] = True
            per = amp * rng.uniform(0.5, 2.0, size=nints) * mask
            sub = noise + per[:, None] * shape[None, :]
            sigma = rng.uniform(0.2, 0.45)
            curve = amp * np.exp(-((fr / sigma) ** 2))
            curve = curve + rng.normal(0.0, 0.5, size=ndm)
        elif kind == "rfi_broad":
            # broadband periodic interference (mains hum): a slow
            # sinusoidal profile in every subint, flat-to-zero-DM curve
            amp = rng.uniform(2.0, 8.0)
            phase = rng.uniform(0.0, 2.0 * np.pi)
            cyc = int(rng.integers(1, 3))
            wave = amp * np.sin(
                2.0 * np.pi * cyc * np.arange(nbins) / nbins + phase
            )
            sub = noise + wave[None, :] * rng.uniform(
                0.7, 1.3, size=(nints, 1)
            )
            curve = amp * (1.0 - 0.5 * fr) + rng.normal(
                0.0, 0.8, size=ndm
            )
        else:
            sub = noise
            curve = rng.normal(0.0, 1.0, size=ndm)
        subints[i] = sub.astype(np.float32)
        dm_curve[i] = curve.astype(np.float32)
    prof = subints.mean(axis=1).astype(np.float32)
    return prof, subints, dm_curve, labels, kinds


# --------------------------------------------------------------------------
# metrics + calibration
# --------------------------------------------------------------------------

def roc_auc(labels: np.ndarray, scores: np.ndarray) -> float:
    """Mann-Whitney AUC with average ranks for ties."""
    labels = np.asarray(labels)
    scores = np.asarray(scores, dtype=np.float64)
    n_pos = int((labels == 1).sum())
    n_neg = int((labels == 0).sum())
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(scores, kind="stable")
    ranks = np.empty(len(scores), dtype=np.float64)
    ranks[order] = np.arange(1, len(scores) + 1)
    # average ranks over tied score groups
    uniq, inv, cnt = np.unique(
        scores, return_inverse=True, return_counts=True
    )
    if len(uniq) != len(scores):
        sums = np.zeros(len(uniq))
        np.add.at(sums, inv, ranks)
        ranks = (sums / cnt)[inv]
    r_pos = ranks[labels == 1].sum()
    return float(
        (r_pos - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg)
    )


def isotonic_calibration(
    raw: np.ndarray, labels: np.ndarray
) -> tuple[list[float], list[float]]:
    """Pool-adjacent-violators fit of P(pulsar | raw score), returned
    as monotone piecewise-linear breakpoints ``(x, y)`` spanning
    [0, 1] for ``np.interp``."""
    order = np.argsort(raw, kind="stable")
    x = np.asarray(raw, dtype=np.float64)[order]
    y = np.asarray(labels, dtype=np.float64)[order]
    vals: list[float] = []
    wts: list[float] = []
    xmid: list[float] = []
    for xi, yi in zip(x, y):
        vals.append(float(yi))
        wts.append(1.0)
        xmid.append(float(xi))
        while len(vals) > 1 and vals[-2] >= vals[-1]:
            w = wts[-2] + wts[-1]
            v = (vals[-2] * wts[-2] + vals[-1] * wts[-1]) / w
            xm = (xmid[-2] * wts[-2] + xmid[-1] * wts[-1]) / w
            vals[-2:] = [v]
            wts[-2:] = [w]
            xmid[-2:] = [xm]
    xs: list[float] = [0.0]
    ys: list[float] = [float(np.clip(vals[0], 0.0, 1.0))]
    for xm, v in zip(xmid, vals):
        xc = float(np.clip(xm, 0.0, 1.0))
        vc = float(np.clip(v, 0.0, 1.0))
        if xc <= xs[-1] + 1e-9:
            continue
        xs.append(xc)
        ys.append(max(vc, ys[-1]))
    if xs[-1] < 1.0:
        xs.append(1.0)
        ys.append(ys[-1])
    return xs, ys


# --------------------------------------------------------------------------
# training
# --------------------------------------------------------------------------

def _train_weights(
    feats: np.ndarray,
    labels: np.ndarray,
    *,
    seed: int,
    hidden: int,
    steps: int,
    lr: float,
) -> dict:
    """Seeded full-batch gradient descent with momentum on the BCE
    loss; pure JAX, deterministic from the seed."""
    import jax
    import jax.numpy as jnp

    mean = feats.mean(axis=0).astype(np.float32)
    scale = (feats.std(axis=0) + 1e-6).astype(np.float32)
    z = jnp.asarray((feats - mean) / scale, dtype=jnp.float32)
    yv = jnp.asarray(labels, dtype=jnp.float32)
    rng = np.random.default_rng(seed)
    params = (
        jnp.asarray(
            rng.normal(0.0, 1.0 / np.sqrt(NFEATURES),
                       size=(NFEATURES, hidden)).astype(np.float32)
        ),
        jnp.zeros(hidden, dtype=jnp.float32),
        jnp.asarray(
            rng.normal(0.0, 1.0 / np.sqrt(hidden),
                       size=hidden).astype(np.float32)
        ),
        jnp.float32(0.0),
    )

    def loss(p):
        w1, b1, w2, b2 = p
        h = jnp.tanh(z @ w1 + b1[None, :])
        logit = h @ w2 + b2
        # numerically-stable BCE with logits + a touch of weight decay
        bce = jnp.mean(
            jnp.maximum(logit, 0.0) - logit * yv
            + jnp.log1p(jnp.exp(-jnp.abs(logit)))
        )
        l2 = sum(jnp.sum(q * q) for q in (w1, w2))
        return bce + 1e-4 * l2

    step_fn = jax.jit(jax.value_and_grad(loss))
    vel = tuple(jnp.zeros_like(p) for p in params)
    last = float("nan")
    for _ in range(steps):
        last, grads = step_fn(params)
        vel = tuple(0.9 * v - lr * g for v, g in zip(vel, grads))
        params = tuple(p + v for p, v in zip(params, vel))
    w1, b1, w2, b2 = (np.asarray(p, dtype=np.float64) for p in params)
    return {
        "norm_mean": [float(v) for v in mean],
        "norm_scale": [float(v) for v in scale],
        "w1": [[round(float(v), 8) for v in row] for row in w1],
        "b1": [round(float(v), 8) for v in b1],
        "w2": [round(float(v), 8) for v in w2],
        "b2": round(float(b2), 8),
        "final_loss": float(last),
    }


def train_model(
    *,
    seed: int = 42,
    n_examples: int = 1200,
    steps: int = 400,
    hidden: int = 16,
    lr: float = 0.05,
    nbins: int = 64,
    nints: int = 16,
    batch: int = 64,
) -> dict:
    """Train + calibrate; returns the complete artifact document."""
    prof, subints, dm_curve, labels, _ = synth_fold_products(
        n_examples, seed, nbins=nbins, nints=nints
    )
    feats = extract_features(prof, subints, dm_curve, batch=batch)
    fit = _train_weights(
        feats, labels, seed=seed, hidden=hidden, steps=steps, lr=lr
    )
    final_loss = fit.pop("final_loss")
    doc = {
        "schema": MODEL_SCHEMA,
        "version": MODEL_VERSION,
        "seed": int(seed),
        "nfeatures": NFEATURES,
        "feature_names": list(FEATURE_NAMES),
        "hidden": int(hidden),
        **fit,
        "calibration": {"x": [0.0, 1.0], "y": [0.0, 1.0]},
        "train": {
            "n_examples": int(n_examples),
            "steps": int(steps),
            "lr": float(lr),
            "auc": 0.0,
            "nbins": int(nbins),
            "nints": int(nints),
        },
    }
    # calibrate on the training set's raw scores, then record the
    # (calibrated) training AUC in the provenance block
    doc["fingerprint"] = model_fingerprint(doc)
    model = RankModel(doc)
    raw = np.concatenate(
        [
            model.predict_raw(feats[lo : lo + batch])
            for lo in range(0, len(feats), batch)
        ]
    )
    xs, ys = isotonic_calibration(raw, labels)
    doc["calibration"] = {
        "x": [round(v, 8) for v in xs],
        "y": [round(v, 8) for v in ys],
    }
    doc["train"]["auc"] = round(roc_auc(labels, raw), 6)
    doc["fingerprint"] = model_fingerprint(doc)
    log.info(
        "trained rank model: %d examples, %d steps, loss %.4f, "
        "train AUC %.4f", n_examples, steps, final_loss,
        doc["train"]["auc"],
    )
    return doc


def evaluate_model(
    model: RankModel,
    *,
    seed: int = 20260806,
    n_examples: int = 600,
    batch: int = 64,
) -> dict:
    """Score a held-out injected ground-truth set (a different seed
    than training) and tally ROC AUC + tier placement — the numbers
    ``peasoup-rank eval`` gates CI on."""
    tr = model.doc.get("train", {})
    prof, subints, dm_curve, labels, kinds = synth_fold_products(
        n_examples, seed,
        nbins=int(tr.get("nbins", 64)), nints=int(tr.get("nints", 16)),
    )
    feats = extract_features(prof, subints, dm_curve, batch=batch)
    from .score import score_feature_matrix

    scores = score_feature_matrix(model, feats, batch=batch)
    tiers = np.asarray([score_tier(float(p)) for p in scores])
    is_pulsar = labels == 1
    is_foil = np.asarray([k.startswith("rfi") for k in kinds])
    n_pulsar = int(is_pulsar.sum())
    n_foil = int(is_foil.sum())
    return {
        "auc": roc_auc(labels, scores),
        "n_examples": int(n_examples),
        "n_pulsar": n_pulsar,
        "n_foil": n_foil,
        "seed": int(seed),
        "fingerprint": model.fingerprint,
        "pulsar_tier1_frac": (
            float((tiers[is_pulsar] == 1).mean()) if n_pulsar else 0.0
        ),
        "foil_tier1_frac": (
            float((tiers[is_foil] == 1).mean()) if n_foil else 0.0
        ),
        "median_pulsar_score": (
            float(np.median(scores[is_pulsar])) if n_pulsar else 0.0
        ),
        "median_foil_score": (
            float(np.median(scores[is_foil])) if n_foil else 0.0
        ),
    }
