"""Batched scoring driver: fold products -> features -> scores.

The dispatch mirrors the survey folder exactly: fixed-width batches
padded by recycling rows (so every dispatch of one geometry reuses ONE
compiled program — zero steady-state recompiles), a ``device.oom``
fault seam, and a ``rank.features`` :class:`DegradationLadder` that
halves the batch and retries. Feature rows are independent
(ops/candidate_features.py), so shrinking the batch is bitwise-neutral
— pinned by tests/test_rank.py.
"""

from __future__ import annotations

import numpy as np

from ..obs import get_logger
from ..resilience import DegradationLadder, faults, is_resource_exhausted
from .model import RankModel

log = get_logger("rank.score")


def neutral_dm_curve(n: int) -> np.ndarray:
    """A flat DM curve for candidates scored without one (no raw data
    left to refold): zero contrast, zero peakedness — the DM features
    go silent instead of inventing a verdict."""
    from ..ops.candidate_features import DM_CURVE_POINTS

    return np.zeros((n, DM_CURVE_POINTS), dtype=np.float32)


def extract_features(
    prof: np.ndarray,  # (N, nbins) f32
    subints: np.ndarray,  # (N, nints, nbins) f32
    dm_curve: np.ndarray,  # (N, DM_CURVE_POINTS) f32
    *,
    batch: int = 64,
) -> np.ndarray:
    """Feature matrix (N, NFEATURES) via fixed pad-recycled batches of
    ``candidate_features_batch``, shrinking under ``device.oom``."""
    from ..ops.candidate_features import candidate_features_batch

    import jax.numpy as jnp

    n_total = len(prof)
    if n_total == 0:
        from ..ops.candidate_features import NFEATURES

        return np.empty((0, NFEATURES), dtype=np.float32)
    nbins = int(prof.shape[-1])
    nints = int(subints.shape[-2])
    batch = max(1, int(batch))
    ladder = DegradationLadder("rank.features", ("batch_shrink",))
    out: list[np.ndarray] = []
    lo = 0
    while lo < n_total:
        hi = min(lo + batch, n_total)
        n = hi - lo
        pad_idx = np.arange(batch) % n + lo
        try:
            faults.fire("device.oom", context=f"rank.features:{lo}")
            feats = np.asarray(
                candidate_features_batch(
                    jnp.asarray(prof[pad_idx]),
                    jnp.asarray(subints[pad_idx]),
                    jnp.asarray(dm_curve[pad_idx]),
                    nbins=nbins,
                    nints=nints,
                )
            )[:n]
        except Exception as exc:
            if not is_resource_exhausted(exc):
                raise
            if batch <= 1:
                ladder.exhausted(batch=batch, error=f"{exc!s:.200}")
                raise
            ladder.step(
                "batch_shrink", batch_old=batch,
                batch_new=batch // 2, error=f"{exc!s:.200}",
            )
            batch //= 2
            continue  # retry the same rows at the smaller batch
        out.append(feats)
        lo = hi
    return np.concatenate(out, axis=0)


def score_feature_matrix(
    model: RankModel, feats: np.ndarray, *, batch: int = 64
) -> np.ndarray:
    """Calibrated probabilities over a feature matrix, dispatched in
    the same fixed pad-recycled batch width so the ``score_apply``
    program compiles once per geometry."""
    n_total = len(feats)
    if n_total == 0:
        return np.empty((0,), dtype=np.float64)
    batch = max(1, int(batch))
    raw = np.empty(n_total, dtype=np.float64)
    lo = 0
    while lo < n_total:
        hi = min(lo + batch, n_total)
        n = hi - lo
        pad_idx = np.arange(batch) % n + lo
        raw[lo:hi] = model.predict_raw(feats[pad_idx])[:n]
        lo = hi
    return model.calibrate(raw)


def score_fold_products(
    model: RankModel,
    prof: np.ndarray,
    subints: np.ndarray,
    dm_curve: np.ndarray | None = None,
    *,
    batch: int = 64,
) -> tuple[np.ndarray, np.ndarray]:
    """The full pass: ``(features, calibrated_scores)``."""
    if dm_curve is None:
        dm_curve = neutral_dm_curve(len(prof))
    feats = extract_features(
        np.asarray(prof, dtype=np.float32),
        np.asarray(subints, dtype=np.float32),
        np.asarray(dm_curve, dtype=np.float32),
        batch=batch,
    )
    return feats, score_feature_matrix(model, feats, batch=batch)
