"""Candidate ranking: calibrated scores over sift fold products.

The triage layer (the PICS/PulsarX direction, arXiv:2309.02544): a
batched, jitted feature extractor (ops/candidate_features.py) feeds a
small pure-JAX MLP scorer whose weights ship as a schema-validated
JSON artifact, trained and calibrated on the injection machinery the
repo already owns (synthetic pulsars + RFI foils). The sift service
scores every catalogue row through the same fixed-batch/OOM-ladder
dispatch as the survey folder; scores, score tiers and the model
fingerprint land in the sift DB (schema v4), the report and the
portal's ``/candidates`` triage page.

- :mod:`peasoup_tpu.rank.model` — the artifact (load/save/validate,
  fingerprint, calibrated prediction, score-tier mapping);
- :mod:`peasoup_tpu.rank.score` — the batched scoring driver
  (pad-recycled fixed batches, ``device.oom`` degradation ladder);
- :mod:`peasoup_tpu.rank.train` — deterministic seeded training +
  isotonic-style calibration + the injected-ground-truth ROC/AUC
  evaluation the CI gate runs (``peasoup-rank eval``).
"""

from .model import (  # noqa: F401
    DEFAULT_MODEL_PATH,
    RankModel,
    score_tier,
)
