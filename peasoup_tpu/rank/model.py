"""The rank model artifact: a small MLP scorer as checked-in JSON.

One JSON document carries everything prediction needs — feature
standardisation, MLP weights, the isotonic-style calibration map, the
feature-name list it was trained against, provenance and a content
fingerprint — validated against ``model.schema.json`` through the
dependency-free :mod:`peasoup_tpu.obs.schema` validator on every load,
so a hand-edited or truncated artifact fails loudly, never scores
garbage. The forward pass runs through the registered
``ops.candidate_features.score_apply`` program (weights are arguments,
so swapping artifacts never recompiles); calibration is a monotone
piecewise-linear map applied on host.
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np

from ..ops.candidate_features import FEATURE_NAMES, NFEATURES

MODEL_SCHEMA = "peasoup_tpu.rank_model"
MODEL_VERSION = 1

_HERE = os.path.dirname(os.path.abspath(__file__))
_SCHEMA_PATH = os.path.join(_HERE, "model.schema.json")

#: The shipped artifact (trained by ``peasoup-rank train``; CI holds
#: its ROC on the injected ground-truth set via ``peasoup-rank eval``).
DEFAULT_MODEL_PATH = os.path.join(_HERE, "model.json")

#: Calibrated-probability thresholds for the triage tiers: tier 1 is
#: "review first", tier 3 is "bulk". Stored per row in the sift DB so
#: the report/portal can count and sort without the model.
SCORE_TIER1 = 0.85
SCORE_TIER2 = 0.5


def score_tier(p: float) -> int:
    """Triage tier of one calibrated score (1 best, 3 worst)."""
    if p >= SCORE_TIER1:
        return 1
    if p >= SCORE_TIER2:
        return 2
    return 3


def model_fingerprint(doc: dict) -> str:
    """Content hash over the canonical artifact (fingerprint field
    excluded) — stamped into every scored sift row so a catalogue
    always names the exact model that ranked it."""
    payload = {k: doc[k] for k in sorted(doc) if k != "fingerprint"}
    digest = hashlib.sha256(
        json.dumps(payload, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()
    return f"sha256:{digest[:16]}"


def validate_model_doc(doc: dict) -> None:
    """Schema + consistency checks; raises ``ValueError`` on a bad
    artifact (wrapping the schema validator's error)."""
    from ..obs.schema import SchemaError, validate

    with open(_SCHEMA_PATH) as f:
        schema = json.load(f)
    try:
        validate(doc, schema)
    except SchemaError as exc:
        raise ValueError(f"bad rank model artifact: {exc}") from exc
    if tuple(doc["feature_names"]) != FEATURE_NAMES:
        raise ValueError(
            "rank model artifact was trained against different "
            f"features {doc['feature_names']} (this build has "
            f"{list(FEATURE_NAMES)})"
        )
    if doc["fingerprint"] != model_fingerprint(doc):
        raise ValueError(
            "rank model artifact fingerprint mismatch (edited or "
            "corrupted file)"
        )
    hidden = int(doc["hidden"])
    w1 = doc["w1"]
    if len(w1) != NFEATURES or any(len(r) != hidden for r in w1):
        raise ValueError("rank model w1 shape mismatch")
    if (
        len(doc["b1"]) != hidden
        or len(doc["w2"]) != hidden
        or len(doc["norm_mean"]) != NFEATURES
        or len(doc["norm_scale"]) != NFEATURES
    ):
        raise ValueError("rank model weight shape mismatch")
    cal = doc["calibration"]
    if len(cal["x"]) != len(cal["y"]) or len(cal["x"]) < 2:
        raise ValueError("rank model calibration map malformed")
    if any(b < a for a, b in zip(cal["y"], cal["y"][1:])):
        raise ValueError("rank model calibration map not monotone")


class RankModel:
    """A loaded, validated artifact ready to score feature matrices."""

    def __init__(self, doc: dict) -> None:
        validate_model_doc(doc)
        self.doc = doc
        self.fingerprint = doc["fingerprint"]
        f32 = np.float32
        self.norm_mean = np.asarray(doc["norm_mean"], dtype=f32)
        self.norm_scale = np.asarray(doc["norm_scale"], dtype=f32)
        self.w1 = np.asarray(doc["w1"], dtype=f32)
        self.b1 = np.asarray(doc["b1"], dtype=f32)
        self.w2 = np.asarray(doc["w2"], dtype=f32)
        self.b2 = f32(doc["b2"])
        self.cal_x = np.asarray(doc["calibration"]["x"], dtype=np.float64)
        self.cal_y = np.asarray(doc["calibration"]["y"], dtype=np.float64)
        self._apply = None

    @classmethod
    def from_file(cls, path: str | None = None) -> "RankModel":
        path = path or DEFAULT_MODEL_PATH
        try:
            with open(path) as f:
                doc = json.load(f)
        except OSError as exc:
            raise ValueError(
                f"cannot read rank model artifact {path}: {exc}"
            ) from exc
        except json.JSONDecodeError as exc:
            raise ValueError(
                f"rank model artifact {path} is not JSON: {exc}"
            ) from exc
        return cls(doc)

    # --- prediction ---------------------------------------------------
    def predict_raw(self, feats: np.ndarray) -> np.ndarray:
        """Uncalibrated MLP probabilities for a feature matrix, through
        the registered ``score_apply`` program. Callers wanting zero
        steady-state recompiles pass fixed-width batches (the scoring
        driver's pad-recycle idiom); one compiled program then serves
        every batch of that width."""
        from ..ops.candidate_features import make_score_apply_fn

        if self._apply is None:
            self._apply = make_score_apply_fn()
        import jax.numpy as jnp

        raw = self._apply(
            jnp.asarray(np.asarray(feats, dtype=np.float32)),
            jnp.asarray(self.norm_mean), jnp.asarray(self.norm_scale),
            jnp.asarray(self.w1), jnp.asarray(self.b1),
            jnp.asarray(self.w2), jnp.asarray(self.b2),
        )
        return np.asarray(raw, dtype=np.float64)

    def calibrate(self, raw: np.ndarray) -> np.ndarray:
        """Monotone piecewise-linear calibration (isotonic fit stored
        as breakpoints): raw MLP probability -> comparable-across-
        observations probability."""
        return np.interp(np.asarray(raw, dtype=np.float64),
                         self.cal_x, self.cal_y)

    def predict(self, feats: np.ndarray) -> np.ndarray:
        return self.calibrate(self.predict_raw(feats))

    # --- persistence --------------------------------------------------
    def save(self, path: str) -> None:
        save_model_doc(self.doc, path)


def save_model_doc(doc: dict, path: str) -> None:
    """Re-fingerprint, validate and atomically write an artifact."""
    doc = dict(doc)
    doc["fingerprint"] = model_fingerprint(doc)
    validate_model_doc(doc)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
