"""Finite-duration response template banks for the FDAS search.

A pulsar with constant frequency drift smears its Fourier power over
neighbouring bins: over an observation of T seconds a drift of
``fdot`` Hz/s moves the signal by ``z = fdot * T**2`` DFT bins, and a
jerk ``fddot`` Hz/s^2 curves it by ``w = fddot * T**3`` bins. The
matched filter for a drifting tone is the complex conjugate of its
own finite-duration Fourier response, so the search correlates the
dereddened spectrum against a bank of such responses — one template
per (z, w) trial — and reads the recovered power off the correlation
output (Ransom et al. 2002; the PRESTO accelsearch formulation).

Everything in this module is host-side numpy and cheap relative to a
search; the bank for one (zmax, wmax) geometry is lru-cached. The
geometry helpers (:func:`template_half_width`, :func:`auto_segment`)
are shared by the device program, the pipeline driver and the warmup
ShapeCtx derivation so all three always agree on shapes — a ctx
derived here compiles the exact program the driver later runs.

Template math: for a tone at bin offset ``d`` from the template
centre the finite-duration response is

    A_{z,w}(d) = (1/M) * sum_m exp(2j*pi*(w*u^3/6 + z*u^2/2 - d*u))

with ``u = (m + 0.5)/M`` the normalised time over the observation,
evaluated by midpoint quadrature with ``M`` samples. ``z`` and ``w``
are the TOTAL drift/curvature in bins over the observation; the
``z*u^2/2`` phase term is the integral of a linearly drifting
frequency, ``w*u^3/6`` of a quadratically drifting one. Templates
are normalised to unit energy so correlation output power is
directly comparable across the bank, and the ``z = w = 0`` template
collapses to (a discretised) delta — the zero-drift row of the FDAS
plane reproduces the plain power spectrum, which is what the z=0
parity tests pin.

Sign convention (matches ``plan/accel_plan.py`` and the time-domain
resampling search): a POSITIVE line-of-sight acceleration ``a``
stretches the apparent period, i.e. ``fdot = -a * f / c`` — so an
``a > 0`` injection is recovered by a NEGATIVE-z template.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import numpy as np

SPEED_OF_LIGHT = 299792458.0  # m/s

# extra single-sided template reach beyond the drift extent: the
# finite-duration response of a tone decays slowly (~1/d) past the
# swept range, and the interbin/harmonic stages downstream read
# power right up to the template edge
_EDGE_PAD = 16

# quadrature floor: enough midpoint samples that the z = w = 0
# template is a delta to f32 precision even for narrow banks
_MIN_QUAD = 256


def template_half_width(zmax: float, wmax: float = 0.0) -> int:
    """Single-sided template extent in bins for a (zmax, wmax) bank.

    A drift of z bins sweeps the tone across |z| bins centred z/2
    from its start frequency; with the template centred on the
    mid-observation frequency the response spans ~|z|/2 + |w|/8 bins
    each side, padded so the slowly-decaying tails are captured.
    Shared by the bank builder, the device program's shape derivation
    and the warmup ShapeCtx hook.
    """
    reach = abs(float(zmax)) / 2.0 + abs(float(wmax)) / 8.0
    return int(np.ceil(reach)) + _EDGE_PAD


def effective_zmax(zmax: float, wmax: float = 0.0) -> int:
    """The pure-z extent whose template width equals the (zmax, wmax)
    bank's: ``template_half_width(effective_zmax(z, w)) ==
    template_half_width(z, w)``. The warmup ShapeCtx carries this one
    int (fdas_zmax), so the registry hook recovers the exact template
    width for jerk banks without a second ctx field."""
    return 2 * (template_half_width(zmax, wmax) - _EDGE_PAD)


def auto_segment(width: int) -> int:
    """Overlap-save FFT segment length for templates of ``width``
    taps: the next power of two >= max(1024, 4*(width-1)), which
    keeps the valid fraction of each segment >= 3/4 while staying in
    pow2 FFT sizes the fft machinery is fastest at."""
    target = max(1024, 4 * (max(int(width), 1) - 1))
    return 1 << int(np.ceil(np.log2(target)))


def z_trials(zmax: float, zstep: float = 2.0) -> np.ndarray:
    """Symmetric f-dot trial grid in bins: 0, ±zstep, … ±zmax.

    zstep defaults to 2 bins — the classic accelsearch spacing where
    adjacent templates overlap at ~the half-power point, so no drift
    inside ±zmax falls between trials.
    """
    zmax = abs(float(zmax))
    if zmax == 0.0:
        return np.zeros(1, dtype=np.float64)
    n = int(np.floor(zmax / float(zstep) + 1e-9))
    ladder = np.arange(1, n + 1, dtype=np.float64) * float(zstep)
    return np.concatenate([[0.0], np.stack([ladder, -ladder], 1).ravel()])


def w_trials(wmax: float, wstep: float = 20.0) -> np.ndarray:
    """Symmetric f-ddot (jerk) trial grid in bins; [0] when the jerk
    plane is off. The default 20-bin spacing mirrors the coarse jerk
    ladders PRESTO uses — curvature tolerance is much wider than
    drift tolerance."""
    wmax = abs(float(wmax))
    if wmax == 0.0:
        return np.zeros(1, dtype=np.float64)
    n = int(np.floor(wmax / float(wstep) + 1e-9))
    ladder = np.arange(1, n + 1, dtype=np.float64) * float(wstep)
    return np.concatenate([[0.0], np.stack([ladder, -ladder], 1).ravel()])


@dataclass(frozen=True)
class FdasTemplateBank:
    """One immutable (z, w) template bank.

    ``templates[t, j]`` is A_{z_t, w_t}(j - half): row ``t`` is the
    conjugate-ready finite-duration response of trial ``t`` laid out
    over ``width = 2*half + 1`` taps. Rows are INDEPENDENT — any
    row-batch split of a correlation against this bank is bitwise
    identical to the unsplit run, which is what lets the OOM ladder
    halve the template batch without perturbing results.
    """

    zmax: float
    wmax: float
    zstep: float
    wstep: float
    half: int
    zs: np.ndarray = field(repr=False)  # (T,) f64, trial drift
    ws: np.ndarray = field(repr=False)  # (T,) f64, trial curvature
    templates: np.ndarray = field(repr=False)  # (T, 2*half+1) c64

    @property
    def ntemplates(self) -> int:
        return int(self.templates.shape[0])

    @property
    def width(self) -> int:
        return 2 * self.half + 1


def _response(
    zs: np.ndarray, ws: np.ndarray, half: int
) -> np.ndarray:
    """Midpoint-quadrature finite-duration responses, (T, 2*half+1)
    complex64, unit energy per row."""
    width = 2 * half + 1
    m = max(_MIN_QUAD, 8 * width)
    u = (np.arange(m, dtype=np.float64) + 0.5) / m  # (M,)
    d = np.arange(-half, half + 1, dtype=np.float64)  # (W,)
    # phase[t, m] for the drift part; the -d*u tone offset enters as
    # a DFT over u, evaluated for all offsets at once
    drift = (
        ws[:, None] * u[None, :] ** 3 / 6.0
        + zs[:, None] * u[None, :] ** 2 / 2.0
    )  # (T, M)
    ph = np.exp(2j * np.pi * drift)  # (T, M)
    tone = np.exp(-2j * np.pi * u[:, None] * d[None, :])  # (M, W)
    resp = ph @ tone / m  # (T, W)
    energy = np.sqrt(np.sum(np.abs(resp) ** 2, axis=1, keepdims=True))
    resp = resp / np.maximum(energy, 1e-30)
    # the zero-drift response is analytically a unit impulse; snap the
    # quadrature's ~1e-16 side-tap residue to the exact delta so the
    # z=0 trial reproduces the plain periodicity spectrum bit for bit
    zero = (zs == 0.0) & (ws == 0.0)
    if zero.any():
        delta = np.zeros(width, dtype=np.complex128)
        delta[half] = 1.0
        resp[zero] = delta
    return resp.astype(np.complex64)


@functools.lru_cache(maxsize=8)
def build_template_bank(
    zmax: float,
    wmax: float = 0.0,
    zstep: float = 2.0,
    wstep: float = 20.0,
) -> FdasTemplateBank:
    """Build (and cache) the full (z, w) product bank for a geometry.

    Trial order is the (w, z) product with zeros first on both axes,
    so template row 0 is always the zero-drift delta and the bank for
    ``wmax = 0`` is exactly the pure-acceleration bank.
    """
    zs1 = z_trials(zmax, zstep)
    ws1 = w_trials(wmax, wstep)
    zs = np.tile(zs1, len(ws1))
    ws = np.repeat(ws1, len(zs1))
    half = template_half_width(zmax, wmax)
    templates = _response(zs, ws, half)
    return FdasTemplateBank(
        zmax=float(zmax),
        wmax=float(wmax),
        zstep=float(zstep),
        wstep=float(wstep),
        half=half,
        zs=zs,
        ws=ws,
        templates=templates,
    )


def bank_geometry(
    zmax: float, wmax: float = 0.0, zstep: float = 2.0, wstep: float = 20.0
) -> tuple[int, int, int]:
    """(ntemplates, width, segment) for a geometry WITHOUT building
    the bank — the warmup ShapeCtx derivation and the registry param
    hook size programs from this, and the driver builds the real bank
    from the same formulas, so the compiled shapes always agree."""
    nt = len(z_trials(zmax, zstep)) * len(w_trials(wmax, wstep))
    half = template_half_width(zmax, wmax)
    width = 2 * half + 1
    return nt, width, auto_segment(width)
