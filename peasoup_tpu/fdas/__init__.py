"""Fourier-domain acceleration search (FDAS).

Template banks over (f-dot, f-ddot) evaluated as batched frequency-
domain correlations of ONE dereddened spectrum per DM trial — the
PRESTO-style correlation formulation (arXiv:1912.12807 runs this
search shape at survey scale) recast as fixed-shape batched array
programs so the whole (DM block x template batch) tile is a single
jitted dispatch.

Layout:

- :mod:`peasoup_tpu.fdas.templates` — host-side finite-duration
  response template-bank generation (f-dot grid from tobs + zmax,
  optional f-ddot plane for the jerk search) and the shared geometry
  formulas (template width, overlap-save segment sizing) the driver,
  the warmup ShapeCtx derivation and the registry hook all use.
- :mod:`peasoup_tpu.ops.fdas` — the registered jitted correlation
  program (overlap-save complex multiply + interbin power + harmonic
  sum + peak compaction, fused in one program).
- :mod:`peasoup_tpu.pipeline.fdas` — the campaign-dispatchable driver
  (DMPlan reuse, checkpointing, OOM degradation ladder, telemetry,
  multihost dealing).
"""

from .templates import (  # noqa: F401
    FdasTemplateBank,
    auto_segment,
    build_template_bank,
    template_half_width,
)
