"""Deterministic fault injection at named host-side seams.

Chaos engineering for a survey pipeline: the recovery paths that keep
a campaign alive for months (lease reaping, retry/backoff, corrupt
artifact quarantine, OOM shrink) are exactly the paths ordinary test
inputs never execute. This registry wraps the seams where reality
fails — file reads, queue claims, sqlite ingest, checkpoint writes,
device dispatch, worker liveness, cache bytes, the clock — with named
**fault sites** driven by a seeded schedule, so a test (or the
``peasoup-chaos`` soak) can make *exactly* the failures it wants
happen *exactly* where it wants, twice in a row, identically.

Grammar (``PEASOUP_FAULTS`` env var or ``--faults``)::

    spec    := entry ("," entry)*
    entry   := "seed=" INT | site (":" key "=" value)*
    site    := fil.read | queue.claim | db.ingest | checkpoint.write
             | device.oom | worker.kill | cache.corrupt | clock.skew
             | multihost.barrier | multihost.merge | preempt.revoke
    key     := p     (per-invocation probability, seeded -> replayable)
             | n     (max injections; bare site defaults to n=1,at=1)
             | at    (an integer -> fire on that 1-based invocation of
                      the site; anything else -> fire when the
                      invocation context contains the value)
             | skew  (clock.skew only: seconds added to the queue's
                      lease clock)

    PEASOUP_FAULTS='fil.read:p=0.1:n=3,worker.kill:at=job2'
    PEASOUP_FAULTS='db.ingest:at=2,cache.corrupt:n=1,seed=42'

Contracts the rest of the system relies on:

- **zero cost when disabled** — :func:`fire` is a module-global
  None-check and return; no site sits inside jitted/traced code (all
  seams are host-side), so the compiled hot path is untouched and the
  perf/audit ratchets cannot see it.
- **determinism** — each site draws from its own
  ``random.Random(f"{seed}:{site}")`` stream, so a schedule replays
  bit-identically given the same seed and invocation order.
- **attribution** — every injection emits a ``fault_injected``
  telemetry event and bumps the global stats table, and the injected
  exception message carries ``[injected:<site>#<ordinal>]`` so the
  recovery event that catches it (retry/degradation/reap) names its
  cause.
"""

from __future__ import annotations

import errno as _errno
import os
import random
import threading

from ..obs import get_logger
from .errors import CorruptArtifactError, TransientIOError, WorkerKilled
from .stats import STATS

log = get_logger("resilience.faults")

ENV_VAR = "PEASOUP_FAULTS"
ENV_SEED = "PEASOUP_FAULT_SEED"

SITES = (
    "fil.read",
    "queue.claim",
    "db.ingest",
    "checkpoint.write",
    "device.oom",
    "worker.kill",
    "cache.corrupt",
    "clock.skew",
    "multihost.barrier",
    "multihost.merge",
    "preempt.revoke",
)


def _make_exception(site: str, tag: str) -> BaseException:
    if site == "fil.read":
        return TransientIOError(
            _errno.EIO, f"injected flaky read {tag}"
        )
    if site == "queue.claim":
        return TransientIOError(
            _errno.EIO, f"injected claim I/O failure {tag}"
        )
    if site == "db.ingest":
        import sqlite3

        return sqlite3.OperationalError(f"database is locked {tag}")
    if site == "checkpoint.write":
        return TransientIOError(
            _errno.EIO, f"injected checkpoint write failure {tag}"
        )
    if site == "device.oom":
        return RuntimeError(
            f"RESOURCE_EXHAUSTED: Out of memory allocating 999999999999 "
            f"bytes {tag}"
        )
    if site == "worker.kill":
        return WorkerKilled(f"injected worker kill {tag}")
    if site == "multihost.barrier":
        # a peer dying at the collective barrier surfaces as a broken
        # connection — TRANSIENT, so the step fails fast and retries
        # instead of hanging (parallel/multihost.py)
        return TransientIOError(
            _errno.ECONNRESET, f"injected multihost barrier failure {tag}"
        )
    if site == "multihost.merge":
        return TransientIOError(
            _errno.EIO, f"injected multihost merge failure {tag}"
        )
    if site == "preempt.revoke":
        # the revoke-delivery seam: an injected failure makes the
        # victim's lease-renewer MISS the preempt request this beat
        # (an unresponsive victim), drilling the grace-deadline
        # escalation to the reap path (campaign/queue.py reap_stale)
        return TransientIOError(
            _errno.EIO, f"injected revoke delivery failure {tag}"
        )
    if site == "cache.corrupt":
        # direct fire (the warmup seam): a garbled persistent-cache
        # entry — classified CORRUPT so the quarantine policy answers
        return CorruptArtifactError(
            f"injected corrupt compilation-cache entry {tag}"
        )
    # clock.skew acts through its dedicated helper; a direct fire()
    # raises the generic transient form
    return TransientIOError(_errno.EIO, f"injected fault {tag}")


class _Rule:
    """One parsed schedule entry for one site."""

    __slots__ = ("site", "p", "n", "at", "skew", "fired", "calls", "rng")

    def __init__(self, site: str, seed: int) -> None:
        self.site = site
        self.p: float | None = None
        self.n: int | None = None
        self.at: str | None = None
        self.skew: float = 0.0
        self.fired = 0
        self.calls = 0
        self.rng = random.Random(f"{seed}:{site}")

    def should_fire(self, context: str) -> bool:
        self.calls += 1
        if self.n is not None and self.fired >= self.n:
            return False
        if self.at is not None:
            if self.at.isdigit():
                hit = self.calls == int(self.at)
            else:
                hit = self.at in context
                # a context match fires once per budget, not on every
                # matching call, unless n raised it
                if hit and self.n is None and self.fired >= 1:
                    hit = False
            if not hit:
                return False
            if self.p is None:
                self.fired += 1
                return True
        if self.p is not None:
            if self.rng.random() >= self.p:
                return False
            self.fired += 1
            return True
        if self.at is None:
            # bare site / n-only: fire on the first n invocations
            if self.n is None and self.fired >= 1:
                return False
            self.fired += 1
            return True
        return False


class FaultPlan:
    """A parsed, seeded schedule over the fault sites."""

    def __init__(self, rules: dict[str, _Rule], seed: int, spec: str):
        self.rules = rules
        self.seed = seed
        self.spec = spec
        self._lock = threading.Lock()
        self.log: list[dict] = []  # every injection, in order

    def to_doc(self) -> dict:
        with self._lock:
            injected = list(self.log)
        return {
            "spec": self.spec,
            "seed": self.seed,
            "injected": injected,
        }


def parse_faults(spec: str, seed: int | None = None) -> FaultPlan:
    """Parse the schedule grammar; raises ValueError on unknown sites
    or malformed entries (a typo'd chaos schedule must fail loudly,
    not silently run fault-free)."""
    rules: dict[str, _Rule] = {}
    entries = [e.strip() for e in spec.split(",") if e.strip()]
    for entry in entries:
        parts = entry.split(":")
        head = parts[0].strip()
        if head.startswith("seed=") and len(parts) == 1:
            seed = int(head[5:])
            continue
        if head not in SITES:
            raise ValueError(
                f"unknown fault site {head!r} (expected one of "
                f"{', '.join(SITES)})"
            )
        if seed is None:
            seed = int(os.environ.get(ENV_SEED, "0") or 0)
        rule = rules.get(head) or _Rule(head, seed)
        for kv in parts[1:]:
            if "=" not in kv:
                raise ValueError(
                    f"malformed fault option {kv!r} in {entry!r} "
                    "(expected key=value)"
                )
            k, v = kv.split("=", 1)
            k = k.strip()
            v = v.strip()
            if k == "p":
                rule.p = float(v)
            elif k == "n":
                rule.n = int(v)
            elif k == "at":
                rule.at = v
            elif k == "skew":
                rule.skew = float(v)
            else:
                raise ValueError(
                    f"unknown fault option {k!r} in {entry!r}"
                )
        rules[head] = rule
    if seed is None:
        seed = 0
    # re-seed every rule now that the final seed is known (a seed=
    # entry may appear anywhere in the list)
    for site, rule in rules.items():
        rule.rng = random.Random(f"{seed}:{site}")
    return FaultPlan(rules, seed, spec)


# the active plan. None = injection disabled = the fast path: fire()
# is one global load + is-None test.
_PLAN: FaultPlan | None = None
_ENV_CHECKED = False


def configure(
    spec: str | None, seed: int | None = None
) -> FaultPlan | None:
    """Install (or clear, with ``spec=None``) the process fault plan.
    Explicit configuration wins over the environment."""
    global _PLAN, _ENV_CHECKED
    _ENV_CHECKED = True  # explicit call settles the question
    _PLAN = parse_faults(spec, seed) if spec else None
    if _PLAN is not None:
        log.warning(
            "fault injection ACTIVE: %s (seed %d)",
            _PLAN.spec, _PLAN.seed,
        )
    return _PLAN


def active_plan() -> FaultPlan | None:
    """The current plan, lazily picking up ``PEASOUP_FAULTS`` on first
    use so CLI processes need no code change to join a chaos run."""
    global _ENV_CHECKED
    if not _ENV_CHECKED:
        _ENV_CHECKED = True
        spec = os.environ.get(ENV_VAR)
        if spec:
            configure(spec)
    return _PLAN


def _inject(site: str, rule: _Rule, context: str) -> BaseException:
    tag = f"[injected:{site}#{rule.fired}]"
    exc = _make_exception(site, tag)
    STATS.fault_injected(site)
    plan = _PLAN
    if plan is not None:
        with plan._lock:
            plan.log.append(
                {"site": site, "ordinal": rule.fired, "context": context}
            )
    from ..obs.telemetry import current

    current().event(
        "fault_injected", site=site, ordinal=rule.fired,
        context=context,
    )
    log.warning("injecting fault at %s (%s) %s", site, context, tag)
    return exc


def fire(site: str, context: str = "") -> None:
    """The fault site seam: no-op unless an active plan schedules an
    injection here, in which case the site's mapped exception is
    raised. Keep call sites OUTSIDE jitted/traced code."""
    plan = _PLAN if _ENV_CHECKED else active_plan()
    if plan is None:
        return
    rule = plan.rules.get(site)
    if rule is None or not rule.should_fire(context):
        return
    raise _inject(site, rule, context)


def maybe_corrupt_file(path: str, context: str = "") -> bool:
    """The ``cache.corrupt`` seam: when scheduled, overwrite the head
    of ``path`` with garbage bytes (deterministic, so the damaged
    artifact is reproducible) BEFORE the caller reads it — the caller
    then exercises its real corrupt-artifact recovery against real
    torn bytes. Returns True when corruption was injected."""
    plan = _PLAN if _ENV_CHECKED else active_plan()
    if plan is None:
        return False
    rule = plan.rules.get("cache.corrupt")
    if rule is None or not os.path.exists(path):
        return False
    if not rule.should_fire(context or path):
        return False
    _inject("cache.corrupt", rule, context or path)  # records, no raise
    with open(path, "r+b") as f:
        f.write(b"\x00CHAOS-CORRUPT\x00")
    return True


def clock_skew_s() -> float:
    """The ``clock.skew`` seam: seconds a scheduled skew adds to the
    queue's lease clock (premature reaping / late expiry drills). The
    first read records the injection; 0.0 when unscheduled."""
    plan = _PLAN if _ENV_CHECKED else active_plan()
    if plan is None:
        return 0.0
    rule = plan.rules.get("clock.skew")
    if rule is None or not rule.skew:
        return 0.0
    if rule.fired == 0:
        rule.fired = 1
        _inject("clock.skew", rule, f"skew={rule.skew}")
    return rule.skew
