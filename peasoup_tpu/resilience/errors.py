"""The error taxonomy every recovery decision routes through.

Survey pipelines that run unattended for months (the GSP/CRAFTS and
FAST drift-scan operations, arXiv:2110.12749 / 1912.12807) survive by
treating failures as *categories with policies*, not as ad-hoc
try/excepts. peasoup-tpu's scattered recovery code all asked the same
four questions with different heuristics; this module is the single
answer:

- **transient** — flaky I/O (EIO/EAGAIN/short read mid-append), sqlite
  ``database is locked``/``busy`` under WAL contention, filesystem
  races. Policy: bounded retry with backoff
  (:class:`~peasoup_tpu.resilience.policy.RetryPolicy`).
- **resource_exhausted** — device/host out-of-memory (the shrink-retry
  trigger). Policy: descend the degradation ladder
  (:class:`~peasoup_tpu.resilience.policy.DegradationLadder`) — retrying
  the same shape would OOM again.
- **corrupt** — a torn/truncated/garbage artifact (checkpoint, tuning
  cache, baseline). Policy: warn + quarantine the file (``*.corrupt``
  rename) and regenerate
  (:func:`~peasoup_tpu.resilience.policy.load_or_recover`); never
  retry, never crash the run.
- **fatal** — everything else: a programming error or genuinely bad
  input. Policy: raise; the campaign layer's attempt budget +
  quarantine is the recovery.

Exception *types* alone cannot classify (jaxlib raises one runtime
error type for every status code; OSError spans flaky and fatal), so
classification reads errno/message contracts pinned by tests
(tests/test_aux.py pins the real JAX OOM signature).
"""

from __future__ import annotations

import errno as _errno
import json

TRANSIENT = "transient"
RESOURCE_EXHAUSTED = "resource_exhausted"
CORRUPT = "corrupt"
FATAL = "fatal"


class TransientIOError(OSError):
    """An explicitly-transient I/O failure (short read of a growing
    file, injected flaky read). Always classified TRANSIENT."""


class CorruptArtifactError(Exception):
    """A loader detected a torn/invalid artifact. Always CORRUPT."""


class WorkerKilled(BaseException):
    """Simulated SIGKILL for fault injection: derives from
    BaseException so no ``except Exception`` recovery path can observe
    it — exactly like a real kill, the claim is NOT released and the
    lease reaper is the only recovery."""


# errnos that indicate a retryable filesystem/network hiccup rather
# than a broken program or a genuinely missing resource
_TRANSIENT_ERRNOS = frozenset(
    x
    for x in (
        _errno.EIO,
        _errno.EAGAIN,
        _errno.EINTR,
        _errno.EBUSY,
        _errno.ETIMEDOUT,
        getattr(_errno, "ESTALE", None),  # NFS handle expiry
        getattr(_errno, "ECONNRESET", None),
    )
    if x is not None
)

_CORRUPT_TYPES = (
    json.JSONDecodeError,
    EOFError,
    UnicodeDecodeError,
)


def is_resource_exhausted(exc: BaseException) -> bool:
    """Device or host out-of-memory signature (XLA compile- or
    run-time). jaxlib exposes no status-code attribute on its runtime
    error, so the typed contract available is: a JaxRuntimeError whose
    ABSL status message LEADS with the canonical code
    RESOURCE_EXHAUSTED (absl::Status string formatting — stabler than
    substring-anywhere). Host allocation failure (MemoryError) joins
    it; the substring heuristics remain only as a fallback for
    wrapped/re-raised text."""
    if isinstance(exc, MemoryError):
        return True
    msg = str(exc)
    try:
        import jax

        if isinstance(
            exc, jax.errors.JaxRuntimeError
        ) and msg.lstrip().startswith("RESOURCE_EXHAUSTED"):
            return True
    except Exception:
        pass  # no jax: fall through to the text heuristics
    return "RESOURCE_EXHAUSTED" in msg or (
        "memory" in msg.lower() and "hbm" in msg.lower()
    )


def _is_sqlite_contention(exc: BaseException) -> bool:
    try:
        import sqlite3
    except Exception:
        return False
    if not isinstance(exc, sqlite3.OperationalError):
        return False
    msg = str(exc).lower()
    return "locked" in msg or "busy" in msg


def is_corrupt(exc: BaseException) -> bool:
    if isinstance(exc, CorruptArtifactError):
        return True
    if isinstance(exc, _CORRUPT_TYPES):
        return True
    # zipfile/np.load damage without importing zipfile eagerly
    name = type(exc).__name__
    if name in ("BadZipFile", "BadZipfile", "UnpicklingError"):
        return True
    try:
        from ..obs.schema import SchemaError

        if isinstance(exc, SchemaError):
            return True
    except Exception:
        pass
    return False


def is_transient(exc: BaseException) -> bool:
    if isinstance(exc, TransientIOError):
        return True
    if _is_sqlite_contention(exc):
        return True
    if isinstance(exc, (FileNotFoundError, PermissionError)):
        # ENOENT/EACCES are protocol states (a racing rename, a claim
        # already taken), not hiccups — call sites handle them
        return False
    if isinstance(exc, TimeoutError):  # OSError subclass: check first
        return True
    if isinstance(exc, OSError):
        return exc.errno in _TRANSIENT_ERRNOS
    return False


def classify(exc: BaseException) -> str:
    """Map an exception to its taxonomy class. Order matters: the
    resource_exhausted check runs first because jax wraps OOM in the
    same type it uses for everything else."""
    if is_resource_exhausted(exc):
        return RESOURCE_EXHAUSTED
    if is_transient(exc):
        return TRANSIENT
    if is_corrupt(exc):
        return CORRUPT
    return FATAL
