"""Cooperative revoke tokens: checkpointed preemption and retirement.

A running search cannot be interrupted at an arbitrary instruction
without losing (or worse, duplicating) work — but it CAN stop cleanly
at a DM-block boundary, where the per-trial checkpoint
(pipeline/checkpoint.py) has just been persisted. This module is the
handshake between whoever wants the claim back (a higher-priority job
revoking a lower-priority one, or the autoscale controller retiring a
worker) and the driver's wave loop:

- the requester writes a request file beside the claim / registry
  entry (campaign/queue.py ``request_preempt``, campaign/registry.py
  ``request_retire``);
- the victim's ``_LeaseRenewer`` beat observes it and flips the
  :class:`RevokeToken` the runner activated for the job;
- the driver calls :func:`check_revoke` after each checkpoint save —
  the first check after the flip raises :class:`SearchPreempted`, with
  the checkpoint consistent by construction;
- the runner catches :class:`SearchPreempted` and releases the claim
  with ZERO attempts consumed (the revoke is scheduling, not failure);
  the job later resumes from the checkpoint with candidates
  bitwise-equal to an uninterrupted run.

The token rides a contextvar, so only the thread actually running the
victim job sees the revoke — warmup/tuning threads and unrelated
pipeline invocations in the same process are untouched, and the check
is a no-op (one contextvar read) when no token is active.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time


class SearchPreempted(Exception):
    """Control-flow: the driver stopped at a checkpoint boundary in
    answer to a revoke. The checkpoint on disk is consistent; the
    runner must release (not fail) the claim."""

    def __init__(self, kind: str, reason: str = "") -> None:
        super().__init__(f"search {kind}ed: {reason}" if reason else kind)
        self.kind = kind
        self.reason = reason


class RevokeToken:
    """One job's revoke state, set by the lease-renewer thread and read
    by the driver thread at checkpoint boundaries."""

    def __init__(self) -> None:
        self._event = threading.Event()
        self._lock = threading.Lock()
        self.kind: str | None = None  # "preempt" | "retire" | "lost"
        self.reason: str = ""
        self.requested_unix: float | None = None
        self.observed_unix: float | None = None

    def revoke(
        self,
        kind: str = "preempt",
        reason: str = "",
        requested_unix: float | None = None,
    ) -> None:
        """Flip the token (idempotent — the first revoke wins)."""
        with self._lock:
            if self._event.is_set():
                return
            self.kind = kind
            self.reason = reason
            self.requested_unix = requested_unix
            self.observed_unix = time.time()
            self._event.set()

    def is_set(self) -> bool:
        return self._event.is_set()


_TOKEN: contextvars.ContextVar[RevokeToken | None] = contextvars.ContextVar(
    "peasoup_revoke_token", default=None
)


def current_token() -> RevokeToken | None:
    return _TOKEN.get()


@contextlib.contextmanager
def activate_token(token: RevokeToken):
    """Install ``token`` for the calling thread's context (the runner
    wraps one job's execution in this)."""
    handle = _TOKEN.set(token)
    try:
        yield token
    finally:
        _TOKEN.reset(handle)


def check_revoke(site: str = "") -> None:
    """The driver-side seam: raise :class:`SearchPreempted` when the
    active token (if any) has been revoked. Call ONLY where the
    persisted state is consistent — immediately after a checkpoint
    save is the contract."""
    token = _TOKEN.get()
    if token is None or not token.is_set():
        return
    from ..obs.telemetry import current

    current().event(
        "revoke_checkpoint_stop",
        revoke_kind=token.kind,
        reason=token.reason,
        site=site,
    )
    raise SearchPreempted(token.kind or "preempt", token.reason)
