"""Unified resilience layer: error taxonomy, retry/degradation policy,
corrupt-artifact recovery, deterministic fault injection, and the
process-global ``resilience`` status accounting.

See README "Resilience & chaos testing". The pieces:

- :mod:`~peasoup_tpu.resilience.errors` — transient /
  resource_exhausted / corrupt / fatal classification.
- :mod:`~peasoup_tpu.resilience.policy` — :class:`RetryPolicy`,
  :class:`DegradationLadder`, :func:`load_or_recover`,
  :func:`guard_thread`.
- :mod:`~peasoup_tpu.resilience.faults` — named fault sites driven by
  a seeded ``PEASOUP_FAULTS`` schedule (zero overhead when disabled).
- :mod:`~peasoup_tpu.resilience.stats` — the counters behind the
  ``resilience`` section in status.json and the telemetry manifest.

The chaos soak that exercises all of it end-to-end lives in
:mod:`peasoup_tpu.tools.chaos` (``peasoup-chaos``).
"""

from . import faults
from .errors import (
    CORRUPT,
    FATAL,
    RESOURCE_EXHAUSTED,
    TRANSIENT,
    CorruptArtifactError,
    TransientIOError,
    WorkerKilled,
    classify,
    is_corrupt,
    is_resource_exhausted,
    is_transient,
)
from .policy import (
    DB_RETRY,
    IO_RETRY,
    DegradationLadder,
    RetryPolicy,
    guard_thread,
    load_or_recover,
    quarantine_artifact,
)
from .revoke import (
    RevokeToken,
    SearchPreempted,
    activate_token,
    check_revoke,
    current_token,
)
from .stats import STATS

__all__ = [
    "RevokeToken",
    "SearchPreempted",
    "activate_token",
    "check_revoke",
    "current_token",
    "CORRUPT",
    "FATAL",
    "RESOURCE_EXHAUSTED",
    "TRANSIENT",
    "CorruptArtifactError",
    "TransientIOError",
    "WorkerKilled",
    "classify",
    "is_corrupt",
    "is_resource_exhausted",
    "is_transient",
    "DB_RETRY",
    "IO_RETRY",
    "DegradationLadder",
    "RetryPolicy",
    "guard_thread",
    "load_or_recover",
    "quarantine_artifact",
    "STATS",
    "faults",
]
