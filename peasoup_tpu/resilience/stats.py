"""Process-global resilience accounting.

Every retry, degradation rung, quarantined artifact, injected fault
and crashed background thread increments a counter here, so one
``resilience`` section in status.json / the telemetry manifest answers
"what has this process survived so far" without grepping the event
log. Counters are process-lifetime (a campaign worker accumulates
across jobs); per-job attribution comes from ``delta_since`` snapshots
recorded into campaign done records, and per-event attribution from
the telemetry event stream.

Deliberately dependency-free (stdlib only): obs.telemetry registers
the snapshot as a status section at construction time, so importing
anything from obs here would cycle.
"""

from __future__ import annotations

import threading

_TABLES = (
    "retries",
    "recoveries",
    "giveups",
    "degradations",
    "corrupt_artifacts",
    "faults_injected",
    "thread_crashes",
    "preemptions",
)


class ResilienceStats:
    """Thread-safe counter tables keyed by site/rung/thread name."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._tables: dict[str, dict[str, int]] = {
            t: {} for t in _TABLES
        }

    def reset(self) -> None:
        with self._lock:
            self._tables = {t: {} for t in _TABLES}

    def _incr(self, table: str, key: str, by: int = 1) -> None:
        with self._lock:
            tab = self._tables[table]
            tab[key] = tab.get(key, 0) + by

    # --- recording (one verb per taxonomy outcome) --------------------
    def retry(self, site: str) -> None:
        self._incr("retries", site)

    def recovered(self, site: str) -> None:
        self._incr("recoveries", site)

    def giveup(self, site: str) -> None:
        self._incr("giveups", site)

    def degradation(self, ladder: str, rung: str) -> None:
        self._incr("degradations", f"{ladder}:{rung}")

    def corrupt_artifact(self, kind: str) -> None:
        self._incr("corrupt_artifacts", kind)

    def fault_injected(self, site: str) -> None:
        self._incr("faults_injected", site)

    def thread_crashed(self, name: str) -> None:
        self._incr("thread_crashes", name)

    def preemption(self, kind: str) -> None:
        """A claim revoked (``kind``: "requested" / "released" /
        "reaped" / "retire") — the scheduling half of elasticity, kept
        in its own table so preemptive scheduling never reads as
        failure recovery."""
        self._incr("preemptions", kind)

    # --- reading ------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-serialisable view: the status.json/manifest
        ``resilience`` section. ``degraded`` flags states an operator
        should look at (a dead background thread, a retry budget spent
        without recovery)."""
        with self._lock:
            tables = {t: dict(v) for t, v in self._tables.items()}
        out: dict = {t: tables[t] for t in _TABLES}
        out["degraded"] = bool(
            tables["thread_crashes"] or tables["giveups"]
        )
        out["total_faults_injected"] = sum(
            tables["faults_injected"].values()
        )
        return out

    def delta_since(self, base: dict) -> dict:
        """Counter deltas vs an earlier ``snapshot()`` — the per-job
        resilience record the campaign runner stores in done records
        (so the rollup can aggregate without double counting)."""
        now = self.snapshot()
        out: dict = {}
        for t in _TABLES:
            before = base.get(t, {}) or {}
            d = {
                k: v - before.get(k, 0)
                for k, v in now[t].items()
                if v - before.get(k, 0)
            }
            if d:
                out[t] = d
        return out


STATS = ResilienceStats()
