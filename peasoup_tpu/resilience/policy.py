"""Retry, degradation and corrupt-artifact policy — the one place
recovery semantics live.

Before this module, every subsystem hand-rolled its own recovery:
three divergent corrupt-file try/excepts (checkpoint, tuning cache,
baselines), two OOM shrink loops with copy-pasted logging, sqlite
contention handled by a pragma alone, and background threads that died
silently. The policies here are deliberately small:

- :class:`RetryPolicy` — bounded attempts, exponential backoff with
  *deterministic* jitter (seeded per site+attempt, so chaos soaks
  replay identically), an optional wall-clock deadline, and a
  telemetry event per attempt (``resilience_retry`` /
  ``resilience_recovered`` / ``resilience_giveup``) tagged with the
  fault site that fired.
- :class:`DegradationLadder` — ordered, observable fallback steps
  (device OOM -> shrink dm_block -> ...; Pallas -> jnp twin). The
  ladder never climbs back up, each step emits a ``degradation`` event
  with its rung index, and exhaustion is explicit.
- :func:`load_or_recover` — the single corrupt-artifact recovery:
  warn, quarantine the damaged file to ``<path>.corrupt`` (rename, not
  delete — forensics survive), return a default. Checkpoints, tuning
  caches and ratchet baselines all route through it.
- :func:`guard_thread` — wrap a background thread's body so a crash
  emits a structured ``thread_crashed`` event and marks the process
  degraded in status.json instead of vanishing.

Every decision double-books: a structured telemetry event (per-run
attribution) and a process-global counter
(:data:`~peasoup_tpu.resilience.stats.STATS`, the ``resilience``
status section).
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from typing import Any, Callable

from ..obs import get_logger
from .errors import CORRUPT, FATAL, RESOURCE_EXHAUSTED, TRANSIENT, classify
from .stats import STATS

log = get_logger("resilience")


def _tel():
    from ..obs.telemetry import current

    return current()


# --------------------------------------------------------------------------
# bounded retry
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff + deterministic jitter.

    ``retry_on`` lists the taxonomy classes worth retrying (transient
    only, by default: retrying an OOM at the same shape just OOMs
    again, and corrupt artifacts have their own recovery). The jitter
    is seeded from (site, attempt) so two identical runs sleep
    identical schedules — chaos soaks depend on it.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter: float = 0.25  # +- fraction of the computed delay
    deadline_s: float | None = None
    retry_on: tuple[str, ...] = (TRANSIENT,)

    def delay(self, attempt: int, site: str = "") -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        d = min(
            self.max_delay_s, self.base_delay_s * (2 ** (attempt - 1))
        )
        if self.jitter:
            r = random.Random(f"{site}#{attempt}")
            d *= 1.0 + self.jitter * (2.0 * r.random() - 1.0)
        return max(0.0, d)

    def call(
        self,
        fn: Callable[..., Any],
        *args: Any,
        site: str = "unnamed",
        context: str = "",
        **kwargs: Any,
    ) -> Any:
        """Run ``fn(*args, **kwargs)`` under this policy. Raises the
        last exception when the budget (attempts or deadline) is spent
        or the failure class is not retryable."""
        t0 = time.monotonic()
        attempt = 0
        while True:
            attempt += 1
            try:
                out = fn(*args, **kwargs)
            except BaseException as exc:
                cls = classify(exc) if isinstance(exc, Exception) else FATAL
                out_of_budget = attempt >= self.max_attempts or (
                    self.deadline_s is not None
                    and time.monotonic() - t0 >= self.deadline_s
                )
                if cls not in self.retry_on or out_of_budget:
                    if cls in self.retry_on:
                        STATS.giveup(site)
                        _tel().event(
                            "resilience_giveup", site=site,
                            attempts=attempt, error_class=cls,
                            context=context,
                            error=f"{type(exc).__name__}: {exc!s:.200}",
                        )
                        log.warning(
                            "%s: giving up after %d attempt(s): %.200s",
                            site, attempt, exc,
                        )
                    raise
                d = self.delay(attempt, site)
                STATS.retry(site)
                _tel().event(
                    "resilience_retry", site=site, attempt=attempt,
                    delay_s=round(d, 4), error_class=cls,
                    context=context,
                    error=f"{type(exc).__name__}: {exc!s:.200}",
                )
                log.warning(
                    "%s failed (attempt %d/%d, retry in %.3gs): %.200s",
                    site, attempt, self.max_attempts, d, exc,
                )
                if d:
                    time.sleep(d)
                continue
            if attempt > 1:
                STATS.recovered(site)
                _tel().event(
                    "resilience_recovered", site=site, attempts=attempt,
                    context=context,
                )
            return out

    def wrap(self, site: str):
        """Decorator form of :meth:`call`."""

        def deco(fn):
            def inner(*args, **kwargs):
                return self.call(fn, *args, site=site, **kwargs)

            inner.__name__ = getattr(fn, "__name__", site)
            return inner

        return deco


# shared defaults: filesystem reads/writes and sqlite contention. The
# env knob exists for soaks that want tighter/looser budgets without
# code changes.
_MAX = int(os.environ.get("PEASOUP_RETRY_MAX", "3") or 3)
IO_RETRY = RetryPolicy(max_attempts=_MAX, base_delay_s=0.05)
DB_RETRY = RetryPolicy(
    max_attempts=max(5, _MAX), base_delay_s=0.02, max_delay_s=0.5
)


# --------------------------------------------------------------------------
# degradation ladder
# --------------------------------------------------------------------------

class DegradationLadder:
    """Ordered fallback steps for one driver run.

    ``rungs`` is the full ordered fallback sequence (top = preferred).
    :meth:`step` records descending to (or repeating) a rung — a
    ladder can step the same rung many times (halving ``dm_block``
    repeatedly is one rung, stepped per retry) but never climbs back
    up within a run. Every step emits a ``degradation`` telemetry
    event carrying the ladder name, rung, rung index and any
    site-specific fields, plus the global counter the status section
    reports; :meth:`exhausted` marks the bottom falling through.
    """

    def __init__(self, name: str, rungs: tuple[str, ...]) -> None:
        self.name = name
        self.rungs = tuple(rungs)
        self._idx = -1  # no degradation yet
        self.steps: list[str] = []

    def step(self, rung: str, **fields) -> None:
        i = self.rungs.index(rung)  # unknown rung: programming error
        if i < self._idx:
            raise ValueError(
                f"ladder {self.name}: cannot climb back up to "
                f"{rung!r} from {self.rungs[self._idx]!r}"
            )
        self._idx = i
        self.steps.append(rung)
        STATS.degradation(self.name, rung)
        _tel().event(
            "degradation", ladder=self.name, rung=rung, rung_index=i,
            step=len(self.steps), **fields,
        )
        log.warning(
            "degradation %s -> %s (rung %d/%d)",
            self.name, rung, i + 1, len(self.rungs),
        )

    def exhausted(self, **fields) -> None:
        STATS.giveup(self.name)
        _tel().event(
            "degradation_exhausted", ladder=self.name,
            rung=self.rungs[self._idx] if self._idx >= 0 else None,
            steps=len(self.steps), **fields,
        )

    @property
    def current_rung(self) -> str | None:
        return self.rungs[self._idx] if self._idx >= 0 else None


# --------------------------------------------------------------------------
# corrupt-artifact recovery
# --------------------------------------------------------------------------

def quarantine_artifact(path: str) -> str | None:
    """Move a damaged artifact aside to ``<path>.corrupt`` (rename,
    never delete: the torn bytes are the post-mortem). Returns the
    quarantine path, or None when the rename itself failed (shared
    filesystems can deny it — recovery proceeds regardless)."""
    qpath = path + ".corrupt"
    try:
        os.replace(path, qpath)
        return qpath
    except OSError:
        return None


def load_or_recover(
    path: str,
    loader: Callable[[str], Any],
    *,
    default: Any = None,
    kind: str = "artifact",
    action: str = "regenerating",
    quarantine: bool = True,
    logger=None,
):
    """The unified corrupt-artifact policy: ``loader(path)`` either
    returns the parsed artifact or raises. A missing file returns
    ``default`` silently (absence is a normal first-run state); ANY
    other failure — np.load raises well outside OSError/ValueError
    (zipfile.BadZipFile, EOFError, pickle errors), json loaders raise
    JSONDecodeError, schema validators raise SchemaError — warns,
    quarantines the file to ``*.corrupt`` (when ``quarantine``; the
    checked-in CI baselines pass False so a torn working tree is not
    renamed under git), records the ``corrupt_artifact`` event, and
    returns ``default``. A damaged artifact degrades to "start over",
    never to a crash."""
    lg = logger or log
    try:
        return loader(path)
    except FileNotFoundError:
        return default
    except Exception as exc:
        qpath = quarantine_artifact(path) if quarantine else None
        STATS.corrupt_artifact(kind)
        _tel().event(
            "corrupt_artifact", artifact=kind, path=path,
            quarantined_to=qpath,
            error=f"{type(exc).__name__}: {exc!s:.200}",
        )
        lg.warning(
            "discarding unreadable %s %s (%s: %.200s)%s; %s",
            kind, path, type(exc).__name__, exc,
            f"; quarantined to {qpath}" if qpath else "",
            action,
        )
        return default


# --------------------------------------------------------------------------
# background-thread crash guard
# --------------------------------------------------------------------------

def guard_thread(name: str, fn: Callable[[], Any], telemetry=None):
    """Run a background thread's body under a crash guard: an escaping
    exception emits a structured ``thread_crashed`` telemetry event
    (on ``telemetry`` when given — ambient context does NOT cross
    thread boundaries — else on whatever is ambient in this thread),
    bumps the global crash counter (flipping ``degraded`` in every
    status.json), and logs with the traceback. Returns the exception
    (or None), so joiners can surface it."""
    try:
        fn()
        return None
    except Exception as exc:
        STATS.thread_crashed(name)
        tel = telemetry if telemetry is not None else _tel()
        try:
            tel.event(
                "thread_crashed", thread=name,
                error=f"{type(exc).__name__}: {exc!s:.300}",
            )
        except Exception:
            pass  # a dead telemetry sink must not mask the crash log
        log.error(
            "background thread %r crashed (run continues degraded)",
            name, exc_info=True,
        )
        return exc
