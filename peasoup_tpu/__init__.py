"""peasoup_tpu — a TPU-native (JAX/XLA/Pallas) pulsar-search framework.

A from-scratch re-design of the capabilities of the CUDA ``peasoup``
pipeline (reference: pinsleepe/peasoup) for TPU hardware:

* incoherent dedispersion over a DM-trial grid as a batched XLA
  gather/reduce (reference: external ``dedisp`` library),
* Fourier-domain acceleration search (resample -> rfft -> interbin
  spectrum -> red-noise removal -> harmonic summing -> peak finding) as
  one batched, jitted array program per DM trial
  (reference: src/pipeline_multi.cu:100-252 per-trial scalar loop),
* candidate distilling/scoring/folding on the host,
* multi-chip scaling via ``jax.sharding.Mesh`` + ``shard_map`` over the
  DM/beam trial grid (reference: one pthread per GPU).

Layout:
    core/      candidate model + array containers
    io/        sigproc filterbank/timeseries I/O, zap/kill files, writers
    plan/      DM-list / acceleration-list / FFT-size planning (host math)
    ops/       device ops (pure jnp reference impls + Pallas kernels)
    parallel/  mesh, shardings, collectives, multibeam coincidence
    pipeline/  search driver, distillers, scorer, folder
    cli/       command-line interfaces (peasoup, coincidencer)
    native/    C++ host runtime (bit unpack, clustering, distill) via ctypes
"""

__version__ = "0.1.0"
