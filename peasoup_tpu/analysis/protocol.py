"""Engine 3: concurrency / file-protocol rules (PSP101-PSP107).

The fleet's exactly-once and torn-read guarantees rest on a small set
of filesystem and threading protocols (campaign/queue.py's module
docstring is the spec): ``O_CREAT|O_EXCL`` creation for claims and
enqueues, tmp + ``os.replace`` for every rewrite a concurrent reader
may race, append-only JSONL for recorders, rename (never delete) for
tombstones and corrupt-artifact quarantine, ``guard_thread`` around
every background thread body, and explicit telemetry hand-off (or a
copied ``contextvars`` context) across thread boundaries. These rules
make the protocols machine-checked instead of reviewer-remembered.

Unlike the PSA rules (generic JAX hazards), these are **dataflow
aware**: a path expression is classified by the string literals that
flow into it (a per-function taint walk over assignments and
``os.path.join`` chains), so ``open(tmp, "w")`` of a ``mkstemp`` name
is sanctioned while ``open(status_path, "w")`` of the shared artifact
is not — same function, same call shape, different provenance.
"""

from __future__ import annotations

import ast

from .astlint import (
    ModuleContext,
    Rule,
    dotted_name,
    register_rule,
)
from .findings import SEV_ERROR

# substrings marking a path literal as a SHARED artifact: files other
# processes/threads read while we write (the campaign tree's protocol
# surface plus any JSON/JSONL document)
_SHARED_MARKERS = (
    "queue/", "/queue", "jobs/", "/jobs", "campaign", "status.json",
    ".json", ".jsonl",
)
# substrings marking a path literal as a private scratch target: the
# tmp half of the tmp+rename idiom, quarantine/tombstone renames
_TMP_MARKERS = (
    ".tmp", ".part", ".reap", ".corrupt", ".ckpt.tmp",
    # ownership-dance tombstones: renamed-aside artifacts a single
    # holder consumes, no longer the shared rendezvous name
    ".release", ".preempt",
)

# functions whose RESULT is a private scratch path
_TMP_SOURCES = ("tempfile.mkstemp", "mkstemp", "tempfile.mktemp")

# name fragments marking a helper as durability-critical: its artifact
# must survive a host crash, not just a process crash, so the tmp file
# must be fsynced before the rename publishes it
_DURABLE_MARKERS = ("checkpoint", "durable")


def _literal_strings(node: ast.AST) -> list[str]:
    return [
        n.value
        for n in ast.walk(node)
        if isinstance(n, ast.Constant) and isinstance(n.value, str)
    ]


def _classify_literal(parts: list[str]) -> str:
    """'tmp' | 'shared' | 'other' for the string literals of one path
    expression. Tmp wins: ``path + ".tmp"`` is the tmp half of the
    atomic idiom even though ``path`` itself is shared."""
    text = "|".join(parts).lower()
    if any(m in text for m in _TMP_MARKERS):
        return "tmp"
    if any(m in text for m in _SHARED_MARKERS):
        return "shared"
    return "other"


class _PathTaint:
    """Per-function name -> {'shared'|'tmp'|'other'} classification.

    One linear pass over the function's assignments: a name assigned
    from an expression containing tmp markers (or a mkstemp call) is
    tmp; containing shared markers, shared. Later assignments override
    earlier ones only upward in specificity (tmp sticks — rebinding a
    tmp name from the shared name, e.g. ``tmp = path + ".tmp"``, is
    the idiom itself).
    """

    def __init__(self, fn: ast.AST):
        self.taint: dict[str, str] = {}
        for node in ast.walk(fn):
            targets: list[str] = []
            value = None
            if isinstance(node, ast.Assign):
                value = node.value
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        targets.append(t.id)
                    elif isinstance(t, ast.Tuple):
                        targets.extend(
                            e.id for e in t.elts if isinstance(e, ast.Name)
                        )
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                value = node.value
                if isinstance(node.target, ast.Name):
                    targets.append(node.target.id)
            if not targets or value is None:
                continue
            cls = self.classify(value)
            for name in targets:
                if cls == "tmp" or self.taint.get(name) != "tmp":
                    self.taint[name] = cls

    def classify(self, expr: ast.AST) -> str:
        for n in ast.walk(expr):
            if isinstance(n, ast.Call):
                callee = dotted_name(n.func) or ""
                if callee in _TMP_SOURCES or callee.endswith("mkstemp"):
                    return "tmp"
        parts = _literal_strings(expr)
        cls = _classify_literal(parts) if parts else "other"
        if cls != "tmp":
            # names referenced by the expression carry their taint in
            for n in ast.walk(expr):
                if isinstance(n, ast.Name):
                    t = self.taint.get(n.id)
                    if t == "tmp":
                        return "tmp"
                    if t == "shared":
                        cls = "shared"
        return cls


def _enclosing_function(ctx: ModuleContext, node: ast.AST):
    for anc in [node, *ctx.ancestors(node)]:
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return ctx.tree


def _open_mode(call: ast.Call) -> str | None:
    mode = None
    if len(call.args) > 1 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    return mode if isinstance(mode, str) else None


@register_rule
class NonAtomicSharedPathWrite(Rule):
    """``open(<shared path>, "w")`` of a protocol artifact.

    Every write landing under ``queue/``, ``jobs/``, a campaign root,
    or any ``*.json``/``*.jsonl`` artifact must flow through a
    sanctioned atomic idiom: ``O_CREAT|O_EXCL`` creation (claims,
    enqueues), tmp + ``os.replace`` (rewrites), or append mode (the
    recorders). A direct ``"w"`` open of the final path gives every
    concurrent reader — the watcher, the reaper, a gang peer — a
    window onto a torn file. (PSA008 heuristically flags json.dump in
    replace-less functions; this rule is the path-aware deepening: the
    open itself is the violation, whatever is written through it.)
    """

    id = "PSP101"
    severity = SEV_ERROR
    title = "non-atomic write to a shared artifact path"
    fix_hint = (
        "write a tempfile in the same directory and os.replace() into "
        "place (campaign/queue._atomic_write_json), os.open(...O_EXCL) "
        "for create-once markers, or mode 'a' for append-only records"
    )
    paths = ("peasoup_tpu/",)
    exclude = ("peasoup_tpu/tools/",)

    def check(self, ctx: ModuleContext):
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            taint = _PathTaint(fn)
            for node in ast.walk(fn):
                if not (
                    isinstance(node, ast.Call)
                    and dotted_name(node.func) == "open"
                    and node.args
                ):
                    continue
                mode = _open_mode(node)
                if mode is None or "w" not in mode:
                    continue  # reads and appends are protocol-clean
                if taint.classify(node.args[0]) == "shared":
                    yield self.finding(
                        ctx, node,
                        "open(..., 'w') directly on a shared artifact "
                        "path: concurrent readers can observe a torn "
                        "file",
                    )


@register_rule
class DeleteWhereQuarantineRequired(Rule):
    """``os.remove``/``os.unlink`` of a damaged artifact.

    The resilience policy (resilience/policy.py ``load_or_recover``)
    quarantines unreadable artifacts by RENAMING them to ``*.corrupt``
    — forensics survive, ``peasoup-campaign prune --corrupt`` reclaims
    the space deliberately. Deleting inside the exception handler that
    just failed to read/parse the file destroys the evidence the chaos
    gate (and any post-mortem) needs.
    """

    id = "PSP102"
    severity = SEV_ERROR
    title = "delete where the quarantine policy requires rename"
    fix_hint = (
        "rename the damaged file aside (resilience.load_or_recover "
        "quarantines to *.corrupt); deletion is prune's job, not the "
        "error path's"
    )
    paths = ("peasoup_tpu/",)
    exclude = ("peasoup_tpu/tools/", "peasoup_tpu/cli/")

    _READERS = ("json.load", "json.loads", "np.load", "numpy.load",
                "pickle.load", "load")
    _UNLINKERS = ("os.remove", "os.unlink")

    def _try_reads_artifact(self, handler: ast.ExceptHandler,
                            tree: ast.AST) -> bool:
        """Does the try block this handler guards parse/read a file?"""
        for node in ast.walk(tree):
            if isinstance(node, ast.Try) and handler in node.handlers:
                for n in ast.walk(ast.Module(body=node.body,
                                             type_ignores=[])):
                    if isinstance(n, ast.Call):
                        callee = dotted_name(n.func) or ""
                        if callee in self._READERS or callee.endswith(
                            (".load", ".loads")
                        ):
                            return True
                return False
        return False

    def check(self, ctx: ModuleContext):
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and dotted_name(node.func) in self._UNLINKERS
                and node.args
            ):
                continue
            handler = next(
                (
                    a for a in ctx.ancestors(node)
                    if isinstance(a, ast.ExceptHandler)
                ),
                None,
            )
            if handler is None:
                continue
            # unlinking the file we failed to READ is the anti-pattern;
            # unlinking a tmp file in a write-path cleanup handler is
            # the atomic idiom's own error path
            fn = _enclosing_function(ctx, node)
            if _PathTaint(fn).classify(node.args[0]) == "tmp":
                continue
            if not self._try_reads_artifact(handler, ctx.tree):
                continue
            yield self.finding(
                ctx, node,
                "deleting an artifact inside its failed-read handler "
                "destroys the forensics the quarantine policy keeps",
            )


@register_rule
class MissingFsyncBeforeRename(Rule):
    """tmp + ``os.replace`` without fsync in a durability-marked helper.

    ``os.replace`` makes the rewrite atomic against CONCURRENT readers,
    but not durable against a HOST crash: without ``os.fsync`` on the
    tmp file, the rename can land in the directory while the data
    blocks are still in the page cache — a power cut leaves a
    zero-length "successfully replaced" artifact. For most protocol
    files that is acceptable (they are reconstructible). For the
    durability-marked helpers — checkpoint writers a preempted job's
    bitwise-equal resume depends on — it is not.
    """

    id = "PSP103"
    severity = SEV_ERROR
    title = "missing fsync before rename in a durability-marked helper"
    fix_hint = (
        "f.flush() + os.fsync(f.fileno()) before os.replace() "
        "(durability-marked writers only: checkpoint/durable helpers)"
    )
    paths = ("peasoup_tpu/",)

    def _durable(self, fn: ast.AST, cls: ast.ClassDef | None) -> bool:
        names = [getattr(fn, "name", "")]
        docs = [ast.get_docstring(fn) or ""]
        if cls is not None:
            names.append(cls.name)
            docs.append(ast.get_docstring(cls) or "")
        blob = "|".join(names + docs).lower()
        return any(m in blob for m in _DURABLE_MARKERS)

    def check(self, ctx: ModuleContext):
        reported: set[int] = set()  # replace nodes already flagged
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            cls = next(
                (
                    a for a in ctx.ancestors(node)
                    if isinstance(a, ast.ClassDef)
                ),
                None,
            )
            if not self._durable(node, cls):
                continue
            replaces = [
                n
                for n in ast.walk(node)
                if isinstance(n, ast.Call)
                and dotted_name(n.func) in ("os.replace", "os.rename")
            ]
            if not replaces:
                continue
            has_fsync = any(
                isinstance(n, ast.Call)
                and (dotted_name(n.func) or "").endswith("fsync")
                for n in ast.walk(node)
            )
            if has_fsync:
                continue
            for rep in replaces:
                if id(rep) in reported:
                    continue  # a nested helper inside the same writer
                reported.add(id(rep))
                yield self.finding(
                    ctx, rep,
                    f"{dotted_name(rep.func)}() in durability-marked "
                    f"helper {getattr(node, 'name', '?')!r} without an "
                    "fsync of the tmp file: a host crash can publish "
                    "an empty artifact",
                )


def _thread_targets(ctx: ModuleContext) -> list[tuple[ast.Call, ast.AST]]:
    """(Thread(...) call, target expression) pairs in this module."""
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func) or ""
        if not (
            name.endswith("Thread")
            and name.split(".", 1)[0] in ("threading", "Thread")
        ):
            continue
        target = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is None and node.args:
            target = node.args[0]
        if target is not None:
            out.append((node, target))
    return out


def _defs_by_name(ctx: ModuleContext) -> dict[str, list[ast.AST]]:
    defs: dict[str, list[ast.AST]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    return defs


def _resolve_target(
    ctx: ModuleContext, target: ast.AST,
    defs: dict[str, list[ast.AST]],
) -> list[ast.AST]:
    """Function bodies a Thread target resolves to, one level deep:
    plain names, ``self._method`` attributes, lambdas (followed into a
    ``ctx.run(fn, ...)`` call — the copied-context idiom)."""
    if isinstance(target, ast.Lambda):
        body = target.body
        if isinstance(body, ast.Call):
            callee = dotted_name(body.func) or ""
            if callee.endswith(".run") and body.args:
                return _resolve_target(ctx, body.args[0], defs)
            return _resolve_target(ctx, body.func, defs)
        return [target]
    name = None
    if isinstance(target, ast.Name):
        name = target.id
    elif isinstance(target, ast.Attribute):
        name = target.attr
    if name is not None and name in defs:
        return list(defs[name])
    return []


def _calls_guard_thread(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func) or ""
            if callee.split(".")[-1] == "guard_thread":
                return True
    return False


@register_rule
class UnguardedThreadTarget(Rule):
    """Background thread body not wrapped in ``guard_thread``.

    An exception escaping a bare thread target kills the thread
    silently: the heartbeat stops beating, the lease stops renewing,
    the warmup never lands — and nothing marks the run degraded. The
    resilience contract (resilience/policy.py) is that every thread
    body runs under :func:`guard_thread`, which emits the structured
    ``thread_crashed`` event, bumps the crash counter (flipping
    ``degraded`` in status.json) and logs the traceback. Covers
    ``threading.Thread(target=...)`` (lambdas followed through the
    copied-context ``ctx.run(fn, ...)`` idiom) and ``run()`` methods
    of ``threading.Thread`` subclasses.
    """

    id = "PSP104"
    severity = SEV_ERROR
    title = "thread target not wrapped in guard_thread"
    fix_hint = (
        "run the body via resilience.guard_thread(name, fn, "
        "telemetry=...) so a crash is a structured degraded event, "
        "not a silent dead thread"
    )
    paths = ("peasoup_tpu/",)
    exclude = ("peasoup_tpu/resilience/",)

    def check(self, ctx: ModuleContext):
        defs = _defs_by_name(ctx)
        for call, target in _thread_targets(ctx):
            bodies = _resolve_target(ctx, target, defs)
            if not bodies:
                # unresolvable target (imported callable): flag it —
                # the guard must be visible at the spawn site
                yield self.finding(
                    ctx, call,
                    "Thread target is not resolvable in this module; "
                    "wrap the body in guard_thread at the spawn site",
                )
                continue
            for fn in bodies:
                if not _calls_guard_thread(fn):
                    yield self.finding(
                        ctx, call,
                        f"Thread target "
                        f"{getattr(fn, 'name', '<lambda>')!r} does not "
                        "run under guard_thread",
                    )
        # Thread subclasses: run() must guard
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            if not any(
                (dotted_name(b) or "").endswith("Thread")
                for b in cls.bases
            ):
                continue
            for method in cls.body:
                if (
                    isinstance(method, ast.FunctionDef)
                    and method.name == "run"
                    and not _calls_guard_thread(method)
                ):
                    yield self.finding(
                        ctx, method,
                        f"{cls.name}.run() does not run its body under "
                        "guard_thread",
                    )


def _lock_names(with_node: ast.With) -> list[str]:
    names = []
    for item in with_node.items:
        expr = item.context_expr
        if isinstance(expr, ast.Call):
            expr = expr.func
        name = dotted_name(expr) or ""
        leaf = name.split(".")[-1]
        if "lock" in leaf.lower() or "mutex" in leaf.lower():
            names.append(leaf)
    return names


def _attr_mutations(method: ast.AST):
    """(node, attr_name) for compound mutations of self.<attr>."""
    _MUTATORS = {
        "append", "extend", "insert", "remove", "pop", "popleft",
        "appendleft", "clear", "update", "add", "discard", "setdefault",
    }
    for node in ast.walk(method):
        if (
            isinstance(node, ast.AugAssign)
            and isinstance(node.target, ast.Attribute)
            and isinstance(node.target.value, ast.Name)
            and node.target.value.id == "self"
        ):
            yield node, node.target.attr
        elif (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Attribute)
            and isinstance(node.targets[0].value, ast.Name)
            and node.targets[0].value.id == "self"
        ):
            yield node, node.targets[0].attr
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATORS
            and isinstance(node.func.value, ast.Attribute)
            and isinstance(node.func.value.value, ast.Name)
            and node.func.value.value.id == "self"
        ):
            yield node, node.func.value.attr


@register_rule
class MutationOutsideOwningLock(Rule):
    """Thread-shared attribute mutated outside its owning lock.

    Deepens PSA009 with per-class attribute/lock **binding**: in a
    class that spawns (or is) a thread, an attribute that is ever
    mutated under ``with self._lock:`` has declared ``_lock`` its
    owner — every other mutation of that attribute must hold the same
    lock, including plain rebinding (the half-guarded invariant is
    worse than none: readers that take the lock still see torn
    compound state). ``__init__`` is exempt (no thread exists yet).
    """

    id = "PSP105"
    severity = SEV_ERROR
    title = "thread-shared attribute mutated outside its owning lock"
    fix_hint = (
        "take the same `with self._lock:` that other mutators of this "
        "attribute hold (or suppress with the reason the access is "
        "single-threaded)"
    )
    paths = ("peasoup_tpu/",)

    def _spawns_thread(self, cls: ast.ClassDef) -> bool:
        if any(
            (dotted_name(b) or "").endswith("Thread") for b in cls.bases
        ):
            return True
        for node in ast.walk(cls):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func) or ""
                if name.endswith("Thread") and name.split(".", 1)[0] in (
                    "threading", "Thread",
                ):
                    return True
        return False

    def _enclosing_locks(self, ctx: ModuleContext, node: ast.AST):
        held = set()
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.With):
                held.update(_lock_names(anc))
        return held

    def check(self, ctx: ModuleContext):
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef) or not self._spawns_thread(
                cls
            ):
                continue
            # pass 1: bind attr -> owning locks
            owners: dict[str, set[str]] = {}
            sites: list[tuple[ast.AST, str, set[str], str]] = []
            for method in cls.body:
                if not isinstance(
                    method, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                for node, attr in _attr_mutations(method):
                    held = self._enclosing_locks(ctx, node)
                    if method.name != "__init__":
                        sites.append((node, attr, held, method.name))
                    owners.setdefault(attr, set()).update(held)
            # pass 2: every mutation of an owned attr must hold a lock
            for node, attr, held, method_name in sites:
                owning = owners.get(attr) or set()
                if not owning:
                    continue  # unowned attrs are PSA009's (warning) turf
                if held & owning:
                    continue
                yield self.finding(
                    ctx, node,
                    f"self.{attr} is lock-owned (mutated under "
                    f"{sorted(owning)} elsewhere in {cls.name}) but "
                    f"mutated lock-free in {method_name}()",
                )


@register_rule
class AmbientTelemetryAcrossThread(Rule):
    """Ambient (contextvar) telemetry read from a thread body.

    The active :class:`RunTelemetry` rides a ``contextvars``
    ContextVar, and context does NOT cross thread boundaries: a thread
    target calling the ambient accessor gets the process-wide no-op
    sink, so its events (and fault/retry attribution) silently vanish.
    The sanctioned patterns are an explicit ``telemetry=`` parameter
    (guard_thread and every recorder accept one) or spawning through a
    copied context (``contextvars.copy_context().run(fn, ...)`` — the
    streaming reader's idiom).
    """

    id = "PSP106"
    severity = SEV_ERROR
    title = "ambient telemetry accessor inside a thread target"
    fix_hint = (
        "pass the telemetry object into the thread explicitly, or "
        "spawn via contextvars.copy_context().run(...)"
    )
    paths = ("peasoup_tpu/",)
    exclude = ("peasoup_tpu/resilience/", "peasoup_tpu/obs/telemetry.py")

    def _ambient_aliases(self, ctx: ModuleContext) -> set[str]:
        """Names this module binds to obs.telemetry.current."""
        aliases = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and (
                node.module or ""
            ).endswith("telemetry"):
                for alias in node.names:
                    if alias.name == "current":
                        aliases.add(alias.asname or alias.name)
        return aliases

    def check(self, ctx: ModuleContext):
        aliases = self._ambient_aliases(ctx)
        defs = _defs_by_name(ctx)
        bodies: list[ast.AST] = []
        copied: set[ast.AST] = set()
        for call, target in _thread_targets(ctx):
            resolved = _resolve_target(ctx, target, defs)
            # a lambda body of the form ctx.run(fn, ...) is the copied-
            # context idiom: everything under fn runs with context
            if isinstance(target, ast.Lambda) and isinstance(
                target.body, ast.Call
            ):
                callee = dotted_name(target.body.func) or ""
                if callee.endswith(".run"):
                    copied.update(resolved)
            bodies.extend(resolved)
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef) and any(
                (dotted_name(b) or "").endswith("Thread")
                for b in cls.bases
            ):
                for method in cls.body:
                    if (
                        isinstance(method, ast.FunctionDef)
                        and method.name == "run"
                    ):
                        bodies.append(method)
        for fn in bodies:
            if fn in copied:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = dotted_name(node.func) or ""
                is_ambient = (
                    callee in aliases
                    or callee.endswith("telemetry.current")
                    or callee.split(".")[-1]
                    in ("current_telemetry", "_current_telemetry")
                )
                if is_ambient:
                    yield self.finding(
                        ctx, node,
                        f"{callee}() in thread target "
                        f"{getattr(fn, 'name', '<lambda>')!r} reads "
                        "the no-op sink (contextvars do not cross "
                        "threads)",
                    )


@register_rule
class SharedArtifactDirectDelete(Rule):
    """``os.remove``/``os.unlink`` of a live shared protocol artifact.

    The fleet's ownership transfers never delete a shared rendezvous
    file in place: a holder RENAMES it to a uuid-suffixed tombstone
    (``.reap.<id>`` / ``.release.<id>``), re-verifies the renamed
    document, and only then consumes the tombstone — and damaged
    artifacts are renamed to ``*.corrupt`` for forensics. A direct
    unlink of the shared path is a blind write: between any read that
    justified it and the unlink itself, a reaper, renewer, or new
    claimant may have replaced the file, and the unlink destroys
    *their* artifact — the read-check-delete race class the mc
    scenarios (renew_vs_reap, release_vs_reap) exhibit concretely.
    Classification is the same literal-dataflow walk as PSP101:
    tombstone/tmp-marked names are sanctioned, shared-marked names
    (queue/, jobs/, ``*.json``...) are not.
    """

    id = "PSP107"
    severity = SEV_ERROR
    title = "direct delete of a shared artifact path"
    fix_hint = (
        "rename the artifact to a uuid-suffixed tombstone "
        "(*.reap.<id>/*.release.<id>), re-verify the renamed document, "
        "then consume the tombstone (campaign/queue._take_claim); "
        "quarantine damaged files to *.corrupt instead of deleting"
    )
    paths = ("peasoup_tpu/",)
    exclude = ("peasoup_tpu/tools/", "peasoup_tpu/cli/")

    _UNLINKERS = ("os.remove", "os.unlink")

    def check(self, ctx: ModuleContext):
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            taint = _PathTaint(fn)
            for node in ast.walk(fn):
                if not (
                    isinstance(node, ast.Call)
                    and dotted_name(node.func) in self._UNLINKERS
                    and node.args
                ):
                    continue
                if taint.classify(node.args[0]) == "shared":
                    yield self.finding(
                        ctx, node,
                        "os.unlink of a shared artifact path: transfer "
                        "ownership by tombstone-rename (and re-verify) "
                        "instead of deleting in place",
                    )


def protocol_rules() -> tuple[str, ...]:
    """The PSP rule IDs (the runner's engine-3 filter)."""
    return tuple(
        cls.id
        for cls in (
            NonAtomicSharedPathWrite,
            DeleteWhereQuarantineRequired,
            MissingFsyncBeforeRename,
            UnguardedThreadTarget,
            MutationOutsideOwningLock,
            AmbientTelemetryAcrossThread,
            SharedArtifactDirectDelete,
        )
    )
