"""Protocol model checking (audit engine 5).

Runs the *real* file-backed protocol code — ``campaign/queue.py``,
``campaign/registry.py``, ``campaign/tenants.py``, ``obs/alerts.py``
— against a deterministic in-memory filesystem interposed at the
``os``/``open`` seam, with a cooperative scheduler that
context-switches simulated workers at every filesystem operation and
systematically explores interleavings (DFS with state-hash
deduplication and conflict-based partial-order reduction) plus
crash-point injection (``WorkerKilled`` between any two FS ops,
modelling SIGKILL mid-protocol).

Invariant violations surface as PSM3xx findings through the standard
findings/baseline framework, each carrying a minimized schedule
string that replays bit-identically (:func:`explorer.replay`).
"""

from .explorer import Scenario, explore, replay, run_schedule
from .invariants import InvariantViolation, MCContext
from .scenarios import MCReport, run_mc, scenario_names, scenarios
from .vfs import MCEnv, VirtualFS, interpose

__all__ = [
    "InvariantViolation",
    "MCContext",
    "MCEnv",
    "MCReport",
    "Scenario",
    "VirtualFS",
    "explore",
    "interpose",
    "replay",
    "run_mc",
    "run_schedule",
    "scenario_names",
    "scenarios",
]
