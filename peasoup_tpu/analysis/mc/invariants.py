"""Scenario-side context and invariant helpers.

Scenario task bodies run *interposed* — their module-under-test calls
hit the virtual filesystem — but the scenario file itself is not
patched, so task code must go through :class:`MCContext` (``now`` /
``advance`` / ``mark`` / ``read_json``) or the module APIs, never raw
``os``/``time``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any

from .vfs import MCEnv, OpDesc


class InvariantViolation(AssertionError):
    """A scenario invariant failed; the message becomes the finding."""


def require(cond: object, msg: str) -> None:
    if not cond:
        raise InvariantViolation(msg)


@dataclass
class MCContext:
    """What a scenario sees: the env, the campaign root, and ``out`` —
    a scratch dict tasks deposit results into for the invariant.
    (``out`` is safe shared state: only one task thread is ever
    runnable, and task results are deterministic functions of the op
    history the state hash already covers.)"""

    env: MCEnv
    root: str = "/camp"
    out: dict[str, Any] = field(default_factory=dict)

    # -- virtual time --------------------------------------------------
    def now(self) -> float:
        return self.env.clock

    def advance(self, dt: float) -> None:
        """Advance the virtual clock — an explicit scheduling op that
        conflicts with everything (time is ambient)."""
        env = self.env

        def fn() -> None:
            env.clock += dt

        env.op(OpDesc("advance", f"+{dt:g}"), fn)

    def mark(self, label: str) -> None:
        """Drop a trace marker (critical-section boundaries etc.) —
        also a scheduling op, stamped with the current clock."""
        env = self.env
        env.op(OpDesc("mark", f"{label}@{env.clock:g}"), lambda: None)

    # -- direct (invariant-phase) filesystem reads ---------------------
    def read_json(self, path: str) -> Any:
        try:
            return json.loads(self.env.fs.read(path))
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    def listdir(self, path: str) -> list[str]:
        return self.env.fs.listdir(path)

    def exists(self, path: str) -> bool:
        return self.env.fs.exists(path)

    def read(self, path: str) -> str | None:
        try:
            return self.env.fs.read(path)
        except FileNotFoundError:
            return None


# -- trace queries ------------------------------------------------------


def count_ops(trace: list[str], kind: str, path: str) -> int:
    """How many times ``kind`` *succeeded* on exactly ``path``."""
    want = f"{kind}:{path}"
    n = 0
    for e in trace:
        _, _, rest = e.partition(":")
        if rest == want:
            n += 1
    return n


def marks(trace: list[str], label: str) -> list[tuple[str, float]]:
    """``(task, clock)`` for every ``mark`` whose label matches."""
    out = []
    for e in trace:
        who, _, rest = e.partition(":")
        if not rest.startswith("mark:"):
            continue
        body = rest[len("mark:") :]
        name, _, clock = body.rpartition("@")
        if name == label:
            out.append((who, float(clock)))
    return out


def cs_intervals(
    trace: list[str], enter: str, exit_: str
) -> list[tuple[str, float, float | None]]:
    """Critical-section intervals from enter/exit marks: ``(task,
    t_enter, t_exit)`` with ``t_exit=None`` for sections never exited
    (killed inside)."""
    open_: dict[str, float] = {}
    out: list[tuple[str, float, float | None]] = []
    for e in trace:
        who, _, rest = e.partition(":")
        if not rest.startswith("mark:"):
            continue
        body = rest[len("mark:") :]
        name, _, clock = body.rpartition("@")
        if name == enter:
            open_[who] = float(clock)
        elif name == exit_ and who in open_:
            out.append((who, open_.pop(who), float(clock)))
    for who, t0 in open_.items():
        out.append((who, t0, None))
    return out
