"""The protocol drill library: what the model checker checks.

Each :class:`~.explorer.Scenario` stages a small fleet — two or three
simulated workers running the *real* ``campaign``/``obs`` protocol
code against the virtual filesystem — and asserts a load-bearing
invariant over every explored interleaving and crash point:

========================  ======  =====================================
scenario                  rule    invariant
========================  ======  =====================================
claim_race                PSM301  exactly one O_EXCL claim winner
claim_crash_reap          PSM302  SIGKILLed claimer's job is recovered,
                                  never double-charged
renew_vs_reap             PSM303  lease renewal and the reaper agree on
                                  ownership (no stomped renewals)
release_vs_reap           PSM303  voluntary release consumes zero
                                  attempts, reaper charges at most one
zombie_complete           PSM301  the done record publishes exactly
                                  once, even with a reaped zombie
preempt_handoff           PSM304  preemption hand-back XOR grace reap;
                                  carried resilience survives the fold
gang_assembly             PSM305  a published gang claim always names a
                                  full member set
gang_insufficient         PSM305  an under-strength gang never claims
registry_group_survival   PSM306  re-registration after a skewed reap
                                  keeps gang-group membership
registry_torn_entry       PSM306  torn (mid-publish) registry entries
                                  are swept after a grace lease
tenant_throttle           PSM307  concurrent claims over-admit by at
                                  most one; the next claim throttles
alerts_lock               PSM308  alert evaluation is mutually
                                  exclusive while the lock is fresh
alerts_release_race       PSM308  releasing a stale-taken-over lock
                                  never clobbers the new holder
alerts_journal            PSM308  journal lines are never torn; one
                                  firing transition per episode
========================  ======  =====================================

Violations become PSM3xx findings whose ``source_line`` embeds the
minimized schedule (``<scenario> schedule=<tokens>``) — feed it back
through :func:`~.explorer.replay` for a bit-identical reproduction.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from ..findings import SEV_ERROR, Finding
from .crash import enumerate_crash_points
from .explorer import (
    DEFAULT_BUDGET,
    Scenario,
    explore,
    minimize,
    schedule_to_str,
)
from .invariants import MCContext, require

ROOT = "/camp"
_Q = f"{ROOT}/queue"


def _job_path(jid: str) -> str:
    return f"{_Q}/jobs/{jid}.json"


def _claim_path(jid: str) -> str:
    return f"{_Q}/claims/{jid}.json"


def _done_path(jid: str) -> str:
    return f"{_Q}/done/{jid}.json"


def _queue(**kw):
    from ...campaign.queue import JobQueue

    return JobQueue(ROOT, **kw)


def _job(jid: str, **kw):
    from ...campaign.queue import Job

    return Job(jid, f"/data/{jid}.fil", **kw)


def _attempts(ctx: MCContext, jid: str = "j1") -> int:
    doc = ctx.read_json(_job_path(jid))
    return int(doc.get("attempts", 0)) if doc else 0


def _published(ctx: MCContext, path: str) -> int:
    """Successful publications of ``path``: every atomic-publish idiom
    lands as exactly one ``create``/``link``/``rename`` trace op on the
    destination (a failed duplicate carries an ``!ExcName`` suffix and
    does not count)."""
    wanted = {f"{k}:{path}" for k in ("create", "link", "rename")}
    n = 0
    for e in ctx.env.trace:
        _, _, rest = e.partition(":")
        if rest in wanted:
            n += 1
    return n


def _killed(ctx: MCContext) -> bool:
    return any(":KILLED:" in e for e in ctx.env.trace)


# ---------------------------------------------------------------------------
# queue: claim mutual exclusion + crash recovery
# ---------------------------------------------------------------------------


def _claim_race() -> Scenario:
    def setup(ctx: MCContext) -> None:
        _queue().add_job(_job("j1"))

    def worker(name: str):
        def body(ctx: MCContext) -> None:
            ctx.out[name] = _queue().try_claim("j1", name) is not None

        return body

    def invariant(ctx: MCContext) -> None:
        w1, w2 = ctx.out.get("w1"), ctx.out.get("w2")
        require(
            w1 != w2,
            f"claim mutual exclusion broken: w1={w1} w2={w2} "
            "(O_EXCL must admit exactly one claimer)",
        )
        doc = ctx.read_json(_claim_path("j1"))
        winner = "w1" if w1 else "w2"
        require(
            doc is not None and doc.get("worker_id") == winner,
            f"claim doc names {doc and doc.get('worker_id')!r}, "
            f"but {winner} won the claim",
        )

    return Scenario(
        name="claim_race",
        rule="PSM301",
        module="peasoup_tpu/campaign/queue.py",
        description="two workers race try_claim on the same job",
        setup=setup,
        tasks=(
            ("w1", worker("w1"), False),
            ("w2", worker("w2"), False),
        ),
        invariant=invariant,
        max_kills=0,
        fix_hint="claim creation must go through O_CREAT|O_EXCL and the "
        "loser must treat FileExistsError as a lost race, not retry",
    )


def _claim_crash_reap() -> Scenario:
    def setup(ctx: MCContext) -> None:
        _queue().add_job(_job("j1"))

    def w1(ctx: MCContext) -> None:
        ctx.out["w1"] = _queue().try_claim("j1", "w1") is not None

    def reaper(ctx: MCContext) -> None:
        q = _queue(backoff_base_s=0.0)
        ctx.advance(400)
        q.reap_stale()
        ctx.advance(10)
        ctx.out["reclaim"] = q.try_claim("j1", "r") is not None

    def invariant(ctx: MCContext) -> None:
        doc = ctx.read_json(_claim_path("j1"))
        w1_holds = doc is not None and doc.get("worker_id") == "w1"
        # a crash can leave a TORN claim younger than its grace lease
        # (created after the reaper's advance): this sweep keeps its
        # hands off it, the NEXT one recovers it — the job is pending,
        # not lost
        torn_pending = (
            _killed(ctx)
            and doc is None
            and ctx.exists(_claim_path("j1"))
        )
        require(
            ctx.out.get("reclaim") or w1_holds or torn_pending,
            "job lost after a crashed claimer: neither the reaper "
            "reclaimed it nor does the original claim survive",
        )
        att = _attempts(ctx)
        require(
            att <= 1,
            f"crash-reap charged {att} attempts for one crashed claim "
            "(double-charging burns the retry budget)",
        )
        if w1_holds:
            require(
                att == 0,
                "the live holder's job was charged an attempt by the "
                "reaper (the tombstone dance must verify before charging)",
            )

    return Scenario(
        name="claim_crash_reap",
        rule="PSM302",
        module="peasoup_tpu/campaign/queue.py",
        description="claimer SIGKILLed at any FS op; reaper recovers",
        setup=setup,
        tasks=(("w1", w1, True), ("reaper", reaper, False)),
        invariant=invariant,
        max_kills=1,
        fix_hint="reap must rename the claim to a private tombstone, "
        "re-verify it, and charge torn (empty) claims zero attempts",
    )


def _renew_vs_reap() -> Scenario:
    def setup(ctx: MCContext) -> None:
        q = _queue()
        q.add_job(_job("j1"))
        ctx.out["claim"] = q.try_claim("j1", "w1")
        ctx.advance(50)  # 10s of lease left; reaper skew pushes past it

    def w1(ctx: MCContext) -> None:
        ctx.out["renew_ok"] = _queue().renew(ctx.out["claim"])

    def reaper(ctx: MCContext) -> None:
        _queue().reap_stale()

    def invariant(ctx: MCContext) -> None:
        renew_ok = bool(ctx.out.get("renew_ok"))
        att = _attempts(ctx)
        require(
            renew_ok != (att == 1),
            f"renew/reap disagree on ownership: renew_ok={renew_ok} "
            f"attempts={att} (exactly one of them owns the outcome)",
        )
        doc = ctx.read_json(_claim_path("j1"))
        held = doc is not None and doc.get("worker_id") == "w1"
        require(
            held == renew_ok,
            f"claim state diverged from renew outcome: renew_ok="
            f"{renew_ok} but claim held={held}",
        )

    return Scenario(
        name="renew_vs_reap",
        rule="PSM303",
        module="peasoup_tpu/campaign/queue.py",
        description="lease renewal races a clock-skewed reaper",
        setup=setup,
        tasks=(("w1", w1, False), ("reaper", reaper, False)),
        invariant=invariant,
        max_kills=0,
        skews={"reaper": 30.0},
        fix_hint="renew must republish via the take-verify-republish "
        "dance and report False on a lost lease; a blind os.replace "
        "lets a reaped zombie stomp the reaper's requeue",
    )


def _release_vs_reap() -> Scenario:
    def setup(ctx: MCContext) -> None:
        q = _queue()
        q.add_job(_job("j1"))
        ctx.out["claim"] = q.try_claim("j1", "w1")

    def w1(ctx: MCContext) -> None:
        q = _queue()
        q.release(ctx.out["claim"])
        q.release(ctx.out["claim"])  # idempotence under interleaving

    def reaper(ctx: MCContext) -> None:
        ctx.advance(70)
        _queue().reap_stale()

    def invariant(ctx: MCContext) -> None:
        att = _attempts(ctx)
        require(
            att <= 1,
            f"release/reap race charged {att} attempts (a clean "
            "hand-back is elasticity, not failure)",
        )
        leftovers = [
            n for n in ctx.listdir(f"{_Q}/claims") if n.startswith("j1")
        ]
        require(
            not leftovers,
            f"claim artifacts leaked after release+reap: {leftovers}",
        )

    return Scenario(
        name="release_vs_reap",
        rule="PSM303",
        module="peasoup_tpu/campaign/queue.py",
        description="double voluntary release races the lease reaper",
        setup=setup,
        tasks=(("w1", w1, False), ("reaper", reaper, False)),
        invariant=invariant,
        max_kills=0,
        fix_hint="release must be a verified tombstone take (no-op on a "
        "lost lease) and never unlink the new owner's claim",
    )


def _zombie_complete() -> Scenario:
    def setup(ctx: MCContext) -> None:
        q = _queue(backoff_base_s=0.0)
        q.add_job(_job("j1"))
        ctx.out["claim"] = q.try_claim("j1", "w1")

    def w1(ctx: MCContext) -> None:
        _queue(backoff_base_s=0.0).complete(
            ctx.out["claim"], worker_id="w1"
        )

    def sweeper(ctx: MCContext) -> None:
        q = _queue(backoff_base_s=0.0)
        ctx.advance(70)
        q.reap_stale()
        c2 = q.try_claim("j1", "w2")
        if c2 is not None:
            q.complete(c2, worker_id="w2")

    def invariant(ctx: MCContext) -> None:
        n = _published(ctx, _done_path("j1"))
        require(
            n == 1,
            f"done record published {n} times (must be exactly once: "
            "a reaped zombie completer may not stomp or duplicate the "
            "re-claimer's publication)",
        )

    return Scenario(
        name="zombie_complete",
        rule="PSM301",
        module="peasoup_tpu/campaign/queue.py",
        description="completer races its own reap + a re-claimer",
        setup=setup,
        tasks=(("w1", w1, True), ("sweeper", sweeper, False)),
        invariant=invariant,
        max_kills=1,
        fix_hint="complete must take the claim first (zombies get "
        "False) and publish the done record via tmp + os.link so a "
        "duplicate surfaces as FileExistsError, never an overwrite",
    )


def _preempt_handoff() -> Scenario:
    def setup(ctx: MCContext) -> None:
        q = _queue()
        q.add_job(_job("j1"))
        ctx.out["claim"] = q.try_claim("j1", "w1")
        q.request_preempt("j1", requester="scaler", grace_s=30.0)

    def victim(ctx: MCContext) -> None:
        q = _queue()
        ctx.out["folded"] = q.record_carried_resilience(
            ctx.out["claim"], {"retries": {"io": 2}}
        )
        q.release_preempted(ctx.out["claim"])

    def reaper(ctx: MCContext) -> None:
        ctx.advance(45)  # past the grace deadline, inside the lease
        _queue().reap_stale()

    def invariant(ctx: MCContext) -> None:
        doc = ctx.read_json(_job_path("j1")) or {}
        pre = int(doc.get("preemptions", 0))
        att = int(doc.get("attempts", 0))
        require(
            pre <= 1 and att <= 1,
            f"preempt hand-back double-counted: preemptions={pre} "
            f"attempts={att}",
        )
        require(
            (pre == 1) != (att == 1),
            f"preempt hand-back and grace reap must be exclusive: "
            f"preemptions={pre} attempts={att}",
        )
        if ctx.out.get("folded"):
            carried = (doc.get("carried_resilience") or {}).get(
                "retries", {}
            )
            require(
                int(carried.get("io", 0)) == 2,
                "carried resilience fold reported success but the "
                f"counters are missing from the job record: {carried}",
            )

    return Scenario(
        name="preempt_handoff",
        rule="PSM304",
        module="peasoup_tpu/campaign/queue.py",
        description="checkpointed hand-back races the grace-deadline reap",
        setup=setup,
        tasks=(("victim", victim, False), ("reaper", reaper, False)),
        invariant=invariant,
        max_kills=0,
        fix_hint="record_carried_resilience must report whether the "
        "fold landed; release_preempted must no-op (not re-record) on "
        "a lost lease",
    )


# ---------------------------------------------------------------------------
# queue: gang scheduling
# ---------------------------------------------------------------------------


def _gang_assembly() -> Scenario:
    def setup(ctx: MCContext) -> None:
        _queue().add_job(_job("j1", nprocs=3))

    def wa(ctx: MCContext) -> None:
        ctx.out["wa"] = _queue(backoff_base_s=0.0).claim_next(
            "wa", group="g", group_members=["wa", "wb", "wc"]
        )

    def watcher(ctx: MCContext) -> None:
        q = _queue(backoff_base_s=0.0)
        ctx.advance(70)
        q.reap_stale()
        ctx.out["c2"] = q.claim_next(
            "wb", group="g", group_members=["wb", "wc", "wd"]
        )

    def invariant(ctx: MCContext) -> None:
        doc = ctx.read_json(_claim_path("j1"))
        if doc is not None:
            gang = doc.get("gang") or {}
            members = gang.get("members") or []
            require(
                gang.get("group") == "g"
                and len(members) == 3
                and int(gang.get("nprocs", 0)) == 3
                and doc.get("worker_id") in members,
                f"published gang claim is malformed: {gang} "
                f"(leader {doc.get('worker_id')!r})",
            )
        elif not _killed(ctx):
            require(
                False,
                "gang job unclaimed with no crash injected: the "
                "leader gate or member-count gate rejected a full gang",
            )

    return Scenario(
        name="gang_assembly",
        rule="PSM305",
        module="peasoup_tpu/campaign/queue.py",
        description="gang leader crashes; a new leader re-assembles",
        setup=setup,
        tasks=(("wa", wa, True), ("watcher", watcher, False)),
        invariant=invariant,
        max_kills=1,
        fix_hint="a gang claim must publish the full member set "
        "atomically with the claim; a torn claim must be reapable",
    )


def _gang_insufficient() -> Scenario:
    def setup(ctx: MCContext) -> None:
        _queue().add_job(_job("j1", nprocs=3))

    def worker(name: str):
        def body(ctx: MCContext) -> None:
            ctx.out[name] = _queue().claim_next(
                name, group="g", group_members=["wa", "wb"]
            )

        return body

    def invariant(ctx: MCContext) -> None:
        require(
            ctx.out.get("wa") is None and ctx.out.get("wb") is None,
            "an under-strength gang (2 members, nprocs=3) claimed a "
            "gang job — it would deadlock waiting for a third rank",
        )
        require(
            not ctx.listdir(f"{_Q}/claims"),
            "claim artifacts leaked from a rejected gang assembly",
        )

    return Scenario(
        name="gang_insufficient",
        rule="PSM305",
        module="peasoup_tpu/campaign/queue.py",
        description="two workers offer a 2-member gang for nprocs=3",
        setup=setup,
        tasks=(
            ("wa", worker("wa"), False),
            ("wb", worker("wb"), False),
        ),
        invariant=invariant,
        max_kills=0,
        fix_hint="claim_next must refuse a gang job unless the caller "
        "is the sorted-first live member of a full-strength group",
    )


# ---------------------------------------------------------------------------
# registry: membership under skewed reapers and torn joins
# ---------------------------------------------------------------------------


def _registry():
    from ...campaign.registry import WorkerRegistry

    return WorkerRegistry


def _registry_group_survival() -> Scenario:
    def setup(ctx: MCContext) -> None:
        _registry()(ROOT, group="g").register("wa")

    def wa(ctx: MCContext) -> None:
        reg = _registry()(ROOT, group="g")
        reg.beat("wa")
        reg.beat("wa")

    def reaper(ctx: MCContext) -> None:
        _registry()(ROOT).reap()

    def invariant(ctx: MCContext) -> None:
        doc = ctx.read_json(f"{_Q}/workers/wa.json")
        if doc is not None:
            require(
                doc.get("group") == "g",
                "a beat-recreated registry entry lost its gang group "
                f"(group={doc.get('group')!r}): the gang pool silently "
                "shrank",
            )

    return Scenario(
        name="registry_group_survival",
        rule="PSM306",
        module="peasoup_tpu/campaign/registry.py",
        description="heartbeats race a clock-skewed membership reaper",
        setup=setup,
        tasks=(("wa", wa, False), ("reaper", reaper, False)),
        invariant=invariant,
        max_kills=0,
        skews={"reaper": 90.0},
        fix_hint="beat's re-registration path must carry the worker's "
        "process group, not default it away",
    )


def _registry_torn_entry() -> Scenario:
    def setup(ctx: MCContext) -> None:
        del ctx

    def wj(ctx: MCContext) -> None:
        _registry()(ROOT, group="g").register("wj")

    def sweeper(ctx: MCContext) -> None:
        ctx.advance(70)
        ctx.out["reaped"] = _registry()(ROOT).reap()

    def invariant(ctx: MCContext) -> None:
        wdir = f"{_Q}/workers"
        for name in ctx.listdir(wdir):
            if not name.endswith(".json"):
                continue
            path = f"{wdir}/{name}"
            try:
                json.loads(ctx.read(path) or "")
                continue
            except json.JSONDecodeError:
                pass
            age = ctx.now() - ctx.env.fs.stat(path).st_ctime
            require(
                age <= 60.0,
                f"torn registry entry {name} leaked past its grace "
                f"lease ({age:g}s old): it has no expiry, so nothing "
                "would ever reap it",
            )

    return Scenario(
        name="registry_torn_entry",
        rule="PSM306",
        module="peasoup_tpu/campaign/registry.py",
        description="joiner SIGKILLed mid-register; sweeper cleans up",
        setup=setup,
        tasks=(("wj", wj, True), ("sweeper", sweeper, False)),
        invariant=invariant,
        max_kills=1,
        fix_hint="reap must age-gate unparsable entries on st_ctime "
        "and unlink them after a full lease",
    )


# ---------------------------------------------------------------------------
# tenants: admission control under concurrency
# ---------------------------------------------------------------------------


def _tenant_throttle() -> Scenario:
    def setup(ctx: MCContext) -> None:
        from ...campaign.tenants import Tenant, TenantRegistry

        TenantRegistry(ROOT).create(
            Tenant(name="ten", token="tok-ten", max_running=1)
        )
        q = _queue()
        for jid in ("j1", "j2", "j3"):
            q.add_job(_job(jid, tenant="ten"))

    def worker(name: str, jid: str):
        def body(ctx: MCContext) -> None:
            ctx.out[name] = _queue().try_claim(jid, name) is not None

        return body

    def invariant(ctx: MCContext) -> None:
        claims = []
        for name in ctx.listdir(f"{_Q}/claims"):
            doc = ctx.read_json(f"{_Q}/claims/{name}")
            if doc is not None:
                claims.append(doc)
        require(
            1 <= len(claims) <= 2,
            f"tenant max_running=1 admitted {len(claims)} concurrent "
            "claims (the documented race window over-admits by at most "
            "one)",
        )
        # with >=1 published claim the tenant is at/over quota: the
        # next admission must throttle (fresh revalidation, no cache)
        require(
            _queue().try_claim("j3", "w3") is None,
            "a tenant at max_running quota was admitted another job "
            "(throttle revalidation failed to see published claims)",
        )

    return Scenario(
        name="tenant_throttle",
        rule="PSM307",
        module="peasoup_tpu/campaign/tenants.py",
        description="two claims race one tenant's max_running=1 quota",
        setup=setup,
        tasks=(
            ("w1", worker("w1", "j1"), False),
            ("w2", worker("w2", "j2"), False),
        ),
        invariant=invariant,
        max_kills=0,
        fix_hint="try_claim must revalidate tenant quotas after the "
        "O_EXCL create (fresh scan, not the cached throttle map) and "
        "abort the claim when the tenant is over quota",
    )


# ---------------------------------------------------------------------------
# alerts: evaluator lock + journal atomicity
# ---------------------------------------------------------------------------


def _engine(rules: list[dict] | None = None):
    from ...obs.alerts import AlertEngine

    return AlertEngine(ROOT, rules=rules if rules is not None else [])


_LOCK = f"{_Q}/alerts.lock"
_JOURNAL = f"{_Q}/alerts.jsonl"
_SNAPSHOT = f"{_Q}/alerts.json"


def _lock_depth_ok(ctx: MCContext) -> None:
    """Trace-ordered critical-section depth from alock-enter/exit
    marks must never exceed one (a killed holder leaves its section
    open — depth 1 — which is fine; overlap is not)."""
    depth = 0
    for e in ctx.env.trace:
        _, _, rest = e.partition(":")
        if rest.startswith("mark:alock-enter@"):
            depth += 1
            require(
                depth <= 1,
                "two evaluators inside the alerts critical section at "
                "once: the advisory lock failed while fresh",
            )
        elif rest.startswith("mark:alock-exit@"):
            depth -= 1


def _alerts_lock() -> Scenario:
    def setup(ctx: MCContext) -> None:
        del ctx

    def evaluator(ctx: MCContext) -> None:
        eng = _engine()
        if eng._acquire_lock(ctx.now()):
            ctx.mark("alock-enter")
            ctx.mark("alock-exit")
            eng._release_lock()

    def invariant(ctx: MCContext) -> None:
        _lock_depth_ok(ctx)
        if not _killed(ctx):
            require(
                not ctx.exists(_LOCK),
                "alerts lock leaked after both evaluators exited "
                "cleanly",
            )

    return Scenario(
        name="alerts_lock",
        rule="PSM308",
        module="peasoup_tpu/obs/alerts.py",
        description="two evaluators contend for a fresh alerts lock",
        setup=setup,
        tasks=(
            ("e1", evaluator, True),
            ("e2", evaluator, False),
        ),
        invariant=invariant,
        max_kills=1,
        fix_hint="a torn (empty) lock within the staleness window is a "
        "LIVE acquirer mid-publish: back off instead of taking over",
    )


def _alerts_release_race() -> Scenario:
    def setup(ctx: MCContext) -> None:
        del ctx

    def e1(ctx: MCContext) -> None:
        eng = _engine()
        got = eng._acquire_lock(ctx.now())
        ctx.out["got1"] = got
        if got:
            eng._release_lock()

    def e2(ctx: MCContext) -> None:
        ctx.advance(70)  # e1's lock (if held) is now legitimately stale
        eng = _engine()
        got = eng._acquire_lock(ctx.now())
        ctx.out["got2"] = got
        ctx.out["tok2"] = eng._lock_token  # holds; never releases

    def invariant(ctx: MCContext) -> None:
        if ctx.out.get("got2"):
            doc = ctx.read_json(_LOCK)
            require(
                doc is not None
                and doc.get("token") == ctx.out.get("tok2"),
                "the deposed evaluator's release clobbered the new "
                f"holder's lock (doc={doc}): mutual exclusion silently "
                "lapses for the next round",
            )

    return Scenario(
        name="alerts_release_race",
        rule="PSM308",
        module="peasoup_tpu/obs/alerts.py",
        description="stale-lock takeover races the old holder's release",
        setup=setup,
        tasks=(("e1", e1, False), ("e2", e2, False)),
        invariant=invariant,
        max_kills=0,
        fix_hint="release must rename the lock aside, verify the "
        "tombstone carries its own token, and link-restore a mismatch "
        "— never blind-unlink",
    )


def _alerts_journal() -> Scenario:
    rule = {
        "name": "sentinel_unrecovered",
        "kind": "sentinel",
        "severity": "page",
    }
    finding = {
        "labels": {"probe": "p1"},
        "value": 1.0,
        "message": "sentinel p1 unrecovered",
    }

    def setup(ctx: MCContext) -> None:
        del ctx

    def evaluator(ctx: MCContext) -> None:
        _engine([dict(rule)]).evaluate(
            samples={}, sentinel_findings=[dict(finding)]
        )

    def invariant(ctx: MCContext) -> None:
        raw = ctx.read(_JOURNAL) or ""
        firing = 0
        for line in raw.splitlines():
            try:
                t = json.loads(line)
            except json.JSONDecodeError:
                require(
                    False,
                    f"torn alerts journal line: {line[:80]!r} (append "
                    "must be all-or-nothing)",
                )
                return
            if t.get("to") == "firing":
                firing += 1
        require(
            firing <= 2,
            f"{firing} firing transitions for one alert episode",
        )
        if not _killed(ctx):
            require(
                firing == 1,
                f"{firing} firing transitions with both evaluators "
                "healthy (must be exactly one per episode)",
            )
            snap = ctx.read_json(_SNAPSHOT) or {}
            states = {
                (a.get("rule"), a.get("state"))
                for a in snap.get("alerts", [])
            }
            require(
                ("sentinel_unrecovered", "firing") in states,
                f"snapshot lost the firing alert: {sorted(states)}",
            )
            require(
                not ctx.exists(_LOCK),
                "alerts lock leaked after two clean evaluation rounds",
            )

    return Scenario(
        name="alerts_journal",
        rule="PSM308",
        module="peasoup_tpu/obs/alerts.py",
        description="two full evaluation rounds, one killable, race",
        setup=setup,
        tasks=(
            ("e1", evaluator, True),
            ("e2", evaluator, False),
        ),
        invariant=invariant,
        max_kills=1,
        fix_hint="transitions must append before the snapshot write, "
        "in one atomic append; the lock must serialize whole rounds",
    )


# ---------------------------------------------------------------------------
# the library + the engine entry point
# ---------------------------------------------------------------------------

_BUILDERS = (
    _claim_race,
    _claim_crash_reap,
    _renew_vs_reap,
    _release_vs_reap,
    _zombie_complete,
    _preempt_handoff,
    _gang_assembly,
    _gang_insufficient,
    _registry_group_survival,
    _registry_torn_entry,
    _tenant_throttle,
    _alerts_lock,
    _alerts_release_race,
    _alerts_journal,
)


def scenarios() -> tuple[Scenario, ...]:
    """The full drill library, in documentation order."""
    return tuple(b() for b in _BUILDERS)


def scenario_names() -> list[str]:
    return [s.name for s in scenarios()]


@dataclass
class MCReport:
    """One model-checking pass over (a subset of) the library."""

    scenarios: int = 0
    schedules: int = 0
    crash_points: int = 0
    reductions: int = 0
    dedup_hits: int = 0
    violations: int = 0
    per_scenario: list[dict] = field(default_factory=list)
    findings: list[Finding] = field(default_factory=list)

    def to_doc(self) -> dict:
        return {
            "scenarios": self.scenarios,
            "schedules": self.schedules,
            "crash_points": self.crash_points,
            "reductions": self.reductions,
            "dedup_hits": self.dedup_hits,
            "violations": self.violations,
            "per_scenario": self.per_scenario,
        }


def run_mc(
    names: list[str] | None = None,
    budget: int | None = None,
    por: bool = True,
) -> MCReport:
    """Model-check the scenario library (audit engine 5). ``names``
    selects a subset; ``budget`` caps schedules per scenario. Each
    violation is minimized to its shortest reproducing schedule and
    reported as a PSM3xx finding (PSM300 for internal task crashes /
    deadlocks — the checker eating its own exceptions is a finding
    too, never a silent pass)."""
    lib = scenarios()
    if names:
        known = {s.name: s for s in lib}
        unknown = [n for n in names if n not in known]
        if unknown:
            raise ValueError(
                f"unknown mc scenario(s) {unknown}; "
                f"known: {sorted(known)}"
            )
        lib = tuple(known[n] for n in names)
    report = MCReport()
    for s in lib:
        res = explore(s, budget=budget or DEFAULT_BUDGET, por=por)
        cps = enumerate_crash_points(s)
        report.scenarios += 1
        report.schedules += res.schedules
        report.crash_points += cps
        report.reductions += res.reductions
        report.dedup_hits += res.dedup_hits
        report.violations += len(res.violations)
        report.per_scenario.append({
            "name": s.name,
            "rule": s.rule,
            "schedules": res.schedules,
            "crash_points": cps,
            "reductions": res.reductions,
            "dedup_hits": res.dedup_hits,
            "exhausted": res.exhausted,
            "violations": len(res.violations),
        })
        for msg, chosen in res.violations:
            mini = minimize(s, chosen, msg)
            internal = msg.startswith("internal:")
            report.findings.append(
                Finding(
                    rule="PSM300" if internal else s.rule,
                    severity=SEV_ERROR,
                    path=s.module,
                    line=1,
                    col=0,
                    message=f"mc:{s.name}: {msg}",
                    fix_hint=s.fix_hint,
                    source_line=(
                        f"{s.name} schedule={schedule_to_str(mini)}"
                    ),
                )
            )
    return report
