"""Virtual filesystem + determinism seams for the model checker.

The modules under test are run unmodified: :func:`interpose` swaps
their module-level ``os``/``time``/``uuid``/``tempfile``/``socket``
references (and injects a module-global ``open``) for proxies bound
to one :class:`MCEnv`. Every filesystem operation funnels through
:meth:`MCEnv.op`, which — when a cooperative scheduler is active —
parks the calling task at a scheduling point before executing, so the
explorer controls exactly which process-step happens next.

Semantics modelled (the load-bearing subset of POSIX):

* ``os.open(path, O_CREAT|O_EXCL|O_WRONLY)`` creates the entry
  *immediately* (the O_EXCL race is visible to peers) but with empty
  content; writes buffer in the file object and **publish on close**.
  A crash between create and close therefore leaves a torn (empty)
  file — exactly the state the reap protocols must survive.
* File descriptors bind the *inode* (:class:`VFile`), not the path: a
  rename mid-write means close publishes into the renamed file, and
  an unlink mid-write orphans the data — both real POSIX behaviours
  the tombstone dances rely on.
* ``os.rename``/``os.replace`` overwrite the destination (POSIX
  rename) and bump the inode's **st_ctime but not st_mtime** — sweeps
  that age tombstones must use ``st_ctime``.
* ``os.link`` aliases the inode (``FileExistsError`` when the name
  exists) — the exactly-once publish primitive.
* Durability: content is volatile until ``os.fsync``;
  :meth:`VirtualFS.host_crash` drops never-synced files and reverts
  synced ones to their last-synced content. Name-space metadata
  (renames) is treated as journaled.

The virtual clock never ticks on its own — it advances only through
an explicit ``advance`` scheduling op — so identical schedules
produce bit-identical traces and state hashes dedup across runs.
"""

from __future__ import annotations

import hashlib
import os as _real_os
import posixpath
import types
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Callable, Iterator

if TYPE_CHECKING:  # pragma: no cover - import cycle guard
    from .scheduler import Scheduler

# op kinds that mutate the namespace/content they touch
_MUTATORS = frozenset(
    {"create", "publish", "unlink", "rename", "link", "fsync", "append"}
)
# op kinds that conflict with everything (time reads are ambient; marks
# delimit invariant-visible critical sections)
_GLOBAL = frozenset({"advance", "mark"})
# inode-bound ops: their descriptor names the *open-time* path, which a
# concurrent rename can make stale — conservatively conflict with any
# namespace edit
_INODE_BOUND = frozenset({"publish", "fsync"})
_NAMESPACE = frozenset({"rename", "link", "unlink", "create"})


@dataclass(frozen=True)
class OpDesc:
    """One filesystem operation, as the scheduler/explorer see it."""

    kind: str
    path: str
    reads: frozenset[str] = frozenset()
    writes: frozenset[str] = frozenset()
    lists: str | None = None

    @property
    def key(self) -> str:
        return f"{self.kind}:{self.path}"


def conflicts(a: OpDesc, b: OpDesc) -> bool:
    """May the order of ``a`` and ``b`` matter? (Used by the partial-
    order reduction; conservative = sound, just less reduction.)"""
    if a.kind in _GLOBAL or b.kind in _GLOBAL:
        return True
    if (a.kind in _INODE_BOUND and b.kind in _NAMESPACE | _INODE_BOUND) or (
        b.kind in _INODE_BOUND and a.kind in _NAMESPACE | _INODE_BOUND
    ):
        return True
    if a.writes & (b.reads | b.writes) or b.writes & (a.reads | a.writes):
        return True
    for lister, other in ((a, b), (b, a)):
        if lister.lists is not None and any(
            posixpath.dirname(p) == lister.lists
            or p.startswith(lister.lists + "/")
            for p in other.writes
        ):
            return True
    return False


class VFile:
    """One inode: live content + last-fsynced content + POSIX times."""

    __slots__ = ("content", "durable", "ctime", "mtime")

    def __init__(self, now: float) -> None:
        self.content = ""
        self.durable: str | None = None
        self.ctime = now
        self.mtime = now


class VirtualFS:
    """Path -> :class:`VFile`. Directories are implicit (``makedirs``
    is a no-op; ``listdir`` of an absent dir is empty)."""

    def __init__(self) -> None:
        self.files: dict[str, VFile] = {}

    # -- queries ------------------------------------------------------
    def exists(self, path: str) -> bool:
        if path in self.files:
            return True
        prefix = path.rstrip("/") + "/"
        return any(p.startswith(prefix) for p in self.files)

    def read(self, path: str) -> str:
        vf = self.files.get(path)
        if vf is None:
            raise FileNotFoundError(2, "No such file or directory", path)
        return vf.content

    def listdir(self, path: str) -> list[str]:
        d = path.rstrip("/")
        out = set()
        for p in self.files:
            if posixpath.dirname(p) == d:
                out.add(posixpath.basename(p))
            elif p.startswith(d + "/"):
                out.add(p[len(d) + 1 :].split("/", 1)[0])
        return sorted(out)

    def stat(self, path: str) -> Any:
        vf = self.files.get(path)
        if vf is None:
            if self.exists(path):  # implicit directory
                return types.SimpleNamespace(
                    st_ctime=0.0, st_mtime=0.0, st_size=0
                )
            raise FileNotFoundError(2, "No such file or directory", path)
        return types.SimpleNamespace(
            st_ctime=vf.ctime, st_mtime=vf.mtime, st_size=len(vf.content)
        )

    # -- mutations ----------------------------------------------------
    def create(self, path: str, now: float, excl: bool) -> VFile:
        vf = self.files.get(path)
        if vf is not None:
            if excl:
                raise FileExistsError(17, "File exists", path)
            vf.content = ""
            vf.durable = None
            vf.ctime = vf.mtime = now
            return vf
        vf = VFile(now)
        self.files[path] = vf
        return vf

    def publish(self, vf: VFile, data: str, now: float) -> None:
        vf.content = data
        vf.mtime = now
        vf.ctime = now

    def unlink(self, path: str) -> None:
        if path not in self.files:
            raise FileNotFoundError(2, "No such file or directory", path)
        del self.files[path]

    def rename(self, src: str, dst: str, now: float) -> None:
        vf = self.files.pop(src, None)
        if vf is None:
            raise FileNotFoundError(2, "No such file or directory", src)
        vf.ctime = now  # POSIX: rename bumps ctime, NOT mtime
        self.files[dst] = vf

    def link(self, src: str, dst: str, now: float) -> None:
        vf = self.files.get(src)
        if vf is None:
            raise FileNotFoundError(2, "No such file or directory", src)
        if dst in self.files:
            raise FileExistsError(17, "File exists", dst)
        vf.ctime = now
        self.files[dst] = vf

    def fsync(self, vf: VFile) -> None:
        vf.durable = vf.content

    def host_crash(self) -> None:
        """Power loss: never-synced files vanish, synced ones revert
        to their last-synced content. Renames (metadata) survive."""
        for path in list(self.files):
            vf = self.files[path]
            if vf.durable is None:
                del self.files[path]
            else:
                vf.content = vf.durable


@dataclass
class _PendingWrite:
    """An open-for-write fd: buffered until close publishes."""

    fd: int
    vf: VFile
    path: str
    base: str = ""  # existing content for "a" mode
    buf: list[str] = field(default_factory=list)
    closed: bool = False


class MCEnv:
    """One model-checking universe: the VFS, the virtual clock, the
    deterministic id counters, the op trace, and the proxy objects
    :func:`interpose` injects into the modules under test."""

    def __init__(self) -> None:
        self.fs = VirtualFS()
        self.clock = 1_000_000.0
        self.skew: dict[str, float] = {}  # task name -> seconds
        self.uuid_n = 0
        self.tmp_n = 0
        self.scheduler: Scheduler | None = None
        self.trace: list[str] = []
        # every executed op's (task, descriptor), in execution order —
        # the partial-order reduction's view of each task's footprint
        self.ops: list[tuple[str, OpDesc]] = []
        self._pending: dict[int, _PendingWrite] = {}
        self._next_fd = 100
        self.os = VirtualOS(self)
        self.time = VirtualTime(self)
        self.uuid = VirtualUuid(self)
        self.tempfile = VirtualTempfile(self)
        self.socket = VirtualSocket(self)
        self.open = VirtualOpen(self)

    # -- scheduling seam ---------------------------------------------
    def op(self, desc: OpDesc, fn: Callable[[], Any]) -> Any:
        """Every FS operation funnels through here. With a scheduler
        active and the caller on a task thread, park at a scheduling
        point first; otherwise (setup / invariant phases) execute
        directly."""
        sch = self.scheduler
        task = sch.current_task() if sch is not None else None
        if task is None or sch is None:
            out = fn()
            self.trace.append(f"-:{desc.key}")
            self.ops.append(("-", desc))
            return out
        return sch.perform(task, desc, fn)

    def task_name(self) -> str:
        sch = self.scheduler
        task = sch.current_task() if sch is not None else None
        return task.name if task is not None else "-"

    def task_pid(self) -> int:
        sch = self.scheduler
        task = sch.current_task() if sch is not None else None
        return task.pid if task is not None else 1

    def now(self) -> float:
        """Skew-adjusted clock for the *calling task* (``time.time``
        through the proxy). File times always use the unskewed
        :attr:`clock` — the filesystem server's clock."""
        return self.clock + self.skew.get(self.task_name(), 0.0)

    def state_hash(self) -> str:
        """Content-addressed state: VFS + clock + id counters + each
        task's (status, op-history hash). Tasks are deterministic
        functions of their FS interaction history, so two runs that
        agree on this hash are in bisimilar states — the explorer
        dedups branches on it."""
        h = hashlib.sha1()
        h.update(
            f"c={self.clock!r};u={self.uuid_n};t={self.tmp_n};".encode()
        )
        for path, vf in sorted(self.fs.files.items()):
            h.update(
                f"{path}|{vf.content}|{vf.durable is not None}"
                f"|{vf.ctime!r}|{vf.mtime!r};".encode()
            )
        if self.scheduler is not None:
            for t in self.scheduler.tasks:
                h.update(f"{t.name}={t.status}:{t.hseq};".encode())
        return h.hexdigest()[:16]

    # -- fd plumbing --------------------------------------------------
    def new_fd(self, vf: VFile, path: str, base: str = "") -> int:
        fd = self._next_fd
        self._next_fd += 1
        self._pending[fd] = _PendingWrite(fd, vf, path, base=base)
        return fd


class VirtualWriteFile:
    """Write handle: buffers everything; close = the publish op."""

    def __init__(self, env: MCEnv, pending: _PendingWrite) -> None:
        self._env = env
        self._p = pending

    def write(self, s: str) -> int:
        self._p.buf.append(s)
        return len(s)

    def flush(self) -> None:
        pass

    def fileno(self) -> int:
        return self._p.fd

    @property
    def closed(self) -> bool:
        return self._p.closed

    def close(self) -> None:
        p = self._p
        if p.closed:
            return
        p.closed = True
        env = self._env
        env._pending.pop(p.fd, None)

        def fn() -> None:
            env.fs.publish(p.vf, p.base + "".join(p.buf), env.clock)

        env.op(
            OpDesc("publish", p.path, writes=frozenset({p.path})), fn
        )

    def __enter__(self) -> "VirtualWriteFile":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class VirtualReadFile:
    """Read handle over a content snapshot taken at the open op."""

    def __init__(self, content: str) -> None:
        self._content = content
        self._pos = 0

    def read(self, n: int = -1) -> str:
        if n < 0:
            out = self._content[self._pos :]
            self._pos = len(self._content)
            return out
        out = self._content[self._pos : self._pos + n]
        self._pos += len(out)
        return out

    def readlines(self) -> list[str]:
        return self.read().splitlines(keepends=True)

    def __iter__(self) -> Iterator[str]:
        return iter(self.readlines())

    def close(self) -> None:
        pass

    def __enter__(self) -> "VirtualReadFile":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class VirtualOpen:
    """The module-global ``open`` injected by :func:`interpose`."""

    def __init__(self, env: MCEnv) -> None:
        self._env = env

    def __call__(self, path: str, mode: str = "r", **kw: Any) -> Any:
        env = self._env
        if mode in ("r", "rt"):

            def rd() -> str:
                return env.fs.read(path)

            content = env.op(
                OpDesc("read", path, reads=frozenset({path})), rd
            )
            return VirtualReadFile(content)
        if mode in ("w", "wt"):

            def mk() -> int:
                vf = env.fs.create(path, env.clock, excl=False)
                return env.new_fd(vf, path)

            fd = env.op(
                OpDesc("create", path, writes=frozenset({path})), mk
            )
            return VirtualWriteFile(env, env._pending[fd])
        if mode in ("a", "at"):

            def ap() -> int:
                vf = env.fs.files.get(path)
                base = vf.content if vf is not None else ""
                if vf is None:
                    vf = env.fs.create(path, env.clock, excl=False)
                return env.new_fd(vf, path, base=base)

            fd = env.op(
                OpDesc(
                    "append",
                    path,
                    reads=frozenset({path}),
                    writes=frozenset({path}),
                ),
                ap,
            )
            return VirtualWriteFile(env, env._pending[fd])
        raise NotImplementedError(f"mc vfs: open mode {mode!r}")


class VirtualPath:
    """``os.path`` proxy: pure lexical helpers delegate to posixpath;
    ``exists`` is a real (scheduled) FS op."""

    sep = "/"

    def __init__(self, env: MCEnv) -> None:
        self._env = env

    def join(self, *parts: str) -> str:
        return posixpath.join(*parts)

    def dirname(self, p: str) -> str:
        return posixpath.dirname(p)

    def basename(self, p: str) -> str:
        return posixpath.basename(p)

    def normpath(self, p: str) -> str:
        return posixpath.normpath(p)

    def splitext(self, p: str) -> tuple[str, str]:
        return posixpath.splitext(p)

    def abspath(self, p: str) -> str:
        return posixpath.normpath(p if p.startswith("/") else "/" + p)

    def isabs(self, p: str) -> bool:
        return p.startswith("/")

    def exists(self, p: str) -> bool:
        env = self._env
        return bool(
            env.op(
                OpDesc("exists", p, reads=frozenset({p})),
                lambda: env.fs.exists(p),
            )
        )

    def isdir(self, p: str) -> bool:
        env = self._env
        return bool(
            env.op(
                OpDesc("exists", p, reads=frozenset({p})),
                lambda: env.fs.exists(p) and p not in env.fs.files,
            )
        )

    def isfile(self, p: str) -> bool:
        env = self._env
        return bool(
            env.op(
                OpDesc("exists", p, reads=frozenset({p})),
                lambda: p in env.fs.files,
            )
        )


class VirtualOS:
    """``os`` proxy covering the protocol modules' op surface."""

    O_CREAT = _real_os.O_CREAT
    O_EXCL = _real_os.O_EXCL
    O_WRONLY = _real_os.O_WRONLY
    O_RDONLY = _real_os.O_RDONLY
    O_RDWR = _real_os.O_RDWR
    O_APPEND = _real_os.O_APPEND
    O_TRUNC = _real_os.O_TRUNC
    sep = "/"
    environ = _real_os.environ  # read-only config peeks

    def __init__(self, env: MCEnv) -> None:
        self._env = env
        self.path = VirtualPath(env)

    # -- fd ops -------------------------------------------------------
    def open(self, path: str, flags: int, mode: int = 0o600) -> int:
        env = self._env
        if not (flags & self.O_CREAT) or not (flags & self.O_EXCL):
            raise NotImplementedError(
                f"mc vfs: os.open flags {flags:#x} (only O_CREAT|O_EXCL)"
            )

        def fn() -> int:
            vf = env.fs.create(path, env.clock, excl=True)
            return env.new_fd(vf, path)

        return int(
            env.op(OpDesc("create", path, writes=frozenset({path})), fn)
        )

    def fdopen(self, fd: int, mode: str = "w", **kw: Any) -> Any:
        if not mode.startswith("w"):
            raise NotImplementedError(f"mc vfs: fdopen mode {mode!r}")
        return VirtualWriteFile(self._env, self._env._pending[fd])

    def close(self, fd: int) -> None:
        # abandoning an fd publishes nothing (the torn-file model);
        # not a scheduling point — the visible op is what follows
        self._env._pending.pop(fd, None)

    def fsync(self, fd: int) -> None:
        env = self._env
        p = env._pending[fd]

        def fn() -> None:
            env.fs.publish(p.vf, p.base + "".join(p.buf), env.clock)
            env.fs.fsync(p.vf)

        env.op(OpDesc("fsync", p.path, writes=frozenset({p.path})), fn)

    # -- namespace ops ------------------------------------------------
    def unlink(self, path: str) -> None:
        env = self._env
        env.op(
            OpDesc("unlink", path, writes=frozenset({path})),
            lambda: env.fs.unlink(path),
        )

    remove = unlink

    def rename(self, src: str, dst: str) -> None:
        # desc path = destination: the published/tombstone name is what
        # invariants count; the source is still in ``writes`` for POR
        env = self._env
        env.op(
            OpDesc("rename", dst, writes=frozenset({src, dst})),
            lambda: env.fs.rename(src, dst, env.clock),
        )

    replace = rename  # POSIX rename overwrites

    def link(self, src: str, dst: str) -> None:
        env = self._env
        env.op(
            OpDesc(
                "link",
                dst,
                reads=frozenset({src}),
                writes=frozenset({src, dst}),
            ),
            lambda: env.fs.link(src, dst, env.clock),
        )

    def listdir(self, path: str) -> list[str]:
        env = self._env
        out = env.op(
            OpDesc("listdir", path, lists=path),
            lambda: env.fs.listdir(path),
        )
        return list(out)

    def stat(self, path: str) -> Any:
        env = self._env
        return env.op(
            OpDesc("stat", path, reads=frozenset({path})),
            lambda: env.fs.stat(path),
        )

    def makedirs(self, path: str, exist_ok: bool = False) -> None:
        # directories are implicit; deliberately not a scheduling point
        del path, exist_ok

    # -- process identity ---------------------------------------------
    def getpid(self) -> int:
        return self._env.task_pid()


class VirtualTime:
    """``time`` proxy: the virtual clock plus the caller's skew. Not a
    scheduling point — the clock only changes at explicit ``advance``
    ops, so reads between ops are deterministic."""

    def __init__(self, env: MCEnv) -> None:
        self._env = env

    def time(self) -> float:
        return self._env.now()

    def monotonic(self) -> float:
        return self._env.now()

    def sleep(self, s: float) -> None:
        del s  # virtual time does not pass while "sleeping"


class _FakeUuid:
    __slots__ = ("hex",)

    def __init__(self, hex_: str) -> None:
        self.hex = hex_

    def __str__(self) -> str:
        return self.hex


class VirtualUuid:
    """``uuid`` proxy: a deterministic counter. The counter repeats in
    every 8-hex-char block so the protocols' ``hex[:8]``/``hex[:12]``
    truncations stay unique — real uuid prefixes never collide, and a
    modelled collision would fault the tombstone dances for a reason
    the real system can't exhibit."""

    def __init__(self, env: MCEnv) -> None:
        self._env = env

    def uuid4(self) -> _FakeUuid:
        n = self._env.uuid_n
        self._env.uuid_n += 1
        return _FakeUuid(f"{n:08x}" * 4)


class VirtualTempfile:
    """``tempfile`` proxy: counter-named files in the target dir."""

    def __init__(self, env: MCEnv) -> None:
        self._env = env

    def mkstemp(
        self,
        suffix: str = "",
        prefix: str = "tmp",
        dir: str | None = None,
        text: bool = False,
    ) -> tuple[int, str]:
        del text
        env = self._env
        name = posixpath.join(
            dir or "/tmp", f"{prefix}{env.tmp_n:04d}{suffix}"
        )
        env.tmp_n += 1

        def fn() -> int:
            vf = env.fs.create(name, env.clock, excl=True)
            return env.new_fd(vf, name)

        fd = env.op(OpDesc("create", name, writes=frozenset({name})), fn)
        return int(fd), name


class VirtualSocket:
    def __init__(self, env: MCEnv) -> None:
        del env

    def gethostname(self) -> str:
        return "mc"


_SEAMS = ("os", "time", "uuid", "tempfile", "socket")
_MISSING = object()


@contextmanager
def interpose(env: MCEnv, modules: tuple[Any, ...]) -> Iterator[MCEnv]:
    """Swap each module's stdlib seams for ``env``'s proxies (and
    shadow the ``open`` builtin with a module global — module-global
    lookup beats builtins). Restores everything on exit, even when the
    run raises."""
    saved: list[tuple[Any, str, Any]] = []
    try:
        for mod in modules:
            for name in _SEAMS:
                cur = getattr(mod, name, _MISSING)
                if not isinstance(cur, types.ModuleType):
                    continue
                saved.append((mod, name, cur))
                setattr(mod, name, getattr(env, name))
            cur_open = mod.__dict__.get("open", _MISSING)
            saved.append((mod, "open", cur_open))
            mod.open = env.open
        yield env
    finally:
        for mod, name, cur in reversed(saved):
            if cur is _MISSING:
                try:
                    delattr(mod, name)
                except AttributeError:
                    pass
            else:
                setattr(mod, name, cur)
