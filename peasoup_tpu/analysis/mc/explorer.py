"""Interleaving exploration: replay-based DFS with state-hash dedup
and conflict-based partial-order reduction.

A *schedule* is a sequence of tokens consumed only at **decision
points** — scheduler states with more than one grantable token. A run
executes its forced schedule prefix and continues with the default
policy (lowest-index task, never a kill), recording every decision
point's enabled set, op descriptors and state hash. The explorer then
branches: for each free decision it pushes ``prefix + alternative``,
pruning alternatives that

* start from an already-explored ``(state-hash, token)`` pair — tasks
  are deterministic functions of their op history, so equal hashes
  mean equal futures (``dedup``); or
* are *independent* of every other enabled op (disjoint paths, no
  listdir-vs-entry mutation, no clock/kill/inode hazards) AND whose
  task's remaining footprint — its ops later in this very run — never
  conflicts with another task's (the dynamic-POR condition: a task
  whose future touches contended paths must be explored early, or the
  orderings where it wins the race are silently lost). Heuristic —
  futures are taken from the observed run, not all runs — backstopped
  by dedup and spot-checked against ``por=False``.

Violations carry the decision sequence; :func:`minimize` shrinks it
to the shortest prefix that still reproduces, and :func:`replay` runs
a schedule string bit-identically (same trace, same violation).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from .invariants import InvariantViolation, MCContext
from .scheduler import MCDeadlock, MCTask, Scheduler
from .vfs import MCEnv, OpDesc, conflicts, interpose

DEFAULT_BUDGET = 400  # schedules per scenario


class ScheduleError(Exception):
    """A replayed schedule diverged from the recorded decisions."""


@dataclass(frozen=True)
class Scenario:
    """One protocol drill: setup, concurrent tasks, and an invariant
    checked after every complete interleaving."""

    name: str
    rule: str  # PSM3xx finding rule id
    module: str  # repo-relative path the finding anchors to
    description: str
    setup: Callable[[MCContext], None]
    tasks: tuple[tuple[str, Callable[[MCContext], Any], bool], ...]
    invariant: Callable[[MCContext], None]
    max_kills: int = 1
    skews: dict[str, float] = field(default_factory=dict)
    fix_hint: str = ""


@dataclass
class Decision:
    chosen: str
    enabled: tuple[str, ...]
    ops: dict[str, OpDesc | None]
    state: str
    n_ops: int = 0  # executed-op count at decision time


@dataclass
class RunResult:
    schedule: tuple[str, ...]  # forced prefix actually consumed
    decisions: list[Decision]
    trace: list[str]
    violation: str | None
    internal: bool  # PSM300-class (task crash / deadlock)
    tasks: dict[str, str]  # task name -> final status
    ops: list[tuple[str, OpDesc]] = field(default_factory=list)

    @property
    def chosen(self) -> tuple[str, ...]:
        return tuple(d.chosen for d in self.decisions)


def _tok_key(tok: str) -> tuple[bool, int]:
    return (tok.startswith("K"), int(tok.lstrip("K")))


def _default_pick(tokens: list[str]) -> str:
    return next(t for t in tokens if not t.startswith("K"))


def target_modules() -> tuple[Any, ...]:
    """The modules whose stdlib seams get interposed: the four
    protocol modules plus ``obs.trace`` (deterministic trace ids)."""
    from ...campaign import queue as qmod
    from ...campaign import registry as rmod
    from ...campaign import tenants as tmod
    from ...obs import alerts as amod
    from ...obs import trace as trmod

    return (qmod, rmod, tmod, amod, trmod)


def run_schedule(
    scenario: Scenario, schedule: tuple[str, ...] = ()
) -> RunResult:
    """Execute one interleaving: forced ``schedule`` prefix at the
    decision points, default policy afterwards."""
    env = MCEnv()
    for name, _, _ in scenario.tasks:
        env.skew[name] = scenario.skews.get(name, 0.0)
    ctx = MCContext(env=env)
    violation: str | None = None
    internal = False
    decisions: list[Decision] = []
    consumed = 0
    with interpose(env, target_modules()):
        scenario.setup(ctx)
        sch = Scheduler(env, max_kills=scenario.max_kills)
        env.scheduler = sch
        tasks = [
            MCTask(i, name, (lambda fn=fn: fn(ctx)), killable)
            for i, (name, fn, killable) in enumerate(scenario.tasks)
        ]
        try:
            sch.start(tasks)
            while True:
                en = sch.enabled()
                if not en:
                    break
                toks = sorted(en, key=_tok_key)
                if len(toks) == 1:
                    sch.grant(toks[0])
                    continue
                if consumed < len(schedule):
                    tok = schedule[consumed]
                    consumed += 1
                    if tok not in en:
                        raise ScheduleError(
                            f"{scenario.name}: token {tok!r} not "
                            f"enabled (enabled={toks})"
                        )
                else:
                    tok = _default_pick(toks)
                decisions.append(
                    Decision(
                        tok,
                        tuple(toks),
                        dict(en),
                        env.state_hash(),
                        len(env.ops),
                    )
                )
                sch.grant(tok)
        except MCDeadlock as e:
            violation = f"internal: {e}"
            internal = True
        finally:
            env.scheduler = None
            sch.shutdown()
        if violation is None:
            for t in tasks:
                if t.status == "error":
                    violation = (
                        f"internal: task {t.name} raised "
                        f"{type(t.error).__name__}: {t.error}"
                    )
                    internal = True
                    break
        if violation is None:
            try:
                scenario.invariant(ctx)
            except InvariantViolation as e:
                violation = str(e)
    return RunResult(
        schedule=tuple(schedule[:consumed]),
        decisions=decisions,
        trace=list(env.trace),
        violation=violation,
        internal=internal,
        tasks={t.name: t.status for t in tasks},
        ops=list(env.ops),
    )


def _por_prunable(
    alt: str,
    d: Decision,
    names: list[str],
    run_ops: list[tuple[str, OpDesc]],
) -> bool:
    """May branch ``alt`` be skipped at this decision? Only when its
    op is independent of every *other* enabled op (kills and global
    ops always conflict) AND — the dynamic condition — the task's
    remaining footprint in this run never conflicts with another
    task's. Without the future check, deferring a task whose *next*
    op is an innocent read also defers its contended write, and the
    interleavings where it wins that race are never generated."""
    op_a = d.ops.get(alt)
    if op_a is None:  # kill token: never prune
        return False
    for tok in d.enabled:
        if tok == alt:
            continue
        op_b = d.ops.get(tok)
        if op_b is None or conflicts(op_a, op_b):
            return False
    me = names[int(alt)]
    future = run_ops[d.n_ops :]
    mine = [op_a] + [op for who, op in future if who == me]
    others = [op for who, op in future if who not in ("-", me)]
    return not any(
        conflicts(x, y) for x in mine for y in others
    )


@dataclass
class ExploreResult:
    scenario: str
    schedules: int = 0
    dedup_hits: int = 0
    reductions: int = 0
    crash_points: int = 0
    exhausted: bool = False
    # distinct violation messages with the decision sequence that
    # produced them, in discovery order
    violations: list[tuple[str, tuple[str, ...]]] = field(
        default_factory=list
    )
    first: RunResult | None = None


def explore(
    scenario: Scenario,
    budget: int | None = None,
    por: bool = True,
    stop_on_first: bool = True,
) -> ExploreResult:
    """DFS over schedule prefixes up to ``budget`` runs."""
    limit = budget or DEFAULT_BUDGET
    names = [name for name, _, _ in scenario.tasks]
    seen: set[tuple[str, str]] = set()
    stack: list[tuple[str, ...]] = [()]
    res = ExploreResult(scenario.name)
    msgs: set[str] = set()
    while stack and res.schedules < limit:
        sched = stack.pop()
        run = run_schedule(scenario, sched)
        res.schedules += 1
        if run.violation is not None and run.violation not in msgs:
            msgs.add(run.violation)
            res.violations.append((run.violation, run.chosen))
            if res.first is None:
                res.first = run
            if stop_on_first:
                return res
        k = len(run.schedule)  # forced prefix = first k decisions
        for i in range(k, len(run.decisions)):
            d = run.decisions[i]
            seen.add((d.state, d.chosen))
            prefix = run.chosen[:i]
            for alt in d.enabled:
                if alt == d.chosen:
                    continue
                if (d.state, alt) in seen:
                    res.dedup_hits += 1
                    continue
                if por and _por_prunable(alt, d, names, run.ops):
                    res.reductions += 1
                    continue
                seen.add((d.state, alt))
                stack.append(prefix + (alt,))
    res.exhausted = not stack
    return res


def minimize(
    scenario: Scenario, chosen: tuple[str, ...], message: str
) -> tuple[str, ...]:
    """Shortest prefix of the violating decision sequence that still
    reproduces ``message`` under default-policy continuation."""
    for n in range(len(chosen) + 1):
        if run_schedule(scenario, chosen[:n]).violation == message:
            return tuple(chosen[:n])
    return tuple(chosen)


def schedule_to_str(schedule: tuple[str, ...]) -> str:
    return ".".join(schedule) if schedule else "-"


def str_to_schedule(s: str) -> tuple[str, ...]:
    s = s.strip()
    if not s or s == "-":
        return ()
    return tuple(tok for tok in s.split(".") if tok)


def replay(scenario: Scenario, schedule_str: str) -> RunResult:
    """Run a recorded schedule string (as embedded in a PSM finding's
    ``source_line``) — deterministic: two replays produce identical
    traces."""
    return run_schedule(scenario, str_to_schedule(schedule_str))
