"""Cooperative scheduler: one runnable simulated worker at a time.

Each scenario task runs the real module code on its own (daemon)
thread, but only one thread is ever runnable: a task parks at every
FS operation (:meth:`Scheduler.perform`) and the explorer *grants*
exactly one parked task per step. Event-pair handshakes — never
locks — serialize the exchange, so the module code under test
executes single-threaded and deterministically.

Crash injection: granting a ``K<i>`` token marks task *i* killed and
wakes it; the parked op raises :class:`~..resilience.errors.
WorkerKilled` (a ``BaseException``) *before executing*, and every
subsequent FS op of that task raises again without parking. Cleanup
handlers therefore cannot mutate shared state — the SIGKILL model —
and unwinding can never deadlock the scheduler.
"""

from __future__ import annotations

import threading
from hashlib import sha1
from typing import Any, Callable

from ...resilience.errors import WorkerKilled
from .vfs import MCEnv, OpDesc


class MCDeadlock(Exception):
    """Module code blocked without reaching an FS op (internal)."""


class _MCAbort(BaseException):
    """Run teardown: unwind a parked task without side effects."""


def _hchain(prev: str, item: str) -> str:
    return sha1(f"{prev}|{item}".encode()).hexdigest()[:16]


class MCTask:
    """One simulated worker process."""

    def __init__(
        self,
        index: int,
        name: str,
        fn: Callable[[], Any],
        killable: bool = False,
    ) -> None:
        self.index = index
        self.name = name
        self.fn = fn
        self.killable = killable
        self.status = "new"  # new|parked|running|done|killed|error|aborted
        self.killed = False
        self.aborted = False
        self.pending: tuple[OpDesc, Callable[[], Any]] | None = None
        self.error: BaseException | None = None
        self.result: Any = None
        self.hseq = "0"  # running hash of this task's op history
        self.pid = 1000 + index
        self._go = threading.Event()
        self._thread: threading.Thread | None = None


class Scheduler:
    """Drives :class:`MCTask` threads one granted step at a time."""

    def __init__(
        self, env: MCEnv, max_kills: int = 1, timeout_s: float = 30.0
    ) -> None:
        self.env = env
        self.tasks: list[MCTask] = []
        self.max_kills = max_kills
        self.kills_used = 0
        self._control = threading.Event()
        self._by_ident: dict[int, MCTask] = {}
        self._timeout = timeout_s

    # -- task-thread side ---------------------------------------------
    def current_task(self) -> MCTask | None:
        return self._by_ident.get(threading.get_ident())

    def perform(
        self, task: MCTask, desc: OpDesc, fn: Callable[[], Any]
    ) -> Any:
        """Called (via :meth:`MCEnv.op`) from the task's own thread:
        park, wait for a grant, then execute the op in place."""
        if task.killed:
            raise WorkerKilled(f"mc: {task.name} killed")
        if task.aborted:
            raise _MCAbort()
        task.pending = (desc, fn)
        task.status = "parked"
        self._control.set()
        task._go.wait()
        task._go.clear()
        task.pending = None
        if task.killed:
            self.env.trace.append(f"{task.name}:KILLED:{desc.key}")
            task.hseq = _hchain(task.hseq, f"KILLED:{desc.key}")
            raise WorkerKilled(f"mc: {task.name} killed at {desc.key}")
        if task.aborted:
            raise _MCAbort()
        task.status = "running"
        self.env.ops.append((task.name, desc))
        try:
            out = fn()
        except BaseException as e:
            self.env.trace.append(
                f"{task.name}:{desc.key}!{type(e).__name__}"
            )
            task.hseq = _hchain(
                task.hseq, f"{desc.key}!{type(e).__name__}"
            )
            raise
        self.env.trace.append(f"{task.name}:{desc.key}")
        task.hseq = _hchain(task.hseq, desc.key)
        return out

    def _task_main(self, task: MCTask) -> None:
        self._by_ident[threading.get_ident()] = task
        task.status = "running"
        try:
            task.result = task.fn()
            task.status = "done"
        except WorkerKilled:
            task.status = "killed"
        except _MCAbort:
            task.status = "aborted"
        except BaseException as e:  # noqa: BLE001 - reported as PSM300
            task.error = e
            task.status = "error"
        finally:
            self._control.set()

    # -- explorer side ------------------------------------------------
    def start(self, tasks: list[MCTask]) -> None:
        """Spawn the task threads one at a time, each running freely
        until its first FS op (or completion) — sequential start keeps
        even pre-op Python code single-threaded."""
        self.tasks = list(tasks)
        for t in self.tasks:
            # audit: ignore[PSA009] -- explorer-thread-only access; the
            # clear/set pair on the (itself thread-safe) Event IS the
            # handshake that keeps every other access single-threaded
            self._control.clear()
            # audit: ignore[PSP104] -- cooperative mc worker thread: the
            # scheduler owns its lifecycle and joins it at shutdown
            t._thread = threading.Thread(
                target=self._task_main,
                args=(t,),
                name=f"mc-{t.name}",
                daemon=True,
            )
            t._thread.start()
            self._wait_control()

    def _wait_control(self) -> None:
        if not self._control.wait(self._timeout):
            raise MCDeadlock(
                "module code blocked without reaching an FS op"
            )

    def enabled(self) -> dict[str, OpDesc | None]:
        """Grantable tokens: ``"<i>"`` per parked task, plus ``"K<i>"``
        when that task is killable and the kill budget remains."""
        out: dict[str, OpDesc | None] = {}
        for t in self.tasks:
            if t.status == "parked" and t.pending is not None:
                out[str(t.index)] = t.pending[0]
                if (
                    t.killable
                    and not t.killed
                    and self.kills_used < self.max_kills
                ):
                    out[f"K{t.index}"] = None
        return out

    def grant(self, token: str) -> None:
        """Wake one parked task (optionally killing it first) and wait
        until it parks again or finishes."""
        if token.startswith("K"):
            task = self.tasks[int(token[1:])]
            task.killed = True
            # audit: ignore[PSA009] -- only the explorer thread grants
            self.kills_used += 1
        else:
            task = self.tasks[int(token)]
        # audit: ignore[PSA009] -- explorer-thread-only: cleared while
        # every task thread is parked on its own _go event
        self._control.clear()
        task._go.set()
        self._wait_control()

    def shutdown(self) -> None:
        """Abort any still-parked tasks (deadlock/early-stop paths)
        and join every thread."""
        for t in self.tasks:
            if t.status == "parked":
                t.aborted = True
                t._go.set()
        for t in self.tasks:
            if t._thread is not None:
                t._thread.join(timeout=5.0)
        # audit: ignore[PSA009] -- all task threads joined above
        self._by_ident.clear()
