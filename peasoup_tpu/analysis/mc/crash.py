"""Crash-point bookkeeping.

Kill tokens (``K<i>``) ride the ordinary decision machinery — a
killable task parked at *any* FS op can be granted a kill instead,
which models SIGKILL between any two filesystem operations — so the
explorer needs no special crash pass. This module only quantifies
the injection surface for the report and the tests.
"""

from __future__ import annotations

from .explorer import Scenario, run_schedule


def is_kill(token: str) -> bool:
    return token.startswith("K")


def kill_target(token: str) -> int:
    return int(token[1:])


def enumerate_crash_points(scenario: Scenario) -> int:
    """How many distinct kill injection points the scenario exposes:
    every FS op a killable task executes in the crash-free baseline
    run is a state the explorer can kill it in instead."""
    killable = {name for name, _, k in scenario.tasks if k}
    if not killable or scenario.max_kills <= 0:
        return 0
    base = run_schedule(scenario, ())
    n = 0
    for entry in base.trace:
        who, _, rest = entry.partition(":")
        if who in killable and not rest.startswith("KILLED:"):
            n += 1
    return n
