"""The JAX/TPU hazard rules (PSA001-PSA010).

Each rule encodes an invariant the pipeline stakes a runtime guarantee
on; see the class docstrings for the failure mode each one prevents.
Rules are small: the shared machinery (jit scopes, tracer references,
suppressions) lives in :mod:`.astlint`.
"""

from __future__ import annotations

import ast

from .astlint import (
    ModuleContext,
    Rule,
    dotted_name,
    register_rule,
)
from .findings import SEV_ERROR, SEV_WARNING

_NP = ("np", "numpy")
_DEVICE_DIRS = (
    "peasoup_tpu/ops/",
    "peasoup_tpu/parallel/",
    "peasoup_tpu/pipeline/",
    "peasoup_tpu/plan/",
)


def _root(name: str | None) -> str:
    return (name or "").split(".", 1)[0]


@register_rule
class HostSyncInJit(Rule):
    """Host synchronisation inside a jitted/scan body.

    ``.item()``, ``.tolist()``, ``float()``/``int()`` on a tracer,
    ``jax.device_get`` and ``np.asarray`` all force a concrete value
    mid-trace: at best a ConcretizationTypeError at runtime, at worst
    (under ``io_callback``-style escapes) a silent device->host round
    trip per step that serialises the whole pipeline.
    """

    id = "PSA001"
    severity = SEV_ERROR
    title = "host sync inside jitted code"
    fix_hint = (
        "keep the value on device (jnp), or hoist the host read out of "
        "the jitted function"
    )
    paths = ("peasoup_tpu/",)

    _SYNC_METHODS = {"item", "tolist", "block_until_ready"}
    _CASTS = {"float", "int", "bool", "complex"}

    def check(self, ctx: ModuleContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.enclosing_jit(node) is None:
                continue
            callee = dotted_name(node.func)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in self._SYNC_METHODS
                and not node.args
            ):
                yield self.finding(
                    ctx, node,
                    f".{node.func.attr}() forces a host sync inside a "
                    "jitted function",
                )
            elif callee in ("jax.device_get",):
                yield self.finding(
                    ctx, node,
                    "jax.device_get() inside a jitted function is a "
                    "host transfer",
                )
            elif callee is not None and _root(callee) in _NP and (
                callee.rsplit(".", 1)[-1] in ("asarray", "array")
            ):
                yield self.finding(
                    ctx, node,
                    f"{callee}() materialises a tracer on the host "
                    "inside a jitted function",
                    "use jnp.asarray / keep the data as a jax Array",
                )
            elif callee in self._CASTS and node.args:
                tracers = ctx.tracer_names_at(node)
                if ctx.references_tracer(node.args[0], tracers):
                    yield self.finding(
                        ctx, node,
                        f"{callee}() on a tracer concretises it inside "
                        "a jitted function",
                    )


@register_rule
class TracerBranch(Rule):
    """Python ``if``/``while`` on a tracer value.

    Control flow on a traced array either raises a
    ConcretizationTypeError or — when the predicate happens to be
    weakly concrete — silently bakes one branch into the compiled
    program, so the other branch never runs for ANY later input.
    """

    id = "PSA002"
    severity = SEV_ERROR
    title = "Python branch on a tracer"
    fix_hint = "use jnp.where / jax.lax.cond / jax.lax.select"
    paths = ("peasoup_tpu/",)

    def check(self, ctx: ModuleContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            if ctx.enclosing_jit(node) is None:
                continue
            test = node.test
            if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
            ):
                continue  # `x is None` checks static structure
            tracers = ctx.tracer_names_at(node)
            if ctx.references_tracer(test, tracers):
                kw = "if" if isinstance(node, ast.If) else "while"
                yield self.finding(
                    ctx, node,
                    f"Python `{kw}` on a tracer value inside a jitted "
                    "function",
                )


@register_rule
class Float64InDeviceCode(Rule):
    """float64 creeping into device code.

    The pipeline is float32-by-design (peasoup's GPU lineage): an f64
    op on TPU either fails to lower or silently runs at ~1/10th
    throughput in the f64 emulation path, and an f64 constant doubles
    its HBM footprint. ``jnp.float64`` is flagged anywhere;
    ``np.float64``/``np.double``/``dtype="float64"`` only inside
    jitted code (host-side f64 staging math is deliberate — the plan/
    layer reproduces the reference's f64 behaviour).
    """

    id = "PSA003"
    severity = SEV_ERROR
    title = "float64 in device code"
    fix_hint = "use float32 (the whole pipeline is f32-by-design)"
    paths = ("peasoup_tpu/",)
    exclude = ("peasoup_tpu/tools/",)

    _F64 = {"float64", "double", "complex128"}

    def check(self, ctx: ModuleContext):
        for node in ast.walk(ctx.tree):
            in_jit = ctx.enclosing_jit(node) is not None
            name = dotted_name(node)
            if name is not None and isinstance(node, ast.Attribute):
                root, leaf = _root(name), name.rsplit(".", 1)[-1]
                if leaf in self._F64 and (
                    root in ("jnp", "jax") or (in_jit and root in _NP)
                ):
                    # skip the inner Attribute of e.g. np.float64(...)
                    p = ctx.parent(node)
                    yield self.finding(
                        ctx, p if isinstance(p, ast.Call) else node,
                        f"{name} in {'jitted' if in_jit else 'device'} "
                        "code",
                    )
            elif isinstance(node, ast.keyword) and node.arg == "dtype":
                # string dtypes only — named dtypes (np.float64) are
                # caught by the Attribute branch above
                if (
                    in_jit
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                    and node.value.value in self._F64
                ):
                    yield self.finding(
                        ctx, node.value,
                        "float64 dtype inside a jitted function",
                    )


@register_rule
class DtypelessNpArray(Rule):
    """``np.array([...])`` without an explicit dtype in device-adjacent
    code.

    NumPy infers float64 for Python floats, so a dtype-less literal
    that later feeds jnp silently promotes (or silently DOWNCASTS when
    jax truncates it back to f32 — two different sets of rounded
    values depending on which path touched it first). An explicit
    dtype documents which one is intended.
    """

    id = "PSA004"
    severity = SEV_WARNING
    title = "dtype-less np.array literal in device-adjacent code"
    fix_hint = "pass dtype= explicitly (np.float32 for device inputs)"
    paths = _DEVICE_DIRS

    _LITERALS = (ast.List, ast.Tuple, ast.ListComp, ast.GeneratorExp)

    def check(self, ctx: ModuleContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee is None or _root(callee) not in _NP:
                continue
            if callee.rsplit(".", 1)[-1] != "array":
                continue
            if not node.args or not isinstance(node.args[0], self._LITERALS):
                continue
            if any(kw.arg == "dtype" for kw in node.keywords):
                continue
            yield self.finding(
                ctx, node,
                f"{callee}() of a literal without an explicit dtype",
            )


@register_rule
class StaticArgHazard(Rule):
    """Non-hashable or array-valued static jit arguments.

    A static argument is a cache key: a list/dict/array default raises
    ``TypeError: unhashable`` at the first call, and an array-typed
    static parameter recompiles the program on every distinct value —
    the silent-recompile hazard the campaign shape buckets exist to
    avoid.
    """

    id = "PSA005"
    severity = SEV_ERROR
    title = "non-hashable / array-valued static jit argument"
    fix_hint = (
        "statics must be hashable scalars/tuples; pass arrays as traced "
        "operands"
    )
    paths = ("peasoup_tpu/",)

    _MUTABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                ast.SetComp)
    _ARRAYISH = {"ndarray", "Array", "ArrayLike", "DeviceArray"}

    def check(self, ctx: ModuleContext):
        for info in ctx.jit_scopes.values():
            call = info.jit_call
            if call is not None:
                for kw in call.keywords:
                    if kw.arg not in ("static_argnums", "static_argnames"):
                        continue
                    if isinstance(kw.value, self._MUTABLE):
                        yield self.finding(
                            ctx, kw.value,
                            f"{kw.arg} should be a literal tuple "
                            "(a mutable value is not hashable as a "
                            "cache key)",
                        )
            if isinstance(info.node, ast.Lambda) or not info.static_names:
                continue
            a = info.node.args
            params = a.posonlyargs + a.args + a.kwonlyargs
            defaults = dict(
                zip([p.arg for p in a.args[::-1]], a.defaults[::-1])
            )
            defaults.update(
                {
                    p.arg: d
                    for p, d in zip(a.kwonlyargs, a.kw_defaults)
                    if d is not None
                }
            )
            for p in params:
                if p.arg not in info.static_names:
                    continue
                d = defaults.get(p.arg)
                if d is not None and isinstance(d, self._MUTABLE):
                    yield self.finding(
                        ctx, d,
                        f"static arg {p.arg!r} has an unhashable "
                        "default",
                    )
                ann = p.annotation
                ann_name = dotted_name(ann) if ann is not None else None
                if ann_name and ann_name.rsplit(".", 1)[-1] in self._ARRAYISH:
                    yield self.finding(
                        ctx, p,
                        f"static arg {p.arg!r} is annotated as an "
                        f"array ({ann_name}): every distinct value "
                        "recompiles, and jax Arrays are unhashable",
                    )


@register_rule
class WallClockForDuration(Rule):
    """``time.time()`` where ``perf_counter`` is required.

    Wall clock steps under NTP slew: a duration measured with
    ``time.time()`` can be negative or wildly wrong, which is exactly
    how the telemetry layer once recorded negative JIT compile times.
    Epoch *timestamps* (``*_unix`` fields, lease expiries shared
    across hosts) are the legitimate use; name the target accordingly
    or suppress with the reason.
    """

    id = "PSA006"
    severity = SEV_WARNING
    title = "time.time() where perf_counter is required"
    fix_hint = (
        "use time.perf_counter() for durations; for wall-clock epochs "
        "store into a *_unix name or suppress with the reason"
    )
    paths = ("peasoup_tpu/",)

    _OK_NAMES = ("unix", "epoch", "wallclock")

    def _epoch_context(self, ctx: ModuleContext, node: ast.Call) -> bool:
        parent = ctx.parent(node)
        # walk up through arithmetic / conditional expressions
        while isinstance(parent, (ast.BinOp, ast.IfExp, ast.BoolOp)):
            node, parent = parent, ctx.parent(parent)
        if isinstance(parent, ast.Assign):
            for t in parent.targets:
                name = (
                    t.id if isinstance(t, ast.Name)
                    else t.attr if isinstance(t, ast.Attribute)
                    else ""
                )
                low = name.lower()
                if low == "now" or any(s in low for s in self._OK_NAMES):
                    return True
        if isinstance(parent, ast.Dict):
            for k, v in zip(parent.keys, parent.values):
                if v is node and isinstance(k, ast.Constant) and any(
                    s in str(k.value).lower() for s in self._OK_NAMES
                ):
                    return True
        return False

    def check(self, ctx: ModuleContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) != "time.time":
                continue
            if self._epoch_context(ctx, node):
                continue
            yield self.finding(
                ctx, node,
                "time.time() used outside an epoch-timestamp context",
            )


@register_rule
class PrintInLibrary(Rule):
    """``print()`` in library code.

    The library speaks through the peasoup_tpu logger and the
    telemetry manifest; stdout belongs to the CLIs (candidate tables
    are parsed from it downstream — a stray print corrupts them).
    """

    id = "PSA007"
    severity = SEV_ERROR
    title = "print() in library code"
    fix_hint = "use the peasoup_tpu logger (peasoup_tpu/obs/log.py)"
    paths = ("peasoup_tpu/",)
    exclude = ("peasoup_tpu/cli/", "peasoup_tpu/tools/")

    def check(self, ctx: ModuleContext):
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                yield self.finding(ctx, node, "print() in library code")


@register_rule
class NonAtomicSharedWrite(Rule):
    """In-place JSON writes to shared files.

    The obs/campaign layers rewrite ``status.json``, queue records and
    rollups with tmp-file + ``os.replace`` so concurrent readers (the
    watcher, other workers, the reaper) never see a torn file. A plain
    ``open(path, "w") + json.dump`` in those layers reintroduces the
    torn-read race.
    """

    id = "PSA008"
    severity = SEV_ERROR
    title = "non-atomic JSON write in a shared-file layer"
    fix_hint = (
        "write to a tempfile in the same directory and os.replace() "
        "into place (see obs/heartbeat._atomic_write_json)"
    )
    paths = (
        "peasoup_tpu/obs/",
        "peasoup_tpu/campaign/",
        "peasoup_tpu/pipeline/",
        "peasoup_tpu/io/",
    )

    def _open_write_names(self, fn: ast.AST) -> dict[str, ast.AST]:
        """as-names bound by `with open(_, "w"...)` in this function."""
        out: dict[str, ast.AST] = {}
        for node in ast.walk(fn):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                call = item.context_expr
                if not (
                    isinstance(call, ast.Call)
                    and dotted_name(call.func) == "open"
                ):
                    continue
                mode = None
                if len(call.args) > 1 and isinstance(
                    call.args[1], ast.Constant
                ):
                    mode = call.args[1].value
                for kw in call.keywords:
                    if kw.arg == "mode" and isinstance(
                        kw.value, ast.Constant
                    ):
                        mode = kw.value.value
                if not (isinstance(mode, str) and "w" in mode):
                    continue
                if isinstance(item.optional_vars, ast.Name):
                    out[item.optional_vars.id] = call
        return out

    def check(self, ctx: ModuleContext):
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            has_replace = any(
                isinstance(n, ast.Call)
                and dotted_name(n.func) in ("os.replace", "os.rename")
                for n in ast.walk(fn)
            )
            if has_replace:
                continue
            writers = self._open_write_names(fn)
            if not writers:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = dotted_name(node.func)
                if callee == "json.dump" and len(node.args) >= 2:
                    f = node.args[1]
                    if isinstance(f, ast.Name) and f.id in writers:
                        yield self.finding(
                            ctx, node,
                            "json.dump() into a plainly-opened file: a "
                            "concurrent reader can see a torn write",
                        )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "write"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in writers
                    and node.args
                    and isinstance(node.args[0], ast.Call)
                    and dotted_name(node.args[0].func) == "json.dumps"
                ):
                    yield self.finding(
                        ctx, node,
                        "f.write(json.dumps(...)) into a plainly-opened "
                        "file: a concurrent reader can see a torn write",
                    )


@register_rule
class UnlockedThreadShared(Rule):
    """Mutation of thread-shared state outside a lock.

    In classes that spawn a ``threading.Thread`` (the heartbeat, the
    queue's lease renewer), attributes mutated from both the worker
    thread and the main thread race unless guarded. Plain rebinding
    is atomic under the GIL; this flags the compound operations that
    are not: augmented assignment and in-place container mutation.
    """

    id = "PSA009"
    severity = SEV_WARNING
    title = "thread-shared mutation outside a lock"
    fix_hint = (
        "guard with `with self._lock:` (threading.Lock), or suppress "
        "with the reason the access is single-threaded"
    )
    paths = ("peasoup_tpu/",)

    _MUTATORS = {
        "append", "extend", "insert", "remove", "pop", "popleft",
        "appendleft", "clear", "update", "add", "discard",
        "setdefault",
    }

    def _spawns_thread(self, cls: ast.ClassDef) -> bool:
        for node in ast.walk(cls):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func) or ""
                if name.endswith("Thread") and _root(name) in (
                    "threading", "Thread",
                ):
                    return True
        return False

    def check(self, ctx: ModuleContext):
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            if not self._spawns_thread(cls):
                continue
            for method in cls.body:
                if not isinstance(
                    method, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) or method.name == "__init__":
                    continue
                for node in ast.walk(method):
                    if (
                        isinstance(node, ast.AugAssign)
                        and isinstance(node.target, ast.Attribute)
                        and isinstance(node.target.value, ast.Name)
                        and node.target.value.id == "self"
                        and not ctx.in_lock(node)
                    ):
                        yield self.finding(
                            ctx, node,
                            f"self.{node.target.attr} augmented outside "
                            f"a lock in thread-spawning class "
                            f"{cls.name}",
                        )
                    elif (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in self._MUTATORS
                        and isinstance(node.func.value, ast.Attribute)
                        and isinstance(node.func.value.value, ast.Name)
                        and node.func.value.value.id == "self"
                        and not ctx.in_lock(node)
                    ):
                        yield self.finding(
                            ctx, node,
                            f"self.{node.func.value.attr}."
                            f"{node.func.attr}() outside a lock in "
                            f"thread-spawning class {cls.name}",
                        )


@register_rule
class NumpyOnTracer(Rule):
    """NumPy called on a tracer inside jitted code.

    ``np.sum(tracer)`` etc. either raises a TracerArrayConversionError
    or — via ``__array__`` escapes — silently computes on host,
    breaking the one-program-per-block design. (``np.array`` /
    ``np.asarray`` are PSA001; this covers the rest of the np
    namespace when an argument is a tracer.)
    """

    id = "PSA010"
    severity = SEV_ERROR
    title = "numpy op on a tracer inside jitted code"
    fix_hint = "use the jnp equivalent inside jitted code"
    paths = ("peasoup_tpu/",)

    _EXCLUDED = {"asarray", "array"}  # PSA001's findings

    def check(self, ctx: ModuleContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee is None or _root(callee) not in _NP:
                continue
            if callee.rsplit(".", 1)[-1] in self._EXCLUDED:
                continue
            if ctx.enclosing_jit(node) is None:
                continue
            tracers = ctx.tracer_names_at(node)
            if any(
                ctx.references_tracer(a, tracers)
                for a in list(node.args)
                + [kw.value for kw in node.keywords]
            ):
                yield self.finding(
                    ctx, node,
                    f"{callee}() applied to a tracer inside a jitted "
                    "function",
                )


def all_rules() -> dict[str, type[Rule]]:
    from .astlint import rule_classes

    return rule_classes()
