"""The AST lint engine: rule plugins over a per-module context.

A rule is a subclass of :class:`Rule` registered with
:func:`register_rule`; it receives a :class:`ModuleContext` (parsed
tree, parent links, comment map, jit-scope analysis) and yields
:class:`~.findings.Finding`\\ s. The engine owns the cross-cutting
mechanics every rule needs:

* **jit scopes** — which function bodies are staged out by
  ``jax.jit``/``partial(jax.jit, ...)`` decorators, ``jax.jit(fn)``
  wrapping, or by being passed as a ``lax.scan`` / ``while_loop`` /
  ``fori_loop`` / ``cond`` body, including nested defs; plus which
  parameters are static (``static_argnums``/``static_argnames``) and
  which are tracers.
* **tracer references** — whether an expression reads a tracer
  parameter *as a value* (``x``) rather than through its static
  metadata (``x.shape``, ``x.ndim``, ``x.dtype``, ``x.size``).
* **suppressions** — ``# audit: ignore[PSA001,PSA006] -- reason``
  drops same-line findings for those rules. The reason is mandatory:
  a bare ``# audit: ignore[...]`` stays inactive (and the engine says
  so), so every tolerated hazard carries its justification in-line.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field

from .findings import Finding, SEV_ERROR

SUPPRESS_RE = re.compile(
    r"#\s*audit:\s*ignore\[([A-Za-z0-9_,\s]+)\]\s*(?:--\s*(\S.*))?"
)

# (callee, positional index) pairs whose argument is traced like a jit
# body even without a jit decorator
_TRACED_BODY_ARGS = {
    ("scan", 0),
    ("while_loop", 0),
    ("while_loop", 1),
    ("fori_loop", 2),
    ("cond", 1),
    ("cond", 2),
    ("checkpoint", 0),
    ("remat", 0),
}

# attribute reads that consume only static metadata of an array
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize", "aval",
                 "sharding", "weak_type"}


def dotted_name(node: ast.AST) -> str | None:
    """``jax.lax.scan`` -> "jax.lax.scan"; None for non-name chains."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jax_jit(node: ast.AST) -> bool:
    return dotted_name(node) in ("jax.jit", "jit", "pjit", "jax.pjit")


def _literal_strs(node: ast.AST) -> list[str] | None:
    """("a", "b") / "a" -> ["a", "b"]; None when not a literal."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, str)):
                return None
            out.append(el.value)
        return out
    return None


def _literal_ints(node: ast.AST) -> list[int] | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, int)):
                return None
            out.append(el.value)
        return out
    return None


@dataclass
class JitInfo:
    """How one function def is staged out."""

    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    how: str  # "decorator" | "wrapped" | "traced-body" | "nested"
    static_names: set[str] = field(default_factory=set)
    # the jit decorator / jax.jit(...) call node, when there is one
    jit_call: ast.Call | None = None

    def param_names(self) -> list[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return [n for n in names if n not in ("self", "cls")]

    def tracer_names(self) -> set[str]:
        return set(self.param_names()) - self.static_names


class ModuleContext:
    """Everything rules need about one source file."""

    def __init__(self, source: str, relpath: str):
        self.source = source
        self.relpath = relpath.replace("\\", "/")
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self._parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent
        self.comments = self._collect_comments()
        self.suppressions, self.inactive_suppressions = (
            self._collect_suppressions()
        )
        self.jit_scopes = self._collect_jit_scopes()

    # --- plumbing ----------------------------------------------------
    def parent(self, node: ast.AST) -> ast.AST | None:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST):
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def finding(self, rule, severity, node, message, fix_hint="") -> Finding:
        line = getattr(node, "lineno", 0)
        return Finding(
            rule=rule,
            severity=severity,
            path=self.relpath,
            line=line,
            col=getattr(node, "col_offset", 0),
            message=message,
            fix_hint=fix_hint,
            source_line=self.source_line(line).strip(),
        )

    # --- comments / suppressions ------------------------------------
    def _collect_comments(self) -> dict[int, str]:
        out: dict[int, str] = {}
        try:
            toks = tokenize.generate_tokens(
                io.StringIO(self.source).readline
            )
            for tok in toks:
                if tok.type == tokenize.COMMENT:
                    out[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError):
            pass
        return out

    def _comment_only(self, line: int) -> bool:
        text = self.source_line(line).strip()
        return not text or text.startswith("#")

    def _collect_suppressions(self):
        """A trailing suppression covers its own line; a suppression on
        a comment-only line covers the next code line (the repo's
        88-column style rarely fits a trailing comment)."""
        active: dict[int, set[str]] = {}
        inactive: dict[int, set[str]] = {}
        nlines = len(self.lines)
        for line, comment in self.comments.items():
            m = SUPPRESS_RE.search(comment)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            target = line
            if self._comment_only(line):
                target = next(
                    (
                        ln
                        for ln in range(line + 1, nlines + 1)
                        if not self._comment_only(ln)
                    ),
                    line,
                )
            dest = active if m.group(2) else inactive
            dest.setdefault(target, set()).update(rules)
        return active, inactive

    def suppressed(self, finding: Finding) -> bool:
        rules = self.suppressions.get(finding.line, ())
        return finding.rule in rules or "ALL" in rules

    # --- jit scope analysis -----------------------------------------
    def _collect_jit_scopes(self) -> dict[ast.AST, JitInfo]:
        scopes: dict[ast.AST, JitInfo] = {}
        defs_by_name: dict[str, list[ast.AST]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)

        def add(node, how, jit_call=None):
            if node in scopes:
                return
            info = JitInfo(node=node, how=how, jit_call=jit_call)
            if jit_call is not None:
                info.static_names = self._static_names(node, jit_call)
            scopes[node] = info

        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _is_jax_jit(dec):
                        add(node, "decorator")
                    elif isinstance(dec, ast.Call):
                        # @jax.jit(...) or @partial(jax.jit, ...)
                        if _is_jax_jit(dec.func):
                            add(node, "decorator", dec)
                        elif (
                            dotted_name(dec.func)
                            in ("partial", "functools.partial")
                            and dec.args
                            and _is_jax_jit(dec.args[0])
                        ):
                            add(node, "decorator", dec)
            elif isinstance(node, ast.Call):
                callee = dotted_name(node.func) or ""
                leaf = callee.rsplit(".", 1)[-1]
                if _is_jax_jit(node.func) and node.args:
                    fn = node.args[0]
                    if isinstance(fn, ast.Name):
                        for d in defs_by_name.get(fn.id, ()):
                            add(d, "wrapped", node)
                    elif isinstance(fn, ast.Lambda):
                        add(fn, "wrapped", node)
                elif callee.startswith(("jax.lax.", "lax.", "jax.")) or (
                    leaf in {k for k, _ in _TRACED_BODY_ARGS}
                ):
                    for k, idx in _TRACED_BODY_ARGS:
                        if leaf == k and len(node.args) > idx:
                            fn = node.args[idx]
                            if isinstance(fn, ast.Name):
                                for d in defs_by_name.get(fn.id, ()):
                                    add(d, "traced-body")
                            elif isinstance(fn, ast.Lambda):
                                add(fn, "traced-body")

        # close over nesting: defs inside a jit scope are traced too
        changed = True
        while changed:
            changed = False
            for node in ast.walk(self.tree):
                if not isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ) or node in scopes:
                    continue
                for anc in self.ancestors(node):
                    if anc in scopes:
                        scopes[node] = JitInfo(node=node, how="nested")
                        changed = True
                        break
        return scopes

    def _static_names(self, fn_node, jit_call: ast.Call) -> set[str]:
        """Static parameter names from a jit decorator/wrapper call."""
        statics: set[str] = set()
        a = getattr(fn_node, "args", None)
        if a is None:
            return statics
        positional = [p.arg for p in a.posonlyargs + a.args]
        for kw in jit_call.keywords:
            if kw.arg == "static_argnames":
                names = _literal_strs(kw.value)
                if names:
                    statics.update(names)
            elif kw.arg == "static_argnums":
                nums = _literal_ints(kw.value)
                if nums:
                    for i in nums:
                        if 0 <= i < len(positional):
                            statics.add(positional[i])
        return statics

    def enclosing_jit(self, node: ast.AST) -> JitInfo | None:
        """Innermost jit scope containing ``node`` (or being it)."""
        if node in self.jit_scopes:
            return self.jit_scopes[node]
        for anc in self.ancestors(node):
            if anc in self.jit_scopes:
                return self.jit_scopes[anc]
        return None

    def jit_root(self, node: ast.AST) -> JitInfo | None:
        """The OUTERMOST jit scope containing ``node`` — its tracer
        params are tracers for everything nested inside."""
        found = None
        if node in self.jit_scopes:
            found = self.jit_scopes[node]
        for anc in self.ancestors(node):
            if anc in self.jit_scopes:
                found = self.jit_scopes[anc]
        return found

    def tracer_names_at(self, node: ast.AST) -> set[str]:
        """Names bound to tracers for code at ``node``: the union of
        tracer params of every enclosing jit-scope function."""
        names: set[str] = set()
        chain = [node] + list(self.ancestors(node))
        for n in chain:
            info = self.jit_scopes.get(n)
            if info is not None and not isinstance(n, ast.Lambda):
                names |= info.tracer_names()
            elif info is not None:
                a = n.args
                names |= {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
        return names

    def references_tracer(self, expr: ast.AST, tracers: set[str]) -> bool:
        """True when ``expr`` reads a tracer name as a *value* (not just
        its static ``.shape``/``.ndim``/``.dtype``/``.size`` metadata,
        and not ``len(x)``/``isinstance(x, ...)``)."""
        if not tracers:
            return False
        parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(expr):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        for node in ast.walk(expr):
            if not isinstance(node, ast.Name) or node.id not in tracers:
                continue
            p = parents.get(node)
            if (
                isinstance(p, ast.Attribute)
                and p.value is node
                and p.attr in _STATIC_ATTRS
            ):
                continue
            if isinstance(p, ast.Call) and node in p.args:
                callee = dotted_name(p.func)
                if callee in ("len", "isinstance", "type"):
                    continue
            return True
        return False

    def in_lock(self, node: ast.AST) -> bool:
        """True when ``node`` sits inside ``with <something lock-ish>:``."""
        for anc in self.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    name = dotted_name(item.context_expr) or ""
                    if isinstance(item.context_expr, ast.Call):
                        name = dotted_name(item.context_expr.func) or ""
                    if "lock" in name.lower() or "mutex" in name.lower():
                        return True
        return False


# --- rule plugin framework -------------------------------------------


class Rule:
    """One lint. Subclass, set the class attrs, implement check()."""

    id: str = ""
    severity: str = SEV_ERROR
    title: str = ""
    fix_hint: str = ""
    # repo-relative path prefixes the rule applies to; () = everywhere
    paths: tuple[str, ...] = ()
    exclude: tuple[str, ...] = ()

    def applies_to(self, relpath: str) -> bool:
        if any(relpath.startswith(p) for p in self.exclude):
            return False
        return not self.paths or any(
            relpath.startswith(p) for p in self.paths
        )

    def check(self, ctx: ModuleContext):
        raise NotImplementedError

    def finding(self, ctx, node, message, fix_hint=None) -> Finding:
        return ctx.finding(
            self.id,
            self.severity,
            node,
            message,
            self.fix_hint if fix_hint is None else fix_hint,
        )


_RULES: dict[str, type[Rule]] = {}


def register_rule(cls: type[Rule]) -> type[Rule]:
    if not cls.id:
        raise ValueError(f"{cls.__name__}: rule id is required")
    if cls.id in _RULES:
        raise ValueError(f"duplicate rule id {cls.id}")
    _RULES[cls.id] = cls
    return cls


def rule_classes() -> dict[str, type[Rule]]:
    # registration side effects: PSA (rules), PSP (protocol),
    # PSK static (kernels)
    from . import kernels, protocol, rules  # noqa: F401

    return dict(_RULES)


# --- engine ----------------------------------------------------------


def lint_source(
    source: str, relpath: str, rule_ids=None
) -> tuple[list[Finding], int]:
    """Lint one module. Returns (findings, suppressed_count). A syntax
    error becomes a PSA000 finding rather than an exception."""
    classes = rule_classes()
    if rule_ids is not None:
        unknown = set(rule_ids) - set(classes)
        if unknown:
            raise ValueError(f"unknown rule ids: {sorted(unknown)}")
        classes = {k: v for k, v in classes.items() if k in rule_ids}
    try:
        ctx = ModuleContext(source, relpath)
    except SyntaxError as e:
        return [
            Finding(
                rule="PSA000",
                severity=SEV_ERROR,
                path=relpath,
                line=e.lineno or 0,
                col=(e.offset or 1) - 1,
                message=f"syntax error: {e.msg}",
                source_line=(e.text or "").strip(),
            )
        ], 0
    findings: list[Finding] = []
    suppressed = 0
    for cls in classes.values():
        rule = cls()
        if not rule.applies_to(ctx.relpath):
            continue
        for f in rule.check(ctx):
            if ctx.suppressed(f):
                suppressed += 1
            else:
                findings.append(f)
    for line, rules in sorted(ctx.inactive_suppressions.items()):
        if line in ctx.suppressions:
            continue
        findings.append(
            Finding(
                rule="PSA000",
                severity=SEV_ERROR,
                path=relpath,
                line=line,
                col=0,
                message=(
                    f"suppression for {sorted(rules)} has no reason and "
                    "is inactive"
                ),
                fix_hint=(
                    "write `# audit: ignore[RULE] -- why this is safe`"
                ),
                source_line=ctx.source_line(line).strip(),
            )
        )
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, suppressed


def lint_path(path: str, relpath: str, rule_ids=None):
    with open(path, encoding="utf-8") as f:
        source = f.read()
    return lint_source(source, relpath, rule_ids)
