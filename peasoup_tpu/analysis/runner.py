"""Audit orchestration: all five engines, the baseline ratchet, and
the versioned ``audit.json`` report.

The engines:

1. **AST lints** — the PSA rules (:mod:`.rules`) over every package
   file.
2. **Program contracts** (:mod:`.contracts`) — every registered jitted
   program abstract-evaled at its representative shapes AND at every
   rung of the campaign bucket ladder (``--no-ladder`` skips the
   rungs), its jaxpr/StableHLO linted.
3. **Concurrency / file protocols** — the PSP rules
   (:mod:`.protocol`); operationally part of the AST pass but
   separately gated (``--no-protocol``).
4. **Pallas kernel contracts** (:mod:`.kernels`) — the PSK static
   rules over ``ops/pallas`` plus the dynamic registry checks
   (twin/probe cross-reference, interpret-mode lowering, Mosaic where
   the toolchain allows).
5. **Protocol model checking** (:mod:`.mc`) — the PSM rules: the
   real queue/registry/tenants/alerts code run against a virtual
   filesystem under exhaustive interleaving + crash-point
   exploration, scenario invariants asserted after every complete
   schedule. Off by default in the Python API (it executes module
   code, not just reads it); the CLI runs it unless ``--no-mc``.

The report is a machine-readable manifest like the telemetry one:
versioned, schema-pinned by a checked-in JSON Schema
(``analysis/audit.schema.json``) and validated by the same
dependency-free validator (:mod:`peasoup_tpu.obs.schema`) before it is
written — the audit cannot emit a report that its own consumers would
reject.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from .astlint import lint_path, rule_classes
from .findings import Baseline, Finding

AUDIT_SCHEMA = "peasoup_tpu.audit"
AUDIT_VERSION = 3  # v3: mc engine (interleaving/crash model checking)

AUDIT_SCHEMA_PATH = os.path.join(
    os.path.dirname(__file__), "audit.schema.json"
)

# directories never scanned by the AST engine
_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def package_files(root: str) -> list[tuple[str, str]]:
    """(abspath, relpath) for every .py file under <root>/peasoup_tpu."""
    pkg = os.path.join(root, "peasoup_tpu")
    out = []
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            ap = os.path.join(dirpath, fname)
            rp = os.path.relpath(ap, root).replace(os.sep, "/")
            out.append((ap, rp))
    return sorted(out)


@dataclass
class AuditResult:
    findings: list[Finding] = field(default_factory=list)  # active
    new: list[Finding] = field(default_factory=list)
    baselined: list[Finding] = field(default_factory=list)
    resolved: list[str] = field(default_factory=list)
    suppressed: int = 0
    files_scanned: int = 0
    programs_checked: list[str] = field(default_factory=list)
    kernels_checked: list[str] = field(default_factory=list)
    ladder_rungs: list[int] = field(default_factory=list)
    ladder_coverage: dict[str, list[int]] = field(default_factory=dict)
    rules: list[str] = field(default_factory=list)
    mc_scenarios: list[str] = field(default_factory=list)
    mc: dict = field(default_factory=dict)  # MCReport.to_doc()

    @property
    def clean(self) -> bool:
        return not self.new

    def to_manifest(self) -> dict:
        return {
            "schema": AUDIT_SCHEMA,
            "version": AUDIT_VERSION,
            "summary": {
                "new": len(self.new),
                "baselined": len(self.baselined),
                "resolved": len(self.resolved),
                "suppressed": self.suppressed,
                "files_scanned": self.files_scanned,
                "programs_checked": len(self.programs_checked),
                "kernels_checked": len(self.kernels_checked),
                "ladder_rungs": len(self.ladder_rungs),
                "mc_scenarios": len(self.mc_scenarios),
            },
            "rules": sorted(self.rules),
            "programs": sorted(self.programs_checked),
            "kernels": sorted(self.kernels_checked),
            "ladder": {
                "rungs": list(self.ladder_rungs),
                "coverage": {
                    k: list(v)
                    for k, v in sorted(self.ladder_coverage.items())
                },
            },
            "mc": dict(self.mc),
            "findings": [f.to_json() for f in self.findings],
            "resolved_fingerprints": sorted(self.resolved),
        }


def _engine_rule_ids(rule_ids, protocol: bool, kernels: bool):
    """Resolve the AST pass's rule set from the explicit ``--rules``
    filter and the engine toggles (PSP = engine 3, static PSK =
    engine 4)."""
    classes = rule_classes()
    selected = set(classes) if rule_ids is None else set(rule_ids)
    if rule_ids is not None:
        unknown = selected - set(classes)
        if unknown:
            raise ValueError(f"unknown rule ids: {sorted(unknown)}")
    if not protocol:
        selected -= {r for r in selected if r.startswith("PSP")}
    if not kernels:
        selected -= {r for r in selected if r.startswith("PSK")}
    return sorted(selected)


def run_audit(
    root: str,
    *,
    rule_ids=None,
    ast_engine: bool = True,
    contracts: bool = True,
    protocol: bool = True,
    kernels: bool = True,
    ladder: bool = True,
    ladder_rung_count: int | None = None,
    baseline_path: str | None = None,
    max_const_bytes: int | None = None,
    kernel_specs=None,
    program_specs=None,
    mc: bool = False,
    mc_scenarios: list[str] | None = None,
    mc_budget: int | None = None,
) -> AuditResult:
    """Run the five engines over the repo at ``root`` and apply the
    baseline ratchet. Engine/internal errors propagate (the CLI maps
    them to exit 2); per-file, per-program and per-kernel problems
    become findings. ``kernel_specs``/``program_specs`` override the
    real registries (tests inject doctored specs). Engine 5 (``mc``)
    defaults OFF here — it executes the protocol modules under a
    scheduler rather than reading source — and ON in the CLI;
    ``mc_scenarios`` selects a subset by name, ``mc_budget`` caps
    schedules explored per scenario."""
    result = AuditResult()
    findings: list[Finding] = []

    effective_rules = _engine_rule_ids(rule_ids, protocol, kernels)
    result.rules = effective_rules

    if ast_engine:
        for abspath, relpath in package_files(root):
            file_findings, nsup = lint_path(
                abspath, relpath, effective_rules
            )
            findings.extend(file_findings)
            result.suppressed += nsup
            result.files_scanned += 1

    if contracts:
        from .contracts import (
            ContractConfig,
            audit_programs,
            audit_programs_ladder,
        )

        cfg = ContractConfig()
        if max_const_bytes is not None:
            cfg.max_const_bytes = max_const_bytes
        report = audit_programs(specs=program_specs, cfg=cfg)
        findings.extend(report.findings)
        result.programs_checked = report.programs
        if ladder:
            from .contracts import ladder_rungs as _rungs

            rungs = (
                _rungs(count=ladder_rung_count)
                if ladder_rung_count
                else None
            )
            lrep = audit_programs_ladder(
                specs=program_specs, rungs=rungs, cfg=cfg
            )
            findings.extend(lrep.findings)
            result.ladder_rungs = lrep.rungs
            result.ladder_coverage = lrep.coverage

    if kernels:
        from .kernels import audit_kernels

        krep = audit_kernels(specs=kernel_specs)
        findings.extend(krep.findings)
        result.kernels_checked = krep.kernels

    if mc:
        from .mc.scenarios import run_mc

        mrep = run_mc(names=mc_scenarios, budget=mc_budget)
        findings.extend(mrep.findings)
        result.mc = mrep.to_doc()
        result.mc_scenarios = [
            p["name"] for p in mrep.per_scenario
        ]

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    result.findings = findings

    baseline = Baseline()
    if baseline_path is not None and os.path.exists(baseline_path):
        baseline = Baseline.load(baseline_path)
    result.new, result.baselined, result.resolved = baseline.apply(findings)
    return result


def write_report(result: AuditResult, path: str) -> None:
    """Validate against the checked-in schema, then write atomically."""
    from peasoup_tpu.obs.schema import validate

    man = result.to_manifest()
    with open(AUDIT_SCHEMA_PATH) as f:
        validate(man, json.load(f))
    from .findings import _atomic_write_json

    _atomic_write_json(path, man)


def render_text(result: AuditResult, verbose: bool = False) -> str:
    """Human report: new findings in full, baselined summarised."""
    lines: list[str] = []
    for f in result.new:
        lines.append(f.render())
    if result.baselined:
        if verbose:
            lines.extend(f.render() for f in result.baselined)
        else:
            per_rule: dict[str, int] = {}
            for f in result.baselined:
                per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
            summary = ", ".join(
                f"{r}x{n}" for r, n in sorted(per_rule.items())
            )
            lines.append(
                f"{len(result.baselined)} baselined finding(s) "
                f"({summary}) — tolerated, ratchet down with "
                "--write-baseline after fixing"
            )
    if result.resolved:
        lines.append(
            f"{len(result.resolved)} baseline entr(ies) no longer "
            "match — run --write-baseline to ratchet the debt down"
        )
    lines.append(
        f"peasoup-audit: {len(result.new)} new, "
        f"{len(result.baselined)} baselined, "
        f"{result.suppressed} suppressed; "
        f"{result.files_scanned} files, "
        f"{len(result.programs_checked)} programs"
        + (
            f" (+{len(result.ladder_rungs)} ladder rungs)"
            if result.ladder_rungs
            else ""
        )
        + f", {len(result.kernels_checked)} kernels"
        + (
            f", {len(result.mc_scenarios)} mc scenarios "
            f"({result.mc.get('schedules', 0)} schedules, "
            f"{result.mc.get('crash_points', 0)} crash points)"
            if result.mc_scenarios
            else ""
        )
    )
    return "\n".join(lines)
