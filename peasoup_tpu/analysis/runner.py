"""Audit orchestration: both engines, the baseline ratchet, and the
versioned ``audit.json`` report.

The report is a machine-readable manifest like the telemetry one:
versioned, schema-pinned by a checked-in JSON Schema
(``analysis/audit.schema.json``) and validated by the same
dependency-free validator (:mod:`peasoup_tpu.obs.schema`) before it is
written — the audit cannot emit a report that its own consumers would
reject.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from .astlint import lint_path, rule_classes
from .findings import Baseline, Finding

AUDIT_SCHEMA = "peasoup_tpu.audit"
AUDIT_VERSION = 1

AUDIT_SCHEMA_PATH = os.path.join(
    os.path.dirname(__file__), "audit.schema.json"
)

# directories never scanned by the AST engine
_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def package_files(root: str) -> list[tuple[str, str]]:
    """(abspath, relpath) for every .py file under <root>/peasoup_tpu."""
    pkg = os.path.join(root, "peasoup_tpu")
    out = []
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            ap = os.path.join(dirpath, fname)
            rp = os.path.relpath(ap, root).replace(os.sep, "/")
            out.append((ap, rp))
    return sorted(out)


@dataclass
class AuditResult:
    findings: list[Finding] = field(default_factory=list)  # active
    new: list[Finding] = field(default_factory=list)
    baselined: list[Finding] = field(default_factory=list)
    resolved: list[str] = field(default_factory=list)
    suppressed: int = 0
    files_scanned: int = 0
    programs_checked: list[str] = field(default_factory=list)
    rules: list[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.new

    def to_manifest(self) -> dict:
        return {
            "schema": AUDIT_SCHEMA,
            "version": AUDIT_VERSION,
            "summary": {
                "new": len(self.new),
                "baselined": len(self.baselined),
                "resolved": len(self.resolved),
                "suppressed": self.suppressed,
                "files_scanned": self.files_scanned,
                "programs_checked": len(self.programs_checked),
            },
            "rules": sorted(self.rules),
            "programs": sorted(self.programs_checked),
            "findings": [f.to_json() for f in self.findings],
            "resolved_fingerprints": sorted(self.resolved),
        }


def run_audit(
    root: str,
    *,
    rule_ids=None,
    ast_engine: bool = True,
    contracts: bool = True,
    baseline_path: str | None = None,
    max_const_bytes: int | None = None,
) -> AuditResult:
    """Run both engines over the repo at ``root`` and apply the
    baseline ratchet. Engine/internal errors propagate (the CLI maps
    them to exit 2); per-file and per-program problems become
    findings."""
    result = AuditResult(rules=sorted(rule_classes()))
    findings: list[Finding] = []

    if ast_engine:
        for abspath, relpath in package_files(root):
            file_findings, nsup = lint_path(abspath, relpath, rule_ids)
            findings.extend(file_findings)
            result.suppressed += nsup
            result.files_scanned += 1

    if contracts:
        from .contracts import ContractConfig, audit_programs

        cfg = ContractConfig()
        if max_const_bytes is not None:
            cfg.max_const_bytes = max_const_bytes
        report = audit_programs(cfg=cfg)
        findings.extend(report.findings)
        result.programs_checked = report.programs

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    result.findings = findings

    baseline = Baseline()
    if baseline_path is not None and os.path.exists(baseline_path):
        baseline = Baseline.load(baseline_path)
    result.new, result.baselined, result.resolved = baseline.apply(findings)
    return result


def write_report(result: AuditResult, path: str) -> None:
    """Validate against the checked-in schema, then write atomically."""
    from peasoup_tpu.obs.schema import validate

    man = result.to_manifest()
    with open(AUDIT_SCHEMA_PATH) as f:
        validate(man, json.load(f))
    from .findings import _atomic_write_json

    _atomic_write_json(path, man)


def render_text(result: AuditResult, verbose: bool = False) -> str:
    """Human report: new findings in full, baselined summarised."""
    lines: list[str] = []
    for f in result.new:
        lines.append(f.render())
    if result.baselined:
        if verbose:
            lines.extend(f.render() for f in result.baselined)
        else:
            per_rule: dict[str, int] = {}
            for f in result.baselined:
                per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
            summary = ", ".join(
                f"{r}x{n}" for r, n in sorted(per_rule.items())
            )
            lines.append(
                f"{len(result.baselined)} baselined finding(s) "
                f"({summary}) — tolerated, ratchet down with "
                "--write-baseline after fixing"
            )
    if result.resolved:
        lines.append(
            f"{len(result.resolved)} baseline entr(ies) no longer "
            "match — run --write-baseline to ratchet the debt down"
        )
    lines.append(
        f"peasoup-audit: {len(result.new)} new, "
        f"{len(result.baselined)} baselined, "
        f"{result.suppressed} suppressed; "
        f"{result.files_scanned} files, "
        f"{len(result.programs_checked)} programs"
    )
    return "\n".join(lines)
