"""Engine 4: Mosaic-aware Pallas kernel contracts (PSK2xx).

Two halves over :mod:`peasoup_tpu.ops.pallas`:

* **Static rules** (registered in the shared AST engine, so they ride
  the same suppression syntax, ``--rules`` filter and fixtures):

  - PSK201 — a module calling ``pl.pallas_call`` with no entry in the
    kernel registry (``ops/pallas/registry.py``): unregistered kernels
    escape the twin/probe/fallback contract entirely.
  - PSK204 — literal BlockSpec tile shapes off the TPU lane/sublane
    quanta (last dim a multiple of 128, second-to-last of 8): Mosaic
    either rejects the tile or silently pads it, burning VMEM.
  - PSK205 — sub-f32 VMEM scratch whose literal sublane dim is below
    the dtype's quantum (bf16 -> 16, int8/fp8 -> 32).
  - PSK206 — ``num_scalar_prefetch`` out of step with the kernel
    registry declaration, or a kernel signature whose parameter count
    disagrees with the grid spec (scalar prefetch + in/out specs +
    scratch), when everything is statically countable.
  - PSK207 — a lane-retiling ``reshape`` inside a kernel body in a
    module whose registry entry declares no retile fallback: Mosaic
    support for lane retiles varies by toolchain, so such a kernel
    MUST sit behind a probe-gated fallback ladder (the spchain
    precedent).

* **Dynamic checks** (:func:`audit_kernels`, over the registry):

  - PSK202 — registry drift: missing entry point, deleted probe,
    or a probe that no longer references the declared jnp twin.
  - PSK203 — the kernel no longer traces/lowers in interpret mode at
    its registered geometry.
  - PSK208 — Mosaic lowering, attempted only where the toolchain
    allows (a real TPU backend): failure is an error, downgraded to a
    warning for kernels with a declared retile fallback (rejection is
    exactly what their ladder exists to absorb).
"""

from __future__ import annotations

import ast

from .astlint import ModuleContext, Rule, dotted_name, register_rule
from .findings import Finding, SEV_ERROR, SEV_WARNING

_PALLAS_PATHS = ("peasoup_tpu/ops/pallas/",)
_PALLAS_EXCLUDE = (
    "peasoup_tpu/ops/pallas/__init__.py",
    "peasoup_tpu/ops/pallas/registry.py",
)

LANE = 128
SUBLANE_F32 = 8
# minimum sublane tile per sub-f32 dtype (pallas_guide.md: the
# second-to-last dim quantum grows as the element narrows)
_SUBLANE_QUANTA = {
    "bfloat16": 16,
    "float16": 16,
    "int8": 32,
    "uint8": 32,
    "float8_e4m3fn": 32,
    "float8_e5m2": 32,
}


def _module_stem(relpath: str) -> str:
    return relpath.rsplit("/", 1)[-1].removesuffix(".py")


def _registry_spec(relpath: str):
    try:
        from peasoup_tpu.ops.pallas.registry import spec_for_module

        return spec_for_module(_module_stem(relpath))
    except Exception:
        return None


def _calls_pallas_call(ctx: ModuleContext) -> ast.Call | None:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and (
            dotted_name(node.func) or ""
        ).endswith("pallas_call"):
            return node
    return None


@register_rule
class UnregisteredKernel(Rule):
    """``pl.pallas_call`` in a module with no kernel-registry entry."""

    id = "PSK201"
    severity = SEV_ERROR
    title = "Pallas kernel module missing from the kernel registry"
    fix_hint = (
        "add a KernelSpec (entry/probe/twin/fallback + interpret "
        "build) to ops/pallas/registry.py"
    )
    paths = _PALLAS_PATHS
    exclude = _PALLAS_EXCLUDE

    def check(self, ctx: ModuleContext):
        call = _calls_pallas_call(ctx)
        if call is None:
            return
        if _registry_spec(ctx.relpath) is None:
            yield self.finding(
                ctx, call,
                f"module {_module_stem(ctx.relpath)!r} builds a Pallas "
                "kernel but has no kernel-registry entry: it escapes "
                "the twin/probe/fallback contract",
            )


def _literal_dims(node: ast.AST) -> list[int | None] | None:
    """Tile-shape tuple -> dims (None for None/non-literal entries);
    None when the node is not a tuple/list literal at all."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    dims: list[int | None] = []
    for el in node.elts:
        if isinstance(el, ast.Constant) and isinstance(el.value, int):
            dims.append(el.value)
        elif isinstance(el, ast.Constant) and el.value is None:
            dims.append(None)
        else:
            dims.append(None)
    return dims


def _is_smem(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "memory_space" and "SMEM" in (
            dotted_name(kw.value) or ""
        ):
            return True
    return any("SMEM" in (dotted_name(a) or "") for a in call.args)


@register_rule
class TileShapeQuanta(Rule):
    """Literal BlockSpec tiles off the (8, 128) f32 quanta.

    Only fully-literal dims are judged (symbolic tile maths is the
    probe's job); 1 is allowed anywhere (unit dims lower to scalar
    broadcast), SMEM blocks are exempt (scalars are untiled).
    """

    id = "PSK204"
    severity = SEV_ERROR
    title = "BlockSpec tile shape off the lane/sublane quanta"
    fix_hint = (
        "last tile dim a multiple of 128 (lane), second-to-last a "
        "multiple of 8 (f32 sublane) — or 1 for unit dims"
    )
    paths = _PALLAS_PATHS
    exclude = _PALLAS_EXCLUDE

    def check(self, ctx: ModuleContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if not name.endswith("BlockSpec") or not node.args:
                continue
            if _is_smem(node):
                continue
            dims = _literal_dims(node.args[0])
            if not dims or len(dims) < 2:
                continue
            lane = dims[-1]
            sub = dims[-2]
            if lane is not None and lane != 1 and lane % LANE:
                yield self.finding(
                    ctx, node,
                    f"BlockSpec lane dim {lane} is not a multiple of "
                    f"{LANE}",
                )
            elif sub is not None and sub != 1 and sub % SUBLANE_F32:
                yield self.finding(
                    ctx, node,
                    f"BlockSpec sublane dim {sub} is not a multiple "
                    f"of {SUBLANE_F32}",
                )


@register_rule
class SubF32ScratchQuanta(Rule):
    """Sub-f32 VMEM scratch below its dtype's sublane quantum."""

    id = "PSK205"
    severity = SEV_ERROR
    title = "sub-f32 VMEM tile below the dtype's sublane quantum"
    fix_hint = (
        "bf16 tiles need sublane multiples of 16, int8/fp8 of 32 "
        "(pallas_guide: tiling constraints)"
    )
    paths = _PALLAS_PATHS
    exclude = _PALLAS_EXCLUDE

    def check(self, ctx: ModuleContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if not name.endswith("VMEM") or len(node.args) < 2:
                continue
            dims = _literal_dims(node.args[0])
            dtype = (dotted_name(node.args[1]) or "").rsplit(".", 1)[-1]
            quantum = _SUBLANE_QUANTA.get(dtype)
            if quantum is None or not dims or len(dims) < 2:
                continue
            sub = dims[-2]
            if sub is not None and sub % quantum:
                yield self.finding(
                    ctx, node,
                    f"VMEM {dtype} scratch sublane dim {sub} is below "
                    f"the {quantum}-row quantum",
                )


def _kernel_defs(ctx: ModuleContext) -> list[ast.FunctionDef]:
    """Function defs passed (directly or through partial) as the first
    argument of a pallas_call in this module."""
    defs = {
        n.name: n
        for n in ast.walk(ctx.tree)
        if isinstance(n, ast.FunctionDef)
    }
    out = []
    partials: dict[str, str] = {}  # local name -> wrapped fn name
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ):
            callee = dotted_name(node.value.func) or ""
            if callee.split(".")[-1] == "partial" and node.value.args:
                inner = dotted_name(node.value.args[0])
                if inner and len(node.targets) == 1 and isinstance(
                    node.targets[0], ast.Name
                ):
                    partials[node.targets[0].id] = inner.split(".")[-1]
    for node in ast.walk(ctx.tree):
        if not (
            isinstance(node, ast.Call)
            and (dotted_name(node.func) or "").endswith("pallas_call")
            and node.args
        ):
            continue
        arg = node.args[0]
        name = dotted_name(arg)
        if isinstance(arg, ast.Call):
            callee = dotted_name(arg.func) or ""
            if callee.split(".")[-1] == "partial" and arg.args:
                name = dotted_name(arg.args[0])
        if name:
            leaf = name.split(".")[-1]
            leaf = partials.get(leaf, leaf)
            if leaf in defs:
                out.append(defs[leaf])
    return out


def _positional_param_count(fn: ast.FunctionDef) -> int:
    a = fn.args
    return len(a.posonlyargs) + len(a.args)


@register_rule
class ScalarPrefetchContract(Rule):
    """``num_scalar_prefetch`` vs the registry and the kernel arity.

    Scalar-prefetch refs arrive FIRST in the kernel signature; a
    miscounted ``num_scalar_prefetch`` shifts every later ref by one
    and Mosaic's error surfaces at lowering time, far from the edit.
    Checked statically when countable: the literal must equal the
    registry's ``scalar_prefetch`` declaration, and — when in/out
    specs and scratch_shapes are literal lists — the kernel's
    positional arity must equal prefetch + ins + outs + scratch.
    """

    id = "PSK206"
    severity = SEV_ERROR
    title = "scalar-prefetch count off the kernel registry/arity"
    fix_hint = (
        "keep num_scalar_prefetch, the KernelSpec.scalar_prefetch "
        "declaration, and the kernel's leading *_ref params in step"
    )
    paths = _PALLAS_PATHS
    exclude = _PALLAS_EXCLUDE

    def check(self, ctx: ModuleContext):
        spec = _registry_spec(ctx.relpath)
        kernels = _kernel_defs(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if not name.endswith("PrefetchScalarGridSpec"):
                continue
            n_prefetch = None
            counts = {}
            for kw in node.keywords:
                if kw.arg == "num_scalar_prefetch":
                    if isinstance(kw.value, ast.Constant) and isinstance(
                        kw.value.value, int
                    ):
                        n_prefetch = kw.value.value
                elif kw.arg in ("in_specs", "out_specs", "scratch_shapes"):
                    if isinstance(kw.value, (ast.List, ast.Tuple)):
                        counts[kw.arg] = len(kw.value.elts)
            if n_prefetch is None:
                yield self.finding(
                    ctx, node,
                    "num_scalar_prefetch is not a literal int: the "
                    "scalar/ref split cannot be audited",
                )
                continue
            if spec is not None and spec.scalar_prefetch != n_prefetch:
                yield self.finding(
                    ctx, node,
                    f"num_scalar_prefetch={n_prefetch} disagrees with "
                    f"the kernel registry declaration "
                    f"({spec.scalar_prefetch})",
                )
                continue
            if len(counts) == 3 and len(kernels) == 1:
                want = (
                    n_prefetch
                    + counts["in_specs"]
                    + counts["out_specs"]
                    + counts["scratch_shapes"]
                )
                got = _positional_param_count(kernels[0])
                if got != want:
                    yield self.finding(
                        ctx, node,
                        f"kernel {kernels[0].name!r} takes {got} "
                        f"positional refs but the grid spec implies "
                        f"{want} (prefetch {n_prefetch} + ins "
                        f"{counts['in_specs']} + outs "
                        f"{counts['out_specs']} + scratch "
                        f"{counts['scratch_shapes']})",
                    )


@register_rule
class LaneRetileWithoutFallback(Rule):
    """Lane-retiling reshape in a kernel without a fallback ladder.

    The ``(span/dec, dec)`` family of reshapes re-tiles the minor
    (lane) dimension inside the kernel; Mosaic support for it varies
    by toolchain, so a kernel doing it must declare
    ``retile_fallback=True`` in its registry entry — meaning a
    probe-gated ladder exists for the driver to descend when THIS
    toolchain rejects the retile. Flat ``reshape(-1)`` and
    unit-row ``reshape(1, n)`` are tile-preserving and exempt.
    """

    id = "PSK207"
    severity = SEV_ERROR
    title = "lane-retiling reshape without a declared retile fallback"
    fix_hint = (
        "declare retile_fallback=True in the KernelSpec and give the "
        "driver a probe-gated ladder (see spchain), or restructure "
        "the kernel to avoid retiling the lane dim"
    )
    paths = _PALLAS_PATHS
    exclude = _PALLAS_EXCLUDE

    def _is_retile(self, call: ast.Call) -> bool:
        args = call.args
        if len(args) == 1 and isinstance(args[0], (ast.Tuple, ast.List)):
            args = list(args[0].elts)
        if len(args) < 2:
            return False  # flatten / 1-D
        first = args[0]
        if (
            len(args) == 2
            and isinstance(first, ast.Constant)
            and first.value == 1
        ):
            return False  # unit-row prepend keeps the lane layout
        return True

    def check(self, ctx: ModuleContext):
        spec = _registry_spec(ctx.relpath)
        if spec is not None and spec.retile_fallback:
            return
        for fn in _kernel_defs(ctx):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                leaf = name.split(".")[-1]
                if leaf != "reshape":
                    continue
                if self._is_retile(node):
                    yield self.finding(
                        ctx, node,
                        f"kernel {fn.name!r} retiles the lane dim "
                        "in-kernel but its module declares no retile "
                        "fallback ladder",
                    )


# --------------------------------------------------------------------------
# dynamic checks over the kernel registry
# --------------------------------------------------------------------------


def _kernel_finding(spec, rule, message, severity=SEV_ERROR, hint=""):
    return Finding(
        rule=rule,
        severity=severity,
        path=f"pallas-registry/{spec.name}",
        line=0,
        col=0,
        message=message,
        fix_hint=hint,
        source_line=f"{rule} {spec.name}",
    )


def _probe_references_twin(probe_fn, twin: str) -> bool:
    import inspect
    import textwrap

    try:
        src = textwrap.dedent(inspect.getsource(probe_fn))
    except (OSError, TypeError):
        return False
    leaf = twin.rsplit(".", 1)[-1]
    return leaf in src


def audit_kernel(spec, mosaic: bool | None = None) -> list[Finding]:
    """Contract-check one registered kernel. ``mosaic=None`` probes
    the backend (TPU only); True forces the Mosaic lowering attempt,
    False skips it."""
    import importlib

    findings: list[Finding] = []
    # PSK202: registry drift — entry, probe, twin all resolvable and
    # the probe actually exercising the declared twin
    try:
        mod = importlib.import_module(spec.module)
    except Exception as exc:
        return [
            _kernel_finding(
                spec, "PSK202",
                f"kernel module {spec.module} failed to import: "
                f"{type(exc).__name__}: {exc!s:.200}",
            )
        ]
    if not hasattr(mod, spec.entry):
        findings.append(
            _kernel_finding(
                spec, "PSK202",
                f"entry point {spec.entry!r} missing from "
                f"{spec.module}",
                hint="fix the KernelSpec or restore the entry point",
            )
        )
    import peasoup_tpu.ops.pallas as pallas_pkg

    probe_fn = getattr(pallas_pkg, spec.probe, None)
    if probe_fn is None:
        findings.append(
            _kernel_finding(
                spec, "PSK202",
                f"probe {spec.probe!r} deleted from ops/pallas: the "
                "driver can no longer arbitrate this kernel's "
                "toolchain eligibility",
                hint=(
                    "restore the compile-and-run probe in "
                    "ops/pallas/__init__.py (oracle-checked against "
                    f"{spec.twin})"
                ),
            )
        )
    else:
        twin_mod, _, twin_attr = spec.twin.rpartition(".")
        try:
            twin_ok = hasattr(importlib.import_module(twin_mod), twin_attr)
        except Exception:
            twin_ok = False
        if not twin_ok:
            findings.append(
                _kernel_finding(
                    spec, "PSK202",
                    f"declared twin {spec.twin} is not importable",
                )
            )
        elif not _probe_references_twin(probe_fn, spec.twin):
            findings.append(
                _kernel_finding(
                    spec, "PSK202",
                    f"probe {spec.probe!r} no longer references the "
                    f"declared twin {spec.twin}: the oracle gate is "
                    "vacuous",
                )
            )
    if findings:
        return findings  # drifted registry: lowering would only noise

    # PSK203: interpret-mode trace/lower at the registered geometry
    import jax

    try:
        fn, args, kwargs = spec.build(True)
        jax.jit(lambda *a: fn(*a, **kwargs)).lower(*args)
    except Exception as exc:
        findings.append(
            _kernel_finding(
                spec, "PSK203",
                f"kernel no longer traces/lowers in interpret mode at "
                f"its registered geometry: {type(exc).__name__}: "
                f"{exc!s:.300}",
                hint=(
                    "the registry build thunk no longer matches the "
                    "kernel; fix the registration next to the kernel"
                ),
            )
        )
        return findings

    # PSK208: Mosaic lowering, where the toolchain allows
    if mosaic is None:
        try:
            mosaic = jax.default_backend() == "tpu"
        except Exception:
            mosaic = False
    if mosaic:
        try:
            fn, args, kwargs = spec.build(False)
            jax.jit(lambda *a: fn(*a, **kwargs)).lower(*args)
        except Exception as exc:
            findings.append(
                _kernel_finding(
                    spec, "PSK208",
                    f"Mosaic lowering failed on this toolchain: "
                    f"{type(exc).__name__}: {exc!s:.300}",
                    severity=(
                        SEV_WARNING if spec.retile_fallback else SEV_ERROR
                    ),
                    hint=(
                        "expected on toolchains the probe rejects — "
                        "the declared fallback ladder absorbs it"
                        if spec.retile_fallback
                        else "the driver has no fallback for this "
                        "kernel on this toolchain"
                    ),
                )
            )
    return findings


class KernelReport:
    def __init__(self) -> None:
        self.findings: list[Finding] = []
        self.kernels: list[str] = []


def audit_kernels(specs=None, mosaic: bool | None = None) -> KernelReport:
    """Contract-check all (or the given) registered kernels. The
    interpret builds are closed over static args, so this traces and
    lowers but never executes device code."""
    if specs is None:
        from peasoup_tpu.ops.pallas.registry import kernel_specs

        specs = kernel_specs()
    report = KernelReport()
    for spec in specs:
        report.kernels.append(spec.name)
        report.findings.extend(audit_kernel(spec, mosaic=mosaic))
    return report


def kernel_rules() -> tuple[str, ...]:
    """The static PSK rule IDs (the runner's engine-4 filter)."""
    return ("PSK201", "PSK204", "PSK205", "PSK206", "PSK207")
