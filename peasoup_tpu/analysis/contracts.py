"""Engine 2: jaxpr/StableHLO contract checks over registered programs.

Each :class:`~peasoup_tpu.ops.registry.ProgramSpec` is abstract-evaled
(traced + lowered, never compiled or executed) on CPU over its
registered representative shapes, and the artefacts are linted:

* **PSC101 f64 op** — the trace runs under ``jax.experimental
  .enable_x64`` so float64 drift that the production x64-disabled
  config silently *downcasts* (np.float64 staging constants, Python
  float promotion through np scalars) becomes a visible f64 op in the
  jaxpr. The walk recurses into sub-jaxprs (scan/cond/pjit bodies).
* **PSC102 host callback / unexpected custom call** — any
  ``custom_call`` whose target is not allowlisted; callback targets
  (``xla_python_cpu_callback`` etc.) are called out specifically.
* **PSC103 oversized baked-in constant** — closure constants above a
  size threshold get burned into the executable: silent recompiles
  per distinct value and HBM bloat (the hazard the campaign shape
  buckets exist to avoid).
* **PSC104 donation mismatch** — buffer donation lowered
  (``tf.aliasing_output``) must match what the registry declares the
  driver relies on, in both directions.
* **PSC105 trace/lower failure** — a registered program that no
  longer traces over its registered shapes is itself a finding (the
  registry is the contract).

**Bucket-ladder mode** (:func:`audit_programs_ladder`): the same
artifact lints run at the shapes a CAMPAIGN would trace — each rung of
the padded-nsamps octave ladder (campaign.runner.bucket_nsamps) is
turned into production ShapeCtxs with the drivers' own plan machinery
(perf.warmup.shape_ctx_for_bucket, plus subband/matmul/streaming
variants so every hook family gets a ctx it accepts), and every
registered program is rebuilt through its ``param`` hook at every
rung. Rung-dependent drift — an f64 constant only materialised past a
shape threshold, a baked table that crosses the size gate at survey
lengths, a donation that vanishes in a ctx-built variant — surfaces
here before a campaign hits it. **PSC106** flags any program the
ladder fails to cover at the required number of rungs: ladder
coverage is part of the registration contract, not best-effort.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field, replace

from .findings import Finding, SEV_ERROR, SEV_WARNING

# custom-call targets that are expected in normal CPU/TPU lowerings
DEFAULT_CUSTOM_CALL_ALLOWLIST = frozenset(
    {
        "Sharding",
        "SPMDFullToShardShape",
        "SPMDShardToFullShape",
        "ducc_fft",
        "dynamic_ducc_fft",
        "LuDecomposition",
    }
)

_CALLBACK_MARKERS = ("callback", "python", "py_")

_CUSTOM_CALL_RE = re.compile(
    r'custom_call\s*@(\w+)|call_target_name\s*=\s*"([^"]+)"'
)


@dataclass
class ContractConfig:
    max_const_bytes: int = 1 << 20  # 1 MiB
    check_x64: bool = True
    allow_custom_calls: frozenset = DEFAULT_CUSTOM_CALL_ALLOWLIST
    severity_const: str = SEV_ERROR
    platform: str = "cpu"


def _program_finding(spec, rule, message, severity=SEV_ERROR, hint="",
                     tag=""):
    return Finding(
        rule=rule,
        severity=severity,
        path=f"ops-registry/{spec.name}{tag}",
        line=0,
        col=0,
        message=message,
        fix_hint=hint,
        source_line=f"{rule} {spec.name}{tag}",
    )


def _walk_jaxprs(jaxpr):
    """Yield a jaxpr and every sub-jaxpr reachable through eqn params
    (scan/while/cond bodies, pjit call_jaxprs, custom_* rules)."""
    seen = set()
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        yield j
        for eqn in j.eqns:
            for val in eqn.params.values():
                stack.extend(_sub_jaxprs(val))


def _sub_jaxprs(val):
    out = []
    if hasattr(val, "jaxpr"):  # ClosedJaxpr
        out.append(val.jaxpr)
    elif hasattr(val, "eqns"):  # raw Jaxpr
        out.append(val)
    elif isinstance(val, (tuple, list)):
        for v in val:
            out.extend(_sub_jaxprs(v))
    return out


def _f64_eqns(closed_jaxpr):
    """(primitive_name, dtype) pairs for eqns PRODUCING f64/c128.

    Only outputs count: a ``convert_element_type(f64 -> f32)`` that
    immediately downcasts a staging constant is benign (the compiled
    program holds the f32 result), while any eqn whose *output* is f64
    means f64 arithmetic actually runs on device."""
    bad = []
    for j in _walk_jaxprs(closed_jaxpr.jaxpr):
        for eqn in j.eqns:
            for v in eqn.outvars:
                aval = getattr(v, "aval", None)
                dt = str(getattr(aval, "dtype", ""))
                if dt in ("float64", "complex128"):
                    bad.append((eqn.primitive.name, dt))
                    break
    return bad


def audit_program(spec, cfg: ContractConfig | None = None) -> list[Finding]:
    """Contract-check one registered program at its representative
    shapes; returns findings."""
    return _audit_built(spec, spec.build, cfg or ContractConfig())


def _audit_built(
    spec, build, cfg: ContractConfig, tag: str = ""
) -> list[Finding]:
    """Trace + lint one build thunk's artifacts. ``tag`` marks ladder
    builds (``@nsamps=<rung>``) so findings carry their rung."""
    import contextlib

    import jax
    from jax.experimental import enable_x64

    findings: list[Finding] = []
    x64 = enable_x64() if cfg.check_x64 else contextlib.nullcontext()
    try:
        fn, args, kwargs = build()
        if not hasattr(fn, "trace"):  # plain function: stage it
            fn = jax.jit(fn)
        with x64:
            traced = fn.trace(*args, **kwargs)
            closed = traced.jaxpr
            text = traced.lower().as_text()
    except Exception as e:  # registry drift is a finding, not a crash
        return [
            _program_finding(
                spec,
                "PSC105",
                f"failed to trace/lower over "
                f"{'ladder' if tag else 'registered'} shapes: "
                f"{type(e).__name__}: {e}",
                hint=(
                    "the registry build thunk no longer matches the "
                    "program; fix the registration next to the op"
                ),
                tag=tag,
            )
        ]

    # PSC101: f64 ops. The jaxpr walk (outputs only) is the source of
    # truth — the HLO text also shows f64 *operands* of the benign
    # f64->f32 staging converts, which are not drift.
    bad = _f64_eqns(closed)
    if bad:
        prims = sorted({p for p, _ in bad})
        findings.append(
            _program_finding(
                spec,
                "PSC101",
                f"float64 ops in jaxpr ({len(bad)} eqns: "
                f"{', '.join(prims[:6])}): f64 drift that the "
                "x64-disabled production config silently downcasts",
                hint=(
                    "pin the offending constants/intermediates to "
                    "float32 (np.float32 / jnp.float32)"
                ),
                tag=tag,
            )
        )

    # PSC102: custom calls / host callbacks
    targets = {t for pair in _CUSTOM_CALL_RE.findall(text) for t in pair if t}
    allowed = cfg.allow_custom_calls | set(spec.allow_custom_calls)
    for target in sorted(targets):
        low = target.lower()
        if any(m in low for m in _CALLBACK_MARKERS):
            findings.append(
                _program_finding(
                    spec,
                    "PSC102",
                    f"host callback in lowered program: {target} — a "
                    "device->host round trip per invocation",
                    hint=(
                        "move the host work out of the jitted program "
                        "(or io_callback it explicitly outside ops/)"
                    ),
                    tag=tag,
                )
            )
        elif target not in allowed:
            findings.append(
                _program_finding(
                    spec,
                    "PSC102",
                    f"unexpected custom call: {target}",
                    hint=(
                        "if intentional, add it to the program's "
                        "allow_custom_calls in its registration"
                    ),
                    tag=tag,
                )
            )

    # PSC103: oversized baked-in constants
    for const in closed.consts:
        nbytes = getattr(const, "nbytes", 0)
        if nbytes > cfg.max_const_bytes:
            shape = getattr(const, "shape", ())
            dtype = getattr(const, "dtype", "?")
            findings.append(
                _program_finding(
                    spec,
                    "PSC103",
                    f"baked-in constant {shape} {dtype} "
                    f"({nbytes / 1e6:.1f} MB > "
                    f"{cfg.max_const_bytes / 1e6:.1f} MB): burned into "
                    "the executable — every distinct value is a silent "
                    "recompile plus resident HBM",
                    severity=cfg.severity_const,
                    hint="pass it as a traced operand instead",
                    tag=tag,
                )
            )

    # PSC104: donation must match the registry declaration
    donated = text.count("tf.aliasing_output") + text.count(
        "jax.buffer_donor"
    )
    if spec.donate and donated == 0:
        findings.append(
            _program_finding(
                spec,
                "PSC104",
                f"registry declares donated args {list(spec.donate)} "
                "but the lowering aliases no buffers — the driver's "
                "memory budget assumes in-place reuse",
                hint="add donate_argnums to the jit wrapper",
                tag=tag,
            )
        )
    elif donated and not spec.donate:
        findings.append(
            _program_finding(
                spec,
                "PSC104",
                f"program donates {donated} buffer(s) the registry "
                "does not declare — callers may still be reading the "
                "donated operands",
                severity=SEV_WARNING,
                hint="declare donate=... in the registration",
                tag=tag,
            )
        )
    return findings


@dataclass
class ContractReport:
    findings: list[Finding] = field(default_factory=list)
    programs: list[str] = field(default_factory=list)


def audit_programs(
    specs=None, cfg: ContractConfig | None = None
) -> ContractReport:
    """Contract-check all (or the given) registered programs."""
    if specs is None:
        from peasoup_tpu.ops.registry import registered_programs

        specs = registered_programs()
    cfg = cfg or ContractConfig()
    report = ContractReport()
    for spec in specs:
        report.programs.append(spec.name)
        report.findings.extend(audit_program(spec, cfg))
    return report


# --------------------------------------------------------------------------
# bucket-ladder contracts
# --------------------------------------------------------------------------

# the synthetic campaign bucket the ladder contracts trace at: small
# band (tiny DM plan -> fast traces) with a 10 ms sample time so the
# whitening boundaries (pos5/pos25) land on nonzero bins even at the
# smallest rungs. (nchans, nbits, tsamp, fch1, foff) — nsamps is the
# rung.
LADDER_BASE_BUCKET = (8, 8, 0.01, 1400.0, -16.0)
LADDER_BASE_NSAMPS = 2048
LADDER_OVERRIDES = {"dm_end": 20.0, "n_widths": 6}
DEFAULT_LADDER_RUNGS = 2


def ladder_rungs(
    base_nsamps: int = LADDER_BASE_NSAMPS,
    count: int = DEFAULT_LADDER_RUNGS,
) -> list[int]:
    """The first ``count`` rungs >= ``base_nsamps`` of the campaign's
    padded-nsamps octave ladder ({2^k, 3*2^(k-1)} —
    campaign.runner.bucket_nsamps), so contracts walk the exact pad
    targets jobs bucket to."""
    from peasoup_tpu.campaign.runner import bucket_nsamps

    rungs: list[int] = []
    n = int(base_nsamps)
    while len(rungs) < count:
        r = bucket_nsamps(n)
        rungs.append(r)
        n = r + 1
    return rungs


def ladder_shape_ctxs(rung: int, overrides: dict | None = None) -> list:
    """Production ShapeCtx variants for one ladder rung: the spsearch
    and search pipelines via the drivers' own plan machinery, plus the
    streaming, subband and subband-matmul variants — one ctx family
    per hook family, so every registered program finds a ctx its hook
    accepts."""
    from peasoup_tpu.perf.warmup import shape_ctx_for_bucket

    nchans, nbits, tsamp, fch1, foff = LADDER_BASE_BUCKET
    bucket = (nchans, nbits, int(rung), tsamp, fch1, foff)
    ov = dict(LADDER_OVERRIDES if overrides is None else overrides)
    ctx_sp = shape_ctx_for_bucket(bucket, "spsearch", ov)
    ctx_search = shape_ctx_for_bucket(bucket, "search", ov)
    ctx_fdas = shape_ctx_for_bucket(bucket, "fdas", ov)
    return [
        ctx_sp,
        ctx_search,
        # FDAS correlation geometry: the fdas hooks decline every ctx
        # without a template batch, so they cover via this variant
        ctx_fdas,
        # streaming geometry: the chunk program's hook declines batch
        # ctxs, so give it the CLI-default chunk at this rung's plan
        replace(ctx_sp, stream_chunk=1024),
        # subband engine variants (gather-staged and matmul-staged)
        replace(ctx_search, subbands=4),
        replace(ctx_search, subbands=4, subband_matmul=True),
        # sub-byte bucket: the device unpacker declines byte data, so
        # its rung coverage rides a 2-bit variant of the same rung
        replace(ctx_sp, nbits=2),
    ]


@dataclass
class LadderReport:
    findings: list[Finding] = field(default_factory=list)
    rungs: list[int] = field(default_factory=list)
    # program name -> rungs at which a hook-built variant was traced
    coverage: dict[str, list[int]] = field(default_factory=dict)


def audit_programs_ladder(
    specs=None,
    rungs: list[int] | None = None,
    cfg: ContractConfig | None = None,
    min_rungs: int | None = None,
    overrides: dict | None = None,
) -> LadderReport:
    """Contract-check all (or the given) registered programs at every
    rung of the campaign bucket ladder. Each program is rebuilt
    through its ShapeCtx ``param`` hook with the first ctx variant
    that accepts it per rung; PSC106 flags programs the ladder covers
    at fewer than ``min_rungs`` rungs (default: every rung)."""
    if specs is None:
        from peasoup_tpu.ops.registry import registered_programs

        specs = registered_programs()
    cfg = cfg or ContractConfig()
    rungs = list(rungs) if rungs is not None else ladder_rungs()
    min_rungs = len(rungs) if min_rungs is None else min(
        min_rungs, len(rungs)
    )
    report = LadderReport(rungs=rungs)
    ctxs_by_rung = {r: ladder_shape_ctxs(r, overrides) for r in rungs}
    for spec in specs:
        covered: list[int] = []
        for rung in rungs:
            built = None
            for ctx in ctxs_by_rung[rung]:
                try:
                    built = spec.build_for(ctx)
                except Exception as exc:
                    report.findings.append(
                        _program_finding(
                            spec,
                            "PSC105",
                            f"ShapeCtx hook raised at rung {rung}: "
                            f"{type(exc).__name__}: {exc}",
                            hint=(
                                "hooks must DECLINE (return None) "
                                "ctxs they cannot build, never raise"
                            ),
                            tag=f"@nsamps={rung}",
                        )
                    )
                    built = None
                    break
                if built is not None:
                    break
            if built is None:
                continue
            covered.append(rung)
            built_spec = built
            report.findings.extend(
                _audit_built(
                    spec,
                    lambda b=built_spec: b,
                    cfg,
                    tag=f"@nsamps={rung}",
                )
            )
        report.coverage[spec.name] = covered
        if len(covered) < min_rungs:
            report.findings.append(
                _program_finding(
                    spec,
                    "PSC106",
                    f"bucket-ladder coverage {len(covered)}/"
                    f"{min_rungs} rungs (rungs {rungs}): the program "
                    "has no ShapeCtx hook (or its hook declines every "
                    "ladder ctx), so campaign-shape drift is invisible "
                    "to the contract engine",
                    hint=(
                        "give the registration a param= ShapeCtx hook "
                        "that builds at bucket geometry (see "
                        "_param_dedisperse_block)"
                    ),
                )
            )
    return report


__all__ = [
    "ContractConfig",
    "ContractReport",
    "DEFAULT_CUSTOM_CALL_ALLOWLIST",
    "LadderReport",
    "audit_program",
    "audit_programs",
    "audit_programs_ladder",
    "ladder_rungs",
    "ladder_shape_ctxs",
]
