"""Findings and the ratchet baseline.

A :class:`Finding` is one rule violation (AST or contract engine). Its
**fingerprint** is content-addressed — ``rule | path | stripped source
line`` — so baselined findings survive unrelated edits that shift line
numbers, and move WITH the offending line when it is cut/pasted. Two
identical lines in one file share a fingerprint; the baseline stores a
count per fingerprint, so adding a second copy of a baselined hazard
still fails the gate.

The :class:`Baseline` is a checked-in JSON document
(``audit_baseline.json``). The ratchet: findings covered by the
baseline are reported but don't fail; anything new does; baseline
entries that no longer match are reported as *resolved* so the file
can be re-written smaller (``peasoup-audit --write-baseline``) —
debt only goes down.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from dataclasses import dataclass, field

BASELINE_SCHEMA = "peasoup_tpu.audit_baseline"
BASELINE_VERSION = 1

SEV_ERROR = "error"
SEV_WARNING = "warning"


@dataclass
class Finding:
    """One rule violation."""

    rule: str  # rule ID, e.g. "PSA001" / "PSC101"
    severity: str  # "error" | "warning"
    path: str  # repo-relative posix path, or "ops-registry/<name>"
    line: int  # 1-based; 0 for whole-program (contract) findings
    col: int  # 0-based
    message: str
    fix_hint: str = ""
    source_line: str = ""  # stripped offending line (fingerprint input)
    baselined: bool = False

    @property
    def fingerprint(self) -> str:
        key = f"{self.rule}|{self.path}|{self.source_line.strip()}"
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "fix_hint": self.fix_hint,
            "fingerprint": self.fingerprint,
            "baselined": self.baselined,
        }

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        tag = " (baselined)" if self.baselined else ""
        out = f"{loc}: {self.rule} [{self.severity}]{tag}: {self.message}"
        if self.fix_hint:
            out += f"\n    hint: {self.fix_hint}"
        return out


def _atomic_write_json(path: str, doc: dict) -> None:
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


@dataclass
class Baseline:
    """Fingerprint -> tolerated count."""

    fingerprints: dict[str, int] = field(default_factory=dict)

    @classmethod
    def _load_strict(cls, path: str) -> "Baseline":
        with open(path) as f:
            doc = json.load(f)
        if doc.get("schema") != BASELINE_SCHEMA:
            raise ValueError(
                f"{path}: not a {BASELINE_SCHEMA} document "
                f"(schema={doc.get('schema')!r})"
            )
        fps = doc.get("fingerprints", {})
        if not isinstance(fps, dict) or not all(
            isinstance(v, int) and v > 0 for v in fps.values()
        ):
            raise ValueError(f"{path}: fingerprints must map fp -> count > 0")
        return cls(fingerprints=dict(fps))

    @classmethod
    def load(cls, path: str) -> "Baseline":
        """Unified corrupt-artifact semantics (resilience policy):
        warn + structured event on damage, but no quarantine rename
        (checked-in file) and no silent empty default — an unreadable
        baseline must fail the audit gate as an internal error, not
        ratchet every existing finding in as new."""
        from ..resilience import load_or_recover

        out = load_or_recover(
            path, cls._load_strict, default=None, kind="audit baseline",
            action="failing the audit gate", quarantine=False,
        )
        if out is None:
            raise ValueError(
                f"{path}: not a readable {BASELINE_SCHEMA} baseline "
                "(missing or corrupt; re-pin with peasoup-audit "
                "--write-baseline)"
            )
        return out

    @classmethod
    def from_findings(cls, findings) -> "Baseline":
        fps: dict[str, int] = {}
        for f in findings:
            fps[f.fingerprint] = fps.get(f.fingerprint, 0) + 1
        return cls(fingerprints=fps)

    def save(self, path: str) -> None:
        _atomic_write_json(
            path,
            {
                "schema": BASELINE_SCHEMA,
                "version": BASELINE_VERSION,
                "generated_by": "peasoup-audit --write-baseline",
                "fingerprints": self.fingerprints,
            },
        )

    def apply(self, findings) -> tuple[list, list, list]:
        """Split findings into (new, baselined) and return the list of
        resolved fingerprints (baseline entries with fewer live matches
        than their tolerated count). Findings are mutated in place
        (``baselined`` flag); within one fingerprint the first matches
        are baselined, the surplus is new."""
        budget = dict(self.fingerprints)
        new, old = [], []
        for f in findings:
            fp = f.fingerprint
            if budget.get(fp, 0) > 0:
                budget[fp] -= 1
                f.baselined = True
                old.append(f)
            else:
                new.append(f)
        resolved = sorted(fp for fp, n in budget.items() if n > 0)
        return new, old, resolved
