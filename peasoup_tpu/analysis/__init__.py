"""Static analysis for JAX/TPU hazards: ``peasoup-audit``.

Four engines, one report:

* **AST lints** (:mod:`.astlint`, PSA rules in :mod:`.rules`): a small
  rule-plugin framework over :mod:`ast` that encodes the hazards this
  codebase stakes runtime guarantees on — host syncs inside jitted
  code, Python control flow on tracers, float64 drift, non-atomic
  writes to files the obs/campaign layers rewrite atomically,
  thread-shared state mutated outside a lock, ``time.time()`` where
  ``perf_counter`` is required.
* **Program contracts** (:mod:`.contracts` over
  :mod:`peasoup_tpu.ops.registry`, PSC rules): every registered jitted
  program is abstract-evaled and its jaxpr/StableHLO linted — no f64
  ops (lowered under x64 so silent downcasts become visible), no
  unexpected host callbacks or custom calls, no oversized baked-in
  constants, donation matching what the registry declares — at the
  tiny representative shapes AND at every rung of the campaign bucket
  ladder (via each program's ShapeCtx hook), so rung-dependent drift
  surfaces before a campaign hits it (PSC106 gates the coverage).
* **Concurrency / file protocols** (:mod:`.protocol`, PSP rules): a
  dataflow-aware pass over the fleet's filesystem and threading
  protocols — shared-artifact writes must ride a sanctioned atomic
  idiom (O_EXCL create, tmp + ``os.replace``, append-only), corrupt
  artifacts quarantine by rename (never delete), durability-marked
  writers fsync before publishing, every thread body runs under
  ``guard_thread``, lock-owned attributes never mutate lock-free, and
  ambient telemetry never crosses a thread boundary uncopied.
* **Pallas kernel contracts** (:mod:`.kernels` over
  :mod:`peasoup_tpu.ops.pallas.registry`, PSK rules): every kernel
  ships its twin/probe/fallback triple (cross-referenced, PSK201/202),
  lowers under interpret mode at its registered geometry (PSK203) and
  under Mosaic where the toolchain allows (PSK208), and its tile
  shapes, scalar-prefetch arity and lane-retile reshapes are linted
  against the TPU quanta (PSK204-PSK207).

Findings ratchet against a checked-in JSON baseline
(``audit_baseline.json``): existing debt is tolerated, anything new
fails the gate. Per-line suppression:
``# audit: ignore[PSA006] -- reason`` (the reason is mandatory; a
bare suppression is inactive).

CLI: ``python -m peasoup_tpu.tools.audit`` (exit 0 clean, 1 new
findings, 2 internal error), wired into ``scripts/check.sh``.
"""

from .findings import Finding, Baseline
from .astlint import lint_source, lint_path, ModuleContext
from .rules import all_rules
from .contracts import (
    ContractConfig,
    audit_program,
    audit_programs,
    audit_programs_ladder,
    ladder_rungs,
    ladder_shape_ctxs,
)
from .kernels import audit_kernel, audit_kernels
from .runner import AuditResult, run_audit, render_text

__all__ = [
    "Finding",
    "Baseline",
    "ModuleContext",
    "lint_source",
    "lint_path",
    "all_rules",
    "ContractConfig",
    "audit_program",
    "audit_programs",
    "audit_programs_ladder",
    "ladder_rungs",
    "ladder_shape_ctxs",
    "audit_kernel",
    "audit_kernels",
    "AuditResult",
    "run_audit",
    "render_text",
]
