"""Static analysis for JAX/TPU hazards: ``peasoup-audit``.

Two engines, one report:

* **AST lints** (:mod:`.astlint`, rules in :mod:`.rules`): a small
  rule-plugin framework over :mod:`ast` that encodes the hazards this
  codebase stakes runtime guarantees on — host syncs inside jitted
  code, Python control flow on tracers, float64 drift, non-atomic
  writes to files the obs/campaign layers rewrite atomically,
  thread-shared state mutated outside a lock, ``time.time()`` where
  ``perf_counter`` is required.
* **Program contracts** (:mod:`.contracts` over
  :mod:`peasoup_tpu.ops.registry`): every registered jitted program is
  abstract-evaled over a tiny representative shape set and its
  jaxpr/StableHLO linted — no f64 ops (lowered under x64 so silent
  downcasts become visible), no unexpected host callbacks or custom
  calls, no oversized baked-in constants, donation matching what the
  registry declares.

Findings ratchet against a checked-in JSON baseline
(``audit_baseline.json``): existing debt is tolerated, anything new
fails the gate. Per-line suppression:
``# audit: ignore[PSA006] -- reason`` (the reason is mandatory; a
bare suppression is inactive).

CLI: ``python -m peasoup_tpu.tools.audit`` (exit 0 clean, 1 new
findings, 2 internal error), wired into ``scripts/check.sh``.
"""

from .findings import Finding, Baseline
from .astlint import lint_source, lint_path, ModuleContext
from .rules import all_rules
from .contracts import ContractConfig, audit_program, audit_programs
from .runner import AuditResult, run_audit, render_text

__all__ = [
    "Finding",
    "Baseline",
    "ModuleContext",
    "lint_source",
    "lint_path",
    "all_rules",
    "ContractConfig",
    "audit_program",
    "audit_programs",
    "AuditResult",
    "run_audit",
    "render_text",
]
