from .distill import HarmonicDistiller, AccelerationDistiller, DMDistiller
from .score import CandidateScorer
from .search import SearchConfig, PeasoupSearch
from .single_pulse import SinglePulseConfig, SinglePulseSearch
from .folder import MultiFolder
