from .distill import HarmonicDistiller, AccelerationDistiller, DMDistiller
from .score import CandidateScorer
from .search import SearchConfig, PeasoupSearch
from .folder import MultiFolder
