"""Host-side single-pulse search driver: the framework's new transient
workload over the dedispersed DM-time plane.

Mirrors pipeline/search.py's shape — a single host process walks the
GLOBAL DM plan in device waves, reusing the dedispersion engines
(ops/dedisperse.py), the mesh/sharding helpers (parallel/), and the
per-trial SearchCheckpoint (keyed by a single-pulse config key, so a
periodicity checkpoint can never resume a single-pulse run or vice
versa). Per-trial device work is ops/singlepulse.py's jitted
normalise -> boxcar-bank -> peak program; the host then clusters the
raw (dm, time, width) events with a friends-of-friends pass so one
broad pulse detected at many DM trials / widths / samples reports as
ONE candidate with its footprint (the clustering stage of Heimdall and
GSP, arXiv:2110.12749).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..core.candidates import (
    SinglePulseCandidate,
    SinglePulseCandidateCollection,
)
from ..io.masks import read_killfile
from ..io.sigproc import Filterbank
from ..obs import get_logger
from ..obs.telemetry import current as current_telemetry
from ..obs.trace import job_span
from ..ops.dedisperse import (
    dedisperse,
    dedisperse_device,
    fil_to_device,
    output_scale,
)
from ..ops.singlepulse import (
    default_widths,
    make_single_pulse_search_fn,
    plan_pad,
)
from ..plan.dm_plan import DMPlan
from ..utils import ProgressBar, trace_span
from .checkpoint import SearchCheckpoint
from .search import _is_oom

log = get_logger("pipeline.single_pulse")


@dataclass
class SinglePulseConfig:
    """Single-pulse search knobs (no reference equivalent — peasoup
    searches periodicity only; defaults follow Heimdall/GSP practice)."""

    outdir: str = "."
    killfilename: str = ""
    limit: int = 1000
    dm_start: float = 0.0
    dm_end: float = 100.0
    dm_tol: float = 1.10
    dm_pulse_width: float = 64.0
    min_snr: float = 6.0  # single-pulse searches threshold lower than
    # periodicity (each trial is one matched filter, not 2^20 bins)
    n_widths: int = 12  # octave-spaced boxcar widths 1..2^(n-1) samples
    max_width: int = 0  # optional cap on the widest boxcar (samples);
    # 0 = only the n_widths / trial-length caps apply
    max_events: int = 256  # static per-trial event-compaction size
    decimate: int = 32  # best-plane max-decimation factor before the
    # peak compaction (bounds crossings to run-length/decimate)
    time_link: float = 1.0  # friends-of-friends: events link when
    # |dt| <= time_link * max(width_i, width_j) + decimate
    dm_link: int = 2  # ... and |d dm_idx| <= dm_link
    verbose: bool = False
    progress_bar: bool = False
    max_num_threads: int = 14
    # TPU-specific knobs, mirroring SearchConfig
    dedisp_block: int = 16
    dm_block: int = 0  # DM trials per device call; 0 = auto from HBM
    hbm_bytes: int = 0
    checkpoint_file: str = ""
    use_pallas: bool = True  # Pallas boxcar kernel on TPU backends
    shard_devices: int = 0  # 0 = auto; N forces an N-chip 'dm' mesh
    tune: bool = False  # per-device tuned dedispersion shape knobs via
    # the tuning cache (perf/tuning.py; the single-pulse driver has no
    # subband path, so only the block knobs tune)
    tuning_cache: str = ""  # tuning_cache.json path ("" = default)


@dataclass
class SinglePulseResult:
    candidates: list
    dm_list: np.ndarray
    widths: tuple[int, ...]
    timers: dict
    nsamps: int
    n_events: int = 0  # raw above-threshold events before clustering
    n_overflowed: int = 0  # trials whose event count exceeded max_events


@dataclass
class PartialSinglePulseResult:
    """A single-pulse search stopped before clustering
    (``run(finalize=False)``): the raw above-threshold events of one
    process's DM slice with GLOBAL dm_idx, ready for the multi-host
    allgather (parallel/multihost.py:run_single_pulse_search). The
    merged global event set then goes through :meth:`finalize` on
    every process, so the clustered candidate list is identical (and
    deterministic) everywhere — the single-pulse analogue of the
    periodicity PartialSearchResult."""

    events: np.ndarray  # _EVENT_DTYPE records, dm_idx GLOBAL
    dm_list: np.ndarray  # the GLOBAL trial list
    widths: tuple[int, ...]
    timers: dict
    nsamps: int
    n_overflowed: int
    t_total_start: float


_EVENT_DTYPE = np.dtype(
    [
        ("dm_idx", np.int64),
        ("sample", np.int64),
        ("width_idx", np.int64),
        ("snr", np.float64),
    ]
)


def cluster_events_fof(
    events: np.ndarray,  # _EVENT_DTYPE records
    widths: tuple[int, ...],
    *,
    time_link: float = 1.0,
    dm_link: int = 2,
    dec: int = 32,
) -> list[np.ndarray]:
    """Friends-of-friends in (time, DM, width): two events are friends
    when their start samples lie within ``time_link * max(w_i, w_j) +
    dec`` AND their DM trials within ``dm_link``. Width enters through
    the time tolerance (a broad detection reaches further), which links
    the width ladder a bright pulse climbs without any explicit width
    adjacency rule. Returns index arrays, one per cluster.

    The pair scan slides over time-sorted events (the time tolerance is
    bounded by the widest filter), so cost is O(n * window) — fine for
    the tens of thousands of events a threshold sweep emits.
    """
    n = len(events)
    if n == 0:
        return []
    order = np.argsort(events["sample"], kind="stable")
    ev = events[order]
    wmax_link = time_link * float(max(widths)) + dec
    parent = np.arange(n, dtype=np.int64)

    def find(a: int) -> int:
        while parent[a] != a:
            parent[a] = parent[parent[a]]
            a = parent[a]
        return a

    w_of = np.asarray(widths, dtype=np.float64)[ev["width_idx"]]
    lo = 0
    for j in range(n):
        while ev["sample"][j] - ev["sample"][lo] > wmax_link:
            lo += 1
        for i in range(lo, j):
            dt = ev["sample"][j] - ev["sample"][i]
            if dt > time_link * max(w_of[i], w_of[j]) + dec:
                continue
            if abs(ev["dm_idx"][j] - ev["dm_idx"][i]) > dm_link:
                continue
            ra, rb = find(i), find(j)
            if ra != rb:
                parent[rb] = ra
        # liveness note: the [lo, j) window is bounded by wmax_link
    roots: dict[int, list[int]] = {}
    for i in range(n):
        roots.setdefault(find(i), []).append(i)
    return [order[np.asarray(members)] for members in roots.values()]


def candidates_from_clusters(
    events: np.ndarray,  # _EVENT_DTYPE records
    clusters: list[np.ndarray],  # index arrays from cluster_events_fof
    widths: tuple[int, ...],
    dm_list: np.ndarray,
    tsamp: float,
) -> list[SinglePulseCandidate]:
    """Package friends-of-friends clusters as SinglePulseCandidates
    (peak member + footprint extents) — shared by the batch finalize
    and the streaming driver's incremental confirmation, so a trigger
    emitted live is field-for-field the candidate a batch run of the
    same data would report."""
    w_arr = np.asarray(widths, dtype=np.int64)
    out = []
    for members in clusters:
        ev = events[members]
        peak = int(np.argmax(ev["snr"]))
        widx = int(ev["width_idx"][peak])
        out.append(
            SinglePulseCandidate(
                dm=float(dm_list[int(ev["dm_idx"][peak])]),
                dm_idx=int(ev["dm_idx"][peak]),
                snr=float(ev["snr"][peak]),
                time_s=float(ev["sample"][peak]) * tsamp,
                sample=int(ev["sample"][peak]),
                width=int(w_arr[widx]),
                width_idx=widx,
                members=len(members),
                dm_idx_lo=int(ev["dm_idx"].min()),
                dm_idx_hi=int(ev["dm_idx"].max()),
                sample_lo=int(ev["sample"].min()),
                sample_hi=int(ev["sample"].max()),
                width_lo=int(w_arr[ev["width_idx"]].min()),
                width_hi=int(w_arr[ev["width_idx"]].max()),
            )
        )
    return out


def select_sp_kernels(
    widths: tuple[int, ...],
    span: int,
    tpad: int,
    decimate: int,
    use_pallas: bool,
) -> tuple[int, int, str | None]:
    """Resolve the single-pulse device-kernel route: ``(pallas_span,
    fused_span, fallback_rung)``, preferring the fused sweep+dec-fold
    chain (ops/pallas/spchain.py) at the full tile span, then — when
    the toolchain probe rejects its (span/dec, dec) retile — RETILED
    fused variants at successively halved spans (the reshape that
    Mosaic refuses at one tile geometry is often fine at a smaller
    one; dec-fold semantics are span-independent, so the bitwise
    oracle still gates each candidate), then the plain boxcar kernel,
    then the jnp twin. All routes are bitwise-identical by the probe
    contract; the rung is a *performance* degradation only.

    ``fallback_rung`` names the resilience degradation rung taken
    (None when the preferred kernel probed clean — or when the backend
    has no Pallas support at all, where the twin is the design point,
    not a degradation)."""
    if not use_pallas or span <= 0:
        return 0, 0, None
    from ..ops.pallas import (
        backend_supports_pallas,
        probe_pallas_boxcar,
        probe_pallas_spchain,
    )
    from ..ops.singlepulse import _QUANT

    if span % decimate == 0 and probe_pallas_spchain(
        len(widths), span, decimate
    ):
        return 0, span, None
    expected = backend_supports_pallas()
    if expected and decimate > 0 and span % decimate == 0:
        s = span // 2
        while s >= max(decimate, _QUANT) and s % _QUANT == 0:
            if (
                s % decimate == 0
                and tpad % s == 0
                and probe_pallas_spchain(len(widths), s, decimate)
            ):
                return 0, s, "spchain_retile"
            s //= 2
    if probe_pallas_boxcar(len(widths), span):
        return span, 0, "boxcar_kernel" if expected else None
    return 0, 0, "jnp_twin" if expected else None


def make_checkpoint_key(
    cfg: SinglePulseConfig, fil, global_ndm: int, widths: tuple[int, ...]
) -> str:
    """Config key over everything that changes per-trial events —
    including the observation's identity and the workload TYPE prefix,
    so a periodicity checkpoint can never resume a single-pulse run."""
    h = fil.header
    fields = (
        "sp-v1",  # single-pulse per-trial payload format version
        fil.nsamps, fil.nchans, global_ndm,
        fil.tsamp, fil.fch1, fil.foff,
        getattr(h, "tstart", None), getattr(h, "source_name", None),
        getattr(h, "nbits", None),
        cfg.dm_start, cfg.dm_end, cfg.dm_tol, cfg.dm_pulse_width,
        cfg.min_snr, tuple(int(w) for w in widths), cfg.max_events,
        cfg.decimate, cfg.killfilename,
    )
    return repr(fields)


class SinglePulseSearch:
    """Walk the DM plan in device waves and cluster the events.

    HBM accounting mirrors PeasoupSearch: the per-trial working set is
    ~4 f32 planes of the padded trial length (normalised series, prefix
    sum, best-S/N, best-width), so the auto dm_block is
    budget / (16 * tpad)."""

    TOTAL_HBM = 12_000_000_000
    TRIALS_DEVICE_LIMIT = 4_000_000_000

    def __init__(self, config: SinglePulseConfig):
        self.config = config
        import os

        devs = jax.local_devices()
        limit = config.hbm_bytes or int(
            os.environ.get("PEASOUP_HBM_BYTES", 0) or 0
        )
        if not limit:
            try:
                limit = (devs[0].memory_stats() or {}).get("bytes_limit", 0)
            except Exception:
                limit = 0
        if limit:
            self.TOTAL_HBM = int(limit)
            self.TRIALS_DEVICE_LIMIT = int(limit) // 3

    def build_dm_plan(self, fil: Filterbank) -> DMPlan:
        """The GLOBAL dedispersion plan (same construction as the
        periodicity search's — the two workloads share the DM-time
        plane by design)."""
        cfg = self.config
        killmask = None
        if cfg.killfilename:
            killmask = read_killfile(cfg.killfilename, fil.nchans)
        return DMPlan.create(
            nsamps=fil.nsamps,
            nchans=fil.nchans,
            tsamp=fil.tsamp,
            fch1=fil.fch1,
            foff=fil.foff,
            dm_start=cfg.dm_start,
            dm_end=cfg.dm_end,
            pulse_width=cfg.dm_pulse_width,
            tol=cfg.dm_tol,
            killmask=killmask,
        )

    def widths_for(self, out_nsamps: int) -> tuple[int, ...]:
        """The run's boxcar bank: octave-spaced, capped so the widest
        filter is at most a quarter of the trial (beyond that the
        'pulse' is baseline, not transient) and by cfg.max_width."""
        cap = max(1, out_nsamps // 4)
        if self.config.max_width:
            cap = min(cap, self.config.max_width)
        return default_widths(self.config.n_widths, max_width=cap)

    def _pick_devices(self) -> list:
        cfg = self.config
        devs = jax.local_devices()
        if cfg.shard_devices > 0:
            return devs[: min(cfg.shard_devices, len(devs))]
        if devs and devs[0].platform == "tpu":
            return devs[: min(len(devs), cfg.max_num_threads)]
        return devs[:1]

    def run(
        self,
        fil: Filterbank,
        dm_slice: tuple[int, int] | None = None,
        finalize: bool = True,
    ) -> "SinglePulseResult | PartialSinglePulseResult":
        """Full search. With ``dm_slice=(lo, hi)`` only that contiguous
        block of the global DM-trial list is dedispersed and searched
        (events come back with GLOBAL dm_idx); with ``finalize=False``
        the run stops before clustering and returns a
        PartialSinglePulseResult for the multi-host event merge."""
        cfg = self.config
        tel = current_telemetry()
        timers: dict[str, float] = {}
        t_total = time.perf_counter()

        # --- plan ------------------------------------------------------
        t0 = time.perf_counter()
        tel.set_stage("plan")
        global_plan = self.build_dm_plan(fil)
        widths = self.widths_for(global_plan.out_nsamps)
        lo = 0
        dm_plan = global_plan
        if dm_slice is not None:
            lo, hi = dm_slice
            dm_plan = global_plan.subset(lo, hi)
        timers["plan"] = time.perf_counter() - t0
        tel.gauge("sp.n_dm_trials", int(global_plan.ndm))
        tel.gauge("sp.n_widths", len(widths))
        tel.event(
            "sp_plan", ndm=int(global_plan.ndm),
            out_nsamps=int(global_plan.out_nsamps),
            widths=[int(w) for w in widths],
            dm_slice=[int(lo), int(lo + dm_plan.ndm)],
        )

        # --- checkpoint store (load before dedispersion: a fully
        # restored run skips the expensive part, like the periodicity
        # driver's resume fast path). Keyed on the GLOBAL trial count
        # with per-slice store files, so resuming under a different
        # process count reuses every completed trial -------------------
        ckpt = None
        restored: dict[int, tuple] = {}
        if cfg.checkpoint_file:
            ckpt = SearchCheckpoint(
                cfg.checkpoint_file,
                make_checkpoint_key(cfg, fil, global_plan.ndm, widths),
                slice_bounds=dm_slice,
            )
            restored = ckpt.load()
        skip_dedisp = dm_plan.ndm > 0 and all(
            d in restored for d in range(dm_plan.ndm)
        )
        if dm_plan.ndm == 0:
            # empty multi-host slice (more processes than DM trials):
            # contribute zero events without touching the device
            part = PartialSinglePulseResult(
                events=np.zeros(0, dtype=_EVENT_DTYPE),
                dm_list=global_plan.dm_list,
                widths=widths,
                timers={
                    **timers, "dedispersion": 0.0, "searching": 0.0,
                },
                nsamps=fil.nsamps,
                n_overflowed=0,
                t_total_start=t_total,
            )
            return part if not finalize else self.finalize(fil, part)

        # --- auto-tuned dedispersion shape knobs -----------------------
        dedisp_block = cfg.dedisp_block
        if cfg.tune:
            try:
                from ..perf.tuning import resolve_plan_for_filterbank

                dplan = resolve_plan_for_filterbank(
                    fil, "spsearch", cfg,
                    cache_path=cfg.tuning_cache or None,
                )
            except Exception as exc:
                log.warning("dedispersion tuning failed: %.200s", exc)
                dplan = None
            if dplan is not None:
                dedisp_block = dplan.dedisp_block or dedisp_block
                tel.event("dedisp_plan", **dplan.summary())
                tel.set_context(dedisp_plan=dplan.summary())

        # --- dedispersion (reusing the periodicity engines) ------------
        t0 = time.perf_counter()
        tel.set_stage("dedispersion")
        devices = self._pick_devices()
        mesh = None
        if len(devices) > 1:
            from ..parallel.mesh import make_mesh

            mesh = make_mesh({"dm": len(devices)}, devices=devices)
        trials_bytes = dm_plan.ndm * dm_plan.out_nsamps
        spill = trials_bytes > self.TRIALS_DEVICE_LIMIT * (
            len(devices) if mesh is not None else 1
        )
        tel.event(
            "sp_device_plan", n_devices=len(devices),
            sharded=mesh is not None, trials_spill=bool(spill),
            trials_bytes=int(trials_bytes),
        )
        scale = output_scale(fil.nbits, int(dm_plan.killmask.sum()))
        if skip_dedisp:
            log.info(
                "Resume fast path: all %d trials checkpointed — "
                "skipping dedispersion", dm_plan.ndm,
            )
            tel.event("sp_resume_fast_path", ndm=int(dm_plan.ndm))
            trials = np.zeros((0, dm_plan.out_nsamps), dtype=np.uint8)
            spill = True
        else:
            with trace_span("Dedisperse"):
                shard_dd = (
                    mesh is not None
                    and not spill
                    and 4 * fil.nsamps * fil.nchans < 3_000_000_000
                )
                if shard_dd:
                    try:
                        from ..parallel.sharded_dedisperse import (
                            dedisperse_sharded,
                        )

                        trials = dedisperse_sharded(
                            fil_to_device(fil),
                            dm_plan.delay_samples(),
                            dm_plan.killmask,
                            dm_plan.out_nsamps,
                            mesh,
                            scale=scale,
                            block=dedisp_block,
                        )
                        jax.block_until_ready(trials)
                    except Exception as exc:
                        # shard_map availability varies by jax release;
                        # a single-device dedispersion is always correct
                        # (the search blocks re-shard onto the mesh)
                        log.warning(
                            "sharded dedispersion unavailable (%.200s); "
                            "falling back to the single-device engine",
                            exc,
                        )
                        tel.event(
                            "sp_sharded_dedisp_fallback",
                            error=f"{exc!s:.200}",
                        )
                        shard_dd = False
                if not shard_dd:
                    dd = dedisperse if spill else dedisperse_device
                    trials = dd(
                        fil.data if spill else fil_to_device(fil),
                        dm_plan.delay_samples(),
                        dm_plan.killmask,
                        dm_plan.out_nsamps,
                        scale=scale,
                        block=dedisp_block,
                    )
                if not spill:
                    # async dispatch (mirrors pipeline/search.py): the
                    # first boxcar waves overlap the dedispersion tail;
                    # PEASOUP_SYNC_DEDISP=1 restores the barrier. The
                    # sharded path above keeps its own sync — it gates
                    # the shard_map-availability fallback.
                    import os as _os

                    if _os.environ.get("PEASOUP_SYNC_DEDISP"):
                        jax.block_until_ready(trials)
                    else:
                        tel.event(
                            "dedisp_async_dispatch",
                            dispatch_s=round(time.perf_counter() - t0, 4),
                        )
        timers["dedispersion"] = time.perf_counter() - t0
        tel.capture_device_memory("dedispersion")

        # --- device waves over the DM axis -----------------------------
        t0 = time.perf_counter()
        tel.set_stage("searching")
        nsamps = dm_plan.out_nsamps
        tpad, span = plan_pad(nsamps)
        # prefer the fused sweep+dec-fold mega-kernel (the best planes
        # never round-trip HBM at full resolution); when its retile
        # probe rejects the full span, try retiled spans, then the
        # plain boxcar kernel, then the jnp twin — all bitwise
        # identical, so a fallback rung is a logged performance
        # degradation, never a correctness event
        pallas_span, fused_span, rung = select_sp_kernels(
            widths, span, tpad, cfg.decimate, cfg.use_pallas
        )
        if rung is not None:
            from ..resilience import DegradationLadder

            DegradationLadder(
                "spsearch.kernel",
                ("spchain_retile", "boxcar_kernel", "jnp_twin"),
            ).step(
                rung, span=int(span), fused_span=int(fused_span),
                pallas_span=int(pallas_span), decimate=int(cfg.decimate),
            )
            log.warning(
                "fused spchain kernel rejected at span=%d; degraded to "
                "rung %s (fused_span=%d, pallas_span=%d)",
                span, rung, fused_span, pallas_span,
            )
        self._pallas_span = pallas_span
        self._fused_span = fused_span
        sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            sharding = NamedSharding(mesh, PartitionSpec("dm"))

        per_dm: dict[int, tuple] = restored
        if per_dm and not skip_dedisp:
            log.info(
                "Resuming: %d/%d DM trials restored from %s",
                len(per_dm), dm_plan.ndm, cfg.checkpoint_file,
            )
            tel.event(
                "sp_checkpoint_resume", restored=len(per_dm),
                ndm=int(dm_plan.ndm),
            )

        # auto block: ~4 f32 planes of tpad per trial (norm, csum,
        # best, argw) with 4x headroom; mesh runs round up to a
        # devices multiple so every chip gets equal rows
        if cfg.dm_block > 0:
            dm_block = cfg.dm_block
        else:
            per_trial = 16 * tpad
            dm_block = int(
                max(1, min(256, (self.TOTAL_HBM // 4) // max(1, per_trial)))
            )
        n_dev = len(devices)
        if n_dev > 1:
            dm_block = max(n_dev, -(-dm_block // n_dev) * n_dev)

        from ..resilience import DegradationLadder, faults

        # the memory ladder: halve dm_block (repeatable rung), and when
        # the blocks are already at the floor fall THROUGH to the CPU
        # backend (host RAM dwarfs HBM; slow beats dead) instead of
        # raising — candidates stay bitwise-equal because the per-trial
        # program is shape-identical and the Pallas kernels are gated on
        # bitwise equality with their jnp twins
        ladder = DegradationLadder(
            "spsearch.memory", ("dm_block_shrink", "cpu_backend")
        )
        shrink = 1
        cpu_mode = False
        while True:
            blk = max(
                n_dev if n_dev > 1 else 1, dm_block // shrink
            )
            if n_dev > 1:
                blk = max(n_dev, -(-blk // n_dev) * n_dev)
            chunks = [
                list(range(s, min(s + blk, dm_plan.ndm)))
                for s in range(0, dm_plan.ndm, blk)
            ]
            tel.event(
                "sp_wave_plan", n_chunks=len(chunks), dm_block=blk,
                shrink=shrink, pallas_span=self._pallas_span,
                fused_span=self._fused_span,
                backend="cpu" if cpu_mode else "default",
            )
            try:
                faults.fire(
                    "device.oom",
                    context=(
                        "spsearch:cpu" if cpu_mode
                        else f"spsearch:shrink{shrink}"
                    ),
                )
                if cpu_mode:
                    with jax.default_device(jax.devices("cpu")[0]):
                        self._run_waves(
                            chunks, blk, trials, per_dm, ckpt, widths,
                            sharding=None, spill=True,
                        )
                else:
                    self._run_waves(
                        chunks, blk, trials, per_dm, ckpt, widths,
                        sharding=sharding, spill=spill,
                    )
                break
            except Exception as exc:
                if not _is_oom(exc):
                    raise
                if blk > max(1, n_dev):
                    shrink *= 2
                    log.warning(
                        "device OOM at dm_block=%d; retrying with "
                        "dm_block=%d: %.200s", blk,
                        max(1, dm_block // shrink), exc,
                    )
                    tel.event(
                        "sp_oom_shrink_retry", dm_block_old=blk,
                        shrink=shrink, error=f"{exc!s:.200}",
                    )
                    # once a later rung stepped, in-rung shrinks keep
                    # the event trail but not a ladder step (a ladder
                    # never climbs back up)
                    if ladder.current_rung in (None, "dm_block_shrink"):
                        ladder.step(
                            "dm_block_shrink", dm_block_old=blk,
                            dm_block_new=max(1, dm_block // shrink),
                            error=f"{exc!s:.200}",
                        )
                    continue
                if cpu_mode:
                    # nothing below the CPU rung
                    ladder.exhausted(dm_block=blk, error=f"{exc!s:.200}")
                    raise
                # shrink exhausted: fall through to the CPU backend.
                # The rung is a new memory regime (host RAM), so block
                # sizing restarts from the top — which also keeps the
                # successful attempt's per-chunk shapes identical to an
                # untroubled run's (the bitwise-equality guarantee).
                cpu_mode = True
                shrink = 1
                trials = np.asarray(trials)  # host-resident input
                n_dev = 1
                self._pallas_span = 0  # TPU kernels are moot on CPU
                self._fused_span = 0
                log.warning(
                    "device OOM with dm_block already at the floor "
                    "(%d); falling through to the CPU backend: %.200s",
                    blk, exc,
                )
                tel.event(
                    "sp_oom_cpu_fallback", dm_block=blk,
                    error=f"{exc!s:.200}",
                )
                ladder.step(
                    "cpu_backend", dm_block=blk, error=f"{exc!s:.200}"
                )
        timers["searching"] = time.perf_counter() - t0
        tel.capture_device_memory("search")

        # --- event extraction (GLOBAL dm_idx) --------------------------
        recs = []
        n_overflowed = 0
        for dm_idx in range(dm_plan.ndm):
            pos_w, snrs, count = per_dm[dm_idx]
            c = int(np.asarray(count))
            k = min(c, len(snrs))
            if c > len(snrs):
                n_overflowed += 1
            for i in range(k):
                recs.append(
                    (dm_idx + lo, int(pos_w[0, i]), int(pos_w[1, i]),
                     float(snrs[i]))
                )
        events = np.asarray(recs, dtype=_EVENT_DTYPE)
        if n_overflowed:
            log.warning(
                "%d DM trials overflowed the %d-event compaction; "
                "keeping the first %d (ascending time) per trial",
                n_overflowed, cfg.max_events, cfg.max_events,
            )
            tel.event(
                "sp_event_overflow", trials=n_overflowed,
                max_events=cfg.max_events,
            )
        part = PartialSinglePulseResult(
            events=events,
            dm_list=global_plan.dm_list,
            widths=widths,
            timers=timers,
            nsamps=fil.nsamps,
            n_overflowed=n_overflowed,
            t_total_start=t_total,
        )
        if not finalize:
            return part
        return self.finalize(fil, part)

    def finalize(
        self, fil: Filterbank, part: PartialSinglePulseResult
    ) -> SinglePulseResult:
        """Cluster a (possibly multi-host-merged) global event set and
        package candidates. Deterministic in the event set, so every
        process of a multi-host run reaches the identical result."""
        cfg = self.config
        tel = current_telemetry()
        timers = part.timers
        events, widths = part.events, part.widths

        t0 = time.perf_counter()
        tel.set_stage("clustering")
        clusters = cluster_events_fof(
            events, widths, time_link=cfg.time_link, dm_link=cfg.dm_link,
            dec=cfg.decimate,
        )
        cands = SinglePulseCandidateCollection()
        cands.append(
            candidates_from_clusters(
                events, clusters, widths, part.dm_list, fil.tsamp
            )
        )
        out = sorted(cands, key=lambda c: -c.snr)[: cfg.limit]
        timers["clustering"] = time.perf_counter() - t0
        timers["total"] = time.perf_counter() - part.t_total_start
        tel.gauge("sp.n_events", len(events))
        tel.gauge("sp.n_clusters", len(clusters))
        tel.gauge("candidates.final", len(out))
        log.info(
            "single-pulse search: %d events -> %d clusters -> %d "
            "candidates", len(events), len(clusters), len(out),
        )
        return SinglePulseResult(
            candidates=out,
            dm_list=part.dm_list,
            widths=widths,
            timers=timers,
            nsamps=part.nsamps,
            n_events=len(events),
            n_overflowed=part.n_overflowed,
        )

    def _run_waves(
        self, chunks, blk, trials, per_dm, ckpt, widths, *, sharding, spill
    ) -> None:
        cfg = self.config
        tel = current_telemetry()
        progress = ProgressBar() if cfg.progress_bar else None
        if progress:
            progress.start()
        search_fn = make_single_pulse_search_fn(
            widths, float(cfg.min_snr), cfg.max_events, cfg.decimate,
            self._pallas_span, self._fused_span,
        )
        tel.set_progress(0, len(chunks), unit="chunks")
        try:
            for ci, chunk in enumerate(chunks):
                if all(d in per_dm for d in chunk):
                    tel.set_progress(ci + 1, len(chunks), unit="chunks")
                    continue
                lo, hi = chunk[0], chunk[-1] + 1
                # fleet-trace span (obs/trace.py, no-op outside a
                # campaign job): one search wave of the job's timeline
                with job_span("wave", wave=ci), trace_span("SP-Chunk"):
                    block = trials[lo:hi]
                    if spill:
                        block = jnp.asarray(block)
                    pad = blk - (hi - lo)
                    if pad:
                        block = jnp.concatenate(
                            [block, jnp.zeros((pad, block.shape[1]),
                                              block.dtype)]
                        )
                    if sharding is not None:
                        block = jax.device_put(block, sharding)
                    samples, widx, snrs, counts = search_fn(block)
                    # one packed fetch per wave (tiny arrays)
                    samples = np.asarray(samples)
                    widx = np.asarray(widx)
                    snrs = np.asarray(snrs)
                    counts = np.asarray(counts)
                for j, dm_idx in enumerate(chunk):
                    per_dm[dm_idx] = (
                        np.stack([samples[j], widx[j]]).astype(np.int32),
                        snrs[j].astype(np.float32),
                        np.int32(counts[j]),
                    )
                if ckpt is not None:
                    with job_span("checkpoint", wave=ci):
                        ckpt.save(per_dm)
                tel.set_progress(ci + 1, len(chunks), unit="chunks")
                if progress:
                    progress.update((ci + 1) / len(chunks))
                # revoke seam: a preempt/retire observed by the lease
                # renewer stops here — the checkpoint just saved is the
                # state the resumed run restores, so candidates stay
                # bitwise-equal to an uninterrupted sweep
                from ..resilience import check_revoke

                check_revoke("spsearch.wave")
        finally:
            if progress:
                progress.stop()
