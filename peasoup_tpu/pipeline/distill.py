"""Candidate distillers: collapse harmonically/accelerationally/DM-related
detections onto their strongest member.

Reference: include/transforms/distiller.hpp. The algorithm sorts by S/N
descending (!IMPORTANT, distiller.hpp:31), then walks survivors in
order; each survivor's ``condition`` marks weaker related candidates
non-unique and (optionally) absorbs them into its ``assoc`` list.

Host-side by design: candidate counts are tiny relative to device work,
and the O(n^2) inner loops vectorise over numpy arrays here (the native
C++ path in peasoup_tpu.native accelerates the worst case).
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.candidates import Candidate

SPEED_OF_LIGHT = 299792458.0


class BaseDistiller:
    """condition() implementations read the precomputed column arrays
    (self.freqs/accs/nhs) instead of walking the Candidate objects —
    the arrays are built once per distill() call, keeping the O(n^2)
    survivor loop in vectorised numpy."""

    def __init__(self, keep_related: bool):
        self.keep_related = keep_related
        self.freqs: np.ndarray | None = None
        self.accs: np.ndarray | None = None
        self.nhs: np.ndarray | None = None

    def condition(self, cands, idx, unique) -> None:
        raise NotImplementedError

    def _native(self, cands):
        """Return (unique_mask, edge_src, edge_dst) from the C++ runtime,
        or None to use the Python survivor loop."""
        return None

    def distill(self, cands: List[Candidate]) -> List[Candidate]:
        size = len(cands)
        # The !IMPORTANT S/N-descending sort (distiller.hpp:31) is
        # std::sort — UNSTABLE introsort, whose arrangement of exactly
        # tied S/N values decides which tie member the distiller crowns.
        # Replay the same libstdc++ algorithm via the native runtime;
        # fall back to a stable sort (tie winners may then differ from
        # the reference, everything else is identical).
        from .. import native

        perm = native.snr_sort_perm(
            np.array([c.snr for c in cands], dtype=np.float32)
        )
        if perm is not None:
            cands = [cands[i] for i in perm]
        else:
            cands = sorted(cands, key=lambda c: -c.snr)  # S/N desc, stable
        self.freqs = np.array([c.freq for c in cands], dtype=np.float64)
        self.accs = np.array([c.acc for c in cands], dtype=np.float64)
        self.nhs = np.array([c.nh for c in cands], dtype=np.int64)
        native_res = self._native(cands)
        if native_res is not None:
            unique, src, dst = native_res
            if self.keep_related:
                for s, d in zip(src, dst):
                    cands[s].append(cands[d])
            return [c for c, u in zip(cands, unique) if u]
        unique = np.ones(size, dtype=bool)
        idx = 0
        while idx < size:
            if unique[idx]:
                self.condition(cands, idx, unique)
            idx += 1
        return [c for c, u in zip(cands, unique) if u]


class HarmonicDistiller(BaseDistiller):
    """Absorb candidates whose freq is a (fractional) harmonic of a
    stronger one (distiller.hpp:63-108)."""

    def __init__(self, tol: float, max_harm: int, keep_related: bool,
                 fractional_harms: bool = True):
        super().__init__(keep_related)
        self.tolerance = tol
        self.max_harm = int(max_harm)
        self.fractional_harms = fractional_harms

    def _native(self, cands):
        from .. import native

        return native.harmonic_distill(
            self.freqs, self.nhs, self.tolerance, self.max_harm,
            self.fractional_harms, self.keep_related,
        )

    def condition(self, cands, idx, unique) -> None:
        size = len(cands)
        if idx + 1 >= size:
            return
        fundi = self.freqs[idx]
        freqs = self.freqs[idx + 1 :]
        nhs = self.nhs[idx + 1 :]
        # hits counts matching (jj, kk) harmonic pairs per candidate: the
        # reference appends to assoc once PER MATCHING PAIR
        # (distiller.hpp:92-101), which feeds nassoc and the ddm ratios.
        if self.fractional_harms:
            max_denoms = np.exp2(nhs).astype(np.int64)
        else:
            max_denoms = np.ones(len(freqs), dtype=np.int64)
        max_kk = int(max_denoms.max()) if len(max_denoms) else 1
        # all kk at once per jj: ratio[k, i] = kk_k*freqs_i/(jj*fundi);
        # chunking over jj keeps the transient matrix at (max_kk, n)
        kk = np.arange(1, max_kk + 1)
        kk_valid = kk[:, None] <= max_denoms[None, :]
        hits = np.zeros(len(freqs), dtype=np.int64)
        for jj in range(1, self.max_harm + 1):
            ratio = (kk[:, None] * freqs[None, :]) / (jj * fundi)
            hits += (
                kk_valid
                & (ratio > 1 - self.tolerance)
                & (ratio < 1 + self.tolerance)
            ).sum(axis=0)
        for off in np.nonzero(hits)[0]:
            target = idx + 1 + off
            if self.keep_related:
                for _ in range(int(hits[off])):
                    cands[idx].append(cands[target])
            unique[target] = False


class AccelerationDistiller(BaseDistiller):
    """Absorb candidates within the frequency window swept by the
    acceleration difference (distiller.hpp:115-164).
    Note: +ve acceleration is away from the observer."""

    def __init__(self, tobs: float, tol: float, keep_related: bool):
        super().__init__(keep_related)
        self.tobs = tobs
        self.tobs_over_c = tobs / SPEED_OF_LIGHT
        self.tolerance = tol

    def _native(self, cands):
        from .. import native

        return native.accel_distill(
            self.freqs, self.accs, self.tobs_over_c, self.tolerance,
            self.keep_related,
        )

    def condition(self, cands, idx, unique) -> None:
        size = len(cands)
        if idx + 1 >= size:
            return
        fundi_freq = self.freqs[idx]
        fundi_acc = self.accs[idx]
        edge = fundi_freq * self.tolerance
        freqs = self.freqs[idx + 1 :]
        accs = self.accs[idx + 1 :]
        delta_acc = fundi_acc - accs
        acc_freq = fundi_freq + delta_acc * fundi_freq * self.tobs_over_c
        upper_case = acc_freq > fundi_freq
        hit = np.where(
            upper_case,
            (freqs > fundi_freq - edge) & (freqs < acc_freq + edge),
            (freqs < fundi_freq + edge) & (freqs > acc_freq - edge),
        )
        for off in np.nonzero(hit)[0]:
            target = idx + 1 + off
            if self.keep_related:
                cands[idx].append(cands[target])
            unique[target] = False


class DMDistiller(BaseDistiller):
    """Plain frequency-ratio matching across DM trials
    (distiller.hpp:168-197)."""

    def __init__(self, tol: float, keep_related: bool):
        super().__init__(keep_related)
        self.tolerance = tol

    def _native(self, cands):
        from .. import native

        return native.dm_distill(self.freqs, self.tolerance, self.keep_related)

    def condition(self, cands, idx, unique) -> None:
        size = len(cands)
        if idx + 1 >= size:
            return
        fundi = self.freqs[idx]
        ratio = self.freqs[idx + 1 :] / fundi
        hit = (ratio > 1 - self.tolerance) & (ratio < 1 + self.tolerance)
        for off in np.nonzero(hit)[0]:
            target = idx + 1 + off
            if self.keep_related:
                cands[idx].append(cands[target])
            unique[target] = False
