"""Host-side search driver: the TPU equivalent of `peasoup`'s main +
Worker loop (reference: src/pipeline_multi.cu:262-419, 83-254).

The reference deals DM trials to one pthread per GPU; here a single
host process walks the DM list (optionally sharded across chips by
peasoup_tpu.parallel), launching ONE jitted program per DM trial that
covers the whole acceleration batch. Candidate bookkeeping (clustering,
distilling, scoring) is host work on tiny arrays, as in the reference.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.candidates import Candidate, CandidateCollection
from ..io.masks import read_killfile, read_zapfile
from ..io.sigproc import Filterbank
from ..ops.dedisperse import dedisperse, output_scale
from ..ops.peaks import cluster_peaks
from ..ops.resample import accel_factor
from ..ops.zap import birdie_mask
from ..plan.accel_plan import AccelerationPlan
from ..plan.dm_plan import DMPlan
from ..plan.fft_plan import choose_fft_size
from ..utils import ProgressBar, trace_span
from .accel_search import make_batched_search_fn
from .checkpoint import SearchCheckpoint
from .distill import AccelerationDistiller, DMDistiller, HarmonicDistiller
from .folder import MultiFolder
from .score import CandidateScorer


@dataclass
class SearchConfig:
    """Mirrors CmdLineOptions with the reference's defaults
    (include/utils/cmdline.hpp:69-209)."""

    outdir: str = "."
    killfilename: str = ""
    zapfilename: str = ""
    max_num_threads: int = 14
    limit: int = 1000
    size: int = 0  # fft size; 0 = prev power of two
    dm_start: float = 0.0
    dm_end: float = 100.0
    dm_tol: float = 1.10
    dm_pulse_width: float = 64.0
    acc_start: float = 0.0
    acc_end: float = 0.0
    acc_tol: float = 1.10
    acc_pulse_width: float = 64.0
    boundary_5_freq: float = 0.05
    boundary_25_freq: float = 0.5
    nharmonics: int = 4
    npdmp: int = 0
    min_snr: float = 9.0
    min_freq: float = 0.1
    max_freq: float = 1100.0
    max_harm: int = 16
    freq_tol: float = 1e-4
    verbose: bool = False
    progress_bar: bool = False
    # TPU-specific knobs (no reference equivalent)
    max_peaks: int = 512  # static peak-compaction size per spectrum
    dedisp_block: int = 16  # DM trials per dedispersion launch
    accel_bucket: int = 16  # accel batch padded to a multiple of this
    dm_block: int = 8  # DM trials searched per device call (per chip)
    checkpoint_file: str = ""  # resumable per-DM-trial result store
    use_pallas: bool = True  # Pallas resample kernel on TPU backends
    # device sharding: 0 = auto (all local TPU chips up to
    # max_num_threads, single-device elsewhere); N = force an N-chip
    # 'dm' mesh (tests use this on the virtual CPU mesh)
    shard_devices: int = 0


@dataclass
class SearchResult:
    candidates: list
    dm_list: np.ndarray
    acc_list_dm0: np.ndarray
    timers: dict
    nsamps: int
    size: int
    n_accel_trials: int = 0  # total DM x accel trials actually searched


def _level_windows(
    size: int, nharms: int, min_freq: float, max_freq: float, tsamp: float
) -> np.ndarray:
    """[start_idx, limit) per harmonic level (peakfinder.hpp:78-84)."""
    size_spec = size // 2 + 1
    tobs = np.float32(size) * np.float32(tsamp)
    bin_width = 1.0 / float(tobs)
    nyquist = bin_width * size_spec
    orig_size = 2.0 * (size_spec - 1.0)
    rows = []
    for nh in range(nharms + 1):
        max_bin = int((max_freq / bin_width) * 2.0**nh)
        limit = min(size_spec, max_bin)
        start = int(orig_size * (min_freq / nyquist) * 2.0**nh)
        rows.append((start, limit))
    return np.asarray(rows, dtype=np.int32)


def _freq_factor(size: int, nh: int, tsamp: float) -> float:
    """Bin index -> frequency for level nh (peakfinder.hpp:89)."""
    size_spec = size // 2 + 1
    tobs = np.float32(size) * np.float32(tsamp)
    bin_width = 1.0 / float(tobs)
    nyquist = bin_width * size_spec
    return 1.0 / size_spec * nyquist / 2.0**nh


class PeasoupSearch:
    def __init__(self, config: SearchConfig):
        self.config = config
        self._eff_dm_block = config.dm_block
        self._dm_sharding = None

    def _pick_devices(self) -> list:
        """Devices to shard DM trials over. Auto mode mirrors the
        reference's one-worker-per-GPU-up-to--t policy
        (pipeline_multi.cu:276-277) on TPU backends; elsewhere it stays
        single-device unless shard_devices forces a mesh (tests)."""
        import jax

        devs = jax.local_devices()
        cfg = self.config
        if cfg.shard_devices > 0:
            return devs[: min(cfg.shard_devices, len(devs))]
        if devs and devs[0].platform == "tpu":
            return devs[: min(len(devs), cfg.max_num_threads)]
        return devs[:1]

    def run(self, fil: Filterbank) -> SearchResult:
        cfg = self.config
        timers: dict[str, float] = {}
        t_total = time.time()

        # --- dedispersion plan + execution ---------------------------------
        killmask = None
        if cfg.killfilename:
            killmask = read_killfile(cfg.killfilename, fil.nchans)
        dm_plan = DMPlan.create(
            nsamps=fil.nsamps,
            nchans=fil.nchans,
            tsamp=fil.tsamp,
            fch1=fil.fch1,
            foff=fil.foff,
            dm_start=cfg.dm_start,
            dm_end=cfg.dm_end,
            pulse_width=cfg.dm_pulse_width,
            tol=cfg.dm_tol,
            killmask=killmask,
        )
        t0 = time.time()
        with trace_span("Dedisperse"):  # NVTX parity: pipeline_multi.cu:318
            trials = dedisperse(
                fil.data,
                dm_plan.delay_samples(),
                dm_plan.killmask,
                dm_plan.out_nsamps,
                scale=output_scale(fil.nbits, int(dm_plan.killmask.sum())),
                block=cfg.dedisp_block,
            )
        timers["dedispersion"] = time.time() - t0

        # --- search setup ---------------------------------------------------
        size = choose_fft_size(fil.nsamps, cfg.size)
        trials_nsamps = dm_plan.out_nsamps
        nsamps_valid = min(trials_nsamps, size)
        tobs = float(np.float32(size) * np.float32(fil.tsamp))
        bin_width = 1.0 / tobs
        # NOTE: the reference passes foff as the accel plan's "bw" —
        # the width term uses the CHANNEL width (pipeline_multi.cu:335-337)
        acc_plan = AccelerationPlan(
            acc_lo=cfg.acc_start,
            acc_hi=cfg.acc_end,
            tol=cfg.acc_tol,
            pulse_width=cfg.acc_pulse_width,
            nsamps=size,
            tsamp=fil.tsamp,
            cfreq=fil.cfreq,
            bw=fil.foff,
        )
        size_spec = size // 2 + 1
        if cfg.zapfilename:
            bf, bw_ = read_zapfile(cfg.zapfilename)
            zapmask = birdie_mask(bf, bw_, bin_width, size_spec)
        else:
            zapmask = np.zeros(size_spec, dtype=bool)
        zapmask_dev = jnp.asarray(zapmask)
        windows = jnp.asarray(
            _level_windows(size, cfg.nharmonics, cfg.min_freq, cfg.max_freq, fil.tsamp)
        )
        factors = [
            _freq_factor(size, nh, fil.tsamp) for nh in range(cfg.nharmonics + 1)
        ]
        pos5 = int(cfg.boundary_5_freq / bin_width)
        pos25 = int(cfg.boundary_25_freq / bin_width)

        harm_finder = HarmonicDistiller(cfg.freq_tol, cfg.max_harm, keep_related=False)
        acc_still = AccelerationDistiller(tobs, cfg.freq_tol, keep_related=True)

        # --- batched DM-trial search ----------------------------------------
        # DM trials are grouped by padded accel-list size and processed in
        # fixed (dm_block, accel_bucket) tiles: one compile per distinct
        # tile shape, vmapped over the block (vs the reference's per-trial
        # kernel launches). The search itself is device work; candidate
        # clustering/distilling below is tiny host work per trial.
        t0 = time.time()
        accel_lists = [
            acc_plan.generate_accel_list(float(dm)) for dm in dm_plan.dm_list
        ]
        bucket = cfg.accel_bucket
        by_bucket: dict[int, list[int]] = {}
        for dm_idx, accs in enumerate(accel_lists):
            padded = int(math.ceil(len(accs) / bucket) * bucket)
            by_bucket.setdefault(padded, []).append(dm_idx)

        pallas_block = 0
        if cfg.use_pallas:
            from ..ops.pallas import probe_pallas_resample
            from ..ops.pallas.resample import choose_block

            af_max = max(
                (float(np.abs(accel_factor(a, fil.tsamp)).max())
                 for a in accel_lists if len(a)),
                default=0.0,
            )
            pallas_block = choose_block(af_max, size)
            # real compile+run probe at the production shape: degrade
            # to the jnp twin instead of crashing on Mosaic toolchains
            # that reject this kernel
            if pallas_block and not probe_pallas_resample(size, pallas_block):
                pallas_block = 0

        # --- device selection: shard DM trials over local chips --------
        # (the reference's analogue: one worker per GPU up to -t,
        # pipeline_multi.cu:276-277)
        devices = self._pick_devices()
        if len(devices) > 1:
            from ..parallel.mesh import make_mesh
            from ..parallel.sharded_search import make_sharded_search_fn

            from jax.sharding import NamedSharding, PartitionSpec

            mesh = make_mesh({"dm": len(devices)}, devices=devices)
            search_block = make_sharded_search_fn(
                mesh, cfg.min_snr, axis="dm", pallas_block=pallas_block
            )
            # per-call block covers dm_block trials per chip; stage
            # blocks directly onto the mesh (no hop through chip 0)
            self._dm_sharding = NamedSharding(mesh, PartitionSpec("dm"))
            self._eff_dm_block = cfg.dm_block * len(devices)
        else:
            search_block = make_batched_search_fn(cfg.min_snr, pallas_block)
            self._dm_sharding = None
            self._eff_dm_block = cfg.dm_block
        tim_len = min(size, trials.shape[1])

        ckpt = None
        per_dm_results: dict[int, tuple] = {}
        if cfg.checkpoint_file:
            ckpt = SearchCheckpoint(
                cfg.checkpoint_file,
                SearchCheckpoint.make_key(cfg, fil, size, dm_plan.ndm),
            )
            per_dm_results = ckpt.load()
            if cfg.verbose and per_dm_results:
                print(
                    f"Resuming: {len(per_dm_results)}/{dm_plan.ndm} DM "
                    f"trials restored from {cfg.checkpoint_file}"
                )

        chunks = [
            dm_indices[start : start + self._eff_dm_block]
            for padded, dm_indices in sorted(by_bucket.items())
            for start in range(0, len(dm_indices), self._eff_dm_block)
        ]
        progress = ProgressBar() if cfg.progress_bar else None
        if progress:
            progress.start()
        last_ckpt = time.time()
        dirty = False
        for n_chunk, chunk in enumerate(chunks):
            if all(d in per_dm_results for d in chunk):
                continue  # restored from checkpoint
            with trace_span("DM-Loop"):  # NVTX parity: pipeline_multi.cu:144
                self._search_chunk(
                    chunk, accel_lists, trials, tim_len, zapmask_dev,
                    windows, search_block, per_dm_results,
                    size=size, nsamps_valid=nsamps_valid,
                    pos5=pos5, pos25=pos25, tsamp=fil.tsamp,
                )
            dirty = True
            # rate-limit full-rewrite saves: a crash loses at most ~10 s
            # of device work instead of paying O(n^2) rewrite I/O
            if ckpt is not None and time.time() - last_ckpt > 10.0:
                ckpt.save(per_dm_results)
                last_ckpt = time.time()
                dirty = False
            if progress:
                progress.update((n_chunk + 1) / len(chunks))
        if ckpt is not None and dirty:
            ckpt.save(per_dm_results)
        if progress:
            progress.stop()
        timers["search_device"] = time.time() - t0

        # --- host candidate bookkeeping (ascending DM order) ----------------
        t_host = time.time()
        dm_trial_cands = CandidateCollection()
        for dm_idx, dm in enumerate(dm_plan.dm_list):
            idxs, snrs, counts = per_dm_results.pop(dm_idx)
            accs = accel_lists[dm_idx]
            accel_trial_cands = CandidateCollection()
            for a_idx in range(len(accs)):
                acc = float(accs[a_idx])
                trial_cands: list[Candidate] = []
                for lvl in range(cfg.nharmonics + 1):
                    n_found = int(counts[lvl, a_idx])
                    pk_idx, pk_snr = cluster_peaks(
                        idxs[lvl, a_idx], snrs[lvl, a_idx], n_found
                    )
                    for b, s in zip(pk_idx, pk_snr):
                        trial_cands.append(
                            Candidate(
                                dm=float(dm),
                                dm_idx=dm_idx,
                                acc=acc,
                                nh=lvl,
                                snr=float(s),
                                freq=float(b) * factors[lvl],
                            )
                        )
                accel_trial_cands.append(harm_finder.distill(trial_cands))
            dm_trial_cands.append(acc_still.distill(accel_trial_cands.cands))
            if cfg.verbose:
                print(
                    f"DM {dm:.3f} ({dm_idx+1}/{dm_plan.ndm}): "
                    f"{len(accs)} accel trials, {len(dm_trial_cands)} cands so far"
                )
        timers["search_host"] = time.time() - t_host
        timers["searching"] = time.time() - t0

        # --- global distilling / scoring / folding --------------------------
        dm_still = DMDistiller(cfg.freq_tol, keep_related=True)
        harm_still = HarmonicDistiller(
            cfg.freq_tol, cfg.max_harm, keep_related=True, fractional_harms=False
        )
        cands = dm_still.distill(dm_trial_cands.cands)
        cands = harm_still.distill(cands)

        scorer = CandidateScorer(
            fil.tsamp, fil.cfreq, fil.foff, abs(fil.foff) * fil.nchans
        )
        scorer.score_all(cands)

        t0 = time.time()
        if cfg.npdmp > 0:
            folder = MultiFolder(
                trials, trials_nsamps, fil.tsamp,
                pos5_freq=cfg.boundary_5_freq, pos25_freq=cfg.boundary_25_freq,
            )
            cands = folder.fold_n(cands, cfg.npdmp)
        timers["folding"] = time.time() - t0

        cands = cands[: cfg.limit]
        timers["total"] = time.time() - t_total
        acc_list_dm0 = acc_plan.generate_accel_list(0.0)
        return SearchResult(
            candidates=cands,
            dm_list=dm_plan.dm_list,
            acc_list_dm0=acc_list_dm0,
            timers=timers,
            nsamps=fil.nsamps,
            size=size,
            n_accel_trials=sum(len(a) for a in accel_lists),
        )

    def _search_chunk(
        self, chunk, accel_lists, trials, tim_len, zapmask_dev, windows,
        search_block, per_dm_results, *, size, nsamps_valid, pos5, pos25,
        tsamp,
    ) -> None:
        """Run one (dm_block, accel_bucket) device tile and bank the
        static-size peak sets for every real trial in the chunk."""
        cfg = self.config
        dm_block = self._eff_dm_block
        real = len(chunk)
        bucket = cfg.accel_bucket
        padded = max(
            int(math.ceil(len(accel_lists[d]) / bucket) * bucket)
            for d in chunk
        )
        # pad the block by repeating the first trial (discarded)
        block_idx = chunk + [chunk[0]] * (dm_block - real)
        afs = np.zeros((dm_block, padded), dtype=np.float32)
        for row, dm_idx in enumerate(block_idx):
            accs = accel_lists[dm_idx]
            afs[row, : len(accs)] = accel_factor(accs, tsamp).astype(
                np.float32
            )
        import jax

        if self._dm_sharding is not None:
            tims_dev = jax.device_put(
                trials[block_idx, :tim_len], self._dm_sharding
            )
            afs_dev = jax.device_put(afs, self._dm_sharding)
        else:
            tims_dev = jnp.asarray(trials[block_idx, :tim_len])
            afs_dev = jnp.asarray(afs)
        max_peaks = cfg.max_peaks
        while True:
            peaks = search_block(
                tims_dev,
                afs_dev,
                zapmask_dev,
                windows,
                size=size,
                nsamps_valid=nsamps_valid,
                nharms=cfg.nharmonics,
                max_peaks=max_peaks,
                pos5=pos5,
                pos25=pos25,
            )
            counts = np.asarray(peaks.counts)
            if counts.max() <= max_peaks:
                break
            # overflow: escalate the static compaction size so no
            # threshold crossing is lost (the reference sizes for
            # 100000, peakfinder.hpp:61); costs one extra compile
            # only on pathological blocks
            max_peaks = 1 << int(np.ceil(np.log2(counts.max())))
        idxs = np.asarray(peaks.idxs)  # (B, L, A, maxp)
        snrs = np.asarray(peaks.snrs)
        for row in range(real):
            # trim to this trial's own maximum count: bounds host
            # memory and detaches the padded block buffers
            mx = max(int(counts[row].max()), 1)
            per_dm_results[chunk[row]] = (
                idxs[row][:, :, :mx].copy(),
                snrs[row][:, :, :mx].copy(),
                counts[row].copy(),
            )
